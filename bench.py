#!/usr/bin/env python
"""Headline benchmark — ResNet-50 synthetic-ImageNet images/sec/chip.

This is BASELINE.json's metric: "ResNet-50 ImageNet images/sec/chip;
step-time parity vs 8xA100 NCCL". The baseline constant below is the
per-GPU ResNet-50 training throughput of an 8xA100 DGX with NCCL allreduce
and mixed precision (~22k images/sec total => 2770 images/sec/GPU, MLPerf
class numbers); vs_baseline >= 1.0 means step-time parity per chip.

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import sys

A100_IMAGES_PER_SEC_PER_GPU = 2770.0


def main() -> None:
    import jax

    from benchmarks.common import setup_cache

    # Persistent compilation cache: ResNet-50 cold-compiles very slowly over
    # the axon tunnel; warm runs (including the driver's) reuse the cache.
    setup_cache()
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributed_tensorflow_guide_tpu.core.dist import initialize
    from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh
    from distributed_tensorflow_guide_tpu.models.resnet import ResNet50, make_loss_fn
    from distributed_tensorflow_guide_tpu.parallel.data_parallel import DataParallel
    from distributed_tensorflow_guide_tpu.train.state import TrainStateWithStats

    initialize()
    n_dev = len(jax.devices())
    per_chip_batch = 128
    global_batch = per_chip_batch * n_dev
    image_size = 224

    mesh = build_mesh(MeshSpec(data=-1))
    dp = DataParallel(mesh)
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)

    rng = jax.random.PRNGKey(0)
    variables = model.init(rng, jnp.zeros((1, image_size, image_size, 3)), train=False)
    params = variables["params"]
    model_state = {"batch_stats": variables["batch_stats"]}
    tx = optax.sgd(0.1, momentum=0.9)
    state = dp.replicate(
        TrainStateWithStats.create(
            apply_fn=model.apply, params=params, tx=tx, model_state=model_state
        )
    )

    step = dp.make_train_step_with_stats(make_loss_fn(model))

    # One fixed on-device batch: the bench measures compute+collectives, not
    # host data generation (data/ pipelines are benchmarked separately).
    rng_np = np.random.RandomState(0)
    batch = dp.shard_batch(
        {
            "image": rng_np.randn(global_batch, image_size, image_size, 3).astype(
                np.float32
            ),
            "label": rng_np.randint(0, 1000, global_batch).astype(np.int32),
        }
    )

    # Timing is closed by materializing host values that data-depend on the
    # final step's loss AND updated params (the steps chain through `state`).
    # block_until_ready alone does not reliably fence execution on every PJRT
    # transport (measured: the axon tunnel acks readiness early, inflating
    # throughput ~25x); a scalar fetch cannot complete before the compute it
    # depends on. The shared implementation lives in benchmarks/common.py.
    from benchmarks.common import time_steps

    n_steps = 20
    dt, state = time_steps(step, state, batch, warmup=3, steps=n_steps)

    images_per_sec_per_chip = global_batch * n_steps / dt / n_dev
    print(
        json.dumps(
            {
                "metric": "resnet50_synthetic_imagenet_throughput",
                "value": round(images_per_sec_per_chip, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(
                    images_per_sec_per_chip / A100_IMAGES_PER_SEC_PER_GPU, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
