#!/usr/bin/env python
"""Headline benchmark — ResNet-50 synthetic-ImageNet images/sec/chip.

This is BASELINE.json's metric: "ResNet-50 ImageNet images/sec/chip;
step-time parity vs 8xA100 NCCL". The baseline constant below is the
per-GPU ResNet-50 training throughput of an 8xA100 DGX with NCCL allreduce
and mixed precision (~22k images/sec total => 2770 images/sec/GPU, MLPerf
class numbers); vs_baseline >= 1.0 means step-time parity per chip.

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Robustness contract (round 2): the axon TPU backend is flaky — round 1's
driver capture died with ``UNAVAILABLE: TPU backend setup/compile error``
and a bare ``jax.devices()`` was observed to hang >120 s. A hang inside the
PJRT C API cannot be interrupted from a thread, so the only reliable
watchdog is a child process with a kill timeout. This file is therefore an
orchestrator + worker in one:

  * default (no args): orchestrate.  Up to ``MAX_ATTEMPTS`` rounds of
    [cheap backend probe -> full bench], each in a subprocess with a hard
    timeout, with backoff between failures.  Re-print the worker's JSON
    line on success (rc 0); on final failure print ONE diagnostic JSON
    line and exit 1 fast.
  * ``--probe``: import jax, list devices, print count.  Bounded by the
    parent's timeout.
  * ``--run``: the actual benchmark (round 1's main()).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

A100_IMAGES_PER_SEC_PER_GPU = 2770.0

MAX_ATTEMPTS = int(os.environ.get("BENCH_MAX_ATTEMPTS", "4"))
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT", "150"))
RUN_TIMEOUT_S = float(os.environ.get("BENCH_RUN_TIMEOUT", "1500"))
BACKOFF_S = (15, 30, 60)       # sleep between attempts i and i+1


def probe() -> None:
    """Child-process backend probe: can jax see an accelerator at all?"""
    import jax

    devs = jax.devices()
    print(f"probe-ok {len(devs)} {devs[0].platform}")


# The axon PJRT plugin reaches the TPU through a local relay process that,
# when healthy, holds half a dozen loopback TCP listeners in the 8000-8299
# range (observed 8083/8097/8102/8103/8107/8113 while the round-2/3 captures
# ran; all of them vanish when the tunnel dies — the signature of the round-3
# and round-4 outages).  Parsing /proc/net/tcp for those listeners is a
# sub-second, connection-free way to tell "transport down" apart from
# "backend slow", so a dead tunnel costs ~5 s of the driver's capture budget
# instead of the 705 s that rounds 3-4 burned on four timed-out backend
# probes.  BENCH_FORCE_FULL_PROBE=1 skips the check (e.g. if a future relay
# moves its ports).
RELAY_PORT_RANGE = (8000, 8299)


def relay_listener_ports(
    paths: tuple[str, ...] = ("/proc/net/tcp", "/proc/net/tcp6"),
) -> list[int] | None:
    """Loopback TCP listeners in the relay's port range, from /proc/net/tcp.

    Returns ``None`` when no table could be read at all (foreign netns,
    non-Linux host) — callers must treat that as "unknown", not "down".
    """
    ports: set[int] = set()
    readable = False
    for path in paths:
        try:
            with open(path) as f:
                lines = f.readlines()[1:]
        except OSError:
            continue
        readable = True
        for line in lines:
            fields = line.split()
            if len(fields) < 4 or fields[3] != "0A":  # 0A == TCP_LISTEN
                continue
            addr, _, port_hex = fields[1].partition(":")
            # loopback: 127.0.0.1 little-endian, or ::1 / ::ffff:127.0.0.1
            loopback = addr in (
                "0100007F",
                "00000000000000000000000001000000",
                "0000000000000000FFFF00000100007F",
            )
            if not loopback:
                continue
            port = int(port_hex, 16)
            if RELAY_PORT_RANGE[0] <= port <= RELAY_PORT_RANGE[1]:
                ports.add(port)
    return sorted(ports) if readable else None


def _diagnostic_line(error: str, **extra) -> str:
    """The single failure-JSON shape the driver parses — defined once."""
    return json.dumps({
        "metric": "resnet50_synthetic_imagenet_throughput",
        "value": None,
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "error": error,
        **extra,
    })


def run_bench() -> None:
    import jax

    from benchmarks.common import setup_cache

    # Persistent compilation cache: ResNet-50 cold-compiles very slowly over
    # the axon tunnel; warm runs (including the driver's) reuse the cache.
    setup_cache()
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributed_tensorflow_guide_tpu.core.dist import initialize
    from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec, build_mesh
    from distributed_tensorflow_guide_tpu.models.resnet import ResNet50, make_loss_fn
    from distributed_tensorflow_guide_tpu.parallel.data_parallel import DataParallel
    from distributed_tensorflow_guide_tpu.train.state import TrainStateWithStats

    initialize()
    n_dev = len(jax.devices())
    # 256/chip: measured +8% over 128 (interleaved A/B trials, round 3 —
    # amortizes per-op overheads on the HBM-bound backward; 512 regresses).
    # BENCH_BATCH / BENCH_REMAT are A/B knobs (defaults = judged config);
    # the orchestrator's child processes inherit them from the env.
    per_chip_batch = int(os.environ.get("BENCH_BATCH", "256"))
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    # fused BN+ReLU A/B (round 8): FusedBatchNormAct folds the normalize-
    # activate pair and reduces batch stats over the bf16 activations —
    # attacks the trace-proven backward BN/conv HBM re-reads. Off by
    # default (judged config unchanged); battery row resnet_fused_bn pins
    # it on, echoed in the JSON line like every A/B knob.
    fused_bn = os.environ.get("BENCH_FUSED_BN", "0") == "1"
    # bucketed-backward all-reduce A/B (round 9): per-bucket custom_vjp
    # markers emit each gradient bucket's pmean mid-backward so XLA can
    # overlap it with the remaining backward compute
    # (parallel/overlap.py). Off by default (judged config unchanged —
    # and on ONE chip the data axis has no wire traffic to hide); battery
    # row dp_overlap pins it on, echoed in the JSON like every A/B knob.
    overlap_setting = os.environ.get("BENCH_OVERLAP", "off")
    global_batch = per_chip_batch * n_dev
    image_size = 224

    # BENCH_MODE: "sustained" (default, round-6 record methodology) times a
    # multi-step-dispatch program over PAIRED windows so the fixed
    # drain-refill ramp cancels (benchmarks/common.py time_steps_sustained)
    # — the measured sustained rate the round-5 verdict asked for instead
    # of the marginal-cost inference; "windows" is the round-5 3x120-step
    # median, kept for A/B continuity.
    mode = os.environ.get("BENCH_MODE", "sustained")
    steps_per_call = int(os.environ.get("BENCH_SPC", "8"))

    mesh = build_mesh(MeshSpec(data=-1))
    dp = DataParallel(mesh, overlap=overlap_setting)
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16, remat=remat,
                     fused_bn=fused_bn)

    rng = jax.random.PRNGKey(0)
    variables = model.init(rng, jnp.zeros((1, image_size, image_size, 3)), train=False)
    params = variables["params"]
    model_state = {"batch_stats": variables["batch_stats"]}
    tx = optax.sgd(0.1, momentum=0.9)
    state = dp.replicate(
        TrainStateWithStats.create(
            apply_fn=model.apply, params=params, tx=tx, model_state=model_state
        )
    )

    step = dp.make_train_step_with_stats(
        make_loss_fn(model),
        steps_per_call=steps_per_call if mode == "sustained" else 1,
    )

    # One fixed on-device batch: the bench measures compute+collectives, not
    # host data generation (data/ pipelines are benchmarked separately).
    rng_np = np.random.RandomState(0)
    batch = dp.shard_batch(
        {
            "image": rng_np.randn(global_batch, image_size, image_size, 3).astype(
                np.float32
            ),
            "label": rng_np.randint(0, 1000, global_batch).astype(np.int32),
        }
    )

    # Timing is closed by materializing host values that data-depend on the
    # final step's loss AND updated params (the steps chain through `state`).
    # block_until_ready alone does not reliably fence execution on every PJRT
    # transport (measured: the axon tunnel acks readiness early, inflating
    # throughput ~25x); a scalar fetch cannot complete before the compute it
    # depends on. The shared implementation lives in benchmarks/common.py.
    #
    # THREE independent 120-step windows, median + spread reported. 120
    # steps: each window pays a fixed ~380 ms pipeline-refill ramp after
    # the preceding fence drains the tunnel (measured round 3: marginal
    # step cost 96.5 ms at batch 256 vs 115.6 ms average over a 20-step
    # window; 20→40→60→120-step windows read 2214→2415→2486→2521
    # img/s/chip on identical compute). Long windows amortize the ramp to
    # <4%; mid-stream mark timing would remove it entirely but is
    # untrustworthy on this transport (value reads appear FIFO-serialized
    # behind enqueued work — measured garbage spreads), so the drained
    # window is the conservative, reproducible instrument.
    from benchmarks.common import time_steps, time_steps_sustained

    # BENCH_STEPS/BENCH_TRIALS: smoke/A-B knobs (CPU can't run the judged
    # 3x120 windows); defaults are the judged methodology.
    n_steps = int(os.environ.get("BENCH_STEPS", "120"))
    n_trials = int(os.environ.get("BENCH_TRIALS", "3"))
    trial_tput: list[float] = []
    extras: dict = {}
    # dispatch/host-gap accounting over every timed window: the number that
    # shows what multi-step dispatch amortizes (utils/profiling.py)
    from distributed_tensorflow_guide_tpu.utils.profiling import (
        DispatchStats,
    )

    dstats = DispatchStats()
    if mode == "sustained":
        # windows in DISPATCH units; the long window covers ~n_steps
        # optimizer steps, the short one a quarter of that, so the
        # difference (the measurement) spans >= half the old window budget.
        d_long = max(2, round(n_steps / steps_per_call))
        d_short = max(1, d_long // 4)
        detail = None
        warm = 1  # one multi-step dispatch = steps_per_call warm steps
        for _ in range(n_trials):
            marginal, detail, state = time_steps_sustained(
                step, state, batch, warmup=warm,
                dispatches_short=d_short, dispatches_long=d_long,
                steps_per_call=steps_per_call, stats=dstats)
            warm = 0
            if marginal > 0:
                trial_tput.append(per_chip_batch / marginal)
            else:
                # degenerate on sub-ms CPU smoke steps (noise exceeds the
                # window delta): fall back to the long window's average
                w = detail["window_long"]
                trial_tput.append(
                    per_chip_batch * w["steps"] / w["secs"])
        extras = {"mode": "sustained", "steps_per_call": steps_per_call,
                  **(detail or {})}
    else:
        dt, state = time_steps(step, state, batch, warmup=3, steps=n_steps,
                               stats=dstats)
        trial_tput.append(global_batch * n_steps / dt / n_dev)
        for _ in range(n_trials - 1):
            dt, state = time_steps(step, state, batch, warmup=0,
                                   steps=n_steps, stats=dstats)
            trial_tput.append(global_batch * n_steps / dt / n_dev)
        extras = {"mode": "windows"}
    dstats.steps = dstats.dispatches * (
        steps_per_call if mode == "sustained" else 1)
    extras.update(dstats.as_dict())
    # the same numbers through the unified metrics plane (obs/metrics.py):
    # one namespace for what the ad-hoc dicts carry per-bench
    from distributed_tensorflow_guide_tpu.obs.metrics import (
        Registry,
        absorb_dispatch,
    )

    obs_reg = Registry()
    absorb_dispatch(obs_reg, dstats)
    extras["obs_metrics"] = obs_reg.snapshot()
    trial_tput.sort()
    median = trial_tput[len(trial_tput) // 2]
    spread_pct = 100.0 * (trial_tput[-1] - trial_tput[0]) / median

    # MFU accounting (model-FLOP convention: 3x the traced forward; conv +
    # dot FLOPs from the jaxpr walker — abstract trace, no compile). Per
    # chip: the forward is traced on the per-chip batch.
    from benchmarks.common import mfu_extras, model_flops_per_step

    loss_fn = make_loss_fn(model)
    abstract_batch = {
        "image": jax.ShapeDtypeStruct(
            (per_chip_batch, image_size, image_size, 3), jnp.float32),
        "label": jax.ShapeDtypeStruct((per_chip_batch,), jnp.int32),
    }
    p_abs, ms_abs = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), (params, model_state))
    step_flops = model_flops_per_step(loss_fn, p_abs, ms_abs, abstract_batch)
    # median is img/s/chip; one "step" here = one per-chip batch
    dt_per_step = per_chip_batch / median
    print(
        json.dumps(
            {
                "metric": "resnet50_synthetic_imagenet_throughput",
                "value": round(median, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(median / A100_IMAGES_PER_SEC_PER_GPU, 3),
                "trials": [round(t, 1) for t in trial_tput],
                "spread_pct": round(spread_pct, 1),
                # echo the A/B knobs so an experiment run can never be
                # mistaken for the judged config (256, no remat)
                "per_chip_batch": per_chip_batch,
                "remat": remat,
                "fused_bn": fused_bn,
                "overlap": dp.overlap,
                **extras,
                **mfu_extras(step_flops, 1, dt_per_step, a100_mfu=None),
            }
        )
    )


def _child(arg: str, timeout: float) -> tuple[int | str, str]:
    """Run ``python bench.py <arg>`` in a fresh process with a hard timeout.

    Returns (returncode | "timeout", combined tail of output).  A fresh
    process per attempt matters: a poisoned PJRT client in this process
    would make every retry fail the same way.
    """
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), arg],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            timeout=timeout,
            text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        return proc.returncode, proc.stdout
    except subprocess.TimeoutExpired as e:
        out = e.stdout or b""
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        return "timeout", out


def _extract_json_line(out: str) -> dict | None:
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if {"metric", "value", "unit"} <= d.keys():
                return d
    return None


def orchestrate() -> int:
    t_start = time.time()
    if os.environ.get("BENCH_FORCE_FULL_PROBE") != "1":
        # Retry the snapshot a few times so a relay that is mid-restart at
        # the exact launch instant doesn't cost the whole round's capture.
        # Zero listeners is the ONLY fast-fail trigger: a stray non-relay
        # listener in range merely falls through to the backend probes (the
        # pre-round-5 behavior), which is the safe direction — a false
        # "down" would lose a capture, a false "up" only loses time.
        ports: list[int] | None = None
        for check in range(3):
            if check:
                time.sleep(10)
            ports = relay_listener_ports()
            if ports or ports is None:
                # listeners found, or tables unreadable — the latter is a
                # permanent condition on this host, not worth 20s of sleeps
                break
        if ports == []:
            # Transport provably down (tables readable, zero listeners):
            # fail in seconds, not minutes, in the same diagnostic JSON
            # shape as a timed-out capture.
            print("[bench] relay pre-probe: no loopback listeners in "
                  f"{RELAY_PORT_RANGE[0]}-{RELAY_PORT_RANGE[1]}; transport down",
                  file=sys.stderr)
            print(_diagnostic_line(
                "axon relay not listening (no loopback TCP listeners "
                f"in {RELAY_PORT_RANGE[0]}-{RELAY_PORT_RANGE[1]}, "
                "3 checks over 20s); TPU transport down — diagnosed "
                f"in {time.time() - t_start:.0f}s without burning "
                "the capture budget",
                preprobe={"relay_ports": [], "checked": "/proc/net/tcp[6]"},
            ))
            return 1
        if ports is None:
            # /proc/net/tcp unreadable (foreign netns, non-Linux): unknown,
            # not down — fall through to the backend probes.
            print("[bench] relay pre-probe: /proc/net/tcp unreadable; "
                  "falling through to backend probes", file=sys.stderr)
        else:
            print(f"[bench] relay pre-probe ok: listeners on {ports}",
                  file=sys.stderr)
    failures: list[str] = []
    hangs = 0
    for attempt in range(MAX_ATTEMPTS):
        if attempt:
            time.sleep(BACKOFF_S[min(attempt - 1, len(BACKOFF_S) - 1)])
        rc, out = _child("--probe", PROBE_TIMEOUT_S)
        if rc != 0 or "probe-ok" not in out:
            failures.append(f"attempt {attempt + 1} probe rc={rc}: "
                            + " | ".join(out.strip().splitlines()[-2:]))
            print(f"[bench] probe failed (attempt {attempt + 1}/{MAX_ATTEMPTS},"
                  f" rc={rc}); backing off", file=sys.stderr)
            # Hang-vs-error asymmetry, learned the hard way across rounds
            # 3-5: a probe that ERRORS (plugin raised, relay refused) can be
            # transient and is worth all MAX_ATTEMPTS retries, but a probe
            # that HANGS to its kill timeout means the relay accepted the
            # connection and the backend behind it is wedged — in three
            # observed outages that state never recovered within any retry
            # budget. Two consecutive hangs end the round at ~5 min instead
            # of 12, leaving the driver capture budget for a later flap-back.
            if rc == "timeout":
                hangs += 1
                if hangs >= 2:
                    print(_diagnostic_line(
                        "TPU backend hung (relay listening but probe hit its "
                        f"{PROBE_TIMEOUT_S:.0f}s kill timeout twice in "
                        f"{time.time() - t_start:.0f}s); historically this "
                        "state does not recover within the capture budget",
                        attempts=failures,
                    ))
                    return 1
            else:
                hangs = 0
            continue
        hangs = 0
        rc, out = _child("--run", RUN_TIMEOUT_S)
        result = _extract_json_line(out) if rc == 0 else None
        if result is not None:
            print(json.dumps(result))
            return 0
        failures.append(f"attempt {attempt + 1} run rc={rc}: "
                        + " | ".join(out.strip().splitlines()[-3:]))
        print(f"[bench] run failed (attempt {attempt + 1}/{MAX_ATTEMPTS},"
              f" rc={rc}); backing off", file=sys.stderr)
    # Final failure: one diagnostic JSON line, nonzero exit, no hang.
    print(_diagnostic_line(
        "TPU backend unavailable after "
        f"{MAX_ATTEMPTS} attempts in {time.time() - t_start:.0f}s",
        attempts=failures[-MAX_ATTEMPTS:],
    ))
    return 1


def main() -> int:
    # --fused-bn: argv spelling of BENCH_FUSED_BN=1 so the battery (which
    # passes argv, not env) can pin the A/B row; the orchestrator's child
    # processes inherit it through the environment.
    if "--fused-bn" in sys.argv:
        os.environ["BENCH_FUSED_BN"] = "1"
        sys.argv = [a for a in sys.argv if a != "--fused-bn"]
    # --overlap on|off|auto: argv spelling of BENCH_OVERLAP so the battery
    # can pin the A/B row; inherited by the orchestrator's children via env.
    if "--overlap" in sys.argv:
        i = sys.argv.index("--overlap")
        try:
            setting = sys.argv[i + 1]
        except IndexError:
            sys.exit("--overlap requires a value (on|off|auto)")
        if setting not in ("on", "off", "auto"):
            sys.exit(f"--overlap must be on|off|auto, got {setting!r}")
        os.environ["BENCH_OVERLAP"] = setting
        del sys.argv[i:i + 2]
    if "--probe" in sys.argv:
        probe()
        return 0
    if "--run" in sys.argv:
        run_bench()
        return 0
    return orchestrate()


if __name__ == "__main__":
    sys.exit(main())
