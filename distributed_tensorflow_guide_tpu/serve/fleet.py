"""Fleet tier: global scheduling over a shard of per-replica engines.

A :class:`FleetScheduler` owns what must be GLOBAL for a scaled-out
server — admission (one door, one queue-depth gate), per-tenant
deficit-round-robin and quotas (fair-share holds fleet-wide, not
per-replica), and request->replica routing — while each replica stays a
stock :class:`~.engine.ServeEngine` running the SAME two jitted serve
programs over its own DP×TP mesh.  Replicas are built with identical
geometry, so the fleet compiles nothing the single-engine path didn't:
``build_step_fns`` memoizes on config+geometry, and every golden
fingerprint survives byte-identical with the fleet knob off.

Three placement policies compose here:

* **Disaggregated prefill/decode** (``roles="disagg"``): prefill-role
  replicas run chunked prefill only; the moment a stream turns
  decode-phase its written KV blocks are exported (one fused d2h
  gather), shipped as a migration record, and adopted by a decode-role
  replica's host spill store, where the normal swap-in path resumes it.
  Prefill is compute-bound and decode is bandwidth-bound — splitting
  the roles stops each from starving the other's resource.  The
  transfer is counted (``migration_bytes``/``migration_secs``) so the
  bench can price it against ``device_dcn_peak`` and reconcile with
  ``obs/recon``; the compiled-side model is the
  ``serve_kv_block_transfer_dcn`` program in ``parallel/multislice.py``.
* **Fleet-level prefix routing** (``prefix_routing=True``): a request
  routes to the replica already holding its longest cached prefix
  (probed against each candidate's radix trie) before falling back to
  least-loaded, so prefix locality concentrates instead of diluting
  across the fleet.
* **Elastic capacity** (``world_chaos=``): ``slice_loss`` /
  ``slice_return`` faults drive replica shed/reabsorb through the
  placement tier with :class:`~..train.elastic_world.ElasticSupervisor`
  semantics — a generation counter, a timeline entry per world change,
  and every live stream of a lost replica RE-ANCHORED (the continuation
  transform, KV lost with the replica) onto the fleet queue front.  The
  autoscale signal joins the PR-14 TTFT-EWMA with queue pressure and
  goodput counters.

Guarantees: every stream — routed anywhere, migrated mid-flight, or
re-anchored through a replica loss — is bitwise identical to a one-shot
``make_generate_fn`` run of that request alone (position-derived
sampling keys; KV migration ships the same bytes the source wrote).
Per-tenant counters aggregate across replicas as a DISJOINT sum:
``submitted`` counts once where the stream was first dispatched, the
terminal status once where it ended, and migration bypasses ``submit``
by contract.  Non-guarantees: there is no cross-replica event-log
identity (each replica's flight recorder sees only its own residency),
and migration is re-anchoring, not replay — the target replica's log
starts at the adoption, never a replayed history.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from distributed_tensorflow_guide_tpu.obs import events as obs_events
from distributed_tensorflow_guide_tpu.serve.engine import (
    EngineOverloaded,
    Event,
    Request,
    ServeEngine,
)

__all__ = ["FleetScheduler"]

ROLES = ("colocated", "prefill", "decode")


@dataclasses.dataclass
class _Item:
    """One fleet-queue entry: a fresh request, or a migration record
    (adoption instead of submission) with a request VIEW of the record
    for DRR/quota accounting."""

    req: Request
    record: dict | None = None


class FleetScheduler:
    """Global admission + DRR + routing over N ServeEngine replicas.

    >>> fleet = FleetScheduler(cfg, params, replicas=2, slots=4,
    ...                        num_blocks=33, block_size=8,
    ...                        prefill_chunk=16)
    >>> fleet.submit(Request(rid=0, prompt=toks, max_new_tokens=16,
    ...                      rng=jax.random.PRNGKey(0)))
    >>> fleet.run()
    >>> fleet.completions()[0]
    """

    def __init__(self, cfg, params, *, replicas: int = 2,
                 roles="colocated",
                 slots: int, num_blocks: int, block_size: int,
                 prefill_chunk: int,
                 temperature: float = 0.0, top_k: int | None = None,
                 adapters=None,
                 max_queue: int | None = None,
                 tenant_quotas=None, drr_quantum: int | None = None,
                 prefix_cache: bool = False,
                 prefix_routing: bool | None = None,
                 host_blocks: int = 0,
                 chaos=None, world_chaos=None,
                 burst_factory=None, recorder=None) -> None:
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if roles == "colocated":
            role_list = ["colocated"] * replicas
        elif roles == "disagg":
            if replicas < 2:
                raise ValueError(
                    "disagg needs >= 2 replicas (one per role)")
            # alternate so any fleet width gets both roles; prefill first
            role_list = ["prefill" if i % 2 == 0 else "decode"
                         for i in range(replicas)]
        else:
            role_list = [str(r) for r in roles]
            if len(role_list) != replicas:
                raise ValueError(
                    f"roles length {len(role_list)} != replicas "
                    f"{replicas}")
            for r in role_list:
                if r not in ROLES:
                    raise ValueError(f"unknown role {r!r}")
        if ("decode" in role_list) != ("prefill" in role_list):
            raise ValueError(
                "prefill and decode roles come as a pair — a role split "
                "with only one side cannot serve")
        self.roles = role_list
        self.disagg = "prefill" in role_list
        self.prefix_routing = (prefix_cache if prefix_routing is None
                               else bool(prefix_routing))
        if self.prefix_routing and not prefix_cache:
            raise ValueError(
                "prefix_routing needs prefix_cache=True (the per-replica "
                "tries are what routing probes)")
        chaos_list = (chaos if isinstance(chaos, (list, tuple))
                      else [chaos] * replicas)
        if len(chaos_list) != replicas:
            raise ValueError(
                f"chaos list length {len(chaos_list)} != replicas "
                f"{replicas}")
        self.rec = (recorder if recorder is not None
                    else obs_events.current())
        self.engines: list[ServeEngine] = []
        for i, role in enumerate(role_list):
            # adoptable replicas get a host-store landing pad at least
            # one full pool deep: migrated KV blocks arrive THERE and
            # resume by the normal swap-in path.  Replica-level quotas
            # and queue gates are OFF — fair-share and the door gate are
            # fleet-global by design.
            hb = host_blocks
            if self.disagg and role != "prefill":
                hb = max(host_blocks, num_blocks)
            self.engines.append(ServeEngine(
                cfg, params, slots=slots, num_blocks=num_blocks,
                block_size=block_size, prefill_chunk=prefill_chunk,
                temperature=temperature, top_k=top_k,
                adapters=adapters,
                max_queue=None, chaos=chaos_list[i],
                burst_factory=burst_factory,
                prefix_cache=prefix_cache, host_blocks=hb,
                tenant_quotas=None, drr_quantum=None,
                recorder=recorder))
        self.num_slots = slots
        self.block_size = block_size
        self.max_queue = max_queue
        self.tenant_quotas = {int(t): dict(q) for t, q in
                              (tenant_quotas or {}).items()}
        sched0 = self.engines[0].sched
        self.drr_quantum = (sched0.blocks_per_seq if drr_quantum is None
                            else int(drr_quantum))
        if self.drr_quantum < 1:
            raise ValueError(
                f"drr_quantum must be >= 1, got {self.drr_quantum}")
        self._deficit: dict[int, int] = {}
        self.queue: list[_Item] = []
        self.world = world_chaos
        self._live: set[int] = set(range(replicas))
        self._tick = 0
        # fleet counters (the bench's DCN reconciliation inputs live
        # here; serve/ never imports benchmarks/)
        self.shed = 0
        self.migrations = 0
        self.migration_bytes = 0
        self.migration_secs = 0.0
        self.migrated_rids: list[int] = []
        self.prefix_route_hits = 0
        self.prefix_route_hit_tokens = 0
        self.generation = 0
        self.replicas_shed = 0
        self.replicas_regrown = 0
        self.timeline: list[dict] = []
        self._fleet_tenants: dict[int, dict[str, int]] = {}
        # autoscale_policy hysteresis state: the direction the signal
        # has been leaning and for how many consecutive evaluations
        self._scale_direction = 0
        self._scale_streak = 0

    # ---- intake ----------------------------------------------------------

    def _ft(self, tenant: int) -> dict[str, int]:
        return self._fleet_tenants.setdefault(int(tenant), {"shed": 0})

    def submit(self, req: Request) -> None:
        """The fleet door: cheap validation plus the GLOBAL queue-depth
        gate (replicas run ungated).  Nothing is recorded for a shed
        request — :class:`EngineOverloaded` stays retriable."""
        cfg = self.engines[0].fns.cfg
        sched0 = self.engines[0].sched
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if int(prompt.max()) >= cfg.vocab_size:
            raise ValueError("prompt token out of vocabulary")
        if req.tenant < 0:
            raise ValueError(f"tenant must be >= 0, got {req.tenant}")
        if prompt.size + req.max_new_tokens > cfg.max_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new {req.max_new_tokens} "
                f"exceeds max_len {cfg.max_len}")
        need = sched0.max_request_blocks(prompt.size, req.max_new_tokens)
        if need > sched0.pool.capacity:
            raise ValueError(
                f"request {req.rid} can never fit: needs {need} blocks, "
                f"pool capacity {sched0.pool.capacity}")
        quota = self.tenant_quotas.get(int(req.tenant), {})
        if quota.get("blocks") is not None and need > quota["blocks"]:
            raise ValueError(
                f"request {req.rid} can never fit tenant {req.tenant}'s "
                f"block quota: needs {need}, quota {quota['blocks']}")
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            self.shed += 1
            self._ft(req.tenant)["shed"] += 1
            if self.rec.enabled:
                self.rec.emit(
                    "req.shed", cat="serve", actor="fleet",
                    payload={"rid": req.rid, "reason": "queue_depth",
                             "tenant": int(req.tenant),
                             "queue_depth": len(self.queue)},
                    t=float(req.arrival))
            raise EngineOverloaded(
                f"request {req.rid} shed: fleet queue depth "
                f"{len(self.queue)} at the max_queue={self.max_queue} "
                "gate — retry later")
        self.queue.append(_Item(req=dataclasses.replace(
            req, prompt=prompt, rng=np.asarray(req.rng, np.uint32))))

    def cancel(self, rid: int) -> bool:
        """Client abandon, fleet-wide: drop a fleet-queued item outright,
        or forward to whichever replica holds the stream."""
        for j, item in enumerate(self.queue):
            if item.req.rid == rid:
                self.queue.pop(j)
                return True
        return any(self.engines[i].cancel(rid)
                   for i in sorted(self._live))

    # ---- global DRR dispatch ---------------------------------------------

    def _tenant_heads(self) -> list[tuple[_Item, int]]:
        heads: list[tuple[_Item, int]] = []
        seen: set[int] = set()
        for item in self.queue:
            t = int(item.req.tenant)
            if t not in seen:
                seen.add(t)
                heads.append((item, t))
        return heads

    def _load(self, i: int) -> int:
        sd = self.engines[i].sched
        return sum(s is not None for s in sd.slots) + len(sd.queue)

    def _store_room(self, i: int) -> int:
        st = self.engines[i].store
        if st is None:
            return 0
        if st.capacity is None:
            return 1 << 30
        return st.capacity - st.live_blocks()

    def _quota_allows(self, tenant: int, req: Request) -> bool:
        """Fleet-wide committed usage: worst-case footprints of the
        tenant's residents AND replica-queued requests across every live
        replica — dispatch is the commitment point, so the global quota
        can never be overrun by replicas admitting independently."""
        quota = self.tenant_quotas.get(int(tenant))
        if not quota:
            return True
        slots_used = 0
        committed = 0
        for i in sorted(self._live):
            sd = self.engines[i].sched
            for s in sd.slots:
                if s is not None and s.tenant == tenant:
                    slots_used += 1
                    committed += s.max_blocks
            for r in sd.queue:
                if int(r.tenant) == tenant:
                    slots_used += 1
                    committed += sd.max_request_blocks(
                        len(r.prompt), r.max_new_tokens)
        if (quota.get("slots") is not None
                and slots_used >= quota["slots"]):
            return False
        if quota.get("blocks") is not None:
            cost = self.engines[0].sched.max_request_blocks(
                len(req.prompt), req.max_new_tokens)
            if committed + cost > quota["blocks"]:
                return False
        return True

    def _route(self, item: _Item) -> int | None:
        """The routing policy, in preference order: (1) a KV-carrying
        migration record goes to the least-loaded adoptable replica with
        store room; (2) a re-prefill item probes the prefix tries and
        goes to the longest cached prefix when routing is on; (3)
        least-loaded wins, lowest index breaking ties.  Only replicas
        with a free-ish slot budget (load < slots) are candidates — the
        fleet queue, not replica queues, is where work waits, which is
        what keeps the global DRR in charge."""
        rec = item.record
        payloads = (rec or {}).get("payloads") or []
        if payloads:
            cands = [i for i in sorted(self._live)
                     if self.roles[i] != "prefill"
                     and self.engines[i].store is not None
                     and self._store_room(i) >= len(payloads)
                     and self._load(i) < self.engines[i].num_slots]
            if not cands:
                return None
            return min(cands, key=lambda i: (self._load(i), i))
        if self.disagg:
            cands = [i for i in sorted(self._live)
                     if self.roles[i] == "prefill"]
            if not cands:  # every prefill replica shed: degrade, not die
                cands = sorted(self._live)
        else:
            cands = sorted(self._live)
        cands = [i for i in cands
                 if self._load(i) < self.engines[i].num_slots]
        if not cands:
            return None
        if self.prefix_routing:
            best, hit = None, 0
            for i in cands:
                sd = self.engines[i].sched
                if sd.prefix is None:
                    continue
                n = len(sd.prefix.match_nodes(
                    item.req.prompt, adapter=int(item.req.adapter)))
                if n > hit:
                    best, hit = i, n
            if best is not None and hit > 0:
                self.prefix_route_hits += 1
                self.prefix_route_hit_tokens += hit * self.block_size
                if self.rec.enabled:
                    self.rec.emit(
                        "fleet.prefix_route", cat="serve", actor="fleet",
                        payload={"rid": item.req.rid, "replica": best,
                                 "hit_tokens": hit * self.block_size})
                return best
        return min(cands, key=lambda i: (self._load(i), i))

    def _dispatch(self, now: float) -> int:
        """Global deficit-round-robin over per-tenant fleet-queue heads —
        the same loop shape as :meth:`Scheduler.admit`, with "a replica
        accepted it" in place of "blocks were found".  Migration records
        dispatch through ``adopt_stream`` (never re-counting
        ``submitted``); fresh requests through the replica's ``submit``,
        whose predicted-TTFT gate may still shed (counted there, exactly
        as a single engine would have)."""
        sched0 = self.engines[0].sched
        dispatched = 0
        while self.queue:
            progressed = False
            deficit_waiting = False
            for item, tenant in self._tenant_heads():
                if item.req.arrival > now:
                    continue
                if not self._quota_allows(tenant, item.req):
                    continue
                cost = sched0.max_request_blocks(
                    len(item.req.prompt), item.req.max_new_tokens)
                self._deficit[tenant] = (self._deficit.get(tenant, 0)
                                         + self.drr_quantum)
                if self._deficit[tenant] < cost:
                    deficit_waiting = True
                    continue
                target = self._route(item)
                if target is None:
                    continue
                self.queue.pop(next(
                    j for j, it in enumerate(self.queue) if it is item))
                eng = self.engines[target]
                if item.record is not None:
                    eng.adopt_stream(item.record)
                else:
                    try:
                        eng.submit(item.req)
                    except EngineOverloaded:
                        pass  # TTFT-gate shed, counted by the replica
                self._deficit[tenant] -= cost
                dispatched += 1
                progressed = True
            if not progressed and not deficit_waiting:
                break
        queued = {int(it.req.tenant) for it in self.queue}
        for t in [t for t in self._deficit if t not in queued]:
            del self._deficit[t]
        return dispatched

    # ---- disaggregation: prefill -> decode migration ---------------------

    def _migrate_prefilled(self, now: float) -> int:
        """Ship every stream that just turned decode-phase on a
        prefill-role replica to a decode-role replica: fused d2h export
        of its written KV blocks, re-anchored through the fleet queue
        FRONT (adopted next tick by the normal swap-in path).  When no
        decode replica has store room the stream simply keeps decoding
        where it is — degraded placement, never a dropped stream."""
        moved = 0
        for i in sorted(self._live):
            if self.roles[i] != "prefill":
                continue
            eng = self.engines[i]
            ready = sorted(
                (s for s in eng.sched.slots
                 if s is not None and s.phase == "decode"
                 and s.written >= 1 and s.budget > 0),
                key=lambda s: s.admitted_seq)
            for s in ready:
                n_blocks = len(eng.sched.migratable_blocks(s.rid))
                if not n_blocks:
                    continue
                has_target = any(
                    self.roles[j] != "prefill"
                    and self.engines[j].store is not None
                    and self._store_room(j) >= n_blocks
                    for j in self._live if j != i)
                if not has_target:
                    continue
                t0 = time.perf_counter()
                record = eng.export_stream(s.rid, with_kv=True)
                self.migration_secs += time.perf_counter() - t0
                self.migrations += 1
                self.migration_bytes += int(record["payload_bytes"])
                self.migrated_rids.append(int(record["rid"]))
                self.queue.insert(
                    0, _Item(req=self._record_req(record), record=record))
                moved += 1
                if self.rec.enabled:
                    self.rec.emit(
                        "fleet.migrate", cat="serve", actor="fleet",
                        payload={"rid": int(record["rid"]),
                                 "from": i, "blocks": n_blocks,
                                 "bytes": int(record["payload_bytes"])},
                        t=now)
        return moved

    @staticmethod
    def _record_req(record: dict) -> Request:
        return Request(
            rid=int(record["rid"]),
            prompt=np.asarray(record["prompt"], np.int32),
            max_new_tokens=int(record["budget"]),
            rng=np.asarray(record["rng"], np.uint32),
            arrival=float(record.get("arrival", float("-inf"))),
            tenant=int(record.get("tenant", 0)),
            adapter=int(record.get("adapter", 0)))

    # ---- elastic capacity: replica shed / reabsorb -----------------------

    def _apply_world(self, tick: int, now: float) -> None:
        if self.world is None:
            return
        due = [f for f in self.world.world_events() if f.position <= tick]
        for f in due:
            self.world.fire(f)
            idx = f.slice_id % len(self.engines)
            if f.kind == "slice_loss":
                if idx in self._live and len(self._live) > 1:
                    self._shed_replica(idx)
                    self.replicas_shed += 1
            elif f.kind == "slice_return":
                if idx not in self._live:
                    self._live.add(idx)
                    self.replicas_regrown += 1
            self.generation += 1
            self.timeline.append({
                "generation": self.generation, "tick": tick,
                "kind": f.kind, "replica": idx,
                "live": sorted(self._live),
                "signal": self.autoscale_signal()})
            if self.rec.enabled:
                self.rec.emit(
                    "fleet.world", cat="serve", actor="fleet",
                    payload={"kind": f.kind, "replica": idx,
                             "generation": self.generation,
                             "live": sorted(self._live)},
                    t=now)

    def _shed_replica(self, idx: int) -> None:
        """A lost replica's live streams re-anchor on the fleet queue
        FRONT in admission-then-queue order (the ``snapshot_state``
        convention): the continuation transform with the KV lost along
        with the replica, so each re-prefills elsewhere and continues
        bitwise.  The engine OBJECT is retained for accounting —
        completed streams and tenant counters persist supervisor-side,
        exactly like a training generation's report outliving its
        processes — and comes back cold (trie and spill store dropped)
        if a ``slice_return`` reabsorbs it."""
        eng = self.engines[idx]
        sd = eng.sched
        live = sorted((s for s in sd.slots if s is not None),
                      key=lambda s: s.admitted_seq)
        rids = [s.rid for s in live] + [r.rid for r in sd.queue]
        items = []
        for rid in rids:
            record = eng.export_stream(rid, with_kv=False)
            items.append(_Item(req=self._record_req(record),
                               record=record))
        self.queue[:0] = items
        sd.release_prefix_cache()
        if eng.store is not None:
            sd.release_spill_store()
        self._live.discard(idx)

    def autoscale_signal(self) -> dict:
        """What an autoscaler would act on: global queue pressure
        against live capacity, the worst live replica's TTFT-EWMA (the
        PR-14 shed-gate statistic), and cumulative goodput tokens."""
        live = sorted(self._live)
        queued = len(self.queue) + sum(
            len(self.engines[i].sched.queue) for i in live)
        capacity = max(1, len(live) * self.num_slots)
        ewmas = [self.engines[i]._ttft_ewma for i in live
                 if self.engines[i]._ttft_ewma is not None]
        goodput = sum(c["tokens"]
                      for eng in self.engines
                      for c in eng.sched.tenants.values())
        pressure = queued / capacity
        return {
            "queued": queued,
            "live_replicas": len(live),
            "total_replicas": len(self.engines),
            "pressure": pressure,
            "ttft_ewma_s": max(ewmas) if ewmas else None,
            "goodput_tokens": goodput,
            "want_more_replicas": bool(
                pressure > 1.0 or len(live) < len(self.engines)),
        }

    def autoscale_policy(self, *, min_replicas: int = 1,
                         max_replicas: int | None = None,
                         up_pressure: float = 1.0,
                         down_pressure: float = 0.25,
                         hysteresis: int = 3) -> dict:
        """:meth:`autoscale_signal` -> a target-replica-count
        RECOMMENDATION.  Advisory only: the supervisor never acts on it
        (shed/regrow stay world-chaos-driven); an external operator is
        the intended consumer.

        Hysteresis: the signal must lean the same direction for
        ``hysteresis`` consecutive evaluations before the target moves
        off the current live count, and then it moves by ONE replica —
        a flapping queue cannot saw the fleet.  Scale-down additionally
        requires an empty queue (draining capacity under backlog is
        never recommended).  The target is clamped to
        ``[min_replicas, max_replicas]`` (default max: the fleet's
        provisioned width)."""
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas}")
        cap = (len(self.engines) if max_replicas is None
               else int(max_replicas))
        if cap < min_replicas:
            raise ValueError(
                f"max_replicas {cap} < min_replicas {min_replicas}")
        sig = self.autoscale_signal()
        live = sig["live_replicas"]
        if sig["pressure"] > up_pressure:
            direction = 1
        elif sig["pressure"] < down_pressure and sig["queued"] == 0:
            direction = -1
        else:
            direction = 0
        if direction != 0 and direction == self._scale_direction:
            self._scale_streak += 1
        else:
            self._scale_direction = direction
            self._scale_streak = 1 if direction else 0
        target = live
        if direction and self._scale_streak >= hysteresis:
            target = live + direction
        target = max(min_replicas, min(cap, target))
        return {
            "target_replicas": target,
            "live_replicas": live,
            "direction": direction,
            "streak": self._scale_streak,
            "hysteresis": hysteresis,
            "min_replicas": min_replicas,
            "max_replicas": cap,
            "signal": sig,
        }

    # ---- the fleet tick --------------------------------------------------

    def step(self, now: float = 0.0) -> tuple[list[Event], str]:
        """One fleet tick: apply due world faults, run the global DRR
        dispatch, step every live replica once, then migrate any
        freshly-prefilled streams off prefill-role replicas.  Returns
        (events, kind) with kind in {"busy", "idle"} — replica ticks,
        dispatches and migrations all count as progress."""
        tick = self._tick
        self._tick += 1
        self._apply_world(tick, now)
        dispatched = self._dispatch(now)
        events: list[Event] = []
        busy = dispatched > 0
        # per-replica wall seconds of THIS tick: replicas are independent
        # machines, so a virtual-clock driver should charge the slowest
        # replica (plus the supervisor's own overhead), not the sum the
        # in-process serial loop happens to pay
        self.step_secs: dict[int, float] = {}
        for i in sorted(self._live):
            t0 = time.perf_counter()
            evs, kind = self.engines[i].step(now)
            self.step_secs[i] = time.perf_counter() - t0
            events.extend(evs)
            busy = busy or kind != "idle"
        if self.disagg:
            busy = bool(self._migrate_prefilled(now)) or busy
        return events, ("busy" if busy else "idle")

    def next_arrival(self) -> float | None:
        """Earliest future arrival anywhere in the fleet — the virtual
        clock's fast-forward target when a tick comes back idle.
        Re-anchored migration records (arrival ``-inf``) never gate."""
        cands = [it.req.arrival for it in self.queue
                 if it.req.arrival != float("-inf")]
        for i in sorted(self._live):
            nxt = self.engines[i].sched.next_arrival()
            if nxt is not None:
                cands.append(nxt)
        return min(cands) if cands else None

    def _has_work(self) -> bool:
        return bool(self.queue) or any(
            self.engines[i].sched.has_queued
            or self.engines[i].sched.has_resident
            for i in sorted(self._live))

    def run(self, max_ticks: int | None = None) -> list[Event]:
        """Drain all submitted work on the tick clock.  Idle ticks are
        tolerated in bounded runs of them (chaos pressure holds and
        pending world returns resolve by tick), then declared a
        deadlock."""
        events: list[Event] = []
        ticks = 0
        stalled = 0
        while self._has_work():
            evs, kind = self.step(now=float("inf"))
            events.extend(evs)
            stalled = 0 if kind != "idle" else stalled + 1
            if stalled > 64:
                raise RuntimeError(
                    "fleet deadlock: work queued but no replica "
                    "progressing")
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
        for i in sorted(self._live):
            self.engines[i]._release_pressure(float("inf"))
        return events

    # ---- introspection ---------------------------------------------------

    def completions(self) -> dict[int, list[int]]:
        """rid -> emitted tokens, merged across replicas.  Disjoint by
        construction: a stream's emitted list TRAVELS with it (popped at
        detach, installed at attach), so a rid appearing on two replicas
        is a conservation bug worth crashing on."""
        out: dict[int, list[int]] = {}
        for eng in self.engines:
            for rid, toks in eng.completions().items():
                if rid in out:
                    raise AssertionError(
                        f"rid {rid} emitted on two replicas — the "
                        "migration seam double-counted a stream")
                out[rid] = toks
        return out

    def health(self) -> dict:
        """Fleet health: per-replica engine healths plus the GLOBAL
        view — element-wise per-tenant aggregation across every replica
        (migration makes this a disjoint sum: submitted once at the
        dispatch replica, terminal status once where the stream ended)
        merged with fleet-door sheds, and the fleet counters."""
        tenants: dict[int, dict[str, int]] = {}
        for eng in self.engines:
            for t, c in eng.sched.tenants.items():
                agg = tenants.setdefault(int(t), {})
                for k, v in c.items():
                    agg[k] = agg.get(k, 0) + int(v)
        for t, c in self._fleet_tenants.items():
            agg = tenants.setdefault(int(t), {})
            for k, v in c.items():
                agg[k] = agg.get(k, 0) + int(v)
        replicas = []
        for i, eng in enumerate(self.engines):
            h = eng.health()
            h["role"] = self.roles[i]
            h["live"] = i in self._live
            replicas.append(h)
        return {
            "replicas": replicas,
            "tenants": {t: dict(c) for t, c in sorted(tenants.items())},
            "queued": len(self.queue),
            "shed": self.shed + sum(h["shed"] for h in replicas),
            "live_replicas": len(self._live),
            "generation": self.generation,
            "replicas_shed": self.replicas_shed,
            "replicas_regrown": self.replicas_regrown,
            "migrations": self.migrations,
            "migration_bytes": self.migration_bytes,
            "migration_secs": self.migration_secs,
            "prefix_route_hits": self.prefix_route_hits,
            "prefix_route_hit_tokens": self.prefix_route_hit_tokens,
            "completed": sum(h["completed"] for h in replicas),
            "autoscale": self.autoscale_policy(),
        }

    def check_leaks(self) -> None:
        """Joint ledger audit across every replica's pool AND host
        store — shed replicas included (they must have released
        everything on the way out)."""
        for eng in self.engines:
            eng.sched.check_leaks()

    def close(self) -> None:
        for eng in self.engines:
            eng.close()
