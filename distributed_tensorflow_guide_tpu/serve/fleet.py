"""Fleet tier: global scheduling over a shard of per-replica engines.

A :class:`FleetScheduler` owns what must be GLOBAL for a scaled-out
server — admission (one door, one queue-depth gate), per-tenant
deficit-round-robin and quotas (fair-share holds fleet-wide, not
per-replica), and request->replica routing — while each replica stays a
stock :class:`~.engine.ServeEngine` running the SAME two jitted serve
programs over its own DP×TP mesh.  Replicas are built with identical
geometry, so the fleet compiles nothing the single-engine path didn't:
``build_step_fns`` memoizes on config+geometry, and every golden
fingerprint survives byte-identical with the fleet knob off.

Three placement policies compose here:

* **Disaggregated prefill/decode** (``roles="disagg"``): prefill-role
  replicas run chunked prefill only; the moment a stream turns
  decode-phase its written KV blocks are exported (one fused d2h
  gather), shipped as a migration record, and adopted by a decode-role
  replica's host spill store, where the normal swap-in path resumes it.
  Prefill is compute-bound and decode is bandwidth-bound — splitting
  the roles stops each from starving the other's resource.  The
  transfer is counted (``migration_bytes``/``migration_secs``) so the
  bench can price it against ``device_dcn_peak`` and reconcile with
  ``obs/recon``; the compiled-side model is the
  ``serve_kv_block_transfer_dcn`` program in ``parallel/multislice.py``.
* **Fleet-level prefix routing** (``prefix_routing=True``): a request
  routes to the replica already holding its longest cached prefix
  (probed against each candidate's radix trie) before falling back to
  least-loaded, so prefix locality concentrates instead of diluting
  across the fleet.
* **Elastic capacity** (``world_chaos=``): ``slice_loss`` /
  ``slice_return`` faults drive replica shed/reabsorb through the
  placement tier with :class:`~..train.elastic_world.ElasticSupervisor`
  semantics — a generation counter, a timeline entry per world change,
  and every live stream of a lost replica RE-ANCHORED (the continuation
  transform, KV lost with the replica) onto the fleet queue front.  The
  autoscale signal joins the PR-14 TTFT-EWMA with queue pressure and
  goodput counters; ``apply_autoscale=True`` closes the loop (add a
  provisioned cold replica / retire one by graceful drain).

Crash consistency (PR 20): the fleet keeps its own ADMISSION LEDGER —
each stream's continuation basis recorded at dispatch, its emitted tail
folded in from the event stream — so a replica HARD CRASH
(``replica_crash`` chaos: no orderly ``detach_stream``, the engine
object and its KV gone) rebuilds every resident from supervisor-side
state alone and re-anchors it queue-front.  A per-replica CIRCUIT
BREAKER trips on consecutive step failures (ejection → bounded backoff
→ half-open probe → recovery), stalled replicas (``replica_stall``: the
watchdog's tick-deadline verdict) sit out a recovery window, and
neither receives new work while excluded.  Handoff records carry a
unique adoption id: a torn migration (``migration_torn`` duplicates the
record in flight) is adopted exactly once.  ``save_snapshot`` /
``restore_latest_snapshot`` persist the WHOLE fleet — global queue,
deficits, tenant counters, ledger, breaker/drain state, and every
replica's engine snapshot — through the PR-5 manifested/CRC ladder.

Guarantees: every stream — routed anywhere, migrated mid-flight,
re-anchored through a replica loss, hard crash, stall, ejection or
drain, or restored from a fleet snapshot — is bitwise identical to a
one-shot ``make_generate_fn`` run of that request alone
(position-derived sampling keys; KV migration ships the same bytes the
source wrote).  Per-tenant counters aggregate across replicas as a
DISJOINT sum: ``submitted`` counts once where the stream was first
dispatched, the terminal status once where it ended, migration bypasses
``submit`` by contract, and a crashed engine's terminal accounting
survives in the fleet graveyard.  Non-guarantees: there is no
cross-replica event-log identity (each replica's flight recorder sees
only its own residency); hard-crash recovery LOSES the replica's KV —
it is re-anchoring (re-prefill from the recorded position), never
replay; the breaker's granularity is the step boundary (a fault is
detected when the tick that hit it returns, not mid-kernel); autoscale
apply is drain-based and never drops a stream, so scale-down completes
only after residents migrate or finish.
"""

from __future__ import annotations

import dataclasses
import json
import time

import numpy as np

from distributed_tensorflow_guide_tpu.obs import events as obs_events
from distributed_tensorflow_guide_tpu.serve.engine import (
    EngineOverloaded,
    Event,
    Request,
    ServeEngine,
)
from distributed_tensorflow_guide_tpu.serve.scheduler import Scheduler

__all__ = ["FleetScheduler"]

ROLES = ("colocated", "prefill", "decode")


@dataclasses.dataclass
class _Item:
    """One fleet-queue entry: a fresh request, or a migration record
    (adoption instead of submission) with a request VIEW of the record
    for DRR/quota accounting."""

    req: Request
    record: dict | None = None


class FleetScheduler:
    """Global admission + DRR + routing over N ServeEngine replicas.

    >>> fleet = FleetScheduler(cfg, params, replicas=2, slots=4,
    ...                        num_blocks=33, block_size=8,
    ...                        prefill_chunk=16)
    >>> fleet.submit(Request(rid=0, prompt=toks, max_new_tokens=16,
    ...                      rng=jax.random.PRNGKey(0)))
    >>> fleet.run()
    >>> fleet.completions()[0]
    """

    def __init__(self, cfg, params, *, replicas: int = 2,
                 roles="colocated",
                 slots: int, num_blocks: int, block_size: int,
                 prefill_chunk: int,
                 temperature: float = 0.0, top_k: int | None = None,
                 adapters=None,
                 max_queue: int | None = None,
                 tenant_quotas=None, drr_quantum: int | None = None,
                 prefix_cache: bool = False,
                 prefix_routing: bool | None = None,
                 host_blocks: int = 0,
                 chaos=None, world_chaos=None, fleet_chaos=None,
                 breaker_threshold: int = 3,
                 breaker_backoff_ticks: int = 4,
                 breaker_max_backoff_ticks: int = 32,
                 stall_recovery_ticks: int = 3,
                 apply_autoscale: bool = False,
                 autoscale_params: dict | None = None,
                 autoscale_every: int = 4,
                 snapshot_dir=None, snapshot_keep: int = 3,
                 burst_factory=None, recorder=None) -> None:
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if breaker_threshold < 1:
            raise ValueError(
                f"breaker_threshold must be >= 1, got {breaker_threshold}")
        if breaker_backoff_ticks < 1:
            raise ValueError(
                f"breaker_backoff_ticks must be >= 1, got "
                f"{breaker_backoff_ticks}")
        if breaker_max_backoff_ticks < breaker_backoff_ticks:
            raise ValueError(
                f"breaker_max_backoff_ticks {breaker_max_backoff_ticks} "
                f"< breaker_backoff_ticks {breaker_backoff_ticks}")
        if stall_recovery_ticks < 1:
            raise ValueError(
                f"stall_recovery_ticks must be >= 1, got "
                f"{stall_recovery_ticks}")
        if autoscale_every < 1:
            raise ValueError(
                f"autoscale_every must be >= 1, got {autoscale_every}")
        if roles == "colocated":
            role_list = ["colocated"] * replicas
        elif roles == "disagg":
            if replicas < 2:
                raise ValueError(
                    "disagg needs >= 2 replicas (one per role)")
            # alternate so any fleet width gets both roles; prefill first
            role_list = ["prefill" if i % 2 == 0 else "decode"
                         for i in range(replicas)]
        else:
            role_list = [str(r) for r in roles]
            if len(role_list) != replicas:
                raise ValueError(
                    f"roles length {len(role_list)} != replicas "
                    f"{replicas}")
            for r in role_list:
                if r not in ROLES:
                    raise ValueError(f"unknown role {r!r}")
        if ("decode" in role_list) != ("prefill" in role_list):
            raise ValueError(
                "prefill and decode roles come as a pair — a role split "
                "with only one side cannot serve")
        self.roles = role_list
        self.disagg = "prefill" in role_list
        self.prefix_routing = (prefix_cache if prefix_routing is None
                               else bool(prefix_routing))
        if self.prefix_routing and not prefix_cache:
            raise ValueError(
                "prefix_routing needs prefix_cache=True (the per-replica "
                "tries are what routing probes)")
        chaos_list = (chaos if isinstance(chaos, (list, tuple))
                      else [chaos] * replicas)
        if len(chaos_list) != replicas:
            raise ValueError(
                f"chaos list length {len(chaos_list)} != replicas "
                f"{replicas}")
        self.rec = (recorder if recorder is not None
                    else obs_events.current())
        # params may be one tree shared by every replica, or a
        # per-replica list — each replica anchored on its own DP×TP mesh
        # (device_put with per-mesh shardings); the step programs are
        # the same memoized objects either way
        params_list = (list(params) if isinstance(params, (list, tuple))
                       else [params] * replicas)
        if len(params_list) != replicas:
            raise ValueError(
                f"params list length {len(params_list)} != replicas "
                f"{replicas}")
        self._cfg = cfg
        self._params = params_list
        self.engines: list[ServeEngine] = []
        self._engine_kw: list[dict] = []
        for i, role in enumerate(role_list):
            # adoptable replicas get a host-store landing pad at least
            # one full pool deep: migrated KV blocks arrive THERE and
            # resume by the normal swap-in path.  Replica-level quotas
            # and queue gates are OFF — fair-share and the door gate are
            # fleet-global by design.
            hb = host_blocks
            if self.disagg and role != "prefill":
                hb = max(host_blocks, num_blocks)
            kw = dict(slots=slots, num_blocks=num_blocks,
                      block_size=block_size, prefill_chunk=prefill_chunk,
                      temperature=temperature, top_k=top_k,
                      adapters=adapters,
                      max_queue=None, chaos=chaos_list[i],
                      burst_factory=burst_factory,
                      prefix_cache=prefix_cache, host_blocks=hb,
                      tenant_quotas=None, drr_quantum=None,
                      recorder=recorder)
            self._engine_kw.append(kw)
            self.engines.append(ServeEngine(cfg, params_list[i], **kw))
        self.num_slots = slots
        self.block_size = block_size
        self.max_queue = max_queue
        self.tenant_quotas = {int(t): dict(q) for t, q in
                              (tenant_quotas or {}).items()}
        sched0 = self.engines[0].sched
        self.drr_quantum = (sched0.blocks_per_seq if drr_quantum is None
                            else int(drr_quantum))
        if self.drr_quantum < 1:
            raise ValueError(
                f"drr_quantum must be >= 1, got {self.drr_quantum}")
        self._deficit: dict[int, int] = {}
        self.queue: list[_Item] = []
        self.world = world_chaos
        self._live: set[int] = set(range(replicas))
        self._tick = 0
        # fleet counters (the bench's DCN reconciliation inputs live
        # here; serve/ never imports benchmarks/)
        self.shed = 0
        self.migrations = 0
        self.migration_bytes = 0
        self.migration_secs = 0.0
        self.migrated_rids: list[int] = []
        self.prefix_route_hits = 0
        self.prefix_route_hit_tokens = 0
        self.generation = 0
        self.replicas_shed = 0
        self.replicas_regrown = 0
        self.timeline: list[dict] = []
        self._fleet_tenants: dict[int, dict[str, int]] = {}
        # autoscale_policy hysteresis state: the direction the signal
        # has been leaning and for how many consecutive evaluations
        self._scale_direction = 0
        self._scale_streak = 0
        # ---- crash consistency + self-healing (PR 20) -------------------
        self.fleet_chaos = fleet_chaos
        self.breaker_threshold = breaker_threshold
        self.breaker_backoff_ticks = breaker_backoff_ticks
        self.breaker_max_backoff_ticks = breaker_max_backoff_ticks
        self.stall_recovery_ticks = stall_recovery_ticks
        self.apply_autoscale = apply_autoscale
        self.autoscale_params = dict(autoscale_params or {})
        self.autoscale_every = autoscale_every
        # the fleet ADMISSION LEDGER: everything the supervisor needs to
        # reconstruct a replica's residents after a hard crash, recorded
        # at dispatch (identity) and from the event stream (tokens) —
        # never read back from a dead engine
        self._ledger: dict[int, dict] = {}
        self._ledger_seq = 0
        # exactly-once migration adoption: (rid, handoff id) pairs
        # already adopted; a torn handoff's duplicate record carries the
        # SAME handoff id and is dropped idempotently at dispatch
        self._adopted: set[tuple[int, int]] = set()
        self._handoff_seq = 0
        self._torn_pending = 0  # armed migration_torn faults
        # per-replica circuit breaker: consecutive step failures trip it
        # open; a half-open probe after bounded backoff closes it again
        self._breaker = [
            {"state": "closed", "fails": 0,
             "backoff": breaker_backoff_ticks, "until": 0}
            for _ in range(replicas)]
        self._stalled: dict[int, int] = {}   # replica -> recover-at tick
        self._draining: set[int] = set()     # autoscale drain victims
        self.replica_crashes = 0
        self.replica_stalls = 0
        self.breaker_ejections = 0
        self.breaker_probes = 0
        self.breaker_recoveries = 0
        self.replica_faults = 0
        self.migration_dups_dropped = 0
        self.autoscale_added = 0
        self.autoscale_retired = 0
        # the graveyard: terminal accounting harvested from crashed
        # engines (the monitoring plane's last scrape) so completions
        # and per-tenant counters survive the object's replacement
        self._grave_completions: dict[int, list[int]] = {}
        self._grave_tenants: dict[int, dict[str, int]] = {}
        self._grave_counters = {"completed": 0, "shed": 0}
        # fleet snapshot/restore through the PR-5 manifested/CRC path
        self.snapshot_dir = snapshot_dir
        self._ckpt = None
        self._last_snap = -1
        if snapshot_dir is not None:
            from distributed_tensorflow_guide_tpu.train.checkpoint import (
                Checkpointer,
            )
            self._ckpt = Checkpointer(snapshot_dir,
                                      max_to_keep=snapshot_keep)

    # ---- intake ----------------------------------------------------------

    def _ft(self, tenant: int) -> dict[str, int]:
        return self._fleet_tenants.setdefault(int(tenant), {"shed": 0})

    def submit(self, req: Request) -> None:
        """The fleet door: cheap validation plus the GLOBAL queue-depth
        gate (replicas run ungated).  Nothing is recorded for a shed
        request — :class:`EngineOverloaded` stays retriable."""
        cfg = self.engines[0].fns.cfg
        sched0 = self.engines[0].sched
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if int(prompt.max()) >= cfg.vocab_size:
            raise ValueError("prompt token out of vocabulary")
        if req.tenant < 0:
            raise ValueError(f"tenant must be >= 0, got {req.tenant}")
        if prompt.size + req.max_new_tokens > cfg.max_len:
            raise ValueError(
                f"prompt {prompt.size} + max_new {req.max_new_tokens} "
                f"exceeds max_len {cfg.max_len}")
        need = sched0.max_request_blocks(prompt.size, req.max_new_tokens)
        if need > sched0.pool.capacity:
            raise ValueError(
                f"request {req.rid} can never fit: needs {need} blocks, "
                f"pool capacity {sched0.pool.capacity}")
        quota = self.tenant_quotas.get(int(req.tenant), {})
        if quota.get("blocks") is not None and need > quota["blocks"]:
            raise ValueError(
                f"request {req.rid} can never fit tenant {req.tenant}'s "
                f"block quota: needs {need}, quota {quota['blocks']}")
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            self.shed += 1
            self._ft(req.tenant)["shed"] += 1
            if self.rec.enabled:
                self.rec.emit(
                    "req.shed", cat="serve", actor="fleet",
                    payload={"rid": req.rid, "reason": "queue_depth",
                             "tenant": int(req.tenant),
                             "queue_depth": len(self.queue)},
                    t=float(req.arrival))
            raise EngineOverloaded(
                f"request {req.rid} shed: fleet queue depth "
                f"{len(self.queue)} at the max_queue={self.max_queue} "
                "gate — retry later")
        self.queue.append(_Item(req=dataclasses.replace(
            req, prompt=prompt, rng=np.asarray(req.rng, np.uint32))))

    def cancel(self, rid: int) -> bool:
        """Client abandon, fleet-wide: drop a fleet-queued item outright,
        or forward to whichever replica holds the stream."""
        for j, item in enumerate(self.queue):
            if item.req.rid == rid:
                self.queue.pop(j)
                return True
        return any(self.engines[i].cancel(rid)
                   for i in sorted(self._live))

    # ---- global DRR dispatch ---------------------------------------------

    def _tenant_heads(self) -> list[tuple[_Item, int]]:
        heads: list[tuple[_Item, int]] = []
        seen: set[int] = set()
        for item in self.queue:
            t = int(item.req.tenant)
            if t not in seen:
                seen.add(t)
                heads.append((item, t))
        return heads

    def _load(self, i: int) -> int:
        sd = self.engines[i].sched
        return sum(s is not None for s in sd.slots) + len(sd.queue)

    def _store_room(self, i: int) -> int:
        st = self.engines[i].store
        if st is None:
            return 0
        if st.capacity is None:
            return 1 << 30
        return st.capacity - st.live_blocks()

    def _quota_allows(self, tenant: int, req: Request) -> bool:
        """Fleet-wide committed usage: worst-case footprints of the
        tenant's residents AND replica-queued requests across every live
        replica — dispatch is the commitment point, so the global quota
        can never be overrun by replicas admitting independently."""
        quota = self.tenant_quotas.get(int(tenant))
        if not quota:
            return True
        slots_used = 0
        committed = 0
        for i in sorted(self._live):
            sd = self.engines[i].sched
            for s in sd.slots:
                if s is not None and s.tenant == tenant:
                    slots_used += 1
                    committed += s.max_blocks
            for r in sd.queue:
                if int(r.tenant) == tenant:
                    slots_used += 1
                    committed += sd.max_request_blocks(
                        len(r.prompt), r.max_new_tokens)
        if (quota.get("slots") is not None
                and slots_used >= quota["slots"]):
            return False
        if quota.get("blocks") is not None:
            cost = self.engines[0].sched.max_request_blocks(
                len(req.prompt), req.max_new_tokens)
            if committed + cost > quota["blocks"]:
                return False
        return True

    def _route(self, item: _Item) -> int | None:
        """The routing policy, in preference order: (1) a KV-carrying
        migration record goes to the least-loaded adoptable replica with
        store room; (2) a re-prefill item probes the prefix tries and
        goes to the longest cached prefix when routing is on; (3)
        least-loaded wins, lowest index breaking ties.  Only replicas
        with a free-ish slot budget (load < slots) are candidates — the
        fleet queue, not replica queues, is where work waits, which is
        what keeps the global DRR in charge.  Every candidate list
        filters through :meth:`_routable` — open/half-open breakers,
        stalled and draining replicas never receive new work."""
        rec = item.record
        payloads = (rec or {}).get("payloads") or []
        routable = [i for i in sorted(self._live) if self._routable(i)]
        if payloads:
            cands = [i for i in routable
                     if self.roles[i] != "prefill"
                     and self.engines[i].store is not None
                     and self._store_room(i) >= len(payloads)
                     and self._load(i) < self.engines[i].num_slots]
            if not cands:
                return None
            return min(cands, key=lambda i: (self._load(i), i))
        if self.disagg:
            cands = [i for i in routable
                     if self.roles[i] == "prefill"]
            if not cands:  # every prefill replica shed: degrade, not die
                cands = routable
        else:
            cands = routable
        cands = [i for i in cands
                 if self._load(i) < self.engines[i].num_slots]
        if not cands:
            return None
        if self.prefix_routing:
            best, hit = None, 0
            for i in cands:
                sd = self.engines[i].sched
                if sd.prefix is None:
                    continue
                n = len(sd.prefix.match_nodes(
                    item.req.prompt, adapter=int(item.req.adapter)))
                if n > hit:
                    best, hit = i, n
            if best is not None and hit > 0:
                self.prefix_route_hits += 1
                self.prefix_route_hit_tokens += hit * self.block_size
                if self.rec.enabled:
                    self.rec.emit(
                        "fleet.prefix_route", cat="serve", actor="fleet",
                        payload={"rid": item.req.rid, "replica": best,
                                 "hit_tokens": hit * self.block_size})
                return best
        return min(cands, key=lambda i: (self._load(i), i))

    def _dispatch(self, now: float) -> int:
        """Global deficit-round-robin over per-tenant fleet-queue heads —
        the same loop shape as :meth:`Scheduler.admit`, with "a replica
        accepted it" in place of "blocks were found".  Migration records
        dispatch through ``adopt_stream`` (never re-counting
        ``submitted``); fresh requests through the replica's ``submit``,
        whose predicted-TTFT gate may still shed (counted there, exactly
        as a single engine would have)."""
        sched0 = self.engines[0].sched
        dispatched = 0
        while self.queue:
            progressed = False
            deficit_waiting = False
            for item, tenant in self._tenant_heads():
                if item.record is not None:
                    # exactly-once adoption: a torn handoff's duplicate
                    # carries the same (rid, handoff) key — drop it
                    # idempotently before any deficit/quota bookkeeping
                    key = (int(item.record["rid"]),
                           int(item.record.get("handoff", -1)))
                    if key in self._adopted:
                        self.queue.pop(next(
                            j for j, it in enumerate(self.queue)
                            if it is item))
                        self.migration_dups_dropped += 1
                        if self.rec.enabled:
                            self.rec.emit(
                                "fleet.migrate_dup", cat="serve",
                                actor="fleet",
                                payload={"rid": key[0],
                                         "handoff": key[1]},
                                t=now)
                        progressed = True
                        continue
                if item.req.arrival > now:
                    continue
                if not self._quota_allows(tenant, item.req):
                    continue
                cost = sched0.max_request_blocks(
                    len(item.req.prompt), item.req.max_new_tokens)
                self._deficit[tenant] = (self._deficit.get(tenant, 0)
                                         + self.drr_quantum)
                if self._deficit[tenant] < cost:
                    deficit_waiting = True
                    continue
                target = self._route(item)
                if target is None:
                    continue
                self.queue.pop(next(
                    j for j, it in enumerate(self.queue) if it is item))
                eng = self.engines[target]
                if item.record is not None:
                    eng.adopt_stream(item.record)
                    self._adopted.add(
                        (int(item.record["rid"]),
                         int(item.record.get("handoff", -1))))
                    self._ledger_note(item, target)
                else:
                    try:
                        eng.submit(item.req)
                    except EngineOverloaded:
                        pass  # TTFT-gate shed, counted by the replica
                    else:
                        self._ledger_note(item, target)
                self._deficit[tenant] -= cost
                dispatched += 1
                progressed = True
            if not progressed and not deficit_waiting:
                break
        queued = {int(it.req.tenant) for it in self.queue}
        for t in [t for t in self._deficit if t not in queued]:
            del self._deficit[t]
        return dispatched

    # ---- the admission ledger (crash reconstruction's only source) -------

    def _ledger_note(self, item: _Item, target: int) -> None:
        """Record a dispatch in the fleet's own ledger: the continuation
        BASIS (prompt/budget/rng at dispatch, plus any history that
        travelled in on a record) and the owning replica.  Tokens the
        replica emits land in ``since`` via :meth:`_observe` — so a hard
        crash can rebuild the stream without touching the dead engine."""
        req, rec = item.req, (item.record or {})
        m = rec.get("meta")
        if m is None and item.record is None:
            m = [float(req.arrival), req.ttft_deadline_s, req.deadline_s]
        self._ledger_seq += 1
        self._ledger[int(req.rid)] = {
            "seq": self._ledger_seq,
            "prompt": np.asarray(req.prompt, np.int32).reshape(-1),
            "budget": int(req.max_new_tokens),
            "rng": np.asarray(req.rng, np.uint32),
            "arrival": float(req.arrival),
            "tenant": int(req.tenant), "adapter": int(req.adapter),
            "emitted_prior": [int(t) for t in rec.get("emitted", [])],
            "first_emit_prior": bool(rec.get("first_emit", False)),
            "meta": None if m is None else [m[0], m[1], m[2]],
            "since": [],
            "owner": int(target),
            "done": False,
        }

    def _observe(self, i: int, evs: list[Event]) -> None:
        """Fold a replica's tick events into the ledger — the
        supervisor's view of each stream's emitted tail, maintained
        BEFORE any crash so reconstruction never needs the replica."""
        for e in evs:
            ent = self._ledger.get(e.rid)
            if ent is None or ent["owner"] != i:
                continue
            if e.status == "ok" and e.token >= 0:
                ent["since"].append(int(e.token))
            if e.done:
                ent["done"] = True

    def _stamp_handoff(self, record: dict) -> dict:
        """Give a migration / re-anchor record its adoption identity:
        the fleet generation it left in, and a unique handoff id — the
        exactly-once key (a resent duplicate copies the id; a later
        legitimate re-handoff of the same rid gets a fresh one)."""
        self._handoff_seq += 1
        record["fleet_gen"] = self.generation
        record["handoff"] = self._handoff_seq
        return record

    def _insert_handoffs(self, items: list[_Item],
                         now: float = 0.0) -> None:
        """Queue-front insertion of handoff records, applying any armed
        ``migration_torn`` faults: the duplicate record (same handoff
        id) rides immediately behind the original, and the adoption
        ledger must swallow it exactly once."""
        out: list[_Item] = []
        for it in items:
            out.append(it)
            if self._torn_pending > 0 and it.record is not None:
                self._torn_pending -= 1
                dup = _Item(req=self._record_req(it.record),
                            record=dict(it.record))
                out.append(dup)
                if self.rec.enabled:
                    self.rec.emit(
                        "fleet.migration_torn", cat="serve",
                        actor="fleet",
                        payload={"rid": int(it.record["rid"]),
                                 "handoff": int(it.record["handoff"])},
                        t=now)
        self.queue[:0] = out

    # ---- disaggregation: prefill -> decode migration ---------------------

    def _migrate_prefilled(self, now: float) -> int:
        """Ship every stream that just turned decode-phase on a
        prefill-role replica to a decode-role replica: fused d2h export
        of its written KV blocks, re-anchored through the fleet queue
        FRONT (adopted next tick by the normal swap-in path).  When no
        decode replica has store room the stream simply keeps decoding
        where it is — degraded placement, never a dropped stream."""
        moved = 0
        for i in sorted(self._live):
            if self.roles[i] != "prefill":
                continue
            eng = self.engines[i]
            ready = sorted(
                (s for s in eng.sched.slots
                 if s is not None and s.phase == "decode"
                 and s.written >= 1 and s.budget > 0),
                key=lambda s: s.admitted_seq)
            for s in ready:
                n_blocks = len(eng.sched.migratable_blocks(s.rid))
                if not n_blocks:
                    continue
                has_target = any(
                    self.roles[j] != "prefill"
                    and self.engines[j].store is not None
                    and self._store_room(j) >= n_blocks
                    for j in self._live
                    if j != i and self._routable(j))
                if not has_target:
                    continue
                t0 = time.perf_counter()
                record = eng.export_stream(s.rid, with_kv=True)
                self.migration_secs += time.perf_counter() - t0
                self.migrations += 1
                self.migration_bytes += int(record["payload_bytes"])
                self.migrated_rids.append(int(record["rid"]))
                self._stamp_handoff(record)
                ent = self._ledger.get(int(record["rid"]))
                if ent is not None:
                    ent["owner"] = None  # in flight, owned by no replica
                self._insert_handoffs(
                    [_Item(req=self._record_req(record), record=record)],
                    now)
                moved += 1
                if self.rec.enabled:
                    self.rec.emit(
                        "fleet.migrate", cat="serve", actor="fleet",
                        payload={"rid": int(record["rid"]),
                                 "from": i, "blocks": n_blocks,
                                 "bytes": int(record["payload_bytes"])},
                        t=now)
        return moved

    @staticmethod
    def _record_req(record: dict) -> Request:
        return Request(
            rid=int(record["rid"]),
            prompt=np.asarray(record["prompt"], np.int32),
            max_new_tokens=int(record["budget"]),
            rng=np.asarray(record["rng"], np.uint32),
            arrival=float(record.get("arrival", float("-inf"))),
            tenant=int(record.get("tenant", 0)),
            adapter=int(record.get("adapter", 0)))

    # ---- elastic capacity: replica shed / reabsorb -----------------------

    def _apply_world(self, tick: int, now: float) -> None:
        if self.world is None:
            return
        due = [f for f in self.world.world_events() if f.position <= tick]
        for f in due:
            self.world.fire(f)
            idx = f.slice_id % len(self.engines)
            if f.kind == "slice_loss":
                if idx in self._live and len(self._live) > 1:
                    self._shed_replica(idx)
                    self.replicas_shed += 1
            elif f.kind == "slice_return":
                if idx not in self._live:
                    self._live.add(idx)
                    self.replicas_regrown += 1
            self.generation += 1
            self.timeline.append({
                "generation": self.generation, "tick": tick,
                "kind": f.kind, "replica": idx,
                "live": sorted(self._live),
                "signal": self.autoscale_signal()})
            if self.rec.enabled:
                self.rec.emit(
                    "fleet.world", cat="serve", actor="fleet",
                    payload={"kind": f.kind, "replica": idx,
                             "generation": self.generation,
                             "live": sorted(self._live)},
                    t=now)

    def _reanchor_streams(self, idx: int, *, drop_caches: bool,
                          now: float = 0.0) -> int:
        """ORDERLY re-anchor of a replica's live streams onto the fleet
        queue FRONT in admission-then-queue order (the
        ``snapshot_state`` convention): the continuation transform with
        the KV left behind, so each re-prefills elsewhere and continues
        bitwise.  This is the graceful path — the replica's host state
        is reachable (world shed, stall, breaker ejection); a HARD crash
        goes through :meth:`_crash_replica`, which never touches the
        dead engine.  Returns the number of streams re-anchored."""
        eng = self.engines[idx]
        sd = eng.sched
        live = sorted((s for s in sd.slots if s is not None),
                      key=lambda s: s.admitted_seq)
        rids = [s.rid for s in live] + [r.rid for r in sd.queue]
        items = []
        for rid in rids:
            record = self._stamp_handoff(
                eng.export_stream(rid, with_kv=False))
            ent = self._ledger.get(int(rid))
            if ent is not None:
                ent["owner"] = None
            items.append(_Item(req=self._record_req(record),
                               record=record))
        self._insert_handoffs(items, now)
        if drop_caches:
            sd.release_prefix_cache()
            if eng.store is not None:
                sd.release_spill_store()
        return len(items)

    def _shed_replica(self, idx: int) -> None:
        """World-event replica loss: streams re-anchor, the engine
        OBJECT is retained for accounting — completed streams and
        tenant counters persist supervisor-side, exactly like a
        training generation's report outliving its processes — and
        comes back cold (trie and spill store dropped) if a
        ``slice_return`` reabsorbs it."""
        self._reanchor_streams(idx, drop_caches=True)
        self._live.discard(idx)
        self._draining.discard(idx)
        self._stalled.pop(idx, None)

    # ---- fleet chaos: hard crash, stall, torn handoff --------------------

    def _apply_fleet_chaos(self, tick: int, now: float) -> None:
        if self.fleet_chaos is None:
            return
        self.fleet_chaos.recorder = self.rec
        self.fleet_chaos.obs_now = now
        for f in self.fleet_chaos.take_fleet(tick):
            if f.kind == "replica_crash":
                idx = int(f.param) % len(self.engines)
                if idx in self._live:
                    self._crash_replica(idx, tick, now)
            elif f.kind == "replica_stall":
                idx = int(f.param) % len(self.engines)
                if idx in self._live:
                    self._stall_replica(idx, tick, now)
            else:  # migration_torn: the NEXT handoff record resends
                self._torn_pending += 1

    def _crash_replica(self, idx: int, tick: int, now: float) -> None:
        """Replica hard-crash: the engine (and its KV) is GONE with no
        orderly ``detach_stream``.  Terminal accounting is harvested
        into the graveyard (the monitoring plane's last scrape); every
        live stream is rebuilt from the fleet's OWN admission ledger —
        base prompt at dispatch plus the tokens the supervisor observed
        — and re-anchored queue-front as a continuation.  A FRESH
        engine (memoized geometry, compiles nothing) takes the slot and
        returns through the breaker's half-open probe."""
        eng = self.engines[idx]
        self.generation += 1
        self._harvest(eng)
        ents = sorted(
            ((rid, ent) for rid, ent in self._ledger.items()
             if ent["owner"] == idx and not ent["done"]),
            key=lambda kv: kv[1]["seq"])
        items = []
        for rid, ent in ents:
            since = ent["since"]
            cont_prompt = ent["prompt"]
            if since:
                cont_prompt = np.concatenate(
                    [cont_prompt, np.asarray(since, np.int32)])
            record = Scheduler.continuation_record(
                rid=rid, prompt=cont_prompt,
                budget=ent["budget"] - len(since),
                rng=ent["rng"],
                emitted=ent["emitted_prior"] + since,
                tenant=ent["tenant"], adapter=ent["adapter"],
                first_emit=ent["first_emit_prior"] or bool(since),
                meta=ent["meta"])
            self._stamp_handoff(record)
            ent["owner"] = None
            items.append(_Item(req=self._record_req(record),
                               record=record))
        self._insert_handoffs(items, now)
        self.engines[idx] = ServeEngine(
            self._cfg, self._params[idx], **self._engine_kw[idx])
        self._live.discard(idx)
        self._draining.discard(idx)
        self._stalled.pop(idx, None)
        br = self._breaker[idx]
        br["state"] = "open"
        br["fails"] = 0
        br["until"] = tick + 1 + br["backoff"]
        self.replica_crashes += 1
        self.timeline.append({
            "generation": self.generation, "tick": tick,
            "kind": "replica_crash", "replica": idx,
            "live": sorted(self._live),
            "signal": self.autoscale_signal()})
        if self.rec.enabled:
            self.rec.emit(
                "fleet.replica_crash", cat="serve", actor="fleet",
                payload={"replica": idx, "reanchored": len(items),
                         "generation": self.generation,
                         "probe_tick": br["until"]},
                t=now)

    def _harvest(self, eng: ServeEngine) -> None:
        """Last scrape of a crashing engine: TERMINAL streams' emitted
        history and per-tenant counters move to the fleet graveyard so
        fleet-merged completions and the disjoint-sum tenant accounting
        survive the object's replacement.  Live streams are NOT read —
        they are the ledger's job."""
        sd = eng.sched
        for rid in sd.finished:
            toks = sd.emitted.get(rid)
            if toks is not None:
                self._grave_completions[int(rid)] = [int(t) for t in toks]
        for t, c in sd.tenants.items():
            agg = self._grave_tenants.setdefault(int(t), {})
            for k, v in c.items():
                agg[k] = agg.get(k, 0) + int(v)
        self._grave_counters["completed"] += len(sd.done)
        self._grave_counters["shed"] += sd.shed

    def _stall_replica(self, idx: int, tick: int, now: float) -> None:
        """The watchdog's verdict, delivered deterministically: the
        device queue is wedged but the HOST process is reachable, so
        streams detach orderly (KV left behind — the device is
        unreachable) and re-anchor while the replica sits out its
        recovery window.  Its warm caches stay (the process never
        died); it rejoins at the deadline."""
        self.generation += 1
        n = self._reanchor_streams(idx, drop_caches=False, now=now)
        self._live.discard(idx)
        self._draining.discard(idx)
        self._stalled[idx] = tick + self.stall_recovery_ticks
        self.replica_stalls += 1
        self.timeline.append({
            "generation": self.generation, "tick": tick,
            "kind": "replica_stall", "replica": idx,
            "live": sorted(self._live),
            "signal": self.autoscale_signal()})
        if self.rec.enabled:
            self.rec.emit(
                "fleet.replica_stall", cat="serve", actor="fleet",
                payload={"replica": idx, "reanchored": n,
                         "recover_tick": self._stalled[idx]},
                t=now)

    def _stall_tick(self, tick: int, now: float) -> None:
        for idx in sorted(self._stalled):
            if tick >= self._stalled[idx]:
                del self._stalled[idx]
                self._live.add(idx)
                self.generation += 1
                self.timeline.append({
                    "generation": self.generation, "tick": tick,
                    "kind": "replica_recovered", "replica": idx,
                    "live": sorted(self._live),
                    "signal": self.autoscale_signal()})
                if self.rec.enabled:
                    self.rec.emit(
                        "fleet.replica_recovered", cat="serve",
                        actor="fleet",
                        payload={"replica": idx, "via": "stall_deadline"},
                        t=now)

    # ---- per-replica circuit breaker -------------------------------------

    def _routable(self, i: int) -> bool:
        """Replicas the router may hand NEW work: live, breaker closed,
        not wedged, not draining.  A half-open replica steps (that IS
        the probe) but receives nothing until the probe closes the
        breaker."""
        return (i in self._live
                and self._breaker[i]["state"] == "closed"
                and i not in self._stalled
                and i not in self._draining)

    def _replica_fault(self, i: int, tick: int, now: float,
                       exc: Exception) -> None:
        """A replica step escaped its engine-level retries.  Count it;
        trip the breaker at the consecutive-failure threshold; a failed
        half-open probe reopens with doubled (bounded) backoff — the
        ``retry_with_backoff`` convention at the step-boundary
        granularity."""
        self.replica_faults += 1
        br = self._breaker[i]
        if self.rec.enabled:
            self.rec.emit(
                "fleet.replica_fault", cat="serve", actor="fleet",
                payload={"replica": i, "fails": br["fails"] + 1,
                         "state": br["state"],
                         "error": type(exc).__name__},
                t=now)
        if br["state"] == "half_open":
            br["state"] = "open"
            br["backoff"] = min(br["backoff"] * 2,
                                self.breaker_max_backoff_ticks)
            br["until"] = tick + 1 + br["backoff"]
            self._reanchor_streams(i, drop_caches=False, now=now)
            self._live.discard(i)
            self.breaker_ejections += 1
            self.generation += 1
            self.timeline.append({
                "generation": self.generation, "tick": tick,
                "kind": "replica_ejected", "replica": i,
                "live": sorted(self._live),
                "signal": self.autoscale_signal()})
            if self.rec.enabled:
                self.rec.emit(
                    "fleet.replica_ejected", cat="serve", actor="fleet",
                    payload={"replica": i, "reason": "probe_failed",
                             "backoff_ticks": br["backoff"]},
                    t=now)
            return
        br["fails"] += 1
        if br["fails"] >= self.breaker_threshold:
            self._eject_replica(i, tick, now)

    def _eject_replica(self, i: int, tick: int, now: float) -> None:
        self.generation += 1
        n = self._reanchor_streams(i, drop_caches=False, now=now)
        br = self._breaker[i]
        br["state"] = "open"
        br["fails"] = 0
        br["until"] = tick + 1 + br["backoff"]
        self._live.discard(i)
        self._draining.discard(i)
        self.breaker_ejections += 1
        self.timeline.append({
            "generation": self.generation, "tick": tick,
            "kind": "replica_ejected", "replica": i,
            "live": sorted(self._live),
            "signal": self.autoscale_signal()})
        if self.rec.enabled:
            self.rec.emit(
                "fleet.replica_ejected", cat="serve", actor="fleet",
                payload={"replica": i, "reason": "launch_failures",
                         "reanchored": n,
                         "backoff_ticks": br["backoff"]},
                t=now)

    def _breaker_tick(self, tick: int, now: float) -> None:
        for i, br in enumerate(self._breaker):
            if br["state"] == "open" and tick >= br["until"]:
                br["state"] = "half_open"
                self._live.add(i)
                self.breaker_probes += 1
                if self.rec.enabled:
                    self.rec.emit(
                        "fleet.replica_probe", cat="serve", actor="fleet",
                        payload={"replica": i,
                                 "backoff_ticks": br["backoff"]},
                        t=now)

    def _breaker_close(self, i: int, tick: int, now: float) -> None:
        """A half-open probe tick completed without raising: close the
        breaker, reset the backoff, and let the router see the replica
        again."""
        br = self._breaker[i]
        br["state"] = "closed"
        br["fails"] = 0
        br["backoff"] = self.breaker_backoff_ticks
        self.breaker_recoveries += 1
        self.generation += 1
        self.timeline.append({
            "generation": self.generation, "tick": tick,
            "kind": "replica_recovered", "replica": i,
            "live": sorted(self._live),
            "signal": self.autoscale_signal()})
        if self.rec.enabled:
            self.rec.emit(
                "fleet.replica_recovered", cat="serve", actor="fleet",
                payload={"replica": i, "via": "probe"},
                t=now)

    # ---- the closed autoscale loop ---------------------------------------

    def _apply_autoscale(self, tick: int, now: float) -> None:
        """Act on :meth:`autoscale_policy` (``apply_autoscale=True``):
        scale-up re-admits a provisioned cold replica (memoized
        geometry — compiles nothing) or cancels an in-progress drain;
        scale-down marks a graceful-drain victim — routing stops, its
        residents migrate or finish, and only then is it removed.  One
        replica per application, never below one routable replica,
        never a dropped stream."""
        pol = self.autoscale_policy(**self.autoscale_params)
        target = pol["target_replicas"]
        live = len(self._live)
        if target > live:
            if self._draining:
                idx = max(self._draining)
                self._draining.discard(idx)
                if self.rec.enabled:
                    self.rec.emit(
                        "fleet.autoscale", cat="serve", actor="fleet",
                        payload={"action": "undrain", "replica": idx,
                                 "target": target},
                        t=now)
                return
            cands = [i for i in range(len(self.engines))
                     if i not in self._live
                     and self._breaker[i]["state"] == "closed"
                     and i not in self._stalled]
            if not cands:
                return
            idx = cands[0]
            self._live.add(idx)
            self.autoscale_added += 1
            self.generation += 1
            self.timeline.append({
                "generation": self.generation, "tick": tick,
                "kind": "autoscale_add", "replica": idx,
                "live": sorted(self._live),
                "signal": pol["signal"]})
            if self.rec.enabled:
                self.rec.emit(
                    "fleet.autoscale", cat="serve", actor="fleet",
                    payload={"action": "add", "replica": idx,
                             "target": target,
                             "live": sorted(self._live)},
                    t=now)
        elif target < live:
            cands = [i for i in sorted(self._live)
                     if self._routable(i)]
            if len(cands) <= 1:
                return
            victim = min(cands, key=lambda i: (self._load(i), -i))
            self._draining.add(victim)
            self.generation += 1
            self.timeline.append({
                "generation": self.generation, "tick": tick,
                "kind": "autoscale_drain", "replica": victim,
                "live": sorted(self._live),
                "signal": pol["signal"]})
            if self.rec.enabled:
                self.rec.emit(
                    "fleet.autoscale", cat="serve", actor="fleet",
                    payload={"action": "drain", "replica": victim,
                             "target": target},
                    t=now)

    def _drain_tick(self, tick: int, now: float) -> None:
        """Advance every graceful drain: replica-queued work re-anchors
        to the fleet (it re-routes), decode-phase residents migrate
        with their KV when an adoptable target has room, everything
        else finishes in place; the moment the replica is empty it is
        retired."""
        for idx in sorted(self._draining):
            eng = self.engines[idx]
            sd = eng.sched
            for r in list(sd.queue):
                record = self._stamp_handoff(
                    eng.export_stream(r.rid, with_kv=False))
                ent = self._ledger.get(int(r.rid))
                if ent is not None:
                    ent["owner"] = None
                self._insert_handoffs(
                    [_Item(req=self._record_req(record), record=record)],
                    now)
            ready = sorted(
                (s for s in sd.slots
                 if s is not None and s.phase == "decode"
                 and s.written >= 1 and s.budget > 0),
                key=lambda s: s.admitted_seq)
            for s in ready:
                n_blocks = len(sd.migratable_blocks(s.rid))
                if not n_blocks:
                    continue
                has_target = any(
                    self.roles[j] != "prefill"
                    and self.engines[j].store is not None
                    and self._store_room(j) >= n_blocks
                    for j in self._live
                    if j != idx and self._routable(j))
                if not has_target:
                    continue
                t0 = time.perf_counter()
                record = eng.export_stream(s.rid, with_kv=True)
                self.migration_secs += time.perf_counter() - t0
                self.migrations += 1
                self.migration_bytes += int(record["payload_bytes"])
                self.migrated_rids.append(int(record["rid"]))
                self._stamp_handoff(record)
                ent = self._ledger.get(int(record["rid"]))
                if ent is not None:
                    ent["owner"] = None
                self._insert_handoffs(
                    [_Item(req=self._record_req(record), record=record)],
                    now)
                if self.rec.enabled:
                    self.rec.emit(
                        "fleet.migrate", cat="serve", actor="fleet",
                        payload={"rid": int(record["rid"]),
                                 "from": idx, "blocks": n_blocks,
                                 "bytes": int(record["payload_bytes"]),
                                 "reason": "drain"},
                        t=now)
            if not sd.has_resident and not sd.queue:
                self._draining.discard(idx)
                self._live.discard(idx)
                self.autoscale_retired += 1
                self.generation += 1
                self.timeline.append({
                    "generation": self.generation, "tick": tick,
                    "kind": "autoscale_retired", "replica": idx,
                    "live": sorted(self._live),
                    "signal": self.autoscale_signal()})
                if self.rec.enabled:
                    self.rec.emit(
                        "fleet.autoscale", cat="serve", actor="fleet",
                        payload={"action": "retired", "replica": idx,
                                 "live": sorted(self._live)},
                        t=now)

    # ---- fleet snapshot / restore ----------------------------------------

    @staticmethod
    def _ser_record(record: dict) -> dict:
        """A queue record as JSON: numpy -> lists, payloads STRIPPED —
        KV bytes are never persisted, so a restored record re-enters as
        a re-prefill continuation (positions make that bitwise-safe)."""
        out = dict(record)
        out["prompt"] = [int(t) for t in record["prompt"]]
        out["rng"] = [int(x) for x in np.asarray(record["rng"]).ravel()]
        out["payloads"] = []
        out["payload_bytes"] = 0
        return out

    def _ser_item(self, item: _Item) -> dict:
        if item.record is not None:
            return {"record": self._ser_record(item.record)}
        r = item.req
        return {"req": {
            "rid": int(r.rid),
            "prompt": [int(t) for t in r.prompt],
            "max_new_tokens": int(r.max_new_tokens),
            "rng": [int(x) for x in np.asarray(r.rng).ravel()],
            "arrival": float(r.arrival),
            "ttft_deadline_s": r.ttft_deadline_s,
            "deadline_s": r.deadline_s,
            "tenant": int(r.tenant), "adapter": int(r.adapter)}}

    @staticmethod
    def _deser_item(d: dict) -> _Item:
        if "record" in d:
            rec = dict(d["record"])
            rec["prompt"] = np.asarray(rec["prompt"], np.int32)
            rec["rng"] = np.asarray(rec["rng"], np.uint32)
            rec["payloads"] = []
            rec["payload_bytes"] = 0
            return _Item(req=FleetScheduler._record_req(rec), record=rec)
        q = dict(d["req"])
        return _Item(req=Request(
            rid=int(q["rid"]),
            prompt=np.asarray(q["prompt"], np.int32),
            max_new_tokens=int(q["max_new_tokens"]),
            rng=np.asarray(q["rng"], np.uint32),
            arrival=float(q["arrival"]),
            ttft_deadline_s=q["ttft_deadline_s"],
            deadline_s=q["deadline_s"],
            tenant=int(q["tenant"]), adapter=int(q["adapter"])))

    def save_snapshot(self, *, async_: bool = False) -> int | None:
        """Serialize the WHOLE fleet through PR 5's manifested /
        CRC-verified checkpoint path as one uint8 JSON blob: the global
        queue (payloads stripped — KV is never persisted), DRR deficits,
        tenant counters, the admission ledger, adoption/breaker/stall/
        drain/autoscale state, the graveyard, and every replica's
        engine-level snapshot dict.  Restore re-prefills all residents
        from their recorded positions, so each in-flight stream finishes
        bitwise vs the uninterrupted run.  Returns the snapshot label,
        or None if the save was skipped."""
        if self._ckpt is None:
            raise ValueError(
                "FleetScheduler(snapshot_dir=...) not configured")
        state = {
            "tick": self._tick,
            "queue": [self._ser_item(it) for it in self.queue],
            "deficit": {str(t): int(v)
                        for t, v in self._deficit.items()},
            "fleet_tenants": {str(t): dict(c) for t, c in
                              self._fleet_tenants.items()},
            "counters": {
                "shed": self.shed, "migrations": self.migrations,
                "migration_bytes": self.migration_bytes,
                "migration_secs": self.migration_secs,
                "prefix_route_hits": self.prefix_route_hits,
                "prefix_route_hit_tokens": self.prefix_route_hit_tokens,
                "generation": self.generation,
                "replicas_shed": self.replicas_shed,
                "replicas_regrown": self.replicas_regrown,
                "replica_crashes": self.replica_crashes,
                "replica_stalls": self.replica_stalls,
                "breaker_ejections": self.breaker_ejections,
                "breaker_probes": self.breaker_probes,
                "breaker_recoveries": self.breaker_recoveries,
                "replica_faults": self.replica_faults,
                "migration_dups_dropped": self.migration_dups_dropped,
                "autoscale_added": self.autoscale_added,
                "autoscale_retired": self.autoscale_retired,
            },
            "migrated_rids": list(self.migrated_rids),
            "adopted": sorted(list(p) for p in self._adopted),
            "handoff_seq": self._handoff_seq,
            "ledger_seq": self._ledger_seq,
            "torn_pending": self._torn_pending,
            "ledger": {str(rid): {
                **{k: ent[k] for k in
                   ("seq", "budget", "arrival", "tenant", "adapter",
                    "emitted_prior", "first_emit_prior", "meta",
                    "since", "owner", "done")},
                "prompt": [int(t) for t in ent["prompt"]],
                "rng": [int(x) for x in
                        np.asarray(ent["rng"]).ravel()],
            } for rid, ent in self._ledger.items()},
            "live": sorted(self._live),
            "stalled": {str(i): t for i, t in self._stalled.items()},
            "draining": sorted(self._draining),
            "breaker": [dict(b) for b in self._breaker],
            "scale": [self._scale_direction, self._scale_streak],
            "timeline": list(self.timeline),
            "grave": {
                "completions": {str(r): toks for r, toks in
                                self._grave_completions.items()},
                "tenants": {str(t): dict(c) for t, c in
                            self._grave_tenants.items()},
                "counters": dict(self._grave_counters)},
            "replicas": [{"sched": eng.sched.snapshot_state(),
                          "tick": eng._tick,
                          "steps": dict(eng.steps)}
                         for eng in self.engines],
        }
        blob = np.frombuffer(json.dumps(state).encode("utf-8"),
                             dtype=np.uint8).copy()
        label = max(self._tick, self._last_snap + 1)
        if not self._ckpt.save(label, {"blob": blob}, force=True,
                               async_=async_):
            return None
        self._last_snap = label
        if self.rec.enabled:
            self.rec.emit(
                "fleet.snapshot_save", cat="serve", actor="fleet",
                payload={"label": int(label),
                         "queued": len(self.queue),
                         "replicas": len(self.engines),
                         "async": bool(async_)})
        return label

    def restore_latest_snapshot(self) -> int | None:
        """Restore the newest VALID fleet snapshot (the PR-5 ladder: a
        truncated or CRC-corrupt member is skipped, falling back to the
        next older one) into THIS fleet, which must be fresh and built
        with the same replica count.  Every pool stays zeroed; every
        formerly-resident stream re-enters as a queued continuation and
        re-prefills through normal admission — bitwise identical to an
        uninterrupted run.  Returns the restored label, or None when no
        valid snapshot exists."""
        if self._ckpt is None:
            raise ValueError(
                "FleetScheduler(snapshot_dir=...) not configured")
        got = self._ckpt.restore_latest_valid(None)
        if got is None:
            if self.rec.enabled:
                self.rec.emit("fleet.snapshot_restore_miss", cat="serve",
                              actor="fleet", payload={})
            return None
        tree, label = got
        state = json.loads(
            np.asarray(tree["blob"], np.uint8).tobytes().decode("utf-8"))
        if len(state["replicas"]) != len(self.engines):
            raise ValueError(
                f"snapshot has {len(state['replicas'])} replicas, this "
                f"fleet has {len(self.engines)} — restore needs the "
                "same provisioned width")
        for eng, snap in zip(self.engines, state["replicas"]):
            eng.sched.restore_state(snap["sched"])
            eng._tick = int(snap["tick"])
            for k, v in snap["steps"].items():
                eng.steps[k] = int(v)
        self._tick = int(state["tick"])
        self.queue = [self._deser_item(d) for d in state["queue"]]
        self._deficit = {int(t): int(v)
                         for t, v in state["deficit"].items()}
        self._fleet_tenants = {
            int(t): {k: int(v) for k, v in c.items()}
            for t, c in state["fleet_tenants"].items()}
        c = state["counters"]
        self.shed = int(c["shed"])
        self.migrations = int(c["migrations"])
        self.migration_bytes = int(c["migration_bytes"])
        self.migration_secs = float(c["migration_secs"])
        self.prefix_route_hits = int(c["prefix_route_hits"])
        self.prefix_route_hit_tokens = int(c["prefix_route_hit_tokens"])
        self.generation = int(c["generation"])
        self.replicas_shed = int(c["replicas_shed"])
        self.replicas_regrown = int(c["replicas_regrown"])
        self.replica_crashes = int(c["replica_crashes"])
        self.replica_stalls = int(c["replica_stalls"])
        self.breaker_ejections = int(c["breaker_ejections"])
        self.breaker_probes = int(c["breaker_probes"])
        self.breaker_recoveries = int(c["breaker_recoveries"])
        self.replica_faults = int(c["replica_faults"])
        self.migration_dups_dropped = int(c["migration_dups_dropped"])
        self.autoscale_added = int(c["autoscale_added"])
        self.autoscale_retired = int(c["autoscale_retired"])
        self.migrated_rids = [int(r) for r in state["migrated_rids"]]
        self._adopted = {(int(a), int(b)) for a, b in state["adopted"]}
        self._handoff_seq = int(state["handoff_seq"])
        self._ledger_seq = int(state["ledger_seq"])
        self._torn_pending = int(state["torn_pending"])
        self._ledger = {int(rid): {
            **{k: ent[k] for k in
               ("seq", "budget", "arrival", "tenant", "adapter",
                "emitted_prior", "first_emit_prior", "meta",
                "since", "owner", "done")},
            "prompt": np.asarray(ent["prompt"], np.int32),
            "rng": np.asarray(ent["rng"], np.uint32),
        } for rid, ent in state["ledger"].items()}
        self._live = set(int(i) for i in state["live"])
        self._stalled = {int(i): int(t)
                         for i, t in state["stalled"].items()}
        self._draining = set(int(i) for i in state["draining"])
        self._breaker = [dict(b) for b in state["breaker"]]
        self._scale_direction, self._scale_streak = (
            int(state["scale"][0]), int(state["scale"][1]))
        self.timeline = list(state["timeline"])
        g = state["grave"]
        self._grave_completions = {
            int(r): [int(t) for t in toks]
            for r, toks in g["completions"].items()}
        self._grave_tenants = {
            int(t): {k: int(v) for k, v in cc.items()}
            for t, cc in g["tenants"].items()}
        self._grave_counters = {k: int(v)
                                for k, v in g["counters"].items()}
        self._last_snap = label
        if self.rec.enabled:
            self.rec.emit(
                "fleet.snapshot_restore", cat="serve", actor="fleet",
                payload={"label": int(label),
                         "queued": len(self.queue)})
        return label

    def autoscale_signal(self) -> dict:
        """What an autoscaler would act on: global queue pressure
        against live capacity, the worst live replica's TTFT-EWMA (the
        PR-14 shed-gate statistic), and cumulative goodput tokens."""
        live = sorted(self._live)
        queued = len(self.queue) + sum(
            len(self.engines[i].sched.queue) for i in live)
        capacity = max(1, len(live) * self.num_slots)
        ewmas = [self.engines[i]._ttft_ewma for i in live
                 if self.engines[i]._ttft_ewma is not None]
        goodput = sum(c["tokens"]
                      for eng in self.engines
                      for c in eng.sched.tenants.values())
        pressure = queued / capacity
        return {
            "queued": queued,
            "live_replicas": len(live),
            "total_replicas": len(self.engines),
            "pressure": pressure,
            "ttft_ewma_s": max(ewmas) if ewmas else None,
            "goodput_tokens": goodput,
            "want_more_replicas": bool(
                pressure > 1.0 or len(live) < len(self.engines)),
        }

    def autoscale_policy(self, *, min_replicas: int = 1,
                         max_replicas: int | None = None,
                         up_pressure: float = 1.0,
                         down_pressure: float = 0.25,
                         hysteresis: int = 3) -> dict:
        """:meth:`autoscale_signal` -> a target-replica-count
        RECOMMENDATION.  Advisory by default (an external operator is
        one intended consumer); ``apply_autoscale=True`` closes the
        loop — :meth:`_apply_autoscale` acts on the target every
        ``autoscale_every`` ticks, adding a provisioned cold replica or
        retiring one by graceful drain.

        Hysteresis: the signal must lean the same direction for
        ``hysteresis`` consecutive evaluations before the target moves
        off the current live count, and then it moves by ONE replica —
        a flapping queue cannot saw the fleet.  Scale-down additionally
        requires an empty queue (draining capacity under backlog is
        never recommended).  The target is clamped to
        ``[min_replicas, max_replicas]`` (default max: the fleet's
        provisioned width)."""
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas}")
        cap = (len(self.engines) if max_replicas is None
               else int(max_replicas))
        if cap < min_replicas:
            raise ValueError(
                f"max_replicas {cap} < min_replicas {min_replicas}")
        sig = self.autoscale_signal()
        live = sig["live_replicas"]
        if sig["pressure"] > up_pressure:
            direction = 1
        elif sig["pressure"] < down_pressure and sig["queued"] == 0:
            direction = -1
        else:
            direction = 0
        if direction != 0 and direction == self._scale_direction:
            self._scale_streak += 1
        else:
            self._scale_direction = direction
            self._scale_streak = 1 if direction else 0
        target = live
        if direction and self._scale_streak >= hysteresis:
            target = live + direction
        target = max(min_replicas, min(cap, target))
        return {
            "target_replicas": target,
            "live_replicas": live,
            "direction": direction,
            "streak": self._scale_streak,
            "hysteresis": hysteresis,
            "min_replicas": min_replicas,
            "max_replicas": cap,
            "signal": sig,
        }

    # ---- the fleet tick --------------------------------------------------

    def step(self, now: float = 0.0) -> tuple[list[Event], str]:
        """One fleet tick: apply due world and fleet faults, advance
        breaker/stall/autoscale/drain state machines, run the global DRR
        dispatch, step every live replica once (an exception escaping a
        replica's own retries becomes a breaker strike, never a fleet
        crash), then migrate any freshly-prefilled streams off
        prefill-role replicas.  Returns (events, kind) with kind in
        {"busy", "idle"} — replica ticks, dispatches, migrations and
        fault handling all count as progress."""
        tick = self._tick
        self._tick += 1
        self._apply_world(tick, now)
        self._apply_fleet_chaos(tick, now)
        self._breaker_tick(tick, now)
        self._stall_tick(tick, now)
        if self.apply_autoscale and tick % self.autoscale_every == 0:
            self._apply_autoscale(tick, now)
        if self._draining:
            self._drain_tick(tick, now)
        dispatched = self._dispatch(now)
        events: list[Event] = []
        busy = dispatched > 0
        # per-replica wall seconds of THIS tick: replicas are independent
        # machines, so a virtual-clock driver should charge the slowest
        # replica (plus the supervisor's own overhead), not the sum the
        # in-process serial loop happens to pay
        self.step_secs: dict[int, float] = {}
        for i in sorted(self._live):
            t0 = time.perf_counter()
            try:
                evs, kind = self.engines[i].step(now)
            except Exception as e:  # noqa: BLE001 — breaker's strike zone
                self.step_secs[i] = time.perf_counter() - t0
                self._replica_fault(i, tick, now, e)
                busy = True
                continue
            self.step_secs[i] = time.perf_counter() - t0
            br = self._breaker[i]
            if br["state"] == "half_open":
                self._breaker_close(i, tick, now)
            elif br["fails"]:
                br["fails"] = 0  # threshold means CONSECUTIVE failures
            self._observe(i, evs)
            events.extend(evs)
            busy = busy or kind != "idle"
        if self.disagg:
            busy = bool(self._migrate_prefilled(now)) or busy
        return events, ("busy" if busy else "idle")

    def next_arrival(self) -> float | None:
        """Earliest future arrival anywhere in the fleet — the virtual
        clock's fast-forward target when a tick comes back idle.
        Re-anchored migration records (arrival ``-inf``) never gate."""
        cands = [it.req.arrival for it in self.queue
                 if it.req.arrival != float("-inf")]
        for i in sorted(self._live):
            nxt = self.engines[i].sched.next_arrival()
            if nxt is not None:
                cands.append(nxt)
        return min(cands) if cands else None

    def _has_work(self) -> bool:
        return bool(self.queue) or any(
            self.engines[i].sched.has_queued
            or self.engines[i].sched.has_resident
            for i in sorted(self._live))

    def run(self, max_ticks: int | None = None) -> list[Event]:
        """Drain all submitted work on the tick clock.  Idle ticks are
        tolerated in bounded runs of them (chaos pressure holds and
        pending world returns resolve by tick), then declared a
        deadlock."""
        events: list[Event] = []
        ticks = 0
        stalled = 0
        while self._has_work():
            evs, kind = self.step(now=float("inf"))
            events.extend(evs)
            stalled = 0 if kind != "idle" else stalled + 1
            if stalled > 64:
                raise RuntimeError(
                    "fleet deadlock: work queued but no replica "
                    "progressing")
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
        for i in sorted(self._live):
            self.engines[i]._release_pressure(float("inf"))
        return events

    # ---- introspection ---------------------------------------------------

    def completions(self) -> dict[int, list[int]]:
        """rid -> emitted tokens, merged across replicas AND the
        graveyard (streams that finished on a since-crashed engine).
        Disjoint by construction: a stream's emitted list TRAVELS with
        it (popped at detach, installed at attach), so a rid appearing
        on two replicas is a conservation bug worth crashing on."""
        out: dict[int, list[int]] = {}
        for rid, toks in self._grave_completions.items():
            out[int(rid)] = list(toks)
        for eng in self.engines:
            for rid, toks in eng.completions().items():
                if rid in out:
                    raise AssertionError(
                        f"rid {rid} emitted on two replicas — the "
                        "migration seam double-counted a stream")
                out[rid] = toks
        return out

    def health(self) -> dict:
        """Fleet health: per-replica engine healths plus the GLOBAL
        view — element-wise per-tenant aggregation across every replica
        (migration makes this a disjoint sum: submitted once at the
        dispatch replica, terminal status once where the stream ended)
        merged with fleet-door sheds and the graveyard (accounting
        harvested from crashed engines), and the fleet counters."""
        tenants: dict[int, dict[str, int]] = {}
        for eng in self.engines:
            for t, c in eng.sched.tenants.items():
                agg = tenants.setdefault(int(t), {})
                for k, v in c.items():
                    agg[k] = agg.get(k, 0) + int(v)
        for src in (self._fleet_tenants, self._grave_tenants):
            for t, c in src.items():
                agg = tenants.setdefault(int(t), {})
                for k, v in c.items():
                    agg[k] = agg.get(k, 0) + int(v)
        replicas = []
        for i, eng in enumerate(self.engines):
            h = eng.health()
            h["role"] = self.roles[i]
            h["live"] = i in self._live
            h["breaker"] = {k: self._breaker[i][k]
                            for k in ("state", "fails", "backoff")}
            h["stalled"] = i in self._stalled
            h["draining"] = i in self._draining
            replicas.append(h)
        return {
            "replicas": replicas,
            "tenants": {t: dict(c) for t, c in sorted(tenants.items())},
            "queued": len(self.queue),
            "shed": (self.shed + self._grave_counters["shed"]
                     + sum(h["shed"] for h in replicas)),
            "live_replicas": len(self._live),
            "generation": self.generation,
            "replicas_shed": self.replicas_shed,
            "replicas_regrown": self.replicas_regrown,
            "migrations": self.migrations,
            "migration_bytes": self.migration_bytes,
            "migration_secs": self.migration_secs,
            "prefix_route_hits": self.prefix_route_hits,
            "prefix_route_hit_tokens": self.prefix_route_hit_tokens,
            "completed": (self._grave_counters["completed"]
                          + sum(h["completed"] for h in replicas)),
            "replica_crashes": self.replica_crashes,
            "replica_stalls": self.replica_stalls,
            "breaker_ejections": self.breaker_ejections,
            "breaker_probes": self.breaker_probes,
            "breaker_recoveries": self.breaker_recoveries,
            "replica_faults": self.replica_faults,
            "launch_failures": sum(h["launch_failures"]
                                   for h in replicas),
            "migration_dups_dropped": self.migration_dups_dropped,
            "autoscale_added": self.autoscale_added,
            "autoscale_retired": self.autoscale_retired,
            "stalled": sorted(self._stalled),
            "draining": sorted(self._draining),
            "autoscale": self.autoscale_policy(),
        }

    def check_leaks(self) -> None:
        """Joint ledger audit across every replica's pool AND host
        store — shed replicas included (they must have released
        everything on the way out)."""
        for eng in self.engines:
            eng.sched.check_leaks()

    def close(self) -> None:
        for eng in self.engines:
            eng.close()
