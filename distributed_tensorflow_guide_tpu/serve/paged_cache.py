"""Paged KV cache: a fixed pool of fixed-size blocks + per-request tables.

The one-shot serving path (models/generation.py) gives every request a
private ``(B, max_len, H, hd)`` cache buffer for its whole lifetime —
HBM is reserved for ``max_len`` slots even while a request has written
eight.  Production traffic (ROADMAP item 1's "millions of users") makes
that the binding constraint on batch size, which is the vLLM observation:
page the cache.  Here the cache collection of every attention layer
becomes a POOL of ``num_blocks`` fixed-size blocks shared by all resident
requests, and each request owns a **block table** — a row of physical
block ids covering its logical positions ``[0, max_len)``.

The split of responsibilities keeps every compiled shape static:

* **host Python** (:class:`BlockPool`) allocates, frees and evicts blocks
  — a free-list the scheduler drives between steps; nothing here traces;
* **device code** (:func:`gather_view` / :func:`scatter_chunk`) reads and
  writes through the table *inside* the compiled step: a gather by block
  id materializes a request's logical cache view, a scatter by
  ``table[pos // bs] * bs + pos % bs`` writes a chunk — both are plain
  static-shape XLA ops, so the engine's step program never retraces as
  the resident population changes.

Unallocated logical blocks point at the reserved **trash block** (the
pool's last id): inactive decode slots write there and the attention
mask hides anything read from it, so the device program needs no branch
on liveness.  The helpers are layout-agnostic (``seq_axis`` names the
blocked axis) because the cache collection has three leaf layouts —
legacy ``(B, S, H, hd)``, kernel ``(B, H, S, hd)`` and the quantized
scale rows ``(B, H, 1, S)``.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

# --------------------------------------------------------------------------
# device-side: gather / scatter through a block table
# --------------------------------------------------------------------------


def gather_view(pool, tables, *, seq_axis: int):
    """Materialize per-request logical cache views from the pool.

    ``pool`` is ``(num_blocks, *dims)`` where ``dims[seq_axis - 1]`` is the
    block size; ``tables`` is ``(B, blocks_per_seq)`` int32 physical block
    ids.  Returns ``(B, *dims)`` with the blocked axis expanded to
    ``blocks_per_seq * block_size`` at ``seq_axis`` — the exact dense view
    the one-shot cache holds, which is what pins the fallback path
    token-identical on CPU.
    """
    g = jnp.take(pool, tables, axis=0)  # (B, n_blk, *dims)
    g = jnp.moveaxis(g, 1, seq_axis)
    shape = list(g.shape)
    merged = (shape[:seq_axis]
              + [shape[seq_axis] * shape[seq_axis + 1]]
              + shape[seq_axis + 2:])
    return g.reshape(merged)


def scatter_chunk(pool, chunk, tables, index, *, block_size: int,
                  seq_axis: int):
    """Write per-request chunks into the pool through the block tables.

    ``chunk`` is ``(B, *dims)`` with C positions along ``seq_axis``;
    request b's chunk lands at logical positions ``[index[b],
    index[b] + C)``, i.e. physical row ``tables[b, p // bs] * bs +
    p % bs`` of the block-flattened pool.  Rows of requests whose table
    points at the trash block land there harmlessly (never read back).
    Static shapes; one scatter.
    """
    B = chunk.shape[0]
    C = chunk.shape[seq_axis]
    pos = index[:, None] + jnp.arange(C)[None, :]  # (B, C)
    phys = jnp.take_along_axis(tables, pos // block_size, axis=1)
    lin = phys * block_size + pos % block_size  # (B, C) flattened rows
    p = jnp.moveaxis(pool, seq_axis, 1)  # (N, bs, *rest)
    rest = p.shape[2:]
    flat = p.reshape((p.shape[0] * block_size,) + rest)
    rows = jnp.moveaxis(chunk, seq_axis, 1).reshape((B * C,) + rest)
    flat = flat.at[lin.reshape(-1)].set(rows)
    p = flat.reshape((pool.shape[0], block_size) + rest)
    return jnp.moveaxis(p, 1, seq_axis)


# --------------------------------------------------------------------------
# host-side: the allocator the scheduler drives
# --------------------------------------------------------------------------


@dataclasses.dataclass
class BlockPool:
    """Host-side block allocator: free-list + refcounted holder ledger.

    ``num_blocks`` includes the reserved trash block (the LAST id), which
    is never handed out — ``capacity`` is what requests can actually own.
    Deterministic: blocks are allocated lowest-id-first, so an identical
    request trace produces identical tables (the scheduler-determinism
    test pins this).

    Prefix sharing (PR 12) turns the per-block owner into a SET of
    holders: :meth:`alloc` creates a block with one holder, :meth:`share`
    ref-bumps an already-live block for a new holder (a request claiming
    a cached prefix, or the prefix index itself pinning a finished
    prefill's blocks), and :meth:`free` removes one holder — the block
    returns to the free list only when its refcount hits zero.  The
    ledger still makes aliasing structurally impossible: every free
    checks the caller actually holds the block, and a holder can never
    be added twice.  ``live_blocks`` counts DISTINCT live blocks, which
    is what makes the paged byte model charge a shared block once.
    """

    num_blocks: int
    block_size: int

    def __post_init__(self) -> None:
        if self.num_blocks < 2:
            raise ValueError("need >= 2 blocks (one is the trash block)")
        self._free: list[int] = sorted(range(self.num_blocks - 1),
                                       reverse=True)
        self._holders: dict[int, set[int]] = {}  # block id -> holder rids
        # invoked with the block id whenever a block's refcount hits 0
        # (the id is about to be re-handed-out and REWRITTEN) — the
        # spill tier uses this to invalidate its device->host content
        # dedup map the instant an association can go stale
        self.on_recycle = None

    @property
    def trash_block(self) -> int:
        return self.num_blocks - 1

    @property
    def capacity(self) -> int:
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def live_blocks(self) -> int:
        """DISTINCT live blocks — a block with N holders counts once."""
        return len(self._holders)

    def refcount(self, block: int) -> int:
        return len(self._holders.get(block, ()))

    def owned_by(self, rid: int) -> list[int]:
        return sorted(b for b, h in self._holders.items() if rid in h)

    def alloc(self, rid: int, n: int) -> list[int] | None:
        """``n`` fresh blocks for request ``rid``, lowest ids first — or
        None (and no state change) when the pool cannot satisfy it."""
        if n > len(self._free):
            return None
        got = [self._free.pop() for _ in range(n)]
        for b in got:
            self._holders[b] = {rid}
        return got

    def share(self, rid: int, blocks: list[int]) -> None:
        """Ref-bump live ``blocks`` for holder ``rid`` (the COW claim: a
        new request adopts a cached prefix without copying anything —
        the first write it would need into a shared block never happens,
        because the scheduler only shares FULL prompt blocks and routes
        every later write into privately allocated blocks)."""
        for b in blocks:
            holders = self._holders.get(b)
            if holders is None:
                raise ValueError(
                    f"request {rid} sharing dead block {b}")
            if rid in holders:
                raise ValueError(
                    f"request {rid} already holds block {b}")
        for b in blocks:
            self._holders[b].add(rid)

    def free(self, rid: int, blocks: list[int]) -> None:
        """Drop ``rid``'s hold on ``blocks``; a block is recycled only
        when its last holder lets go (refcount 0)."""
        for b in blocks:
            if rid not in self._holders.get(b, ()):
                raise ValueError(
                    f"request {rid} freeing block {b} it does not own "
                    f"(holders: {sorted(self._holders.get(b, ()))})")
        released = False
        for b in blocks:
            holders = self._holders[b]
            holders.discard(rid)
            if not holders:
                del self._holders[b]
                self._free.append(b)
                released = True
                if self.on_recycle is not None:
                    self.on_recycle(b)
        if released:
            self._free.sort(reverse=True)

    def stats(self) -> dict:
        """Occupancy snapshot for the metrics plane
        (``obs.metrics.absorb_pool``) — pure reads, no state change."""
        shared = sum(1 for h in self._holders.values() if len(h) > 1)
        return {
            "capacity": self.capacity,
            "free": len(self._free),
            "live": len(self._holders),
            "shared": shared,
            "holds": sum(len(h) for h in self._holders.values()),
        }

    def check_leaks(self) -> None:
        """Every block accounted for exactly once (the accounting test):
        free + distinct-live == capacity, nothing both free and live,
        and no live block with an empty holder set (a refcount leak)."""
        if len(self._free) + len(self._holders) != self.capacity:
            raise AssertionError(
                f"block leak: {len(self._free)} free + "
                f"{len(self._holders)} owned != {self.capacity}")
        if set(self._free) & set(self._holders):
            raise AssertionError("block aliased free AND owned")
        empty = [b for b, h in self._holders.items() if not h]
        if empty:
            raise AssertionError(
                f"refcount leak: live blocks with no holder: {empty}")


@dataclasses.dataclass
class BlockStore:
    """Host-RAM spill tier under the device :class:`BlockPool`.

    Where the pool hands out *ids into a device buffer*, the store holds
    the *payload itself*: one entry per spilled block, a list of numpy
    rows (one per cache-collection leaf — k, v, and the int8 scale rows
    when quantized) captured by a d2h copy at demotion time.  Holder
    semantics deliberately mirror the pool's refcounted ledger —
    :meth:`put` creates a block with one holder, :meth:`share` ref-bumps
    it for another (a COW-shared device block spills ONCE and its host
    copy is shared the same way), :meth:`free` drops a hold and deletes
    the payload at refcount 0 — so :meth:`check_leaks` can audit the two
    tiers with the same discipline.

    ``capacity`` bounds the number of live host blocks (``None`` =
    unbounded: host RAM is the big tier); a full store makes :meth:`put`
    return ``None`` and the caller falls back to the destructive path
    (re-prefill), never a wrong token.  Host ids are monotonically
    increasing and never recycled, which keeps every (id -> content)
    association unambiguous across a run.
    """

    capacity: int | None = None

    def __post_init__(self) -> None:
        if self.capacity is not None and self.capacity < 1:
            raise ValueError("BlockStore capacity must be >= 1 or None")
        self._next = 0
        self._payloads: dict[int, list[np.ndarray]] = {}
        self._holders: dict[int, set[int]] = {}

    def live_blocks(self) -> int:
        return len(self._payloads)

    def refcount(self, block: int) -> int:
        return len(self._holders.get(block, ()))

    def owned_by(self, rid: int) -> list[int]:
        return sorted(b for b, h in self._holders.items() if rid in h)

    def put(self, rid: int, payload: list[np.ndarray]) -> int | None:
        """Store one spilled block for holder ``rid``; returns the host
        block id, or None (no state change) when the store is full."""
        if self.capacity is not None and len(self._payloads) >= self.capacity:
            return None
        h = self._next
        self._next += 1
        self._payloads[h] = payload
        self._holders[h] = {rid}
        return h

    def get(self, block: int) -> list[np.ndarray]:
        payload = self._payloads.get(block)
        if payload is None:
            raise ValueError(f"reading dead host block {block}")
        return payload

    def share(self, rid: int, blocks: list[int]) -> None:
        """Ref-bump live host ``blocks`` for holder ``rid`` — the spill
        analogue of :meth:`BlockPool.share` (same validation)."""
        for b in blocks:
            holders = self._holders.get(b)
            if holders is None:
                raise ValueError(
                    f"request {rid} sharing dead host block {b}")
            if rid in holders:
                raise ValueError(
                    f"request {rid} already holds host block {b}")
        for b in blocks:
            self._holders[b].add(rid)

    def free(self, rid: int, blocks: list[int]) -> None:
        """Drop ``rid``'s hold; payload deleted at refcount 0."""
        for b in blocks:
            if rid not in self._holders.get(b, ()):
                raise ValueError(
                    f"request {rid} freeing host block {b} it does not "
                    f"own (holders: {sorted(self._holders.get(b, ()))})")
        for b in blocks:
            holders = self._holders[b]
            holders.discard(rid)
            if not holders:
                del self._holders[b]
                del self._payloads[b]

    def bytes_stored(self) -> int:
        return sum(sum(int(a.nbytes) for a in p)
                   for p in self._payloads.values())

    def stats(self) -> dict:
        """Occupancy snapshot for the metrics plane
        (``obs.metrics.absorb_spill_store``) — pure reads."""
        shared = sum(1 for h in self._holders.values() if len(h) > 1)
        return {
            "live": len(self._payloads),
            "shared": shared,
            "holds": sum(len(h) for h in self._holders.values()),
            "bytes": self.bytes_stored(),
        }

    def check_leaks(self) -> None:
        """Every payload has a holder set and vice versa, and no live
        host block has an empty holder set (a refcount leak)."""
        if set(self._payloads) != set(self._holders):
            raise AssertionError(
                f"host tier leak: payloads {sorted(self._payloads)} != "
                f"holders {sorted(self._holders)}")
        empty = [b for b, h in self._holders.items() if not h]
        if empty:
            raise AssertionError(
                f"host refcount leak: blocks with no holder: {empty}")


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` cache slots."""
    return -(-tokens // block_size)


def table_row(blocks: list[int], blocks_per_seq: int,
              trash: int) -> np.ndarray:
    """A request's table row: its physical blocks in logical order, the
    unallocated tail pointing at the trash block."""
    row = np.full((blocks_per_seq,), trash, np.int32)
    row[:len(blocks)] = np.asarray(blocks, np.int32)
    return row
