"""Continuous-batching serving engine over the paged KV pool.

Exactly TWO compiled programs serve every request mix, and neither ever
retraces as the population changes:

* ``serve_decode_step`` — all ``slots`` rows advance one token. Each
  slot feeds its pending token at its own write position (the ``(B,)``
  index vector), writes its k/v through its block table, and samples the
  next token with the request's position-derived key. Empty and
  mid-prefill slots ride along with all-trash tables: their writes land
  in the trash block, the causal mask zeroes whatever they read, and the
  host discards their samples.
* ``serve_prefill_chunk_step`` — ONE request advances by one
  ``prefill_chunk``-token chunk (B=1, static chunk width; the chunk is
  just a C>1 decode through the same ``_paged_decode_attend`` path).
  Long prompts stream through in chunks interleaved with decode steps,
  so admission never stalls resident streams for a whole prefill. The
  final chunk's sample at the prompt's last valid row IS the request's
  first generated token.

Both programs are pool -> pool: the cache pool is donated and returned,
so XLA aliases it in place (the state->state analogue of the one-shot
decode cache's scratch donation). Sampling keys derive from
(request rng, absolute position) — ``fold_in(rng, p)`` for the token at
position ``p`` — which makes every per-request stream bitwise identical
to a one-shot ``make_generate_fn`` run of that request alone, no matter
how scheduling interleaved it (the engine-vs-one-shot parity tests pin
this, greedy and sampled, across the decode levers).
"""

from __future__ import annotations

import dataclasses
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distributed_tensorflow_guide_tpu.models.generation import (
    _sample,
    decode_config,
    sample_rows,
)
from distributed_tensorflow_guide_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)
from distributed_tensorflow_guide_tpu.serve.paged_cache import table_row
from distributed_tensorflow_guide_tpu.serve.scheduler import (
    DECODE,
    PREFILL,
    Request,
    Scheduler,
)

__all__ = ["Event", "Request", "ServeEngine", "build_step_fns",
           "paged_cache_pool", "lint_contracts"]


@dataclasses.dataclass(frozen=True)
class Event:
    """One streamed token: ``first`` marks the request's first generated
    token (TTFT edge), ``done`` its completion."""

    time: float
    rid: int
    token: int
    first: bool
    done: bool


def paged_config(cfg: TransformerConfig, *, num_blocks: int,
                 block_size: int) -> TransformerConfig:
    """The serving view of a training config, paged flavour."""
    return dataclasses.replace(decode_config(cfg),
                               paged_num_blocks=num_blocks,
                               paged_block_size=block_size)


def paged_cache_shapes(pcfg: TransformerConfig, slots: int):
    """Abstract tree of the paged pool — derived from the model exactly
    like generation.cache_shapes, so the allocated pool can never drift
    from what the step programs trace. Pool leaves are (num_blocks, ...)
    — independent of the batch width, which is what lets the S-slot
    decode program and the B=1 prefill program share one pool."""
    model = Transformer(pcfg)
    n_blk = pcfg.max_len // pcfg.paged_block_size
    variables = jax.eval_shape(
        model.init, jax.random.PRNGKey(0),
        jnp.zeros((slots, 1), jnp.int32),
        jnp.zeros((slots,), jnp.int32),
        block_tables=jnp.zeros((slots, n_blk), jnp.int32))
    return variables["cache"]


def paged_cache_pool(pcfg: TransformerConfig, slots: int):
    """Allocate the zeroed block pool."""
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        paged_cache_shapes(pcfg, slots))


_STEP_FNS = {}


def build_step_fns(cfg: TransformerConfig, *, slots: int, num_blocks: int,
                   block_size: int, prefill_chunk: int,
                   temperature: float = 0.0, top_k: int | None = None):
    """Build the two jitted step programs (shared by the engine and the
    lint contracts, so what the linter audits is what serves).

    Memoized on everything that reaches the trace: config (which carries
    the pool geometry), sampling knobs, and the donation gate. ``slots``
    and ``prefill_chunk`` deliberately do NOT key the memo — the jitted
    programs shape-specialize on their arguments, so engines that differ
    only in slot count or chunk width share one traced pair, and
    spinning an engine up with a geometry already served compiles
    nothing at all."""
    donate = jax.default_backend() != "cpu"
    memo_key = (cfg, num_blocks, block_size, temperature, top_k, donate)
    hit = _STEP_FNS.get(memo_key)
    if hit is not None:
        return hit
    pcfg = paged_config(cfg, num_blocks=num_blocks, block_size=block_size)
    model = Transformer(pcfg)
    n_blk = pcfg.max_len // block_size

    def decode_step(params, pool, tables, written, last_tok, keys):
        """(S,) tokens in, (S,) tokens out; pool threaded state->state."""
        logits, mut = model.apply(
            {"params": params, "cache": pool},
            last_tok[:, None], written, block_tables=tables,
            mutable=["cache"])
        pos_keys = jax.vmap(jax.random.fold_in)(keys, written + 1)
        nxt = sample_rows(logits[:, -1], pos_keys, temperature, top_k)
        return nxt, mut["cache"]

    def prefill_chunk_step(params, pool, tables, start, chunk, valid, key):
        """One (1, prefill_chunk) slice of one prompt. ``valid`` is how
        many rows of the chunk are real prompt (the rest are pads whose
        writes land inside the admitted blocks and are either overwritten
        by decode before anything attends them, or masked forever);
        the returned sample comes from row ``valid - 1`` with the key
        for absolute position ``start + valid`` — on the final chunk
        that is exactly the one-shot prefill sample at position P."""
        logits, mut = model.apply(
            {"params": params, "cache": pool},
            chunk, start, block_tables=tables, mutable=["cache"])
        last = lax.dynamic_index_in_dim(logits[0], valid - 1, axis=0,
                                        keepdims=False)
        tok = _sample(last[None], jax.random.fold_in(key, start[0] + valid),
                      temperature, top_k)[0]
        return tok, mut["cache"]

    # donation intent is (1,) — the pool — for both programs; the CPU
    # backend doesn't implement input-output aliasing, same gate as
    # make_generate_fn
    decode_jit = jax.jit(decode_step,
                         donate_argnums=(1,) if donate else ())
    prefill_jit = jax.jit(prefill_chunk_step,
                          donate_argnums=(1,) if donate else ())
    fns = SimpleNamespace(
        decode=decode_jit, prefill=prefill_jit, model=model, cfg=pcfg,
        n_blk=n_blk, declared_donate_argnums=(1,), donates_pool=donate,
        temperature=temperature, top_k=top_k)
    _STEP_FNS[memo_key] = fns
    return fns


class ServeEngine:
    """The serving loop: host scheduling around the two static programs.

    >>> eng = ServeEngine(cfg, params, slots=4, num_blocks=33,
    ...                   block_size=8, prefill_chunk=16)
    >>> eng.submit(Request(rid=0, prompt=toks, max_new_tokens=16,
    ...                    rng=jax.random.PRNGKey(0), arrival=0.0))
    >>> events = eng.run()          # drain everything (virtual time)
    >>> eng.completions()[0]        # the request's generated tokens
    """

    def __init__(self, cfg: TransformerConfig, params, *, slots: int,
                 num_blocks: int, block_size: int, prefill_chunk: int,
                 temperature: float = 0.0, top_k: int | None = None):
        self.fns = build_step_fns(
            cfg, slots=slots, num_blocks=num_blocks,
            block_size=block_size, prefill_chunk=prefill_chunk,
            temperature=temperature, top_k=top_k)
        self.params = params
        self.num_slots = slots
        self.sched = Scheduler(
            slots=slots, num_blocks=num_blocks, block_size=block_size,
            prefill_chunk=prefill_chunk, max_len=self.fns.cfg.max_len)
        self.pool = paged_cache_pool(self.fns.cfg, slots)
        self._trash_row = table_row(
            [], self.fns.n_blk, self.sched.pool.trash_block)
        self.steps = {"decode": 0, "prefill": 0, "idle": 0}

    # ---- intake ----------------------------------------------------------

    def submit(self, req: Request) -> None:
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        if prompt.size and int(prompt.max()) >= self.fns.cfg.vocab_size:
            raise ValueError("prompt token out of vocabulary")
        self.sched.submit(dataclasses.replace(
            req, prompt=prompt, rng=np.asarray(req.rng, np.uint32)))

    # ---- the tick --------------------------------------------------------

    def step(self, now: float = 0.0) -> tuple[list[Event], str]:
        """Admit arrived requests, launch (at most) one program, apply
        its results. Returns (events, kind) with kind in
        {"prefill", "decode", "idle"} — the bench times this call to get
        per-launch service time."""
        self.sched.admit(now)
        kind, arg = self.sched.plan()
        if kind == PREFILL:
            events = self._run_prefill(arg, now)
        elif kind == DECODE:
            events = self._run_decode(arg, now)
        else:
            events = []
        self.steps[kind] += 1
        return events, kind

    def _run_prefill(self, i: int, now: float) -> list[Event]:
        s = self.sched.slots[i]
        CH = self.sched.prefill_chunk
        start = s.chunk_cursor * CH
        valid = min(CH, len(s.prompt) - start)
        chunk = np.zeros((1, CH), np.int32)
        chunk[0, :valid] = s.prompt[start:start + valid]
        tables = table_row(s.blocks, self.fns.n_blk,
                           self.sched.pool.trash_block)[None]
        tok, self.pool = self.fns.prefill(
            self.params, self.pool, jnp.asarray(tables),
            jnp.full((1,), start, jnp.int32), jnp.asarray(chunk),
            jnp.int32(valid), jnp.asarray(s.rng))
        return [Event(now, *ev) for ev in
                self.sched.apply_prefill(i, int(tok))]

    def _run_decode(self, ready: list[int], now: float) -> list[Event]:
        S, n_blk = self.num_slots, self.fns.n_blk
        tables = np.tile(self._trash_row, (S, 1))
        written = np.zeros((S,), np.int32)
        last_tok = np.zeros((S,), np.int32)
        keys = np.zeros((S, 2), np.uint32)
        for i in ready:
            s = self.sched.slots[i]
            tables[i] = table_row(s.blocks, n_blk,
                                  self.sched.pool.trash_block)
            written[i] = s.written
            last_tok[i] = s.pending
            keys[i] = s.rng
        nxt, self.pool = self.fns.decode(
            self.params, self.pool, jnp.asarray(tables),
            jnp.asarray(written), jnp.asarray(last_tok),
            jnp.asarray(keys))
        nxt = np.asarray(nxt)
        events = []
        for i in ready:
            events.extend(Event(now, *ev) for ev in
                          self.sched.apply_decode(i, int(nxt[i])))
        return events

    # ---- drain -----------------------------------------------------------

    def run(self, max_ticks: int | None = None) -> list[Event]:
        """Drain all submitted work ignoring arrival times (tick clock).
        The load bench drives :meth:`step` itself with a virtual clock
        instead."""
        events: list[Event] = []
        ticks = 0
        while self.sched.has_queued or self.sched.has_resident:
            evs, kind = self.step(now=float("inf"))
            events.extend(evs)
            if kind == "idle":
                raise RuntimeError(
                    "engine deadlock: work queued but nothing schedulable")
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
        return events

    def completions(self) -> dict[int, list[int]]:
        """rid -> every token emitted so far (complete or not)."""
        return {rid: list(toks)
                for rid, toks in self.sched.emitted.items()}

    def live_blocks(self) -> int:
        """Blocks currently owned by resident requests — what the paged
        byte model charges a decode step for (vs. max_len always)."""
        return self.sched.pool.live_blocks()


# ---- program contracts (analysis/) ------------------------------------------


def lint_contracts():
    """Contracts for the two serving entry programs.

    Collective-free (strict empty census: the engine is pure SPMD under
    DP/TP sharding — a stray psum would deadlock a replicated server),
    host-callback-free, pool donated in ``alias`` mode (the pool is
    state->state: every donated leaf must come back out, which is the
    in-place-update guarantee; this is the serving analogue of the
    one-shot cache's scratch donation — the ISSUE's "scratch-donated
    pool" — expressed for a buffer the host threads between ticks), and
    a hard ceiling on the largest f32 intermediate that sits BELOW the
    size of a full-``max_len`` f32 score tensor — the lint fails if
    anyone reintroduces dense (slots, heads, chunk, max_len) attention
    scores into the compiled serve path."""
    from distributed_tensorflow_guide_tpu.analysis.contracts import (
        DonationSpec,
        ProgramContract,
    )

    # fixture geometry: chosen so every legitimate f32 intermediate
    # (largest: one updated pool leaf, num_blocks*heads*block*head_dim =
    # 5*2*8*8 = 640 elems) fits under the cap while a dense f32 score
    # tensor (decode: slots*heads*1*max_len = 2048; prefill chunk:
    # 1*heads*chunk*max_len = 4096) would blow through it
    S, NB, BS, CH, MAXLEN = 4, 5, 8, 8, 256
    F32_CAP = 1024

    def _build(kind):
        def _b():
            from distributed_tensorflow_guide_tpu.analysis.fixtures import (
                tiny_lm_cfg,
            )

            cfg = dataclasses.replace(
                tiny_lm_cfg(vocab_size=32, max_len=MAXLEN),
                decode_impl="pallas")
            fns = build_step_fns(cfg, slots=S, num_blocks=NB,
                                 block_size=BS, prefill_chunk=CH)
            params = jax.eval_shape(
                lambda p: fns.model.init(
                    jax.random.PRNGKey(0), p,
                    jnp.zeros((S,), jnp.int32),
                    block_tables=jnp.zeros((S, fns.n_blk), jnp.int32)),
                jax.ShapeDtypeStruct((S, 1), "int32"))["params"]
            pool = jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype),
                paged_cache_shapes(fns.cfg, S))
            i32 = "int32"
            if kind == "decode":
                args = (params, pool,
                        jax.ShapeDtypeStruct((S, fns.n_blk), i32),
                        jax.ShapeDtypeStruct((S,), i32),
                        jax.ShapeDtypeStruct((S,), i32),
                        jax.ShapeDtypeStruct((S, 2), "uint32"))
                return fns.decode, args
            args = (params, pool,
                    jax.ShapeDtypeStruct((1, fns.n_blk), i32),
                    jax.ShapeDtypeStruct((1,), i32),
                    jax.ShapeDtypeStruct((1, CH), i32),
                    jax.ShapeDtypeStruct((), i32),
                    jax.ShapeDtypeStruct((2,), "uint32"))
            return fns.prefill, args

        return _b

    common = dict(
        policy="f32",
        collectives={},  # strict: the serve programs are collective-free
        max_f32_intermediate_elems=F32_CAP,
        donation=DonationSpec(argnums=(1,), mode="alias"),
        sources=("distributed_tensorflow_guide_tpu.serve.engine",
                 "distributed_tensorflow_guide_tpu.serve.paged_cache",
                 "distributed_tensorflow_guide_tpu.models.transformer"),
    )
    return [
        ProgramContract(
            name="serve_decode_step",
            build=_build("decode"),
            notes="fixed-slot paged decode: pool aliased in place, no "
                  "full-max_len f32 score tensor",
            **common),
        ProgramContract(
            name="serve_prefill_chunk_step",
            build=_build("prefill"),
            notes="B=1 chunked prefill through the same attention path",
            **common),
    ]
