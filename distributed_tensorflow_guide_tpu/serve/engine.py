"""Continuous-batching serving engine over the paged KV pool.

Exactly TWO compiled programs serve every request mix, and neither ever
retraces as the population changes:

* ``serve_decode_step`` — all ``slots`` rows advance one token. Each
  slot feeds its pending token at its own write position (the ``(B,)``
  index vector), writes its k/v through its block table, and samples the
  next token with the request's position-derived key. Empty and
  mid-prefill slots ride along with all-trash tables: their writes land
  in the trash block, the causal mask zeroes whatever they read, and the
  host discards their samples.
* ``serve_prefill_chunk_step`` — ONE request advances by one
  ``prefill_chunk``-token chunk (B=1, static chunk width; the chunk is
  just a C>1 decode through the same ``_paged_decode_attend`` path).
  Long prompts stream through in chunks interleaved with decode steps,
  so admission never stalls resident streams for a whole prefill. The
  final chunk's sample at the prompt's last valid row IS the request's
  first generated token.

Both programs are pool -> pool: the cache pool is donated and returned,
so XLA aliases it in place (the state->state analogue of the one-shot
decode cache's scratch donation). Sampling keys derive from
(request rng, absolute position) — ``fold_in(rng, p)`` for the token at
position ``p`` — which makes every per-request stream bitwise identical
to a one-shot ``make_generate_fn`` run of that request alone, no matter
how scheduling interleaved it (the engine-vs-one-shot parity tests pin
this, greedy and sampled, across the decode levers).

Serving under fire (PR 11) — the same position-derived keys are what
make every recovery path *bitwise-safe*:

* a transient launch failure (injected ``serve_step_exception`` or a
  real one) is retried through the shared ``retry_with_backoff`` — the
  tick's inputs are rebuilt from host state, so the re-run IS the
  original tick;
* a hung compiled step becomes :class:`WatchdogTimeout` (pass
  ``step_deadline_s``) instead of a silent stall, and retries like any
  transient;
* cancellation / TTFT / total deadlines are swept at step boundaries
  (``Scheduler.sweep``) — slot+blocks free with ``check_leaks`` clean;
* overload is refused at the door (queue-depth gate in the scheduler,
  predicted-TTFT gate here) with the retriable
  :class:`EngineOverloaded`;
* :meth:`ServeEngine.save_snapshot` serializes all HOST state through
  the manifested/CRC-verified checkpoint path; a killed engine
  restores the newest valid snapshot and every in-flight stream
  continues bitwise identical to an uninterrupted run — the block pool
  is never saved, residents simply re-prefill (the preemption path).
"""

from __future__ import annotations

import dataclasses
import glob
import io as _io
import json
import os
import time
import zlib
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distributed_tensorflow_guide_tpu.core.dist import retry_with_backoff
from distributed_tensorflow_guide_tpu.models.generation import (
    _sample,
    decode_config,
    sample_rows,
)
from distributed_tensorflow_guide_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)
from distributed_tensorflow_guide_tpu.obs import events as obs_events
from distributed_tensorflow_guide_tpu.serve.paged_cache import (
    BlockStore,
    table_row,
)
from distributed_tensorflow_guide_tpu.serve.prefix_index import CACHE_RID
from distributed_tensorflow_guide_tpu.serve.scheduler import (
    DECODE,
    PREFILL,
    EngineOverloaded,
    Request,
    Scheduler,
)
from distributed_tensorflow_guide_tpu.utils.watchdog import (
    Watchdog,
    WatchdogTimeout,
)

__all__ = ["Event", "Request", "ServeEngine", "EngineOverloaded",
           "WatchdogTimeout", "build_step_fns", "paged_cache_pool",
           "adapter_bank_shapes", "init_adapter_bank", "lint_contracts"]

# pool-pressure chaos faults allocate under this reserved owner id (real
# rids are non-negative) and release after this many engine ticks
_CHAOS_RID = -7
_PRESSURE_HOLD_TICKS = 4


@dataclasses.dataclass(frozen=True)
class Event:
    """One streamed token: ``first`` marks the request's first generated
    token (TTFT edge), ``done`` its completion. Terminal lifecycle events
    (cancellation, deadline breach) carry ``token == -1``, ``done=True``
    and ``status`` in {"cancelled", "expired"}; real tokens are
    ``status == "ok"``."""

    time: float
    rid: int
    token: int
    first: bool
    done: bool
    status: str = "ok"


def paged_config(cfg: TransformerConfig, *, num_blocks: int,
                 block_size: int) -> TransformerConfig:
    """The serving view of a training config, paged flavour."""
    return dataclasses.replace(decode_config(cfg),
                               paged_num_blocks=num_blocks,
                               paged_block_size=block_size)


def paged_cache_shapes(pcfg: TransformerConfig, slots: int):
    """Abstract tree of the paged pool — derived from the model exactly
    like generation.cache_shapes, so the allocated pool can never drift
    from what the step programs trace. Pool leaves are (num_blocks, ...)
    — independent of the batch width, which is what lets the S-slot
    decode program and the B=1 prefill program share one pool."""
    model = Transformer(pcfg)
    n_blk = pcfg.max_len // pcfg.paged_block_size
    variables = jax.eval_shape(
        model.init, jax.random.PRNGKey(0),
        jnp.zeros((slots, 1), jnp.int32),
        jnp.zeros((slots,), jnp.int32),
        block_tables=jnp.zeros((slots, n_blk), jnp.int32))
    return variables["cache"]


def paged_cache_pool(pcfg: TransformerConfig, slots: int):
    """Allocate the zeroed block pool."""
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        paged_cache_shapes(pcfg, slots))


def adapter_bank_shapes(cfg: TransformerConfig):
    """Abstract tree of the multi-LoRA (A, B) delta banks (the flax
    "adapters" collection) — derived from the model exactly like the
    pool so user-supplied banks can never drift from what the step
    programs trace. Bank shapes are independent of slots/paging (each
    site is ``(lora_adapters + 1, d_in, rank)`` x ``(..., rank, d_out)``),
    so any config with the same lora geometry yields the same tree.
    Requires ``cfg.lora_rank``."""
    if cfg.lora_rank is None:
        raise ValueError("adapter_bank_shapes requires cfg.lora_rank")
    model = Transformer(cfg)
    variables = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                               jnp.zeros((1, 1), jnp.int32))
    return variables["adapters"]


def init_adapter_bank(cfg: TransformerConfig):
    """A zeroed adapter bank: every id (including every non-zero one)
    starts bitwise-base until its rows are written."""
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        adapter_bank_shapes(cfg))


@jax.jit
def _pool_gather(pool, idx):
    """KV spill d2h: rows ``idx`` of every pool leaf, ONE dispatch for
    the whole tree.  Not a step program — jit-cached per (pool shapes,
    idx width); the engine pads every batch to a multiple of 8 so only
    one width ever compiles, at init-warmup time."""
    return [leaf[idx] for leaf in jax.tree.leaves(pool)]


@jax.jit
def _pool_scatter(pool, idx, rows):
    """KV spill h2d: write ``rows[i]`` into leaf ``i`` at ``idx``, ONE
    dispatch for the whole tree.  Functional — the donated pool the
    step programs alias is never mutated in place.  Duplicate indices
    (the trash-block padding) all carry identical rows, so the scatter
    stays deterministic."""
    leaves, treedef = jax.tree.flatten(pool)
    return jax.tree.unflatten(
        treedef, [leaf.at[idx].set(r.astype(leaf.dtype))
                  for leaf, r in zip(leaves, rows)])


def _moe_fold(stats):
    """Fold the model's per-layer ``moe_stats`` sow tree into
    ``(load (E,), overflow (E,), overflow_tok (T,))`` — each summed over
    layers (sow appends one tuple entry per MoEMLP site). Runs inside the
    jitted step, so the engine gets three small arrays back instead of a
    nested per-block tree."""
    import collections.abc as _abc

    load, overflow, of_tok = [], [], []

    def walk(node):
        if isinstance(node, _abc.Mapping):
            if "load" in node and "overflow" in node:
                load.extend(node["load"])
                overflow.extend(node["overflow"])
                of_tok.extend(node["overflow_tok"])
            else:
                for k in sorted(node):
                    walk(node[k])

    walk(stats)
    return sum(load), sum(overflow), sum(of_tok)


_STEP_FNS = {}


def build_step_fns(cfg: TransformerConfig, *, slots: int, num_blocks: int,
                   block_size: int, prefill_chunk: int,
                   temperature: float = 0.0, top_k: int | None = None):
    """Build the two jitted step programs (shared by the engine and the
    lint contracts, so what the linter audits is what serves).

    Memoized on everything that reaches the trace: config (which carries
    the pool geometry), sampling knobs, and the donation gate. ``slots``
    and ``prefill_chunk`` deliberately do NOT key the memo — the jitted
    programs shape-specialize on their arguments, so engines that differ
    only in slot count or chunk width share one traced pair, and
    spinning an engine up with a geometry already served compiles
    nothing at all."""
    donate = jax.default_backend() != "cpu"
    memo_key = (cfg, num_blocks, block_size, temperature, top_k, donate)
    hit = _STEP_FNS.get(memo_key)
    if hit is not None:
        return hit
    pcfg = paged_config(cfg, num_blocks=num_blocks, block_size=block_size)
    model = Transformer(pcfg)
    n_blk = pcfg.max_len // block_size
    lora = pcfg.lora_rank is not None
    moe = pcfg.moe

    if lora:
        # still exactly two jitted programs: the LoRA engine's pair takes
        # two extra operands — the shared (A, B) delta banks and the
        # per-slot adapter-id vector — and every slot's delta is gathered
        # by id inside the one compiled step (no per-adapter programs)
        def decode_step(params, pool, tables, written, last_tok, keys,
                        adapters, adapter_ids):
            logits, mut = model.apply(
                {"params": params, "cache": pool, "adapters": adapters},
                last_tok[:, None], written, block_tables=tables,
                adapter=adapter_ids, mutable=["cache"])
            pos_keys = jax.vmap(jax.random.fold_in)(keys, written + 1)
            nxt = sample_rows(logits[:, -1], pos_keys, temperature, top_k)
            return nxt, mut["cache"]

        def prefill_chunk_step(params, pool, tables, start, chunk, valid,
                               key, adapters, adapter_ids):
            logits, mut = model.apply(
                {"params": params, "cache": pool, "adapters": adapters},
                chunk, start, block_tables=tables,
                adapter=adapter_ids, mutable=["cache"])
            last = lax.dynamic_index_in_dim(logits[0], valid - 1, axis=0,
                                            keepdims=False)
            tok = _sample(last[None],
                          jax.random.fold_in(key, start[0] + valid),
                          temperature, top_k)[0]
            return tok, mut["cache"]
    elif moe:
        # still exactly two jitted programs: the MoE pair runs the router
        # dispatch INSIDE the step (mutable=["moe_stats"] so the sown
        # census comes back) and returns the per-slot overflow flags the
        # engine's stall-and-retry loop consumes. Idle slots are masked
        # out of routing (written == 0), so a garbage slot can never
        # consume a capacity seat a live slot needs.
        def decode_step(params, pool, tables, written, last_tok, keys):
            logits, mut = model.apply(
                {"params": params, "cache": pool},
                last_tok[:, None], written, block_tables=tables,
                moe_mask=written > 0, mutable=["cache", "moe_stats"])
            load, overflow, of_tok = _moe_fold(mut["moe_stats"])
            pos_keys = jax.vmap(jax.random.fold_in)(keys, written + 1)
            nxt = sample_rows(logits[:, -1], pos_keys, temperature, top_k)
            return nxt, mut["cache"], of_tok > 0, load, overflow

        def prefill_chunk_step(params, pool, tables, start, chunk, valid,
                               key):
            # the dispatch buffer widens to the chunk length (MoEMLP:
            # multi-token calls are dropless by construction), so a
            # prefill chunk can never overflow — only pad rows past
            # ``valid`` are masked out of the census
            mask = (jnp.arange(chunk.shape[1]) < valid)[None, :]
            logits, mut = model.apply(
                {"params": params, "cache": pool},
                chunk, start, block_tables=tables,
                moe_mask=mask, mutable=["cache", "moe_stats"])
            load, overflow, _ = _moe_fold(mut["moe_stats"])
            last = lax.dynamic_index_in_dim(logits[0], valid - 1, axis=0,
                                            keepdims=False)
            tok = _sample(last[None],
                          jax.random.fold_in(key, start[0] + valid),
                          temperature, top_k)[0]
            return tok, mut["cache"], load, overflow
    else:
        def decode_step(params, pool, tables, written, last_tok, keys):
            """(S,) tokens in, (S,) tokens out; pool threaded
            state->state."""
            logits, mut = model.apply(
                {"params": params, "cache": pool},
                last_tok[:, None], written, block_tables=tables,
                mutable=["cache"])
            pos_keys = jax.vmap(jax.random.fold_in)(keys, written + 1)
            nxt = sample_rows(logits[:, -1], pos_keys, temperature, top_k)
            return nxt, mut["cache"]

        def prefill_chunk_step(params, pool, tables, start, chunk, valid,
                               key):
            """One (1, prefill_chunk) slice of one prompt. ``valid`` is
            how many rows of the chunk are real prompt (the rest are pads
            whose writes land inside the admitted blocks and are either
            overwritten by decode before anything attends them, or masked
            forever); the returned sample comes from row ``valid - 1``
            with the key for absolute position ``start + valid`` — on the
            final chunk that is exactly the one-shot prefill sample at
            position P."""
            logits, mut = model.apply(
                {"params": params, "cache": pool},
                chunk, start, block_tables=tables, mutable=["cache"])
            last = lax.dynamic_index_in_dim(logits[0], valid - 1, axis=0,
                                            keepdims=False)
            tok = _sample(last[None],
                          jax.random.fold_in(key, start[0] + valid),
                          temperature, top_k)[0]
            return tok, mut["cache"]

    # donation intent is (1,) — the pool — for both programs; the CPU
    # backend doesn't implement input-output aliasing, same gate as
    # make_generate_fn
    decode_jit = jax.jit(decode_step,
                         donate_argnums=(1,) if donate else ())
    prefill_jit = jax.jit(prefill_chunk_step,
                          donate_argnums=(1,) if donate else ())
    fns = SimpleNamespace(
        decode=decode_jit, prefill=prefill_jit, model=model, cfg=pcfg,
        n_blk=n_blk, declared_donate_argnums=(1,), donates_pool=donate,
        temperature=temperature, top_k=top_k, lora=lora, moe=moe)
    _STEP_FNS[memo_key] = fns
    return fns


class ServeEngine:
    """The serving loop: host scheduling around the two static programs.

    >>> eng = ServeEngine(cfg, params, slots=4, num_blocks=33,
    ...                   block_size=8, prefill_chunk=16)
    >>> eng.submit(Request(rid=0, prompt=toks, max_new_tokens=16,
    ...                    rng=jax.random.PRNGKey(0), arrival=0.0))
    >>> events = eng.run()          # drain everything (virtual time)
    >>> eng.completions()[0]        # the request's generated tokens
    """

    def __init__(self, cfg: TransformerConfig, params, *, slots: int,
                 num_blocks: int, block_size: int, prefill_chunk: int,
                 temperature: float = 0.0, top_k: int | None = None,
                 max_queue: int | None = None,
                 chaos=None, burst_factory=None,
                 step_deadline_s: float | None = None,
                 retry_attempts: int = 3,
                 retry_base_delay_s: float = 0.05,
                 snapshot_dir=None, snapshot_keep: int = 3,
                 prefix_cache: bool = False,
                 host_blocks: int = 0, persist_cache: bool = False,
                 tenant_quotas=None, drr_quantum: int | None = None,
                 adapters=None, recorder=None,
                 online_tune: bool | None = None):
        # online in-situ autotuning (round 21): True/False set the
        # process-wide override (the tuning table is process state, so
        # the knob is too — autotune.set_online_tune), None inherits the
        # DTG_ONLINE_TUNE env gate. On a sweep-capable backend the first
        # trace of an unseen (kernel, shape, dtype, device_kind) key then
        # pays one bounded sweep during warmup instead of falling back to
        # defaults; on CPU this is always a no-op (hermeticity contract).
        if online_tune is not None:
            from distributed_tensorflow_guide_tpu.ops import autotune
            autotune.set_online_tune(online_tune)
        if cfg.weight_dtype == "fp8":
            from distributed_tensorflow_guide_tpu.core.precision import (
                require_fp8,
            )
            require_fp8()
        self.fns = build_step_fns(
            cfg, slots=slots, num_blocks=num_blocks,
            block_size=block_size, prefill_chunk=prefill_chunk,
            temperature=temperature, top_k=top_k)
        self.params = params
        self.num_slots = slots
        # cache hierarchy (PR 16): host_blocks > 0 attaches a host-RAM
        # spill tier of that many blocks under the device pool —
        # preemption and trie eviction demote instead of destroy, and
        # the scheduler swaps demoted blocks back in (prefetched ahead
        # of admission). 0 = off: byte-identical to the pool-only
        # engine. The swap path is ENTIRELY host-side eager copies —
        # it never touches the two compiled step programs.
        if host_blocks < 0:
            raise ValueError(f"host_blocks must be >= 0, got {host_blocks}")
        if persist_cache:
            if snapshot_dir is None:
                raise ValueError(
                    "persist_cache requires ServeEngine(snapshot_dir=...)")
            if not prefix_cache:
                raise ValueError(
                    "persist_cache requires prefix_cache=True (the trie "
                    "is what indexes the persisted blocks)")
            if not host_blocks:
                raise ValueError(
                    "persist_cache requires host_blocks > 0 (restored "
                    "cache contents land in the host tier)")
        self.persist_cache = bool(persist_cache)
        self.store = (BlockStore(capacity=host_blocks) if host_blocks
                      else None)
        # observability (PR 14): strictly observe-only. Resolved ONCE
        # here; every emission site guards on ``rec.enabled`` so a
        # disabled recorder costs one attribute check per site
        # (benchmarks/bench_obs.py pins the overhead), and nothing the
        # recorder sees ever feeds a compiled program (the bitwise
        # recorder-on/off parity tests pin that).
        self.rec = recorder if recorder is not None else obs_events.current()
        self.sched = Scheduler(
            slots=slots, num_blocks=num_blocks, block_size=block_size,
            prefill_chunk=prefill_chunk, max_len=self.fns.cfg.max_len,
            max_queue=max_queue, prefix_cache=prefix_cache,
            tenant_quotas=tenant_quotas, drr_quantum=drr_quantum,
            host_store=self.store,
            cache_io=(SimpleNamespace(d2h=self._cache_d2h,
                                      d2h_many=self._cache_d2h_many,
                                      h2d=self._cache_h2d,
                                      h2d_many=self._cache_h2d_many)
                      if self.store is not None else None),
            recorder=self.rec)
        if self.fns.lora:
            # the bank is a jit-operand (not a closed-over constant):
            # swapping adapter weights never retraces the two programs
            self.adapters = jax.tree.map(
                jnp.asarray,
                adapters if adapters is not None
                else init_adapter_bank(self.fns.cfg))
        elif adapters is not None:
            raise ValueError(
                "ServeEngine(adapters=...) requires cfg.lora_rank")
        else:
            self.adapters = None
        self.pool = paged_cache_pool(self.fns.cfg, slots)
        self._trash_row = table_row(
            [], self.fns.n_blk, self.sched.pool.trash_block)
        if self.store is not None:
            # warm the d2h/h2d transfer path (the fused gather/scatter
            # programs compile once per pool geometry at their single
            # padded width): a roundtrip through the trash block —
            # scratch by design, and the write-back restores its
            # bytes — so the first REAL swap isn't charged XLA
            # compiles mid-serve
            trash = self.sched.pool.trash_block
            self._cache_h2d(trash, self._cache_d2h(trash))
        self.steps = {"decode": 0, "prefill": 0, "idle": 0}
        # MoE serving census (observe-only, absorbed by obs/metrics):
        # per-expert token load / overflow counts summed over launches
        # and layers, plus the stall tally of the degrade-to-overflow
        # retry loop (a stalled slot-tick is one discarded sample)
        if self.fns.moe:
            n_e = self.fns.cfg.moe_experts
            self._moe_load = np.zeros((n_e,), np.int64)
            self._moe_overflow = np.zeros((n_e,), np.int64)
            self._moe_stall_slot_ticks = 0
            self._moe_stall_ticks = 0
        # failure hardening (PR 11)
        self.chaos = chaos  # a testing.chaos.FaultSchedule (or None)
        self.burst_factory = burst_factory  # (n, now) -> [Request]
        self.retry_attempts = retry_attempts
        self.retry_base_delay_s = retry_base_delay_s
        self._injected_exc = 0  # pending chaos launch failures
        # every failed launch ATTEMPT (retried-and-recovered ones
        # included) — what the fleet breaker and dtg_serve metrics read
        self.launch_failures = 0
        self._pressure_holds: list[tuple[float, list[int]]] = []
        self._tick = 0
        self._ttft_ewma: float | None = None  # predicted-TTFT shed gate
        self.last_tick_s = 0.0
        self._step_deadline_s = step_deadline_s
        self._watchdog = (Watchdog(name="serve-engine",
                                   recorder=self.rec)
                          if step_deadline_s else None)
        self.snapshot_dir = snapshot_dir
        self._ckpt = None
        self._last_snap = -1
        if snapshot_dir is not None:
            # lazy import: orbax only loads when snapshots are in play
            from distributed_tensorflow_guide_tpu.train.checkpoint import (
                Checkpointer,
            )
            self._ckpt = Checkpointer(snapshot_dir,
                                      max_to_keep=snapshot_keep)

    # ---- intake ----------------------------------------------------------

    def submit(self, req: Request) -> None:
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        if prompt.size and int(prompt.max()) >= self.fns.cfg.vocab_size:
            raise ValueError("prompt token out of vocabulary")
        if self.fns.lora:
            if not 0 <= req.adapter <= self.fns.cfg.lora_adapters:
                raise ValueError(
                    f"request {req.rid} adapter {req.adapter} out of "
                    f"range [0, {self.fns.cfg.lora_adapters}]")
        elif req.adapter != 0:
            raise ValueError(
                f"request {req.rid} names adapter {req.adapter} but the "
                "engine config has no lora_rank")
        # predicted-SLO gate: if recent TTFTs already blow this request's
        # TTFT budget, admitting it is a guaranteed miss that would ALSO
        # push every queued request further out — shed at the door
        # instead (retriable; nothing recorded). Queue-depth shedding
        # lives in Scheduler.submit behind max_queue.
        if (req.ttft_deadline_s is not None
                and self._ttft_ewma is not None
                and self._ttft_ewma > req.ttft_deadline_s):
            self.sched.shed += 1
            if self.rec.enabled:
                self.rec.emit(
                    "req.shed", cat="serve", actor="engine",
                    payload={"rid": req.rid, "reason": "ttft",
                             "tenant": int(req.tenant),
                             "ttft_s": self._ttft_ewma},
                    t=float(req.arrival))
            raise EngineOverloaded(
                f"request {req.rid} shed: recent TTFT "
                f"{self._ttft_ewma:.3f}s exceeds its "
                f"{req.ttft_deadline_s:.3f}s deadline — retry later")
        self.sched.submit(dataclasses.replace(
            req, prompt=prompt, rng=np.asarray(req.rng, np.uint32)))
        if self.rec.enabled:
            self.rec.emit(
                "req.submit", cat="serve", actor="engine",
                payload={"rid": req.rid, "tenant": int(req.tenant),
                         "adapter": int(req.adapter),
                         "prompt_len": int(prompt.size),
                         "max_new": int(req.max_new_tokens)},
                t=float(req.arrival))

    def cancel(self, rid: int) -> bool:
        """Client abandon: free the stream's slot+blocks at the next step
        boundary. Returns False for unknown/already-terminal rids."""
        return self.sched.cancel(rid)

    # ---- cache hierarchy io (PR 16) --------------------------------------

    def _cache_d2h(self, block: int) -> list[np.ndarray]:
        """Copy one pool block's rows to host — one numpy array per
        cache-collection leaf (k, v, and the int8 scale rows when
        quantized), in ``jax.tree.leaves`` order.  Routed through the
        batch path so even a single-block spill costs ONE dispatch."""
        return self._cache_d2h_many([block])[0]

    def _cache_d2h_many(self, blocks: list[int]) -> list[list[np.ndarray]]:
        """Copy several pool blocks' rows to host in ONE
        :func:`_pool_gather` dispatch for the whole tree.  The batch is
        padded to a multiple of 8 with trash-block rows (dropped before
        returning) so the gather compiles at ONE width — warmed at
        engine init, never mid-serve.  Rows are copied out of the
        stacked result so the payloads the host store retains don't pin
        the padded buffer."""
        n = len(blocks)
        pad = -(-n // 8) * 8 - n
        trash = self.sched.pool.trash_block
        idx = jnp.asarray(list(blocks) + [trash] * pad)
        stacked = [np.asarray(s) for s in _pool_gather(self.pool, idx)]
        return [[s[j].copy() for s in stacked] for j in range(n)]

    def _cache_h2d(self, block: int, payload: list[np.ndarray]) -> None:
        """Write a host payload into device pool block ``block`` — the
        single-block face of :meth:`_cache_h2d_many`."""
        self._cache_h2d_many([block], [payload])

    def _cache_h2d_many(self, blocks: list[int],
                        payloads: list[list[np.ndarray]]) -> None:
        """Write several host payloads into their device pool blocks in
        ONE :func:`_pool_scatter` dispatch for the whole tree —
        functional updates, so the donated pool the step programs alias
        is never mutated behind XLA's back.  Per-op dispatch overhead
        dominates the eager swap path, which is why the whole tree
        fuses into one program and why the batch is padded to a
        multiple of 8 with writes of the first payload into the trash
        block (scratch by design): one compiled width, warmed at
        engine init — a varying-width batch would reintroduce mid-serve
        compile stalls."""
        n = len(blocks)
        pad = -(-n // 8) * 8 - n
        trash = self.sched.pool.trash_block
        idx = jnp.asarray(list(blocks) + [trash] * pad)
        rows = [jnp.asarray(np.stack(
                    [np.asarray(p[i]) for p in payloads]
                    + [np.asarray(payloads[0][i])] * pad))
                for i in range(len(payloads[0]))]
        self.pool = _pool_scatter(self.pool, idx, rows)

    # ---- fleet tier: stream export / adoption (PR 18) --------------------

    def export_stream(self, rid: int, *, with_kv: bool = True) -> dict:
        """Detach a live stream into a portable migration record for
        another replica's :meth:`adopt_stream`.  ``with_kv=True`` d2h-
        copies the stream's written KV blocks (one fused gather for the
        whole tree) BEFORE the scheduler frees them, so a decode-phase
        stream resumes at the target by swap-in instead of re-prefill;
        ``with_kv=False`` ships the continuation alone (the target
        re-prefills — same stream bitwise either way, by the position-
        derived sampling keys).  The record's ``payload_bytes`` is what
        the fleet charges against the DCN roofline."""
        keep = self.sched.migratable_blocks(rid) if with_kv else []
        payloads = self._cache_d2h_many(keep) if keep else []
        record = self.sched.detach_stream(rid)
        record["payloads"] = payloads
        record["payload_bytes"] = sum(
            int(a.nbytes) for p in payloads for a in p)
        return record

    def adopt_stream(self, record: dict) -> None:
        """Adopt a migrated stream exported by another replica.  KV
        payloads land in THIS engine's host spill store (the adoption
        landing pad) and the stream resumes by the normal swap-in path
        at its next admission — ``submitted`` is never recounted (the
        scheduler's attach bypasses submit by contract)."""
        if record.get("payloads") and self.store is None:
            raise RuntimeError(
                "adopting KV payloads needs ServeEngine(host_blocks>0) "
                "(the adoption landing pad); export with_kv=False to "
                "re-prefill instead")
        self.sched.attach_stream(record)

    # ---- the tick --------------------------------------------------------

    def step(self, now: float = 0.0) -> tuple[list[Event], str]:
        """One engine tick: apply due chaos faults, sweep lifecycle
        (cancellations / deadlines), admit arrived requests, launch (at
        most) one program, apply its results. Returns (events, kind)
        with kind in {"prefill", "decode", "idle"} — the bench times
        this call to get per-launch service time."""
        tick = self._tick
        self._tick += 1
        rec = self.rec
        if rec.enabled:
            self.sched.now = now  # timestamps scheduler decisions
        if self.chaos is not None:
            if rec.enabled:
                self.chaos.recorder = rec
                self.chaos.obs_now = now
            self._apply_chaos(tick, now)
        self._release_pressure(tick)
        events = [Event(now, *t) for t in self.sched.sweep(now)]
        if self.store is not None:
            # prefetch ahead of schedule: queued spilled continuations'
            # h2d copies land NOW, before this tick's launch, so a
            # swap-in resume at a later admit finds its blocks already
            # on device instead of serializing the copies with it
            self.sched.prefetch()
        self.sched.admit(now)
        kind, arg = self.sched.plan()
        launch = None
        if rec.enabled and kind != "idle":
            # capture launch identity BEFORE the program runs: apply_*
            # frees a slot the moment its request completes
            if kind == PREFILL:
                s = self.sched.slots[arg]
                launch = {"slot": arg, "rid": s.rid,
                          "chunk": s.chunk_cursor}
            else:
                launch = {"slots": list(arg),
                          "rids": [self.sched.slots[i].rid for i in arg]}
        t0 = time.perf_counter()
        if kind == PREFILL:
            events.extend(self._run_prefill(arg, now))
        elif kind == DECODE:
            events.extend(self._run_decode(arg, now))
        self.last_tick_s = time.perf_counter() - t0
        self.steps[kind] += 1
        if launch is not None:
            launch["tick"] = tick
            launch["dur_s"] = self.last_tick_s
            rec.emit(f"{kind}.launch", cat="serve", actor="engine",
                     payload=launch, t=now)
        for e in events:
            if e.first and e.status == "ok":
                arrival = self.sched.meta.get(e.rid, (now, None, None))[0]
                ttft = max(0.0, now - arrival)
                if np.isfinite(ttft):
                    self._ttft_ewma = (
                        ttft if self._ttft_ewma is None
                        else 0.8 * self._ttft_ewma + 0.2 * ttft)
        if rec.enabled and events:
            self._emit_lifecycle(events, now, tick)
        return events, kind

    def _emit_lifecycle(self, events: list[Event], now: float,
                        tick: int) -> None:
        """Map the tick's swept/produced events onto recorder instants:
        ``req.first_token`` / ``req.done`` for streams, ``req.cancelled``
        / ``req.expired`` for sweep casualties."""
        rec = self.rec
        for e in events:
            if e.status != "ok":
                rec.emit(f"req.{e.status}", cat="serve", actor="engine",
                         payload={"rid": e.rid, "tick": tick}, t=now)
                continue
            if e.first:
                payload = {"rid": e.rid, "tick": tick}
                arrival = self.sched.meta.get(e.rid, (now, None, None))[0]
                ttft = now - arrival
                if np.isfinite(ttft):
                    payload["ttft_s"] = float(max(0.0, ttft))
                rec.emit("req.first_token", cat="serve", actor="engine",
                         payload=payload, t=now)
            if e.done:
                rec.emit("req.done", cat="serve", actor="engine",
                         payload={"rid": e.rid, "tick": tick,
                                  "tokens": len(self.sched.emitted.get(
                                      e.rid, []))},
                         t=now)

    def _launch(self, fn, tag: str):
        """One guarded program launch: a per-attempt watchdog deadline
        (a hung compiled step becomes :class:`WatchdogTimeout`, not a
        silent stall) wrapped in the shared ``retry_with_backoff`` — a
        transient failure re-runs the SAME tick bitwise, because every
        launch input is rebuilt from host state and the sampling keys
        are position-derived. Injected chaos failures fire BEFORE the
        program runs (the pool is untouched); a real failure that lands
        mid-launch on a donating backend is not retriable in place (the
        pool was donated) — that path recovers via snapshot restore, as
        docs/serving.md spells out."""

        def attempt():
            try:
                if self._injected_exc:
                    self._injected_exc -= 1
                    from distributed_tensorflow_guide_tpu.testing.chaos \
                        import ChaosInjectedError
                    raise ChaosInjectedError(
                        f"chaos: injected serve step exception ({tag})")
                wd = self._watchdog
                if wd is None:
                    return fn()
                wd.arm(tag, self._step_deadline_s)
                try:
                    return fn()
                except KeyboardInterrupt:
                    wd.check()  # trip becomes the clean, retriable error
                    raise
                finally:
                    wd.disarm()
            except Exception:
                self.launch_failures += 1
                raise

        return retry_with_backoff(
            attempt, attempts=self.retry_attempts,
            base_delay_s=self.retry_base_delay_s, max_delay_s=1.0,
            what=tag)

    def _run_prefill(self, i: int, now: float) -> list[Event]:
        s = self.sched.slots[i]
        CH = self.sched.prefill_chunk
        start = s.chunk_cursor * CH
        valid = min(CH, len(s.prompt) - start)
        chunk = np.zeros((1, CH), np.int32)
        chunk[0, :valid] = s.prompt[start:start + valid]
        tables = table_row(s.blocks, self.fns.n_blk,
                           self.sched.pool.trash_block)[None]
        args = (self.params, self.pool, jnp.asarray(tables),
                jnp.full((1,), start, jnp.int32), jnp.asarray(chunk),
                jnp.int32(valid), jnp.asarray(s.rng))
        if self.fns.lora:
            args += (self.adapters,
                     jnp.full((1,), s.adapter, jnp.int32))
        if self.fns.moe:
            tok, self.pool, load, overflow = self._launch(
                lambda: self.fns.prefill(*args),
                tag="serve_prefill_chunk_step")
            self._moe_load += np.asarray(load).astype(np.int64)
            self._moe_overflow += np.asarray(overflow).astype(np.int64)
        else:
            tok, self.pool = self._launch(
                lambda: self.fns.prefill(*args),
                tag="serve_prefill_chunk_step")
        return [Event(now, *ev) for ev in
                self.sched.apply_prefill(i, int(tok))]

    def _run_decode(self, ready: list[int], now: float) -> list[Event]:
        S, n_blk = self.num_slots, self.fns.n_blk
        tables = np.tile(self._trash_row, (S, 1))
        written = np.zeros((S,), np.int32)
        last_tok = np.zeros((S,), np.int32)
        keys = np.zeros((S, 2), np.uint32)
        adapter_ids = np.zeros((S,), np.int32)
        for i in ready:
            s = self.sched.slots[i]
            tables[i] = table_row(s.blocks, n_blk,
                                  self.sched.pool.trash_block)
            written[i] = s.written
            last_tok[i] = s.pending
            keys[i] = s.rng
            adapter_ids[i] = s.adapter
        args = (self.params, self.pool, jnp.asarray(tables),
                jnp.asarray(written), jnp.asarray(last_tok),
                jnp.asarray(keys))
        if self.fns.lora:
            args += (self.adapters, jnp.asarray(adapter_ids))
        if self.fns.moe:
            nxt, self.pool, of_tok, load, overflow = self._launch(
                lambda: self.fns.decode(*args),
                tag="serve_decode_step")
            self._moe_load += np.asarray(load).astype(np.int64)
            self._moe_overflow += np.asarray(overflow).astype(np.int64)
            of = np.asarray(of_tok)
            nxt = np.asarray(nxt)
            events = []
            stalled = 0
            for i in ready:
                if of[i]:
                    # degrade-to-overflow: the slot's sampled token came
                    # from a forward that skipped its expert at some
                    # layer — discard it and leave pending/written
                    # untouched, so the SAME token retries next tick
                    # (cache rewrites are idempotent; dispatch fills in
                    # slot order, so the lowest contending slot always
                    # advances). A hot expert costs goodput, never a
                    # dropped or corrupted token.
                    stalled += 1
                    continue
                events.extend(Event(now, *ev) for ev in
                              self.sched.apply_decode(i, int(nxt[i])))
            if stalled:
                self._moe_stall_slot_ticks += stalled
                self._moe_stall_ticks += 1
            return events
        nxt, self.pool = self._launch(
            lambda: self.fns.decode(*args),
            tag="serve_decode_step")
        nxt = np.asarray(nxt)
        events = []
        for i in ready:
            events.extend(Event(now, *ev) for ev in
                          self.sched.apply_decode(i, int(nxt[i])))
        return events

    # ---- chaos application (testing.chaos serve kinds) -------------------

    def _apply_chaos(self, tick: int, now: float) -> None:
        from distributed_tensorflow_guide_tpu.testing.chaos import (
            corrupt_checkpoint,
        )
        for f in self.chaos.take_serve(tick):
            if f.kind == "serve_step_exception":
                self._injected_exc += 1
            elif f.kind == "client_abandon":
                rid = self._abandon_target(int(f.param))
                if rid is not None:
                    self.cancel(rid)
            elif f.kind == "arrival_burst":
                if self.burst_factory is None:
                    raise ValueError(
                        "arrival_burst fault needs "
                        "ServeEngine(burst_factory=...)")
                # a tenant-targeted burst exercises fair-share admission:
                # legacy 2-arg factories still work for tenantless faults
                reqs = (self.burst_factory(int(f.param), now)
                        if f.tenant is None
                        else self.burst_factory(int(f.param), now,
                                                int(f.tenant)))
                for req in reqs:
                    try:
                        self.submit(req)
                    except EngineOverloaded:
                        pass  # the gate shedding the burst IS the scenario
            elif f.kind == "pool_pressure":
                self._grab_pressure(tick, int(f.param))
            else:  # snapshot_truncate / snapshot_corrupt
                if self.snapshot_dir is None:
                    raise ValueError(
                        f"{f.kind} fault needs ServeEngine("
                        "snapshot_dir=...)")
                if self._ckpt is not None:
                    self._ckpt.wait()  # commit pending async saves first
                try:
                    corrupt_checkpoint(
                        self.snapshot_dir,
                        mode=("truncate" if f.kind == "snapshot_truncate"
                              else "flip"))
                except FileNotFoundError:
                    pass  # no committed snapshot yet — nothing to damage

    def _abandon_target(self, idx: int) -> int | None:
        live = sorted(
            {s.rid for s in self.sched.slots if s is not None}
            | {r.rid for r in self.sched.queue})
        if not live:
            return None
        return live[idx % len(live)]

    def _grab_pressure(self, tick: int, nblocks: int) -> None:
        # a co-tenant spike: blocks vanish from the pool for a few ticks
        # under the reserved chaos owner, forcing eviction/re-prefill on
        # residents — released by _release_pressure (or at run() exit)
        pool = self.sched.pool
        n = min(nblocks, pool.free_blocks)
        if n <= 0:
            return
        blocks = pool.alloc(_CHAOS_RID, n)
        if blocks:
            self._pressure_holds.append(
                (tick + _PRESSURE_HOLD_TICKS, blocks))

    def _release_pressure(self, tick: float) -> None:
        keep = []
        for release_at, blocks in self._pressure_holds:
            if tick >= release_at:
                self.sched.pool.free(_CHAOS_RID, blocks)
            else:
                keep.append((release_at, blocks))
        self._pressure_holds = keep

    # ---- drain -----------------------------------------------------------

    def run(self, max_ticks: int | None = None) -> list[Event]:
        """Drain all submitted work ignoring arrival times (tick clock).
        The load bench drives :meth:`step` itself with a virtual clock
        instead."""
        events: list[Event] = []
        ticks = 0
        while self.sched.has_queued or self.sched.has_resident:
            evs, kind = self.step(now=float("inf"))
            events.extend(evs)
            if kind == "idle":
                if self._pressure_holds:
                    continue  # chaos holds blocks; they release by tick
                raise RuntimeError(
                    "engine deadlock: work queued but nothing schedulable")
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
        self._release_pressure(float("inf"))
        return events

    def completions(self) -> dict[int, list[int]]:
        """rid -> every token emitted so far (complete or not)."""
        return {rid: list(toks)
                for rid, toks in self.sched.emitted.items()}

    def live_blocks(self) -> int:
        """Blocks currently owned by resident requests — what the paged
        byte model charges a decode step for (vs. max_len always)."""
        return self.sched.pool.live_blocks()

    def health(self) -> dict:
        """Engine health counters — what the CLI/examples surface so a
        degraded engine is observable, not silent."""
        sd = self.sched
        return {
            "resident": sum(s is not None for s in sd.slots),
            "queued": len(sd.queue),
            "completed": len(sd.done),
            "shed": sd.shed,
            "cancelled": sd.cancelled,
            "expired": sd.expired,
            "preemptions": sd.preemptions,
            "live_blocks": sd.pool.live_blocks(),
            "prefix_hit_tokens": sd.prefix_hit_tokens,
            "prefill_tokens_saved": sd.prefill_tokens_saved,
            "prefix_evictions": sd.prefix_evictions,
            "prefix_nodes": sd.prefix.size if sd.prefix is not None else 0,
            "spill_out_blocks": sd.spill_out_blocks,
            "spill_in_blocks": sd.spill_in_blocks,
            "spill_d2h_bytes": sd.spill_d2h_bytes,
            "spill_h2d_bytes": sd.spill_h2d_bytes,
            "spill_prefetched_blocks": sd.spill_prefetched_blocks,
            "spill_resumes": sd.spill_resumes,
            "swapin_tokens_saved": sd.swapin_tokens_saved,
            "migrated_out": sd.migrated_out,
            "migrated_in": sd.migrated_in,
            "host_blocks": (self.store.live_blocks()
                            if self.store is not None else 0),
            "host_bytes": (self.store.bytes_stored()
                           if self.store is not None else 0),
            "tenants": {t: dict(c) for t, c in sorted(sd.tenants.items())},
            "last_tick_s": self.last_tick_s,
            "ticks": self._tick,
            "launch_failures": self.launch_failures,
            **({"moe": {
                "expert_load": [int(x) for x in self._moe_load],
                "expert_overflow": [int(x) for x in self._moe_overflow],
                "stall_slot_ticks": int(self._moe_stall_slot_ticks),
                "stall_ticks": int(self._moe_stall_ticks),
            }} if self.fns.moe else {}),
        }

    # ---- snapshot / restore ----------------------------------------------

    def save_snapshot(self, *, async_: bool = False) -> int | None:
        """Serialize ALL host-side serving state (the scheduler's
        continuation view of every live request, emitted tokens,
        terminal statuses, counters) through PR 5's manifested /
        CRC-verified checkpoint path. One uint8 blob: the state is a
        dynamic Python structure, so it rides as JSON bytes and the
        manifest's size+CRC checks cover it (``snapshot_truncate`` /
        ``snapshot_corrupt`` are both caught at restore). The device
        pool is NOT saved — restore re-prefills residents from their
        recorded positions, which PR 10's position-derived keys make
        bitwise-safe. Returns the snapshot label, or None if the save
        was skipped."""
        if self._ckpt is None:
            raise ValueError("ServeEngine(snapshot_dir=...) not configured")
        state = {"sched": self.sched.snapshot_state(),
                 "tick": self._tick,
                 "steps": dict(self.steps)}
        blob = np.frombuffer(json.dumps(state).encode("utf-8"),
                             dtype=np.uint8).copy()
        label = max(self._tick, self._last_snap + 1)
        if not self._ckpt.save(label, {"blob": blob}, force=True,
                               async_=async_):
            return None
        self._last_snap = label
        if self.persist_cache:
            self._save_cache_contents(label)
        if self.rec.enabled:
            self.rec.emit(
                "snapshot.save", cat="serve", actor="engine",
                payload={"label": int(label),
                         "requests": len(state["sched"]["requests"]),
                         "async": bool(async_)})
        return label

    def _cache_file(self, label: int) -> str:
        return os.path.join(str(self.snapshot_dir),
                            f"cache_{int(label)}.npz")

    def _save_cache_contents(self, label: int) -> int:
        """Persist the prefix trie's PAYLOADS (device-resident blocks
        d2h'd, spilled blocks straight from the host tier) next to
        snapshot ``label`` as one npz + a CRC sidecar — the warm-restart
        path: a restored engine swallows these into the host tier and
        re-prefills ZERO cached-prefix tokens.  Returns the number of
        nodes written."""
        sd = self.sched
        nodes = []
        arrays = {}
        for j, (adapter, path, node) in enumerate(sd.prefix.walk()):
            payload = (self._cache_d2h(node.block)
                       if node.block is not None
                       else sd.store.get(node.host))
            nodes.append({"adapter": int(adapter),
                          "path": [int(t) for t in path]})
            for k, a in enumerate(payload):
                arrays[f"n{j}_l{k}"] = np.asarray(a)
        sig = [[list(leaf.shape[1:]), str(leaf.dtype)]
               for leaf in jax.tree.leaves(self.pool)]
        meta = json.dumps({"version": 1, "label": int(label),
                           "leaves": sig, "nodes": nodes})
        path = self._cache_file(label)
        buf = _io.BytesIO()
        np.savez(buf, meta=np.frombuffer(meta.encode("utf-8"), np.uint8),
                 **arrays)
        raw = buf.getvalue()
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(raw)
        os.replace(tmp, path)
        with open(path[:-4] + ".crc", "w") as f:
            f.write(str(zlib.crc32(raw)))
        # trim cache files alongside the checkpointer's max_to_keep
        keep = {self._cache_file(s) for s in self._ckpt.all_steps()}
        for old in glob.glob(os.path.join(str(self.snapshot_dir),
                                          "cache_*.npz")):
            if old not in keep:
                for p in (old, old[:-4] + ".crc"):
                    try:
                        os.remove(p)
                    except OSError:
                        pass
        if self.rec.enabled:
            self.rec.emit("snapshot.cache_save", cat="serve",
                          actor="engine",
                          payload={"label": int(label),
                                   "nodes": len(nodes),
                                   "bytes": len(raw)})
        return len(nodes)

    def _restore_cache_contents(self, label: int) -> int:
        """Warm-restore the cache file for snapshot ``label`` into the
        HOST tier: every node re-enters the trie as a spilled entry
        (zero device blocks consumed) and promotes on demand when a
        claim wants it.  Any failure — missing file, CRC mismatch,
        signature drift, truncation — falls back to a cold cache (the
        continuations simply re-prefill; never a wrong token).  Returns
        the number of nodes restored."""
        sd = self.sched
        path = self._cache_file(label)
        try:
            with open(path, "rb") as f:
                raw = f.read()
            with open(path[:-4] + ".crc") as f:
                want = int(f.read().strip())
            if zlib.crc32(raw) != want:
                raise ValueError("cache file CRC mismatch")
            data = np.load(_io.BytesIO(raw))
            meta = json.loads(bytes(data["meta"]).decode("utf-8"))
            sig = [[list(leaf.shape[1:]), str(leaf.dtype)]
                   for leaf in jax.tree.leaves(self.pool)]
            if meta.get("version") != 1 or meta["leaves"] != sig:
                raise ValueError("cache file leaf signature mismatch")
            restored = 0
            for j, nd in enumerate(meta["nodes"]):
                payload = [np.asarray(data[f"n{j}_l{k}"])
                           for k in range(len(sig))]
                for a, (shape, dtype) in zip(payload, sig):
                    if list(a.shape) != shape or str(a.dtype) != dtype:
                        raise ValueError(
                            "cache file node payload shape mismatch")
                h = sd.store.put(CACHE_RID, payload)
                if h is None:
                    break  # host tier full — keep what fits
                if sd.prefix.insert_spilled(nd["path"], h,
                                            adapter=int(nd["adapter"])):
                    restored += 1
                else:
                    sd.store.free(CACHE_RID, [h])
        except Exception as e:
            if self.rec.enabled:
                self.rec.emit("snapshot.cache_restore_miss", cat="serve",
                              actor="engine",
                              payload={"label": int(label),
                                       "error": str(e)})
            return 0
        if self.rec.enabled:
            self.rec.emit("snapshot.cache_restore", cat="serve",
                          actor="engine",
                          payload={"label": int(label),
                                   "nodes": restored})
        return restored

    def restore_latest_snapshot(self) -> int | None:
        """Restore the newest VALID snapshot (the PR-5 ladder: a
        truncated or CRC-corrupt snapshot is skipped, falling back to
        the next older one) into THIS engine, which must be fresh. The
        pool stays zeroed; every formerly-resident request re-enters as
        a queued continuation and re-prefills through normal admission,
        so each stream continues bitwise identical to an uninterrupted
        run. Returns the restored label, or None when no valid snapshot
        exists."""
        if self._ckpt is None:
            raise ValueError("ServeEngine(snapshot_dir=...) not configured")
        got = self._ckpt.restore_latest_valid(None)
        if got is None:
            if self.rec.enabled:
                self.rec.emit("snapshot.restore_miss", cat="serve",
                              actor="engine", payload={})
            return None
        tree, label = got
        state = json.loads(
            np.asarray(tree["blob"], np.uint8).tobytes().decode("utf-8"))
        self.sched.restore_state(state["sched"])
        if self.persist_cache:
            # warm the trie BEFORE the first admit so every restored
            # continuation routes through the prefix-claim path and
            # re-prefills only its suffix (the fix-of-opportunity:
            # restore cost scales with suffix length, not prompt length)
            self._restore_cache_contents(label)
        self._tick = int(state["tick"])
        for k, v in state["steps"].items():
            self.steps[k] = int(v)
        self._last_snap = label
        if self.rec.enabled:
            self.rec.emit(
                "snapshot.restore", cat="serve", actor="engine",
                payload={"label": int(label),
                         "requests": len(state["sched"]["requests"])})
        return label

    def close(self) -> None:
        """Release background resources (watchdog thread, checkpointer)
        and drop the prefix cache's block references — device AND host
        tier — plus any banked spill records, so the joint
        ``Scheduler.check_leaks()`` audits clean after shutdown."""
        self.sched.release_prefix_cache()
        if self.store is not None:
            self.sched.release_spill_store()
        if self._watchdog is not None:
            self._watchdog.close()
        if self._ckpt is not None:
            self._ckpt.close()


# ---- program contracts (analysis/) ------------------------------------------


def lint_contracts():
    """Contracts for the serving entry programs (base decode/prefill
    pair plus the multi-LoRA decode variant).

    Collective-free (strict empty census: the engine is pure SPMD under
    DP/TP sharding — a stray psum would deadlock a replicated server),
    host-callback-free, pool donated in ``alias`` mode (the pool is
    state->state: every donated leaf must come back out, which is the
    in-place-update guarantee; this is the serving analogue of the
    one-shot cache's scratch donation — the ISSUE's "scratch-donated
    pool" — expressed for a buffer the host threads between ticks), and
    a hard ceiling on the largest f32 intermediate that sits BELOW the
    size of a full-``max_len`` f32 score tensor — the lint fails if
    anyone reintroduces dense (slots, heads, chunk, max_len) attention
    scores into the compiled serve path."""
    from distributed_tensorflow_guide_tpu.analysis.contracts import (
        CostPin,
        CostSpec,
        DonationSpec,
        ProgramContract,
    )

    # fixture geometry: chosen so every legitimate f32 intermediate
    # (largest: one updated pool leaf, num_blocks*heads*block*head_dim =
    # 5*2*8*8 = 640 elems) fits under the cap while a dense f32 score
    # tensor (decode: slots*heads*1*max_len = 2048; prefill chunk:
    # 1*heads*chunk*max_len = 4096) would blow through it
    S, NB, BS, CH, MAXLEN = 4, 5, 8, 8, 256
    F32_CAP = 1024

    def _build(kind):
        def _b():
            from distributed_tensorflow_guide_tpu.analysis.fixtures import (
                tiny_lm_cfg,
            )

            lora = kind == "decode_lora"
            cfg = dataclasses.replace(
                tiny_lm_cfg(vocab_size=32, max_len=MAXLEN),
                decode_impl="pallas",
                **({"lora_rank": 2, "lora_adapters": 2} if lora else {}),
                **({"moe_experts": 4, "moe_capacity": 2}
                   if "moe" in kind else {}),
                **({"weight_dtype": "int8"}
                   if kind in ("decode_wq8", "decode_moe_wq8")
                   else {"weight_dtype": "fp8"} if kind == "decode_wqfp8"
                   else {}))
            fns = build_step_fns(cfg, slots=S, num_blocks=NB,
                                 block_size=BS, prefill_chunk=CH)
            variables = jax.eval_shape(
                lambda p: fns.model.init(
                    jax.random.PRNGKey(0), p,
                    jnp.zeros((S,), jnp.int32),
                    block_tables=jnp.zeros((S, fns.n_blk), jnp.int32)),
                jax.ShapeDtypeStruct((S, 1), "int32"))
            params = variables["params"]
            pool = jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype),
                paged_cache_shapes(fns.cfg, S))
            i32 = "int32"
            if kind.startswith("decode"):
                args = (params, pool,
                        jax.ShapeDtypeStruct((S, fns.n_blk), i32),
                        jax.ShapeDtypeStruct((S,), i32),
                        jax.ShapeDtypeStruct((S,), i32),
                        jax.ShapeDtypeStruct((S, 2), "uint32"))
                if lora:
                    adapters = jax.tree.map(
                        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype),
                        variables["adapters"])
                    args += (adapters, jax.ShapeDtypeStruct((S,), i32))
                return fns.decode, args
            args = (params, pool,
                    jax.ShapeDtypeStruct((1, fns.n_blk), i32),
                    jax.ShapeDtypeStruct((1,), i32),
                    jax.ShapeDtypeStruct((1, CH), i32),
                    jax.ShapeDtypeStruct((), i32),
                    jax.ShapeDtypeStruct((2,), "uint32"))
            return fns.prefill, args

        return _b

    common = dict(
        policy="f32",
        collectives={},  # strict: the serve programs are collective-free
        max_f32_intermediate_elems=F32_CAP,
        donation=DonationSpec(argnums=(1,), mode="alias"),
        sources=("distributed_tensorflow_guide_tpu.serve.engine",
                 "distributed_tensorflow_guide_tpu.serve.paged_cache",
                 "distributed_tensorflow_guide_tpu.models.transformer"),
    )

    # every quantized kernel elem in the fixture model: per layer
    # qkv 768 + proj 256 + up 512 + down 512 = 2048, x 2 layers, plus
    # lm_head 16*32 = 512 -> 4608 elems; int8 storage saves 3 bytes on
    # each one per decode step (the narrow-origin matmul read)
    WQ8_SAVED_BYTES = 3 * 4608

    def _wq8_hbm_read_expect():
        """The f32 sibling's derived read bytes minus the weight-only
        savings — pinning the wq8 program AGAINST its own f32 trace, so
        the pin can only pass if quantization removed exactly the kernel
        bytes and changed nothing else about the program's traffic."""
        import jax.numpy as _jnp

        from distributed_tensorflow_guide_tpu.analysis import (
            cost as cost_mod,
            rules as rules_mod,
        )

        fn, args = _build("decode")()
        jaxpr = jax.make_jaxpr(fn)(*args)
        traced = rules_mod.TracedProgram(
            name="serve_decode_step", jaxpr=jaxpr,
            arg_leaf_avals=[
                [jax.ShapeDtypeStruct(_jnp.shape(x), _jnp.result_type(x))
                 for x in jax.tree.leaves(a)] for a in args])
        f32_vec = cost_mod.program_cost(traced, sibling)
        return f32_vec.hbm_bytes_read - WQ8_SAVED_BYTES

    # the MoE fixture's quantized kernel elems: per layer qkv 768 +
    # proj 256 + expert banks w_in 4*16*32 = 2048 + w_out 4*32*16 = 2048
    # (the routed FFN replaces MLP up/down; the f32 router is exempt),
    # x 2 layers, plus lm_head 512 -> 10752; int8 storage saves 3 bytes
    # per elem on the decode read — the ~4x cold-bank diet, byte-exact
    MOE_WQ8_SAVED_BYTES = 3 * 10752

    def _moe_wq8_hbm_read_expect():
        """The f32 MoE sibling's derived read bytes minus the weight-only
        savings — the serve_decode_step_wq8 trace-and-subtract discipline
        applied to the expert banks, so the pin only passes if
        quantization removed exactly the kernel+bank bytes and changed
        nothing else about the MoE program's traffic."""
        import jax.numpy as _jnp

        from distributed_tensorflow_guide_tpu.analysis import (
            cost as cost_mod,
            rules as rules_mod,
        )

        fn, args = _build("decode_moe")()
        jaxpr = jax.make_jaxpr(fn)(*args)
        traced = rules_mod.TracedProgram(
            name="serve_decode_step_moe", jaxpr=jaxpr,
            arg_leaf_avals=[
                [jax.ShapeDtypeStruct(_jnp.shape(x), _jnp.result_type(x))
                 for x in jax.tree.leaves(a)] for a in args])
        f32_vec = cost_mod.program_cost(traced, moe_sibling)
        return f32_vec.hbm_bytes_read - MOE_WQ8_SAVED_BYTES

    moe_sibling = ProgramContract(
        name="serve_decode_step_moe",
        build=_build("decode_moe"),
        # the MoE pair carries the expert banks (2 layers x 4 experts x
        # (16*32 + 32*16) f32 = 16 KiB of extra resident params) on top
        # of the shared pool band — its own ceiling, same discipline
        cost=CostSpec(max_peak_live_bytes=131072),
        notes="expert-parallel decode: router dispatch + fixed-capacity "
              "expert contraction INSIDE the step; per-slot overflow "
              "flags drive the engine's stall-and-retry (degrade, never "
              "drop); idle slots masked out of capacity",
        **common)

    sibling = ProgramContract(
        name="serve_decode_step",
        build=_build("decode"),
        # one 96KiB ceiling across the serve programs: the aliased
        # pool keeps all three in the 75-91KiB band, and a dead pool
        # donation would blow straight through it
        cost=CostSpec(max_peak_live_bytes=98304),
        notes="fixed-slot paged decode: pool aliased in place, no "
              "full-max_len f32 score tensor",
        **common)
    return [
        sibling,
        ProgramContract(
            name="serve_decode_step_wq8",
            build=_build("decode_wq8"),
            quantized_matmuls=True,
            cost=CostSpec(
                pins=(CostPin(
                    "hbm_bytes_read", _wq8_hbm_read_expect,
                    note="f32 decode read bytes minus 3 B x 4608 "
                         "quantized kernel elems"),),
                max_peak_live_bytes=98304),
            notes="weight-only int8 decode: same program as "
                  "serve_decode_step with every projection kernel "
                  "stored int8 + f32 column scales, dequant fused into "
                  "the matmul (no f32 weight copy under the f32 cap)",
            **common),
        ProgramContract(
            name="serve_decode_step_wqfp8",
            build=_build("decode_wqfp8"),
            # NOT fp8_matmuls: the e4m3 kernels widen through a separate
            # convert eqn before the dot, so every contraction sees f32
            # operands (the weight-only discipline) — there is no fp8 dot
            # for the gate to pass. The pin reuses the int8 expect: fp8
            # is the same 1 byte/elem storage, so the saved read bytes
            # are identical (3 B x 4608 kernel elems vs the f32 sibling).
            cost=CostSpec(
                pins=(CostPin(
                    "hbm_bytes_read", _wq8_hbm_read_expect,
                    note="f32 decode read bytes minus 3 B x 4608 "
                         "fp8-stored kernel elems (same byte diet as "
                         "int8)"),),
                max_peak_live_bytes=98304),
            notes="weight-only fp8 decode: e4m3 projection kernels + f32 "
                  "column scales, dequant fused into the matmul; relative "
                  "(mantissa) error instead of int8's absolute grid",
            **common),
        ProgramContract(
            name="serve_prefill_chunk_step",
            build=_build("prefill"),
            cost=CostSpec(max_peak_live_bytes=98304),
            notes="B=1 chunked prefill through the same attention path",
            **common),
        ProgramContract(
            name="serve_decode_step_lora",
            build=_build("decode_lora"),
            cost=CostSpec(max_peak_live_bytes=98304),
            notes="multi-adapter decode: gathered low-rank deltas stay "
                  "collective-free and under the f32 intermediate cap",
            **common),
        moe_sibling,
        ProgramContract(
            name="serve_decode_step_moe_wq8",
            build=_build("decode_moe_wq8"),
            quantized_matmuls=True,
            cost=CostSpec(
                pins=(CostPin(
                    "hbm_bytes_read", _moe_wq8_hbm_read_expect,
                    note="f32 MoE decode read bytes minus 3 B x 10752 "
                         "quantized kernel+bank elems — the cold expert "
                         "bank pays the same fused-dequant diet as the "
                         "dense projections"),),
                max_peak_live_bytes=131072),
            notes="weight-only int8 MoE decode: per-expert qkernel+scale "
                  "banks, dequant fused AFTER the expert gather "
                  "(wq_bank_matmul); same program shape as "
                  "serve_decode_step_moe",
            **common),
        ProgramContract(
            name="serve_prefill_chunk_step_moe",
            build=_build("prefill_moe"),
            cost=CostSpec(max_peak_live_bytes=131072),
            notes="B=1 MoE chunked prefill: the dispatch buffer widens "
                  "to the chunk length (dropless by construction — a "
                  "prefill token can never overflow), pad rows masked "
                  "out of the census",
            **common),
    ]
