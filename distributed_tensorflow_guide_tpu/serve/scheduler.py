"""Request scheduler: continuous batching over a fixed-slot decode batch.

All of the *dynamic* serving state lives here, on the host, in plain
Python — which requests are resident, which physical blocks they own,
how far each one has written — so the device programs
(serve/engine.py) stay fully static: a decode step always runs all
``slots`` rows, a prefill step always runs one ``prefill_chunk``-token
chunk. The scheduler changes the POPULATION between steps (Orca's
iteration-level scheduling): a finished request frees its slot and
blocks at the step boundary, a queued prompt is admitted into any empty
slot mid-flight, and nothing retraces.

Decisions are deterministic functions of the submitted trace: FIFO
admission by arrival time, lowest-id slots and blocks first, preemption
evicts the MOST RECENTLY admitted victim (its re-queued continuation
carries the original prompt plus everything already emitted, and the
position-derived sampling keys of models/generation.py make the
regenerated stream bitwise the one it would have produced uninterrupted
— eviction is free of replay divergence by construction). The
scheduler-determinism test replays a seeded arrival trace twice and
pins identical event logs.

Request lifecycle (PR 11) also lives here: per-request TTFT/total
deadlines and client cancellation are applied by :meth:`Scheduler.sweep`
at step boundaries ONLY — a launched program is never torn down mid
-step, so the pool ledger stays leak-free (``check_leaks`` clean) by
construction.  Overload is refused at ``submit`` (queue-depth gate →
:class:`EngineOverloaded`, a retriable rejection) instead of degrading
resident streams.  :meth:`snapshot_state`/:meth:`restore_state`
serialize every live request as a *continuation* — the exact transform
``_preempt`` applies — which is why engine restore re-prefills and
still lands on the same streams bitwise.

Prefix sharing & tenancy (PR 12): with ``prefix_cache=True`` admission
consults the radix :class:`~.prefix_index.PrefixIndex` and CLAIMS the
longest cached prefix by ref-bump (``pool.share``) instead of
re-prefilling it — the claim is capped to a multiple of
``lcm(block_size, prefill_chunk)`` strictly below the prompt length, so
the suffix prefill starts chunk-aligned, at least one prompt token is
always recomputed (the final sample needs a live chunk), and every
subsequent write (suffix chunks, pads, decode) lands in privately
allocated blocks — shared blocks are never written, which is the whole
copy-on-write discipline.  When the pool runs dry, LRU leaf-first trie
eviction is tried BEFORE preemption.  Requests carry a ``tenant`` id:
admission becomes deficit-round-robin across the per-tenant queue heads
(exactly head-of-line FIFO when one tenant is present) under optional
per-tenant slot/block quotas, so one tenant's burst cannot starve
another.

Cache hierarchy (PR 16): with a host :class:`~.paged_cache.BlockStore`
and a ``cache_io`` d2h/h2d adapter attached, preemption and trie LRU
eviction become DEMOTIONS instead of destructions — the victim's written
blocks swap out to host RAM (COW-shared blocks spill once, deduplicated
through a device->host content map), a preempted request resumes by
swap-in at admission instead of re-prefilling, and queued spilled
continuations are prefetched back onto device BETWEEN ticks so the h2d
copies land ahead of the decode launches that consume them.  The swap
path never changes tokens: position-derived sampling keys already make a
re-prefilled continuation bitwise-identical to the uninterrupted stream,
and a swap-in restores the *same bytes* the re-prefill would recompute —
the hierarchy moves cost, not content.  All device<->host traffic is
counted (``spill_*`` counters) so the byte model in benchmarks/common.py
can reconcile it against the PCIe roofline.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from distributed_tensorflow_guide_tpu.obs import events as obs_events
from distributed_tensorflow_guide_tpu.serve.paged_cache import (
    BlockPool,
    blocks_for,
)
from distributed_tensorflow_guide_tpu.serve.prefix_index import (
    CACHE_RID,
    PrefixIndex,
)

PREFILL, DECODE = "prefill", "decode"


class EngineOverloaded(RuntimeError):
    """Admission refused under overload — RETRIABLE by contract: nothing
    about the request was recorded, so re-submitting the identical
    request later yields the identical stream. Shedding at the door is
    what keeps resident streams inside their SLOs instead of degrading
    everyone a little."""

    retriable = True


@dataclasses.dataclass
class Request:
    """One serving request. ``rng`` is the request's own PRNG key (raw
    (2,) uint32, what ``jax.random.PRNGKey`` returns) — sampling keys
    derive from (rng, absolute position), which is what makes the
    engine's per-request stream bitwise a one-shot
    ``make_generate_fn(...)​(params, prompt[None], rng)`` run.

    ``ttft_deadline_s``/``deadline_s`` are optional budgets measured
    from ``arrival``: breach terminates the request with status
    ``"expired"`` at the next step boundary (TTFT applies only until
    the first token; total always). ``None`` = no deadline."""

    rid: int
    prompt: np.ndarray  # (P,) int32
    max_new_tokens: int
    rng: np.ndarray  # (2,) uint32
    arrival: float = 0.0
    ttft_deadline_s: float | None = None
    deadline_s: float | None = None
    tenant: int = 0  # fair-share / quota accounting unit
    adapter: int = 0  # LoRA adapter id (0 = base model)


@dataclasses.dataclass
class _Slot:
    rid: int
    prompt: np.ndarray  # current prompt (original + pre-preemption emits)
    budget: int  # tokens still to emit from THIS residency
    rng: np.ndarray
    blocks: list[int]
    phase: str = PREFILL
    chunk_cursor: int = 0  # next prefill chunk index
    written: int = 0  # cache positions written so far
    pending: int = 0  # last sampled token (k/v not yet written)
    emitted_here: int = 0  # tokens emitted during THIS residency
    admitted_seq: int = 0
    tenant: int = 0
    adapter: int = 0
    prefix_len: int = 0  # cache positions claimed from the prefix index
    max_blocks: int = 0  # worst-case footprint (quota commitment)


class Scheduler:
    """Slots + pool + queue; the engine asks it what to run each tick."""

    def __init__(self, *, slots: int, num_blocks: int, block_size: int,
                 prefill_chunk: int, max_len: int,
                 max_queue: int | None = None,
                 prefix_cache: bool = False,
                 tenant_quotas: dict[int, dict] | None = None,
                 drr_quantum: int | None = None,
                 host_store=None, cache_io=None,
                 recorder=None) -> None:
        if max_len % prefill_chunk:
            raise ValueError(
                f"prefill_chunk {prefill_chunk} must divide max_len "
                f"{max_len} (pad writes must stay inside the table)")
        if max_len % block_size:
            raise ValueError(
                f"block_size {block_size} must divide max_len {max_len}")
        if drr_quantum is not None and drr_quantum < 1:
            raise ValueError(f"drr_quantum must be >= 1, got {drr_quantum}")
        self.slots: list[_Slot | None] = [None] * slots
        self.pool = BlockPool(num_blocks, block_size)
        self.block_size = block_size
        self.prefill_chunk = prefill_chunk
        self.max_len = max_len
        self.blocks_per_seq = max_len // block_size
        self.queue: list[Request] = []  # FIFO; preemptions go to the front
        self.emitted: dict[int, list[int]] = {}  # rid -> all emitted tokens
        self.first_emit: dict[int, bool] = {}  # rid -> saw first token yet
        self.done: set[int] = set()
        self._seq = 0  # admission counter (preemption picks the youngest)
        self._prefer_prefill = True  # interleave chunked prefill w/ decode
        self.preemptions = 0
        # lifecycle (PR 11): terminal statuses, deadlines, overload gate
        self.max_queue = max_queue  # submit sheds past this queue depth
        self.meta: dict[int, tuple[float, float | None, float | None]] = {}
        self.finished: dict[int, str] = {}  # rid -> done|cancelled|expired
        self._cancel_pending: set[int] = set()
        self.shed = 0
        self.cancelled = 0
        self.expired = 0
        # prefix sharing & tenancy (PR 12)
        self.prefix: PrefixIndex | None = (
            PrefixIndex(block_size) if prefix_cache else None)
        # claim granularity: a claim must be BOTH block-aligned (whole
        # shared blocks) and chunk-aligned (the suffix prefill starts on
        # a chunk boundary), and strictly below the prompt length (the
        # final chunk's sample must come from a live program)
        self._claim_g = math.lcm(block_size, prefill_chunk)
        # tenant -> {"slots": int|None, "blocks": int|None}
        self.tenant_quotas = {int(t): dict(q) for t, q in
                              (tenant_quotas or {}).items()}
        # deficit-round-robin: quantum defaults to the worst-case request
        # footprint, which makes single-tenant admission EXACTLY the
        # legacy head-of-line FIFO (the deficit gate can never block)
        self.drr_quantum = (self.blocks_per_seq if drr_quantum is None
                            else int(drr_quantum))
        self._deficit: dict[int, int] = {}
        self.tenant_of: dict[int, int] = {}  # rid -> tenant
        self.tenants: dict[int, dict[str, int]] = {}
        self.prefix_hit_tokens = 0
        self.prefill_tokens_saved = 0
        self.prefix_evictions = 0
        # cache hierarchy (PR 16): host spill tier + d2h/h2d adapter.
        # Both None = hierarchy off, every code path below is byte-
        # identical to the pool-only scheduler (the determinism pins).
        if (host_store is None) != (cache_io is None):
            raise ValueError(
                "host_store and cache_io come as a pair (the store holds "
                "spilled payloads, the io adapter moves them)")
        self.store = host_store
        self.io = cache_io
        # device block id -> host block id with IDENTICAL content; an
        # entry exists only while the device block is live and immutable
        # (COW: shared full blocks are never written; the pool's
        # on_recycle hook drops the entry the moment a block could be
        # re-handed-out and rewritten).  This is what makes COW-shared
        # blocks spill ONCE: later demoters find the live host copy and
        # ref-bump it instead of copying again.
        self._dev_to_host: dict[int, int] = {}
        self.pool.on_recycle = (
            lambda b: self._dev_to_host.pop(b, None))
        # rid -> spill record for a demoted (preempted) request:
        # {"entries": [("host", h) | ("dev", d, h)], "written", "pending"}.
        # A ("dev", d, h) entry is PREFETCH-STAGED: the payload is back
        # in device block d but the host hold h is retained so staging
        # is revocable for free under pressure.
        self._spilled: dict[int, dict] = {}
        self._prefetch_clock = 0
        self.spill_out_blocks = 0
        self.spill_in_blocks = 0
        self.spill_d2h_bytes = 0
        self.spill_h2d_bytes = 0
        self.spill_prefetched_blocks = 0
        self.spill_resumes = 0
        self.swapin_tokens_saved = 0
        # fleet tier (PR 18): streams detached to / adopted from another
        # replica's scheduler.  A migrated-out request counts as
        # ``preempted`` for its tenant (migration IS the ``_preempt``
        # continuation transform, applied cross-replica); ``submitted``
        # is never re-counted on adoption — that is the conservation
        # contract the fleet's aggregated ``health()["tenants"]`` pins.
        self.migrated_out = 0
        self.migrated_in = 0
        # observability (PR 14): observe-only. The engine passes its
        # recorder so both sides share one event stream, and refreshes
        # ``now`` (the semantic clock) at the top of every tick.
        self.rec = (recorder if recorder is not None
                    else obs_events.current())
        self.now = 0.0

    def _tc(self, tenant: int) -> dict[str, int]:
        return self.tenants.setdefault(int(tenant), {
            "submitted": 0, "admitted": 0, "tokens": 0, "done": 0,
            "shed": 0, "cancelled": 0, "expired": 0, "preempted": 0})

    # ---- intake ----------------------------------------------------------

    def max_request_blocks(self, prompt_len: int, max_new: int) -> int:
        padded = -(-prompt_len // self.prefill_chunk) * self.prefill_chunk
        return blocks_for(max(padded, prompt_len + max_new),
                          self.block_size)

    def submit(self, req: Request) -> None:
        P = int(len(req.prompt))
        if P < 1:
            raise ValueError("empty prompt")
        if req.tenant < 0:
            raise ValueError(f"tenant must be >= 0, got {req.tenant}")
        if req.adapter < 0:
            raise ValueError(f"adapter must be >= 0, got {req.adapter}")
        if P + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {P} + max_new {req.max_new_tokens} exceeds "
                f"max_len {self.max_len}")
        need = self.max_request_blocks(P, req.max_new_tokens)
        if need > self.pool.capacity:
            raise ValueError(
                f"request {req.rid} can never fit: needs "
                f"{need} blocks, pool capacity {self.pool.capacity}")
        quota = self.tenant_quotas.get(int(req.tenant), {})
        if quota.get("blocks") is not None and need > quota["blocks"]:
            raise ValueError(
                f"request {req.rid} can never fit tenant {req.tenant}'s "
                f"block quota: needs {need}, quota {quota['blocks']}")
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            self.shed += 1
            self._tc(req.tenant)["shed"] += 1
            if self.rec.enabled:
                self.rec.emit(
                    "req.shed", cat="serve", actor="scheduler",
                    payload={"rid": req.rid, "reason": "queue_depth",
                             "tenant": int(req.tenant),
                             "queue_depth": len(self.queue)},
                    t=float(req.arrival))
            raise EngineOverloaded(
                f"request {req.rid} shed: queue depth {len(self.queue)} at "
                f"the max_queue={self.max_queue} gate — retry later "
                "(nothing was recorded; the retried stream is identical)")
        self.queue.append(req)
        self.emitted.setdefault(req.rid, [])
        self.first_emit.setdefault(req.rid, False)
        self.tenant_of.setdefault(req.rid, int(req.tenant))
        self._tc(req.tenant)["submitted"] += 1
        # the request's lifecycle clock: original arrival + deadlines.
        # Continuations re-enter via queue.insert (not submit), so this
        # records exactly once per rid and deadline checks always measure
        # from the ORIGINAL arrival, never a preemption re-queue.
        self.meta.setdefault(req.rid, (float(req.arrival),
                                       req.ttft_deadline_s, req.deadline_s))

    # ---- admission -------------------------------------------------------

    def admit(self, now: float) -> list[int]:
        """Deficit-round-robin admission over per-tenant queue heads.

        Each round visits every tenant with a queued head (in queue
        order — continuations at the front keep their priority), credits
        its deficit with ``drr_quantum`` blocks, and admits the head when
        the deficit covers the request's worst-case footprint, the
        tenant's quotas allow it, and the pool can supply the blocks
        (after claiming any cached prefix — see :meth:`_claim_blocks`).
        Rounds repeat while some candidate is blocked ONLY by its
        deficit; the call returns when a round admits nobody else.

        Within a tenant this is strict head-of-line FIFO (no reordering
        past the head), and with a single tenant and the default quantum
        (= ``blocks_per_seq`` >= any request's cost) the deficit gate
        never blocks — admission order, slot choice and block ids are
        EXACTLY the legacy FIFO loop's, which is what keeps every PR-10/11
        determinism pin intact."""
        admitted: list[int] = []
        while None in self.slots:
            progressed = False
            deficit_waiting = False
            for req, tenant in self._tenant_heads():
                if None not in self.slots:
                    break
                if req.arrival > now:
                    continue
                if not self._quota_allows(tenant, req):
                    continue
                cost = self.max_request_blocks(len(req.prompt),
                                               req.max_new_tokens)
                self._deficit[tenant] = (self._deficit.get(tenant, 0)
                                         + self.drr_quantum)
                if self._deficit[tenant] < cost:
                    deficit_waiting = True
                    continue
                record = self._spilled.get(req.rid)
                if record is not None:
                    # demoted continuation: resume by swap-in — phase
                    # DECODE with the restored cache, zero re-prefill
                    blocks = self._swap_in_record(req.rid, record)
                    if blocks is None:
                        continue
                    prefix_len = 0
                else:
                    claim = self._claim_blocks(req)
                    if claim is None:
                        continue
                    blocks, prefix_len = claim
                # remove by IDENTITY: dataclass equality would compare
                # numpy prompt arrays elementwise
                self.queue.pop(next(
                    i for i, r in enumerate(self.queue) if r is req))
                s = self.slots.index(None)
                self.slots[s] = _Slot(
                    rid=req.rid, prompt=np.asarray(req.prompt, np.int32),
                    budget=req.max_new_tokens, rng=req.rng, blocks=blocks,
                    chunk_cursor=prefix_len // self.prefill_chunk,
                    written=prefix_len, admitted_seq=self._seq,
                    tenant=int(req.tenant), adapter=int(req.adapter),
                    prefix_len=prefix_len, max_blocks=cost)
                if record is not None:
                    del self._spilled[req.rid]
                    resumed = self.slots[s]
                    resumed.phase = DECODE
                    resumed.written = int(record["written"])
                    resumed.pending = int(record["pending"])
                    self.spill_resumes += 1
                    self.swapin_tokens_saved += int(record["written"])
                    if self.rec.enabled:
                        self.rec.emit(
                            "spill.resume", cat="serve",
                            actor="scheduler",
                            payload={"rid": req.rid, "slot": s,
                                     "written": int(record["written"])},
                            t=self.now)
                self._seq += 1
                self._deficit[tenant] -= cost
                self._tc(tenant)["admitted"] += 1
                if prefix_len:
                    self.prefix_hit_tokens += prefix_len
                    self.prefill_tokens_saved += prefix_len
                if self.rec.enabled:
                    payload = {"rid": req.rid, "slot": s,
                               "tenant": tenant,
                               "prefix_len": prefix_len,
                               "blocks": len(blocks)}
                    w = self.now - float(req.arrival)
                    if math.isfinite(w):
                        payload["queue_wait_s"] = max(0.0, w)
                    self.rec.emit("req.admit", cat="serve",
                                  actor="scheduler", payload=payload,
                                  t=self.now)
                    if prefix_len:
                        self.rec.emit("prefix.hit", cat="serve",
                                      actor="scheduler",
                                      payload={"rid": req.rid,
                                               "tokens": prefix_len},
                                      t=self.now)
                admitted.append(s)
                progressed = True
            if not progressed and not deficit_waiting:
                break
        # standard DRR reset: a tenant with nothing queued carries no credit
        queued_tenants = {int(r.tenant) for r in self.queue}
        for t in [t for t in self._deficit if t not in queued_tenants]:
            del self._deficit[t]
        return admitted

    def _tenant_heads(self) -> list[tuple[Request, int]]:
        """(head request, tenant) per tenant, in queue-front order — the
        deterministic round order (continuations at the front go first)."""
        heads: list[tuple[Request, int]] = []
        seen: set[int] = set()
        for req in self.queue:
            t = int(req.tenant)
            if t not in seen:
                seen.add(t)
                heads.append((req, t))
        return heads

    def _quota_allows(self, tenant: int, req: Request) -> bool:
        """Slot/block quota check against COMMITTED usage (worst-case
        footprints of residents), so a quota can never be overrun later
        by decode growth. A blocked tenant is SKIPPED for the round —
        never head-of-line blocking for other tenants."""
        quota = self.tenant_quotas.get(tenant)
        if not quota:
            return True
        mine = [s for s in self.slots
                if s is not None and s.tenant == tenant]
        if quota.get("slots") is not None and len(mine) >= quota["slots"]:
            return False
        if quota.get("blocks") is not None:
            committed = sum(s.max_blocks for s in mine)
            cost = self.max_request_blocks(len(req.prompt),
                                           req.max_new_tokens)
            if committed + cost > quota["blocks"]:
                return False
        return True

    # ---- cache hierarchy: demotion / swap-in / prefetch (PR 16) ----------

    def _payload_bytes(self, payload) -> int:
        return sum(int(a.nbytes) for a in payload)

    def _demote_block(self, rid: int, block: int) -> int | None:
        """Move holder ``rid``'s interest in device ``block`` to the host
        tier: returns a host block id holding ``block``'s content, or
        None (no state change) when the store is full.  Deduplicated —
        if a live host copy of this exact content already exists
        (``_dev_to_host``), it is ref-bumped instead of copied, so a
        COW-shared block spills once no matter how many holders demote
        it.  Does NOT drop the pool hold; the caller frees the device
        block after banking the returned host id."""
        return self._demote_blocks(rid, [block])[0]

    def _demote_blocks(self, rid: int, blocks: list[int]) -> list:
        """Batched :meth:`_demote_block`: one d2h gather dispatch per
        pool leaf for the subset that actually needs copying (dedup
        hits just ref-bump).  Mirrors :meth:`_swap_in_blocks` — per-op
        dispatch overhead dominates single-block transfers, so both
        directions of the swap path batch.  Returns a per-block list of
        host ids with None entries where the store filled up (those
        blocks are left untouched)."""
        dedup = []
        copy_blocks = []
        for b in blocks:
            h = self._dev_to_host.get(b)
            if h is None or self.store.refcount(h) == 0:
                h = None
                copy_blocks.append(b)
            dedup.append(h)
        d2h_many = getattr(self.io, "d2h_many", None)
        if d2h_many is not None and copy_blocks:
            payloads = dict(zip(copy_blocks, d2h_many(copy_blocks)))
        else:
            payloads = {b: self.io.d2h(b) for b in copy_blocks}
        out = []
        full = False
        for b, h in zip(blocks, dedup):
            if h is not None:
                self.store.share(rid, [h])
            elif full:
                out.append(None)
                continue
            else:
                p = payloads[b]
                h = self.store.put(rid, p)
                if h is None:
                    full = True
                    out.append(None)
                    continue
                self._dev_to_host[b] = h
                self.spill_d2h_bytes += self._payload_bytes(p)
            self.spill_out_blocks += 1
            out.append(h)
        return out

    def _swap_in_block(self, rid: int, dst: int, host: int) -> None:
        """h2d one host block into device block ``dst`` (already
        allocated to ``rid``); the host hold is NOT dropped here."""
        self._swap_in_blocks(rid, [(dst, host)])

    def _swap_in_blocks(self, rid: int,
                        pairs: list[tuple[int, int]]) -> None:
        """h2d a batch of host blocks into already-allocated device
        blocks — one dispatch per pool leaf when the io adapter offers
        ``h2d_many``.  The eager scatter's per-op dispatch overhead is
        the swap path's dominant cost and it amortizes across the
        batch, so every multi-block swap-in (record resume, prefetch,
        multi-node claim promotion) routes through here.  Host holds
        are NOT dropped here."""
        if not pairs:
            return
        payloads = [self.store.get(h) for _, h in pairs]
        h2d_many = getattr(self.io, "h2d_many", None)
        if h2d_many is not None:
            h2d_many([d for d, _ in pairs], payloads)
        else:
            for (d, _), p in zip(pairs, payloads):
                self.io.h2d(d, p)
        self.spill_in_blocks += len(pairs)
        self.spill_h2d_bytes += sum(
            self._payload_bytes(p) for p in payloads)

    def _reclaim_one(self, reason: str) -> bool:
        """Free one device block, cheapest-first: revoke a prefetch-staged
        block (free — the host copy was retained), demote the coldest
        trie block to host (one d2h copy, trie structure preserved), and
        only then the destructive LRU leaf eviction.  With the hierarchy
        off this is EXACTLY the legacy behavior: only the destructive
        branch exists."""
        if self.store is not None and self._revoke_prefetch():
            return True
        if self.prefix is not None and self.store is not None:
            freed = self.prefix.demote_many(
                self.pool, self._cache_demote_batch, limit=8)
            if freed:
                if self.rec.enabled:
                    self.rec.emit("spill.demote", cat="serve",
                                  actor="scheduler",
                                  payload={"blocks": freed,
                                           "reason": reason}, t=self.now)
                return True
        if (self.prefix is not None
                and self.prefix.evict_one(self.pool) is not None):
            self.prefix_evictions += 1
            if self.rec.enabled:
                self.rec.emit("prefix.evict", cat="serve",
                              actor="scheduler",
                              payload={"reason": reason}, t=self.now)
            return True
        return False

    def _cache_demote(self, block: int) -> int | None:
        """The trie's demote callable: spill for CACHE_RID and drop the
        cache's pool hold on success."""
        h = self._demote_block(CACHE_RID, block)
        if h is not None:
            self.pool.free(CACHE_RID, [block])
        return h

    def _cache_demote_batch(self, blocks: list[int]) -> list:
        """Batch form of :meth:`_cache_demote` for the trie's
        :meth:`~.prefix_index.PrefixIndex.demote_many`."""
        hs = self._demote_blocks(CACHE_RID, blocks)
        self.pool.free(CACHE_RID,
                       [b for b, h in zip(blocks, hs) if h is not None])
        return hs

    def _promote_nodes(self, nodes) -> list[int] | None:
        """Swap a batch of spilled trie nodes' payloads back onto device
        (one h2d dispatch per pool leaf) so a claim can ref-bump them.
        Returns the new device block ids in node order, or None when the
        blocks cannot be found even after reclaim (the claim falls back
        to re-prefill).  Safe against self-reclaim: the claim shares
        every device-resident node of its chain BEFORE promoting, so
        reclaim can neither demote nor evict a block the claim stands
        on, and spilled nodes are untouchable by either ladder rung."""
        got = self.pool.alloc(CACHE_RID, len(nodes))
        while got is None and self._reclaim_one("promote"):
            got = self.pool.alloc(CACHE_RID, len(nodes))
        if got is None:
            return None
        self._swap_in_blocks(
            CACHE_RID, [(d, n.host) for d, n in zip(got, nodes)])
        for d, node in zip(got, nodes):
            h = node.host
            self.store.free(CACHE_RID, [h])
            if self.store.refcount(h) > 0:
                self._dev_to_host[d] = h
            node.block = d
            node.host = None
        return got

    def _demote_slot(self, slot: _Slot) -> bool:
        """Preemption as demotion: spill the victim's WRITTEN blocks to
        host and bank a spill record so admission resumes it by swap-in
        (phase DECODE, zero re-prefill) instead of re-prefilling.
        Only decode-phase victims qualify — a mid-prefill victim has
        cheap state to rebuild and its partial chunks are not all
        block-aligned.  Returns False (caller frees destructively) when
        the hierarchy is off or the store cannot take the copies."""
        if self.store is None or slot.phase != DECODE or slot.written < 1:
            return False
        n_keep = blocks_for(slot.written, self.block_size)
        keep = slot.blocks[:n_keep]
        if self.store.capacity is not None:
            new_copies = sum(
                1 for b in keep
                if (h := self._dev_to_host.get(b)) is None
                or self.store.refcount(h) == 0)
            if (self.store.live_blocks() + new_copies
                    > self.store.capacity):
                return False
        hs = self._demote_blocks(slot.rid, keep)
        if any(h is None for h in hs):
            # bounded store pre-checked above — defensive
            self.store.free(slot.rid, [h for h in hs if h is not None])
            return False
        entries: list[tuple] = [("host", h) for h in hs]
        self.pool.free(slot.rid, slot.blocks)
        self._spilled[slot.rid] = {
            "entries": entries,
            "written": int(slot.written),
            "pending": int(slot.pending),
        }
        if self.rec.enabled:
            self.rec.emit("spill.out", cat="serve", actor="scheduler",
                          payload={"rid": slot.rid, "blocks": n_keep,
                                   "written": int(slot.written)},
                          t=self.now)
        return True

    def _swap_in_record(self, rid: int, record: dict) -> list[int] | None:
        """Materialize a spill record's blocks on device for admission.
        Staged entries already own their device block (drop the retained
        host hold); unstaged entries h2d into freshly allocated blocks.
        All-or-nothing: on allocation failure nothing changes and the
        record stays banked for a later tick."""
        entries = record["entries"]
        # recompute `need` after every reclaim: a reclaim can revoke a
        # staged entry of THIS record (it is still queued), flipping a
        # ("dev", ...) entry back to ("host", ...)
        while True:
            need = sum(1 for e in entries if e[0] == "host")
            fresh = self.pool.alloc(rid, need)
            if fresh is not None:
                break
            if not self._reclaim_one("swap_in"):
                return None
        blocks: list[int] = []
        hosts: list[int] = []
        fi = 0
        bs = self.block_size
        for e in entries:
            if e[0] == "dev":
                blocks.append(e[1])
                hosts.append(e[2])
            else:
                blocks.append(fresh[fi])
                hosts.append(e[1])
                fi += 1
        self._swap_in_blocks(rid, [
            (blocks[j], hosts[j]) for j, e in enumerate(entries)
            if e[0] == "host"])
        for j, (d, h) in enumerate(zip(blocks, hosts)):
            self.store.free(rid, [h])
            # bank the content association only for FULL immutable
            # blocks — the partial tail block is rewritten by decode
            if ((j + 1) * bs <= record["written"]
                    and self.store.refcount(h) > 0):
                self._dev_to_host[d] = h
        return blocks

    def prefetch(self) -> int:
        """Stage queued spilled continuations' host blocks back onto
        device AHEAD of admission (the engine calls this between sweep
        and admit every tick), so the h2d copies overlap decode launches
        instead of serializing with the resume.  Greedy in queue order,
        but never below a growth reserve of one free block per resident
        slot — staging must not starve decode growth into preempting
        somebody.  Staged blocks keep their host hold (revocable for
        free).  Returns the number of blocks staged."""
        if self.store is None or not self._spilled:
            return 0
        self._prefetch_clock += 1
        staged = 0
        resident = sum(1 for s in self.slots if s is not None)
        for req in self.queue:
            record = self._spilled.get(req.rid)
            if record is None:
                continue
            # a recently revoked record sits out a few ticks — without
            # the cooldown a tight pool thrashes stage -> revoke ->
            # re-stage, paying a real h2d copy each lap
            if record.get("cool_until", 0) > self._prefetch_clock:
                continue
            todo = [(j, e[1])
                    for j, e in enumerate(record["entries"])
                    if e[0] == "host"]
            if not todo:
                continue
            if self.pool.free_blocks - len(todo) < resident:
                continue  # not enough headroom for the WHOLE record
            got = self.pool.alloc(req.rid, len(todo))
            if got is None:
                return staged
            self._swap_in_blocks(req.rid, [
                (d, h) for d, (_, h) in zip(got, todo)])
            for d, (j, h) in zip(got, todo):
                record["entries"][j] = ("dev", d, h)
                if ((j + 1) * self.block_size <= record["written"]
                        and self.store.refcount(h) > 0):
                    self._dev_to_host[d] = h
                self.spill_prefetched_blocks += 1
                staged += 1
        if staged and self.rec.enabled:
            self.rec.emit("spill.prefetch", cat="serve",
                          actor="scheduler",
                          payload={"blocks": staged}, t=self.now)
        return staged

    def _revoke_prefetch(self) -> bool:
        """Un-stage ONE prefetched block to relieve pool pressure — the
        host hold was retained, so this frees a device block without
        losing anything.  Deepest-queued record, last entry first (the
        work farthest from being needed)."""
        for req in reversed(self.queue):
            record = self._spilled.get(req.rid)
            if record is None:
                continue
            for j in range(len(record["entries"]) - 1, -1, -1):
                e = record["entries"][j]
                if e[0] == "dev":
                    _, d, h = e
                    self.pool.free(req.rid, [d])
                    record["entries"][j] = ("host", h)
                    record["cool_until"] = self._prefetch_clock + 8
                    return True
        return False

    def _drop_spill_record(self, rid: int) -> None:
        """Release every hold a spill record owns (terminal sweep of a
        queued spilled continuation, or engine shutdown)."""
        record = self._spilled.pop(rid, None)
        if record is None:
            return
        for e in record["entries"]:
            if e[0] == "dev":
                _, d, h = e
                self.pool.free(rid, [d])
                self.store.free(rid, [h])
            else:
                self.store.free(rid, [e[1]])

    def release_spill_store(self) -> int:
        """Drop every spill record (engine close).  Trie host holds are
        released by :meth:`release_prefix_cache`.  Returns the number of
        records dropped."""
        rids = list(self._spilled)
        for rid in rids:
            self._drop_spill_record(rid)
        return len(rids)

    def check_leaks(self) -> None:
        """Joint device+host ledger audit: the pool and store invariants,
        plus the cross-tier ones — every spill-record entry holds what it
        claims on both tiers, every spilled trie node's host block is
        held for the cache, and the dedup map only keys live device
        blocks."""
        self.pool.check_leaks()
        if self.store is None:
            return
        self.store.check_leaks()
        for rid, record in self._spilled.items():
            host_owned = set(self.store.owned_by(rid))
            dev_owned = set(self.pool.owned_by(rid))
            for e in record["entries"]:
                h = e[2] if e[0] == "dev" else e[1]
                if h not in host_owned:
                    raise AssertionError(
                        f"spill record {rid}: host block {h} not held")
                if e[0] == "dev" and e[1] not in dev_owned:
                    raise AssertionError(
                        f"spill record {rid}: staged device block "
                        f"{e[1]} not held")
        if self.prefix is not None:
            cache_host = set(self.store.owned_by(CACHE_RID))
            for _, _, node in self.prefix.walk():
                if node.block is None and node.host not in cache_host:
                    raise AssertionError(
                        f"spilled trie node host block {node.host} "
                        "not held for CACHE_RID")
        for d in self._dev_to_host:
            if self.pool.refcount(d) == 0:
                raise AssertionError(
                    f"dedup map keys recycled device block {d}")

    def _claim_blocks(self, req: Request) -> tuple[list[int], int] | None:
        """The request's admission blocks: cached-prefix blocks claimed by
        ref-bump first (prefix cache on), then fresh blocks for the rest
        of the padded prompt footprint — trying LRU leaf eviction before
        giving up when the pool is dry.  Returns ``(blocks, prefix_len)``
        or None (no state change) when the blocks cannot be found.  The
        claim is ref-bumped BEFORE the fresh alloc so eviction can never
        free a block the claim is standing on."""
        P = len(req.prompt)
        padded = -(-P // self.prefill_chunk) * self.prefill_chunk
        need = blocks_for(padded, self.block_size)
        shared: list[int] = []
        prefix_len = 0
        if self.prefix is not None:
            if self.store is not None:
                # hierarchy on: the match may include SPILLED nodes —
                # promote them by swap-in so the claim still saves their
                # prefill.  Two passes: first ref-bump every device-
                # resident node of the chain (so reclaim during the
                # promotion allocs can never free a block the claim
                # stands on), then promote ALL spilled nodes in one
                # batched h2d.  On a promotion failure (pool dry even
                # after reclaim) drop the whole claim and fall back to
                # a plain alloc — a shorter claim could misalign the
                # suffix chunk start.
                hit_nodes = self.prefix.match_nodes(
                    req.prompt, adapter=int(req.adapter))
                cap = ((P - 1) // self._claim_g) * self._claim_g
                prefix_len = min(len(hit_nodes) * self.block_size, cap)
                use = hit_nodes[:prefix_len // self.block_size]
                spilled = [n for n in use if n.block is None]
                failed = any(n.host is None for n in spilled)
                if not failed:
                    for n in use:
                        if n.block is not None:
                            self.pool.share(req.rid, [n.block])
                            shared.append(n.block)
                    if spilled:
                        promoted = self._promote_nodes(spilled)
                        if promoted is None:
                            failed = True
                        else:
                            self.pool.share(req.rid, promoted)
                            shared = [n.block for n in use]
                            self.swapin_tokens_saved += (
                                len(spilled) * self.block_size)
                if failed:
                    if shared:
                        self.pool.free(req.rid, shared)
                    shared = []
                    prefix_len = 0
            else:
                hit = self.prefix.match(req.prompt,
                                        adapter=int(req.adapter))
                cap = ((P - 1) // self._claim_g) * self._claim_g
                prefix_len = min(len(hit) * self.block_size, cap)
                shared = hit[:prefix_len // self.block_size]
                if shared:
                    self.pool.share(req.rid, shared)
        fresh = self.pool.alloc(req.rid, need - len(shared))
        while fresh is None and self._reclaim_one("admit"):
            fresh = self.pool.alloc(req.rid, need - len(shared))
        if fresh is None:
            if shared:
                self.pool.free(req.rid, shared)
            return None
        return shared + fresh, prefix_len

    # ---- tick planning ---------------------------------------------------

    def plan(self) -> tuple[str, object]:
        """What the engine should launch this tick: ``("prefill", slot)``
        one chunk for the oldest mid-prefill slot, ``("decode", [slots])``
        one decode step over the active population, or ``("idle", None)``.
        When both phases have work they ALTERNATE (chunked prefill
        interleaved with decode — a long prompt no longer stalls every
        resident stream for its whole prefill)."""
        prefills = [i for i, s in enumerate(self.slots)
                    if s is not None and s.phase == PREFILL]
        decodes = [i for i, s in enumerate(self.slots)
                   if s is not None and s.phase == DECODE]
        if prefills and (self._prefer_prefill or not decodes):
            self._prefer_prefill = False
            best = min(prefills,
                       key=lambda i: self.slots[i].admitted_seq)
            return (PREFILL, best)
        if decodes:
            self._prefer_prefill = bool(prefills)
            ready = self._grow_for_decode(decodes)
            if ready:
                return (DECODE, ready)
            prefills = [i for i, s in enumerate(self.slots)
                        if s is not None and s.phase == PREFILL]
            if prefills:
                best = min(prefills,
                           key=lambda i: self.slots[i].admitted_seq)
                return (PREFILL, best)
        return ("idle", None)

    def _grow_for_decode(self, decodes: list[int]) -> list[int]:
        """Every decoding slot must own the block its next write lands in;
        grow by one block where needed. When the pool is dry the prefix
        cache (if on) gives up LRU leaves FIRST — dropping cold cached
        suffixes nobody holds — and only then is the youngest other
        resident preempted (the prefix-off behavior, unchanged)."""
        ready = []
        for i in list(decodes):
            slot = self.slots[i]
            if slot is None:  # preempted by an earlier growth this tick
                continue
            while len(slot.blocks) * self.block_size < slot.written + 1:
                got = self.pool.alloc(slot.rid, 1)
                if got is not None:
                    slot.blocks.extend(got)
                    continue
                if self._reclaim_one("decode_grow"):
                    continue
                victim = self._pick_victim(exclude=i)
                if victim is None:
                    break  # stalled: no blocks, nothing to preempt
                self._preempt(victim)
            else:
                ready.append(i)
        return [i for i in ready if self.slots[i] is not None]

    def _pick_victim(self, exclude: int) -> int | None:
        """Deterministic victim choice, pinned by the _pick_victim test:
        the YOUNGEST resident by admission order (highest
        ``admitted_seq``) is evicted first — the request that has
        received the least service loses its residency, which bounds
        re-prefill waste and can never starve the head-of-line request.
        ``admitted_seq`` is unique (one counter, bumped per admission),
        so the max is total and two seeded runs can never diverge here —
        this ordering is also the restore path's anchor:
        ``snapshot_state`` writes residents in admission order."""
        live = [(s.admitted_seq, i) for i, s in enumerate(self.slots)
                if s is not None and i != exclude and s.blocks]
        if not live:
            return None
        return max(live)[1]  # youngest admission goes first

    def _preempt(self, i: int) -> None:
        slot = self.slots[i]
        # hierarchy on: demote the written blocks to host instead of
        # destroying them — the continuation below still queues, but
        # admission resumes it by swap-in with zero re-prefill
        spilled = self._demote_slot(slot)
        if not spilled:
            self.pool.free(slot.rid, slot.blocks)
        # continuation request: this residency's prompt plus every token
        # it emitted; budget = whatever is still owed. Position-derived
        # sampling keys make the re-run emit exactly the tokens it would
        # have produced uninterrupted, so preemption never forks the
        # stream. Goes to the FRONT of the queue (it was already served).
        cont_prompt = slot.prompt
        if slot.emitted_here:
            tail = self.emitted[slot.rid][-slot.emitted_here:]
            cont_prompt = np.concatenate(
                [slot.prompt, np.asarray(tail, np.int32)])
        self.queue.insert(0, Request(
            rid=slot.rid, prompt=cont_prompt,
            max_new_tokens=slot.budget, rng=slot.rng,
            arrival=float("-inf"),
            tenant=slot.tenant, adapter=slot.adapter))
        self.slots[i] = None
        self.preemptions += 1
        self._tc(slot.tenant)["preempted"] += 1
        if self.rec.enabled:
            self.rec.emit("req.preempt", cat="serve", actor="scheduler",
                          payload={"rid": slot.rid, "slot": i,
                                   "emitted": slot.emitted_here,
                                   "spilled": spilled,
                                   "tenant": slot.tenant}, t=self.now)

    # ---- fleet tier: stream migration (PR 18) ----------------------------

    def migratable_blocks(self, rid: int) -> list[int]:
        """Device blocks whose contents must travel for ``rid`` to resume
        by swap-in on another replica: the WRITTEN blocks of a resident
        decode-phase slot, in position order.  Empty for mid-prefill
        residents and queued requests — their continuation re-prefills
        at the target, which lands on the same stream bitwise anyway
        (position-derived sampling keys)."""
        for s in self.slots:
            if s is not None and s.rid == rid:
                if s.phase != DECODE or s.written < 1:
                    return []
                return list(s.blocks[:blocks_for(s.written,
                                                 self.block_size)])
        return []

    def detach_stream(self, rid: int) -> dict:
        """Detach a live request into a portable migration record — the
        ``_preempt`` continuation transform, except the continuation
        leaves this scheduler entirely instead of re-queueing here.
        Every local hold is released (pool blocks; a queued spilled
        continuation drops its spill record — the target re-prefills);
        the record carries everything :meth:`attach_stream` needs to
        continue the stream bitwise elsewhere.  Emitted tokens and
        lifecycle meta TRAVEL with the stream (popped here, installed
        there), so fleet-aggregated per-tenant counters stay a disjoint
        sum: ``submitted`` counted once at the source, the terminal
        status once at wherever the stream finishes.  KV payloads do NOT
        travel here — the engine d2h-copies :meth:`migratable_blocks`
        BEFORE calling this and attaches them to the returned record.
        Raises KeyError for unknown or terminal rids."""
        if rid in self.finished:
            raise KeyError(
                f"rid {rid} is terminal ({self.finished[rid]}); "
                "only live streams migrate")
        record: dict | None = None
        for i, s in enumerate(self.slots):
            if s is not None and s.rid == rid:
                cont_prompt = s.prompt
                if s.emitted_here:
                    tail = self.emitted[rid][-s.emitted_here:]
                    cont_prompt = np.concatenate(
                        [s.prompt, np.asarray(tail, np.int32)])
                self.pool.free(rid, s.blocks)
                self.slots[i] = None
                # migration IS preemption from this tenant's viewpoint:
                # the residency ended before the budget was spent
                self._tc(s.tenant)["preempted"] += 1
                record = {
                    "rid": rid, "prompt": cont_prompt,
                    "budget": int(s.budget), "rng": s.rng,
                    "arrival": float("-inf"),  # already served once
                    "tenant": int(s.tenant), "adapter": int(s.adapter),
                    "written": int(s.written) if s.phase == DECODE else 0,
                    "pending": int(s.pending) if s.phase == DECODE else 0,
                }
                break
        if record is None:
            for j, r in enumerate(self.queue):
                if r.rid == rid:
                    if self.store is not None:
                        self._drop_spill_record(rid)
                    self.queue.pop(j)
                    record = {
                        "rid": rid,
                        "prompt": np.asarray(r.prompt, np.int32),
                        "budget": int(r.max_new_tokens), "rng": r.rng,
                        "arrival": float(r.arrival),
                        "tenant": int(r.tenant),
                        "adapter": int(r.adapter),
                        "written": 0, "pending": 0,
                    }
                    break
        if record is None:
            raise KeyError(f"rid {rid} not live on this scheduler")
        record["emitted"] = list(self.emitted.pop(rid, []))
        record["first_emit"] = bool(self.first_emit.pop(rid, False))
        m = self.meta.pop(rid, None)
        record["meta"] = None if m is None else [m[0], m[1], m[2]]
        self.tenant_of.pop(rid, None)
        record["payloads"] = []
        record["payload_bytes"] = 0
        self.migrated_out += 1
        if self.rec.enabled:
            self.rec.emit("req.migrate_out", cat="serve",
                          actor="scheduler",
                          payload={"rid": rid,
                                   "written": int(record["written"]),
                                   "tenant": int(record["tenant"])},
                          t=self.now)
        return record

    @staticmethod
    def continuation_record(*, rid: int, prompt, budget: int, rng,
                            emitted=(), tenant: int = 0, adapter: int = 0,
                            first_emit: bool | None = None,
                            meta=None,
                            arrival: float = float("-inf")) -> dict:
        """Build an :meth:`attach_stream`-compatible record WITHOUT a
        source scheduler — the supervisor-side continuation transform
        for a replica that died with no orderly :meth:`detach_stream`.
        ``prompt`` must already be the continuation prompt (the base
        prompt at dispatch plus every token observed since), ``budget``
        the remaining token budget, and ``emitted`` the stream's FULL
        emitted history (it travels with the record so fleet-merged
        completions stay a disjoint sum).  KV never survives a hard
        crash, so the record carries no payloads: the adopter
        re-prefills, which position-derived sampling keys make bitwise
        identical to the uninterrupted stream."""
        if budget < 1:
            raise ValueError(
                f"rid {rid}: a continuation needs budget >= 1, got "
                f"{budget} (an exhausted stream is terminal, not live)")
        emitted = [int(t) for t in emitted]
        return {
            "rid": int(rid),
            "prompt": np.asarray(prompt, np.int32).reshape(-1),
            "budget": int(budget),
            "rng": np.asarray(rng, np.uint32),
            "arrival": float(arrival),
            "tenant": int(tenant), "adapter": int(adapter),
            "written": 0, "pending": 0,
            "emitted": emitted,
            "first_emit": (bool(emitted) if first_emit is None
                           else bool(first_emit)),
            "meta": None if meta is None else [meta[0], meta[1], meta[2]],
            "payloads": [], "payload_bytes": 0,
        }

    def attach_stream(self, record: dict) -> None:
        """Adopt a migrated stream: install its identity maps and queue
        the continuation at the FRONT (it was already served elsewhere).
        KV payloads (if any) are banked into the host spill store as a
        spill record, so admission resumes the stream by swap-in — the
        same bytes the source replica wrote, which is why the continued
        stream is bitwise the uninterrupted one.  All-or-nothing: a full
        store rolls back every put and raises RuntimeError with no state
        change.  Deliberately bypasses :meth:`submit` — ``submitted``
        was counted at the source and must never recount here (the
        fleet-aggregation conservation pin)."""
        rid = int(record["rid"])
        if (rid in self.finished
                or any(s is not None and s.rid == rid
                       for s in self.slots)
                or any(r.rid == rid for r in self.queue)):
            raise ValueError(
                f"rid {rid} already live or terminal on this scheduler")
        payloads = record.get("payloads") or []
        if payloads:
            if self.store is None:
                raise RuntimeError(
                    "adopting KV payloads needs a host spill store "
                    "(attach landing pad); re-export without KV to "
                    "re-prefill instead")
            hs: list[int] = []
            for p in payloads:
                h = self.store.put(rid, p)
                if h is None:
                    self.store.free(rid, hs)
                    raise RuntimeError(
                        f"host store full adopting rid {rid} "
                        f"({len(payloads)} KV blocks)")
                hs.append(h)
            self._spilled[rid] = {
                "entries": [("host", h) for h in hs],
                "written": int(record["written"]),
                "pending": int(record["pending"]),
            }
        self.emitted[rid] = list(record.get("emitted", []))
        self.first_emit[rid] = bool(record.get("first_emit", False))
        self.tenant_of[rid] = int(record.get("tenant", 0))
        m = record.get("meta")
        if m is not None:
            self.meta[rid] = (
                float(m[0]),
                None if m[1] is None else float(m[1]),
                None if m[2] is None else float(m[2]))
        self.queue.insert(0, Request(
            rid=rid, prompt=np.asarray(record["prompt"], np.int32),
            max_new_tokens=int(record["budget"]),
            rng=np.asarray(record["rng"], np.uint32),
            arrival=float(record.get("arrival", float("-inf"))),
            tenant=int(record.get("tenant", 0)),
            adapter=int(record.get("adapter", 0))))
        self.migrated_in += 1
        if self.rec.enabled:
            self.rec.emit("req.migrate_in", cat="serve",
                          actor="scheduler",
                          payload={"rid": rid,
                                   "kv_blocks": len(payloads),
                                   "written": int(record["written"]),
                                   "tenant": int(record["tenant"])},
                          t=self.now)

    # ---- result application ---------------------------------------------

    def prefill_done_chunks(self, slot_idx: int) -> int:
        s = self.slots[slot_idx]
        return -(-len(s.prompt) // self.prefill_chunk)

    def apply_prefill(self, slot_idx: int, token: int) -> list[tuple]:
        """One chunk finished for ``slot_idx``; ``token`` is the program's
        sample from the chunk's last valid row (meaningful only on the
        final chunk). Returns [(rid, token, first, done)] events."""
        s = self.slots[slot_idx]
        s.chunk_cursor += 1
        s.written = min(s.chunk_cursor * self.prefill_chunk,
                        len(s.prompt))
        if s.chunk_cursor < self.prefill_done_chunks(slot_idx):
            return []
        # final chunk: the sample at position P is the first new token
        s.written = len(s.prompt)
        s.phase = DECODE
        s.pending = int(token)
        if self.prefix is not None:
            # cache the FULL prompt blocks (all their positions hold true
            # prompt KV, written by deterministic chunk-aligned prefill —
            # bitwise what any token-identical prompt would compute);
            # existing nodes win, new nodes ref-bump for the cache
            n_full = len(s.prompt) // self.block_size
            if n_full:
                self.prefix.insert(
                    s.prompt[:n_full * self.block_size],
                    s.blocks[:n_full], adapter=int(s.adapter),
                    pool=self.pool)
        return self._emit(slot_idx, int(token))

    def apply_decode(self, slot_idx: int, token: int) -> list[tuple]:
        s = self.slots[slot_idx]
        s.written += 1  # the step wrote pending's k/v at `written`
        s.pending = int(token)
        return self._emit(slot_idx, int(token))

    def _emit(self, slot_idx: int, token: int) -> list[tuple]:
        s = self.slots[slot_idx]
        rid = s.rid
        self.emitted[rid].append(token)
        first = not self.first_emit[rid]
        self.first_emit[rid] = True
        s.budget -= 1
        s.emitted_here += 1
        self._tc(s.tenant)["tokens"] += 1
        done = s.budget == 0
        if done:
            self.pool.free(rid, s.blocks)
            self.slots[slot_idx] = None
            self.done.add(rid)
            self.finished[rid] = "done"
            self._tc(s.tenant)["done"] += 1
        return [(rid, token, first, done)]

    # ---- lifecycle: cancellation, deadlines (PR 11) ----------------------

    def cancel(self, rid: int) -> bool:
        """Client cancellation — honored at the NEXT step boundary (the
        sweep), never mid-launch, so the in-flight program completes and
        the ledger stays clean. Returns False for unknown/terminal rids
        (cancelling twice, or after completion, is a no-op)."""
        known = rid in self.emitted and rid not in self.finished
        if known:
            self._cancel_pending.add(rid)
        return known

    def _terminal_status(self, rid: int, now: float) -> str | None:
        if rid in self._cancel_pending:
            return "cancelled"
        arrival, ttft_dl, total_dl = self.meta.get(rid, (0.0, None, None))
        if total_dl is not None and now - arrival > total_dl:
            return "expired"
        if (ttft_dl is not None and not self.first_emit.get(rid, False)
                and now - arrival > ttft_dl):
            return "expired"
        return None

    def sweep(self, now: float) -> list[tuple]:
        """Step-boundary lifecycle sweep: pending cancellations and
        deadline breaches terminate requests HERE. Resident victims free
        their slot and blocks immediately (``check_leaks`` clean); queued
        victims (including preempted continuations — their clock is the
        ORIGINAL arrival in ``meta``) just leave the queue. Emits one
        terminal pseudo-event ``(rid, -1, False, True, status)`` per
        casualty; the already-emitted tokens remain in ``emitted`` as a
        bitwise prefix of the uninterrupted stream."""
        out = []
        for i, s in enumerate(self.slots):
            if s is None:
                continue
            status = self._terminal_status(s.rid, now)
            if status:
                self.pool.free(s.rid, s.blocks)
                self.slots[i] = None
                out.append(self._finish(s.rid, status))
        if self.queue:
            keep = []
            for req in self.queue:
                status = self._terminal_status(req.rid, now)
                if status is None:
                    keep.append(req)
                else:
                    if self.store is not None:
                        self._drop_spill_record(req.rid)
                    if req.rid not in self.finished:
                        out.append(self._finish(req.rid, status))
            self.queue = keep
        self._cancel_pending.clear()
        return out

    def _finish(self, rid: int, status: str) -> tuple:
        self.finished[rid] = status
        if status == "cancelled":
            self.cancelled += 1
        else:
            self.expired += 1
        self._tc(self.tenant_of.get(rid, 0))[status] += 1
        return (rid, -1, False, True, status)

    # ---- prefix cache management -----------------------------------------

    def release_prefix_cache(self) -> int:
        """Drop the whole trie and release its block holds (engine close;
        also what makes ``check_leaks`` meaningful at shutdown). Returns
        the number of blocks released."""
        if self.prefix is None:
            return 0
        return self.prefix.drop(self.pool, store=self.store)

    # ---- snapshot / restore (PR 11) --------------------------------------

    def snapshot_state(self) -> dict:
        """JSON-serializable host state for the engine snapshot: every
        resident as a CONTINUATION (the ``_preempt`` transform — prompt
        plus emitted tail, remaining budget, same rng), residents first
        in admission order then the queue in order; plus the emitted /
        terminal maps and counters. The block pool and device cache are
        deliberately NOT captured — restore re-prefills each
        continuation, and position-derived sampling keys make the re-run
        land on the same stream bitwise."""
        requests = []
        live = sorted((s for s in self.slots if s is not None),
                      key=lambda s: s.admitted_seq)
        for s in live:
            prompt = s.prompt
            if s.emitted_here:
                tail = self.emitted[s.rid][-s.emitted_here:]
                prompt = np.concatenate(
                    [s.prompt, np.asarray(tail, np.int32)])
            requests.append({
                "rid": int(s.rid),
                "prompt": [int(t) for t in prompt],
                "budget": int(s.budget),
                "rng": [int(x) for x in np.asarray(s.rng).ravel()],
                "arrival": float("-inf"),  # already served once
                "tenant": int(s.tenant),
                "adapter": int(s.adapter),
            })
        for r in self.queue:
            requests.append({
                "rid": int(r.rid),
                "prompt": [int(t) for t in np.asarray(r.prompt)],
                "budget": int(r.max_new_tokens),
                "rng": [int(x) for x in np.asarray(r.rng).ravel()],
                "arrival": float(r.arrival),
                "tenant": int(r.tenant),
                "adapter": int(r.adapter),
            })
        return {
            "requests": requests,
            "emitted": {str(k): [int(t) for t in v]
                        for k, v in self.emitted.items()},
            "first_emit": sorted(
                int(k) for k, v in self.first_emit.items() if v),
            "done": sorted(int(r) for r in self.done),
            "finished": {str(k): v for k, v in self.finished.items()},
            "meta": {str(k): [v[0], v[1], v[2]]
                     for k, v in self.meta.items()},
            "counters": {"seq": self._seq,
                         "preemptions": self.preemptions,
                         "shed": self.shed,
                         "cancelled": self.cancelled,
                         "expired": self.expired,
                         "prefix_hit_tokens": self.prefix_hit_tokens,
                         "prefill_tokens_saved": self.prefill_tokens_saved,
                         "prefix_evictions": self.prefix_evictions,
                         "spill_out_blocks": self.spill_out_blocks,
                         "spill_in_blocks": self.spill_in_blocks,
                         "spill_d2h_bytes": self.spill_d2h_bytes,
                         "spill_h2d_bytes": self.spill_h2d_bytes,
                         "spill_prefetched_blocks":
                             self.spill_prefetched_blocks,
                         "spill_resumes": self.spill_resumes,
                         "swapin_tokens_saved": self.swapin_tokens_saved,
                         "migrated_out": self.migrated_out,
                         "migrated_in": self.migrated_in},
            "tenant_of": {str(k): int(v)
                          for k, v in self.tenant_of.items()},
            "tenants": {str(k): dict(v)
                        for k, v in self.tenants.items()},
            # the prefix trie is deliberately NOT captured: it is host
            # state derived from token ids + deterministic prefills, and
            # the restoring engine's pool is zeroed — the trie rebuilds
            # itself as continuations re-prefill (bitwise-identical KV).
            # Spill RECORDS are likewise not captured (their payloads
            # are process RAM): a queued spilled continuation restores
            # as an ordinary continuation and re-prefills — or claims a
            # warm persisted prefix when the engine saved cache contents
            # (persist_cache).  Either way the stream is unchanged.
        }

    def restore_state(self, snap: dict) -> None:
        """Rebuild from :meth:`snapshot_state` output onto a FRESH
        scheduler (no residents, empty queue — the restoring engine owns
        a zeroed pool). Every snapshotted request re-enters as a queued
        continuation and re-prefills through normal admission."""
        if self.has_resident or self.queue:
            raise RuntimeError(
                "restore_state needs a fresh scheduler (residents or "
                "queue present)")
        self.queue = [
            Request(rid=int(r["rid"]),
                    prompt=np.asarray(r["prompt"], np.int32),
                    max_new_tokens=int(r["budget"]),
                    rng=np.asarray(r["rng"], np.uint32),
                    arrival=float(r["arrival"]),
                    tenant=int(r.get("tenant", 0)),
                    adapter=int(r.get("adapter", 0)))
            for r in snap["requests"]
        ]
        self.emitted = {int(k): [int(t) for t in v]
                        for k, v in snap["emitted"].items()}
        self.first_emit = {rid: False for rid in self.emitted}
        for rid in snap["first_emit"]:
            self.first_emit[int(rid)] = True
        self.done = {int(r) for r in snap["done"]}
        self.finished = {int(k): v for k, v in snap["finished"].items()}
        self.meta = {
            int(k): (float(v[0]),
                     None if v[1] is None else float(v[1]),
                     None if v[2] is None else float(v[2]))
            for k, v in snap["meta"].items()
        }
        c = snap["counters"]
        self._seq = int(c["seq"])
        self.preemptions = int(c["preemptions"])
        self.shed = int(c["shed"])
        self.cancelled = int(c["cancelled"])
        self.expired = int(c["expired"])
        self.prefix_hit_tokens = int(c.get("prefix_hit_tokens", 0))
        self.prefill_tokens_saved = int(c.get("prefill_tokens_saved", 0))
        self.prefix_evictions = int(c.get("prefix_evictions", 0))
        self.spill_out_blocks = int(c.get("spill_out_blocks", 0))
        self.spill_in_blocks = int(c.get("spill_in_blocks", 0))
        self.spill_d2h_bytes = int(c.get("spill_d2h_bytes", 0))
        self.spill_h2d_bytes = int(c.get("spill_h2d_bytes", 0))
        self.spill_prefetched_blocks = int(
            c.get("spill_prefetched_blocks", 0))
        self.spill_resumes = int(c.get("spill_resumes", 0))
        self.swapin_tokens_saved = int(c.get("swapin_tokens_saved", 0))
        self.migrated_out = int(c.get("migrated_out", 0))
        self.migrated_in = int(c.get("migrated_in", 0))
        self.tenant_of = {int(k): int(v)
                          for k, v in snap.get("tenant_of", {}).items()}
        self.tenants = {int(k): {kk: int(vv) for kk, vv in v.items()}
                        for k, v in snap.get("tenants", {}).items()}

    # ---- introspection ---------------------------------------------------

    @property
    def has_resident(self) -> bool:
        return any(s is not None for s in self.slots)

    @property
    def has_queued(self) -> bool:
        return bool(self.queue)

    def next_arrival(self) -> float | None:
        if not self.queue:
            return None
        return float(min(r.arrival for r in self.queue))
