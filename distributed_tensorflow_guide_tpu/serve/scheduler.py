"""Request scheduler: continuous batching over a fixed-slot decode batch.

All of the *dynamic* serving state lives here, on the host, in plain
Python — which requests are resident, which physical blocks they own,
how far each one has written — so the device programs
(serve/engine.py) stay fully static: a decode step always runs all
``slots`` rows, a prefill step always runs one ``prefill_chunk``-token
chunk. The scheduler changes the POPULATION between steps (Orca's
iteration-level scheduling): a finished request frees its slot and
blocks at the step boundary, a queued prompt is admitted into any empty
slot mid-flight, and nothing retraces.

Decisions are deterministic functions of the submitted trace: FIFO
admission by arrival time, lowest-id slots and blocks first, preemption
evicts the MOST RECENTLY admitted victim (its re-queued continuation
carries the original prompt plus everything already emitted, and the
position-derived sampling keys of models/generation.py make the
regenerated stream bitwise the one it would have produced uninterrupted
— eviction is free of replay divergence by construction). The
scheduler-determinism test replays a seeded arrival trace twice and
pins identical event logs.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from distributed_tensorflow_guide_tpu.serve.paged_cache import (
    BlockPool,
    blocks_for,
)

PREFILL, DECODE = "prefill", "decode"


@dataclasses.dataclass
class Request:
    """One serving request. ``rng`` is the request's own PRNG key (raw
    (2,) uint32, what ``jax.random.PRNGKey`` returns) — sampling keys
    derive from (rng, absolute position), which is what makes the
    engine's per-request stream bitwise a one-shot
    ``make_generate_fn(...)​(params, prompt[None], rng)`` run."""

    rid: int
    prompt: np.ndarray  # (P,) int32
    max_new_tokens: int
    rng: np.ndarray  # (2,) uint32
    arrival: float = 0.0


@dataclasses.dataclass
class _Slot:
    rid: int
    prompt: np.ndarray  # current prompt (original + pre-preemption emits)
    budget: int  # tokens still to emit from THIS residency
    rng: np.ndarray
    blocks: list[int]
    phase: str = PREFILL
    chunk_cursor: int = 0  # next prefill chunk index
    written: int = 0  # cache positions written so far
    pending: int = 0  # last sampled token (k/v not yet written)
    emitted_here: int = 0  # tokens emitted during THIS residency
    admitted_seq: int = 0


class Scheduler:
    """Slots + pool + queue; the engine asks it what to run each tick."""

    def __init__(self, *, slots: int, num_blocks: int, block_size: int,
                 prefill_chunk: int, max_len: int) -> None:
        if max_len % prefill_chunk:
            raise ValueError(
                f"prefill_chunk {prefill_chunk} must divide max_len "
                f"{max_len} (pad writes must stay inside the table)")
        if max_len % block_size:
            raise ValueError(
                f"block_size {block_size} must divide max_len {max_len}")
        self.slots: list[_Slot | None] = [None] * slots
        self.pool = BlockPool(num_blocks, block_size)
        self.block_size = block_size
        self.prefill_chunk = prefill_chunk
        self.max_len = max_len
        self.blocks_per_seq = max_len // block_size
        self.queue: list[Request] = []  # FIFO; preemptions go to the front
        self.emitted: dict[int, list[int]] = {}  # rid -> all emitted tokens
        self.first_emit: dict[int, bool] = {}  # rid -> saw first token yet
        self.done: set[int] = set()
        self._seq = 0  # admission counter (preemption picks the youngest)
        self._prefer_prefill = True  # interleave chunked prefill w/ decode
        self.preemptions = 0

    # ---- intake ----------------------------------------------------------

    def max_request_blocks(self, prompt_len: int, max_new: int) -> int:
        padded = -(-prompt_len // self.prefill_chunk) * self.prefill_chunk
        return blocks_for(max(padded, prompt_len + max_new),
                          self.block_size)

    def submit(self, req: Request) -> None:
        P = int(len(req.prompt))
        if P < 1:
            raise ValueError("empty prompt")
        if P + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {P} + max_new {req.max_new_tokens} exceeds "
                f"max_len {self.max_len}")
        if self.max_request_blocks(P, req.max_new_tokens) > \
                self.pool.capacity:
            raise ValueError(
                f"request {req.rid} can never fit: needs "
                f"{self.max_request_blocks(P, req.max_new_tokens)} blocks, "
                f"pool capacity {self.pool.capacity}")
        self.queue.append(req)
        self.emitted.setdefault(req.rid, [])
        self.first_emit.setdefault(req.rid, False)

    # ---- admission -------------------------------------------------------

    def admit(self, now: float) -> list[int]:
        """FIFO head-of-line admission: fill empty slots with arrived
        requests whose prefill footprint fits the pool right now. Strict
        FIFO (no reordering past the head) keeps admission latency fair
        and the trace deterministic."""
        admitted = []
        while self.queue and None in self.slots:
            req = self.queue[0]
            if req.arrival > now:
                break
            P = len(req.prompt)
            padded = -(-P // self.prefill_chunk) * self.prefill_chunk
            blocks = self.pool.alloc(req.rid, blocks_for(padded,
                                                         self.block_size))
            if blocks is None:
                break
            self.queue.pop(0)
            s = self.slots.index(None)
            self.slots[s] = _Slot(
                rid=req.rid, prompt=np.asarray(req.prompt, np.int32),
                budget=req.max_new_tokens, rng=req.rng, blocks=blocks,
                admitted_seq=self._seq)
            self._seq += 1
            admitted.append(s)
        return admitted

    # ---- tick planning ---------------------------------------------------

    def plan(self) -> tuple[str, object]:
        """What the engine should launch this tick: ``("prefill", slot)``
        one chunk for the oldest mid-prefill slot, ``("decode", [slots])``
        one decode step over the active population, or ``("idle", None)``.
        When both phases have work they ALTERNATE (chunked prefill
        interleaved with decode — a long prompt no longer stalls every
        resident stream for its whole prefill)."""
        prefills = [i for i, s in enumerate(self.slots)
                    if s is not None and s.phase == PREFILL]
        decodes = [i for i, s in enumerate(self.slots)
                   if s is not None and s.phase == DECODE]
        if prefills and (self._prefer_prefill or not decodes):
            self._prefer_prefill = False
            best = min(prefills,
                       key=lambda i: self.slots[i].admitted_seq)
            return (PREFILL, best)
        if decodes:
            self._prefer_prefill = bool(prefills)
            ready = self._grow_for_decode(decodes)
            if ready:
                return (DECODE, ready)
            prefills = [i for i, s in enumerate(self.slots)
                        if s is not None and s.phase == PREFILL]
            if prefills:
                best = min(prefills,
                           key=lambda i: self.slots[i].admitted_seq)
                return (PREFILL, best)
        return ("idle", None)

    def _grow_for_decode(self, decodes: list[int]) -> list[int]:
        """Every decoding slot must own the block its next write lands in;
        grow by one block where needed, preempting the youngest other
        resident when the pool is dry."""
        ready = []
        for i in list(decodes):
            slot = self.slots[i]
            if slot is None:  # preempted by an earlier growth this tick
                continue
            while len(slot.blocks) * self.block_size < slot.written + 1:
                got = self.pool.alloc(slot.rid, 1)
                if got is not None:
                    slot.blocks.extend(got)
                    continue
                victim = self._pick_victim(exclude=i)
                if victim is None:
                    break  # stalled: no blocks, nothing to preempt
                self._preempt(victim)
            else:
                ready.append(i)
        return [i for i in ready if self.slots[i] is not None]

    def _pick_victim(self, exclude: int) -> int | None:
        live = [(s.admitted_seq, i) for i, s in enumerate(self.slots)
                if s is not None and i != exclude and s.blocks]
        if not live:
            return None
        return max(live)[1]  # youngest admission goes first

    def _preempt(self, i: int) -> None:
        slot = self.slots[i]
        self.pool.free(slot.rid, slot.blocks)
        # continuation request: this residency's prompt plus every token
        # it emitted; budget = whatever is still owed. Position-derived
        # sampling keys make the re-run emit exactly the tokens it would
        # have produced uninterrupted, so preemption never forks the
        # stream. Goes to the FRONT of the queue (it was already served).
        cont_prompt = slot.prompt
        if slot.emitted_here:
            tail = self.emitted[slot.rid][-slot.emitted_here:]
            cont_prompt = np.concatenate(
                [slot.prompt, np.asarray(tail, np.int32)])
        self.queue.insert(0, Request(
            rid=slot.rid, prompt=cont_prompt,
            max_new_tokens=slot.budget, rng=slot.rng,
            arrival=float("-inf")))
        self.slots[i] = None
        self.preemptions += 1

    # ---- result application ---------------------------------------------

    def prefill_done_chunks(self, slot_idx: int) -> int:
        s = self.slots[slot_idx]
        return -(-len(s.prompt) // self.prefill_chunk)

    def apply_prefill(self, slot_idx: int, token: int) -> list[tuple]:
        """One chunk finished for ``slot_idx``; ``token`` is the program's
        sample from the chunk's last valid row (meaningful only on the
        final chunk). Returns [(rid, token, first, done)] events."""
        s = self.slots[slot_idx]
        s.chunk_cursor += 1
        s.written = min(s.chunk_cursor * self.prefill_chunk,
                        len(s.prompt))
        if s.chunk_cursor < self.prefill_done_chunks(slot_idx):
            return []
        # final chunk: the sample at position P is the first new token
        s.written = len(s.prompt)
        s.phase = DECODE
        s.pending = int(token)
        return self._emit(slot_idx, int(token))

    def apply_decode(self, slot_idx: int, token: int) -> list[tuple]:
        s = self.slots[slot_idx]
        s.written += 1  # the step wrote pending's k/v at `written`
        s.pending = int(token)
        return self._emit(slot_idx, int(token))

    def _emit(self, slot_idx: int, token: int) -> list[tuple]:
        s = self.slots[slot_idx]
        rid = s.rid
        self.emitted[rid].append(token)
        first = not self.first_emit[rid]
        self.first_emit[rid] = True
        s.budget -= 1
        s.emitted_here += 1
        done = s.budget == 0
        if done:
            self.pool.free(rid, s.blocks)
            self.slots[slot_idx] = None
            self.done.add(rid)
        return [(rid, token, first, done)]

    # ---- introspection ---------------------------------------------------

    @property
    def has_resident(self) -> bool:
        return any(s is not None for s in self.slots)

    @property
    def has_queued(self) -> bool:
        return bool(self.queue)

    def next_arrival(self) -> float | None:
        if not self.queue:
            return None
        return float(min(r.arrival for r in self.queue))
