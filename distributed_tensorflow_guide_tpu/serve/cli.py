"""``dtg-serve`` — run the continuous-batching engine on a demo workload.

A console-script sibling of ``dtg-lint``: builds a small randomly
initialised model (or loads nothing — this is a scheduling demo, not a
quality demo), submits a staggered mix of prompts, and streams every
token event as it is emitted, then prints the per-request completions
and the pool/scheduler counters. The point is to make the serving loop
observable from a shell one-liner:

    dtg-serve --requests 6 --slots 2 --prefill-chunk 8

For trained-checkpoint serving see examples/gpt2_serve.py; for load
numbers see benchmarks/bench_serving.py.
"""

from __future__ import annotations

import argparse
import os


def main() -> None:
    ap = argparse.ArgumentParser(prog="dtg-serve")
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--num-blocks", type=int, default=17)
    ap.add_argument("--prefill-chunk", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=12)
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--top-k", type=int, default=20)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--prefix-cache", action="store_true",
                    help="share a common system prompt across the demo "
                         "requests through the radix prefix cache "
                         "(watch prefill_tokens_saved in health())")
    ap.add_argument("--lora-rank", type=int, default=0,
                    help="serve the mix multi-LoRA: requests cycle "
                         "through 3 adapters (0 = base) inside the "
                         "shared decode step")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="serve through an N-replica FleetScheduler "
                         "(global admission/DRR/routing over N stock "
                         "engines) instead of a single engine; watch "
                         "the per-replica healths and fleet counters")
    ap.add_argument("--fleet-roles", choices=["colocated", "disagg"],
                    default="colocated",
                    help="with --fleet: 'disagg' splits prefill/decode "
                         "roles and ships KV blocks at the phase flip")
    args = ap.parse_args()

    # device env before any jax import (the dtg-lint pattern)
    os.environ.setdefault("JAX_PLATFORMS", os.environ.get(
        "JAX_PLATFORMS", ""))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_tensorflow_guide_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )
    from distributed_tensorflow_guide_tpu.serve.engine import (
        Request,
        ServeEngine,
    )

    import dataclasses

    cfg = TransformerConfig(vocab_size=256, num_layers=2, num_heads=2,
                            d_model=32, d_ff=64, max_len=64, causal=True,
                            dtype=jnp.float32)
    bank = None
    if args.lora_rank:
        from distributed_tensorflow_guide_tpu.serve.engine import (
            init_adapter_bank,
        )

        cfg = dataclasses.replace(cfg, lora_rank=args.lora_rank,
                                  lora_adapters=2)
        leaves, treedef = jax.tree.flatten(init_adapter_bank(cfg))
        keys = jax.random.split(jax.random.PRNGKey(args.seed + 7),
                                len(leaves))
        bank = jax.tree.unflatten(treedef, [
            (0.05 * jax.random.normal(k, l.shape, l.dtype)).at[0].set(0.0)
            for k, l in zip(keys, leaves)])
    params = Transformer(cfg).init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, 8), jnp.int32))["params"]
    if args.fleet:
        from distributed_tensorflow_guide_tpu.serve.fleet import (
            FleetScheduler,
        )

        eng = FleetScheduler(cfg, params, replicas=args.fleet,
                             roles=args.fleet_roles, slots=args.slots,
                             num_blocks=args.num_blocks,
                             block_size=args.block_size,
                             prefill_chunk=args.prefill_chunk,
                             temperature=args.temperature,
                             top_k=args.top_k, adapters=bank,
                             prefix_cache=args.prefix_cache)
    else:
        eng = ServeEngine(cfg, params, slots=args.slots,
                          num_blocks=args.num_blocks,
                          block_size=args.block_size,
                          prefill_chunk=args.prefill_chunk,
                          temperature=args.temperature, top_k=args.top_k,
                          prefix_cache=args.prefix_cache, adapters=bank)
    rng = np.random.RandomState(args.seed)
    sys_prompt = (rng.randint(0, cfg.vocab_size, 16).astype(np.int32)
                  if args.prefix_cache else None)
    for rid in range(args.requests):
        plen = int(rng.choice([4, 8, 16]))
        prompt = rng.randint(0, cfg.vocab_size, plen).astype(np.int32)
        if sys_prompt is not None:
            prompt = np.concatenate([sys_prompt, prompt[:4]])
        eng.submit(Request(
            rid=rid,
            prompt=prompt,
            max_new_tokens=args.max_new,
            rng=jax.random.PRNGKey(args.seed * 1000 + rid),
            adapter=(rid % 3 if args.lora_rank else 0),
            tenant=rid % 2))
    for ev in eng.run():
        if ev.status != "ok":
            print(f"req {ev.rid:3d} ! {ev.status}")
            continue
        mark = "*" if ev.first else ("." if not ev.done else "$")
        print(f"req {ev.rid:3d} {mark} token {ev.token}")
    print("--")
    for rid, toks in sorted(eng.completions().items()):
        print(f"req {rid}: {toks}")
    if args.fleet:
        print(f"health={eng.health()}")
        # shutdown contract: every replica's ledgers clean, loudly
        eng.check_leaks()
    else:
        print(f"steps={eng.steps} health={eng.health()}")
        # shutdown contract: every block accounted for, loudly
        eng.sched.pool.check_leaks()
    eng.close()
    print("pool.check_leaks(): clean")


if __name__ == "__main__":
    main()
