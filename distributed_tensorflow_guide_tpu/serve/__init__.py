"""Continuous-batching serving: paged KV pool + request scheduler +
two static step programs, with prefix-sharing COW blocks, multi-tenant
fair-share admission, batched multi-LoRA decode and a scale-out fleet
tier (global admission/DRR/routing over N stock engines, disaggregated
prefill/decode, fleet-level prefix routing — see docs/serving.md)."""

from distributed_tensorflow_guide_tpu.serve.engine import (
    Event,
    ServeEngine,
    adapter_bank_shapes,
    build_step_fns,
    init_adapter_bank,
    paged_cache_pool,
    paged_config,
)
from distributed_tensorflow_guide_tpu.serve.fleet import (
    FleetScheduler,
)
from distributed_tensorflow_guide_tpu.serve.scheduler import (
    EngineOverloaded,
)
from distributed_tensorflow_guide_tpu.serve.paged_cache import (
    BlockPool,
    BlockStore,
    blocks_for,
    gather_view,
    scatter_chunk,
    table_row,
)
from distributed_tensorflow_guide_tpu.serve.prefix_index import (
    PrefixIndex,
)
from distributed_tensorflow_guide_tpu.serve.scheduler import (
    Request,
    Scheduler,
)

__all__ = [
    "BlockPool",
    "BlockStore",
    "EngineOverloaded",
    "Event",
    "FleetScheduler",
    "PrefixIndex",
    "Request",
    "Scheduler",
    "ServeEngine",
    "adapter_bank_shapes",
    "blocks_for",
    "build_step_fns",
    "gather_view",
    "init_adapter_bank",
    "paged_cache_pool",
    "paged_config",
    "scatter_chunk",
    "table_row",
]
