"""Continuous-batching serving: paged KV pool + request scheduler +
two static step programs (see docs/serving.md)."""

from distributed_tensorflow_guide_tpu.serve.engine import (
    Event,
    ServeEngine,
    build_step_fns,
    paged_cache_pool,
    paged_config,
)
from distributed_tensorflow_guide_tpu.serve.scheduler import (
    EngineOverloaded,
)
from distributed_tensorflow_guide_tpu.serve.paged_cache import (
    BlockPool,
    blocks_for,
    gather_view,
    scatter_chunk,
    table_row,
)
from distributed_tensorflow_guide_tpu.serve.scheduler import (
    Request,
    Scheduler,
)

__all__ = [
    "BlockPool",
    "EngineOverloaded",
    "Event",
    "Request",
    "Scheduler",
    "ServeEngine",
    "blocks_for",
    "build_step_fns",
    "gather_view",
    "paged_cache_pool",
    "paged_config",
    "scatter_chunk",
    "table_row",
]
