"""Radix prefix index: host-side trie mapping prompts to cached blocks.

The paged pool (PR 10) made the KV cache block-structured; refcounts
(PR 12) made full blocks shareable.  This module is the lookup structure
that turns those two facts into a TTFT lever: a trie over token ids at
**block granularity** — each node is exactly ``block_size`` tokens and
holds the physical block id whose KV rows were produced by prefilling
those tokens at that absolute position.  An arriving prompt walks the
trie (:meth:`PrefixIndex.match`), claims the matched blocks by ref-bump,
and prefills only the suffix.

Why the cached KV is bitwise-safe to adopt: chunk boundaries in the
engine's prefill program are **absolute positions** (chunk k covers
``[k*CH, (k+1)*CH)``), so any two requests that agree on tokens
``[0, n)`` run byte-identical prefill chunks over that range and write
byte-identical KV rows.  The block a node holds is therefore exactly
what the claiming request would have computed itself — which is what
keeps every stream bitwise equal to the same request served alone with
the cache off.  Two corollaries the engine relies on:

* the trie is keyed by **adapter id at the root** — a LoRA delta changes
  K/V content, so prompts prefilled under different adapters must never
  share blocks even when token-identical;
* the trie is NOT keyed by tenant — token-identical prompts share across
  tenants by design.  Sharing is a capacity optimisation, not a privacy
  boundary (docs/serving.md spells out the non-guarantees).

The index itself holds a reference on every cached block (holder id
:data:`CACHE_RID`), so a finished request's prompt blocks survive it.
Eviction is **LRU leaf-first**: only a leaf node whose block has no
other holder (refcount 1 — just the cache) may be dropped, which frees
deepest, coldest suffixes first and never yanks a block out from under
a resident request.  All bookkeeping is deterministic: the LRU clock is
a logical counter bumped on every match/insert touch, never wall time.

On snapshot/restore the trie is deliberately NOT serialized: the pool's
device state is restored by re-prefilling continuations, and the trie
rebuilds itself from those same deterministic prefills — host state
derived from token ids needs no bytes in the snapshot.
"""

from __future__ import annotations

from .paged_cache import BlockPool

# Holder id under which the index refcounts cached blocks.  Negative and
# distinct from any request id (rids are non-negative; the engine's
# chaos-burst synthetic rids are >= 1000) and from the engine's
# _CHAOS_RID (-7).
CACHE_RID = -2


class _Node:
    __slots__ = ("key", "block", "parent", "children", "touched", "host")

    def __init__(self, key, block, parent, touched, host=None):
        self.key = key          # tuple of block_size token ids
        self.block = block      # physical block id, or None when spilled
        self.parent = parent    # _Node or a root dict's owner (None)
        self.children = {}      # key tuple -> _Node
        self.touched = touched  # logical LRU clock value
        self.host = host        # host BlockStore id when spilled, else None


class PrefixIndex:
    """Block-granularity radix trie over (adapter, token ids)."""

    def __init__(self, block_size: int):
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.block_size = block_size
        self._roots: dict[int, dict] = {}  # adapter -> {key: _Node}
        self._clock = 0
        self._count = 0

    @property
    def size(self) -> int:
        """Number of cached blocks (== trie nodes)."""
        return self._count

    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.touched = self._clock

    def match(self, tokens, adapter: int = 0) -> list[int]:
        """Longest cached prefix of ``tokens``: the block ids, in logical
        order, of consecutive matched full blocks from position 0.  Every
        matched node is LRU-touched.  A spilled node (host tier) breaks
        the chain — callers without a :class:`BlockStore` can only adopt
        device-resident blocks; hierarchy-aware callers use
        :meth:`match_nodes`."""
        hit: list[int] = []
        for node in self.match_nodes(tokens, adapter):
            if node.block is None:
                break
            hit.append(node.block)
        return hit

    def match_nodes(self, tokens, adapter: int = 0) -> list:
        """Like :meth:`match` but returns the ``_Node`` chain itself,
        including spilled nodes (``node.block is None``,
        ``node.host`` set) — the hierarchy-aware claim path promotes
        those by swap-in.  Every matched node is LRU-touched."""
        bs = self.block_size
        children = self._roots.get(adapter)
        hit: list = []
        if children is None:
            return hit
        toks = list(tokens)
        for i in range(len(toks) // bs):
            key = tuple(int(t) for t in toks[i * bs:(i + 1) * bs])
            node = children.get(key)
            if node is None:
                break
            self._touch(node)
            hit.append(node)
            children = node.children
        return hit

    def insert(self, tokens, blocks: list[int], adapter: int = 0, *,
               pool: BlockPool, store=None) -> int:
        """Cache the full blocks of a finished prefill.

        ``blocks[i]`` holds the KV of ``tokens[i*bs:(i+1)*bs]``; only
        the first ``len(tokens) // bs`` FULL blocks are insertable (a
        partial block is still written by decode — never shareable).
        An existing node wins: if a prefix is already cached (two
        identical prompts prefilled concurrently), the incumbent block
        stays and the newcomer's private block is simply not cached.
        Exception: a SPILLED incumbent is repointed at the newcomer's
        device block (a free promotion — the fresh prefill just rebuilt
        the same bytes on device, so pass ``store`` to let the host copy
        go).  New nodes ref-bump their block for :data:`CACHE_RID`.
        Returns the number of nodes created."""
        bs = self.block_size
        n_full = min(len(tokens) // bs, len(blocks))
        children = self._roots.setdefault(adapter, {})
        parent = None
        created = 0
        toks = list(tokens)
        for i in range(n_full):
            key = tuple(int(t) for t in toks[i * bs:(i + 1) * bs])
            node = children.get(key)
            if node is None:
                self._clock += 1
                node = _Node(key, blocks[i], parent, self._clock)
                pool.share(CACHE_RID, [node.block])
                children[key] = node
                self._count += 1
                created += 1
            else:
                if node.block is None and store is not None:
                    pool.share(CACHE_RID, [blocks[i]])
                    store.free(CACHE_RID, [node.host])
                    node.block = blocks[i]
                    node.host = None
                self._touch(node)
            parent = node
            children = node.children
        return created

    def insert_spilled(self, tokens, host_id: int,
                       adapter: int = 0) -> bool:
        """Index ``host_id`` (a host-tier block already held for
        :data:`CACHE_RID`) as the node for the LAST full block of
        ``tokens`` — the warm-restore path, where cache contents arrive
        from disk straight into the host tier and promote on demand.
        All ancestor nodes must already exist (callers feed paths in
        depth order).  Returns False (incumbent wins, caller still owns
        the host hold) when the node already exists or an ancestor is
        missing."""
        bs = self.block_size
        toks = list(tokens)
        n_full = len(toks) // bs
        if n_full < 1:
            return False
        children = self._roots.setdefault(adapter, {})
        parent = None
        for i in range(n_full - 1):
            key = tuple(int(t) for t in toks[i * bs:(i + 1) * bs])
            node = children.get(key)
            if node is None:
                return False
            parent = node
            children = node.children
        key = tuple(int(t) for t in toks[(n_full - 1) * bs:n_full * bs])
        if key in children:
            return False
        self._clock += 1
        children[key] = _Node(key, None, parent, self._clock,
                              host=host_id)
        self._count += 1
        return True

    def walk(self):
        """Yield ``(adapter, path_tokens, node)`` for every node, parents
        strictly before children; ``path_tokens`` is the full token tuple
        from the root through the node (``depth * block_size`` ids).
        Deterministic order (insertion order of dicts) — the persistence
        path relies on parents-first so :meth:`insert_spilled` can replay
        it."""
        for adapter, children in self._roots.items():
            stack = [((), n) for n in reversed(list(children.values()))]
            while stack:
                prefix, node = stack.pop()
                path = prefix + node.key
                yield adapter, path, node
                stack.extend(
                    (path, c) for c in reversed(list(node.children.values())))

    def stats(self) -> dict:
        """Trie-shape snapshot for the metrics plane
        (``obs.metrics.absorb_prefix``) — pure reads, no LRU touches."""
        leaves = 0
        depth = 0
        spilled = 0
        for children in self._roots.values():
            stack = [(n, 1) for n in children.values()]
            while stack:
                node, d = stack.pop()
                depth = max(depth, d)
                if node.block is None:
                    spilled += 1
                if node.children:
                    stack.extend(
                        (c, d + 1) for c in node.children.values())
                else:
                    leaves += 1
        return {
            "nodes": self._count,
            "leaves": leaves,
            "max_depth": depth,
            "adapters": len(self._roots),
            "spilled": spilled,
        }

    def _evictable(self, adapter: int, node: _Node,
                   pool: BlockPool) -> bool:
        return (not node.children and node.block is not None
                and pool.refcount(node.block) == 1)

    def evict_one(self, pool: BlockPool) -> int | None:
        """Drop the least-recently-touched evictable LEAF (block held by
        nobody but the cache) and release its block.  Returns the freed
        block id, or None when nothing can be evicted."""
        victim = None
        victim_adapter = None
        for adapter, children in self._roots.items():
            stack = list(children.values())
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if self._evictable(adapter, node, pool):
                    if victim is None or node.touched < victim.touched:
                        victim = node
                        victim_adapter = adapter
        if victim is None:
            return None
        siblings = (victim.parent.children if victim.parent is not None
                    else self._roots[victim_adapter])
        del siblings[victim.key]
        self._count -= 1
        pool.free(CACHE_RID, [victim.block])
        return victim.block

    def demote_one(self, pool: BlockPool, demote) -> int | None:
        """Spill the least-recently-touched device-resident node whose
        block is held by nobody but the cache, via ``demote`` — a
        callable ``(block_id) -> host_id | None`` (the scheduler's
        d2h/dedup helper, which also drops the cache's pool hold on
        success).  Unlike :meth:`evict_one` there is NO leaf requirement:
        demotion preserves trie structure (the node stays, pointing at
        the host tier), so an interior cold block can make room without
        orphaning its descendants.  Returns the freed device block id,
        or None when nothing is demotable or the host tier is full."""
        victim = None
        for adapter, children in self._roots.items():
            stack = list(children.values())
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if (node.block is not None
                        and pool.refcount(node.block) == 1):
                    if victim is None or node.touched < victim.touched:
                        victim = node
        if victim is None:
            return None
        h = demote(victim.block)
        if h is None:
            return None
        freed = victim.block
        victim.block = None
        victim.host = h
        return freed

    def demote_many(self, pool: BlockPool, demote_batch,
                    limit: int = 8) -> list[int]:
        """Batched :meth:`demote_one`: spill up to ``limit`` of the
        least-recently-touched demotable nodes in ONE ``demote_batch``
        call — ``(block_ids) -> [host_id | None]``, parallel results
        (None = host tier full; that node stays resident).  Spilling a
        few extra cold blocks per pressure event amortizes the d2h
        dispatch overhead and pre-frees headroom for the allocations
        that tend to follow the first.  Returns the freed device block
        ids (possibly empty)."""
        cands = []
        for children in self._roots.values():
            stack = list(children.values())
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if (node.block is not None
                        and pool.refcount(node.block) == 1):
                    cands.append(node)
        if not cands:
            return []
        cands.sort(key=lambda n: n.touched)
        victims = cands[:limit]
        freed = []
        for node, h in zip(victims,
                           demote_batch([n.block for n in victims])):
            if h is None:
                continue
            freed.append(node.block)
            node.block = None
            node.host = h
        return freed

    def drop(self, pool: BlockPool, store=None) -> int:
        """Release every cached block — device holds AND (with ``store``)
        host-tier holds of spilled nodes — and empty the trie (engine
        close / restore).  Returns the number of blocks released."""
        freed = 0
        for children in self._roots.values():
            stack = list(children.values())
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if node.block is not None:
                    pool.free(CACHE_RID, [node.block])
                elif store is not None:
                    store.free(CACHE_RID, [node.host])
                freed += 1
        self._roots = {}
        self._count = 0
        return freed
