"""``python -m distributed_tensorflow_guide_tpu.launch`` — the run.sh killer.

Reference analogue (SURVEY.md §2 R9): every example ships a ``run.sh`` that
backgrounds 1 PS + N workers on localhost ports with ``--job_name`` /
``--task_index`` role flags, and supervises nothing — a crashed PS leaves
every worker hung on gRPC forever, and stale processes from the previous run
must be ``kill``-ed by hand.

The SPMD inversion: there are no roles, so the launcher spawns N *identical*
processes of the *same* command, differing only in ``JAX_PROCESS_ID``. It
synthesizes the coordinator env (the ``TF_CONFIG`` analogue —
tensorflow/python/distribute/cluster_resolver/tfconfig_cluster_resolver.py:48),
streams each child's output with a ``[p{k}]`` prefix, and supervises: on the
first nonzero exit the survivors get a grace period (peers blocked in a
collective on the dead rank never finish) and are then reaped, and the
launcher's exit code reflects the failure.

Usage::

    # 4-process CPU cluster, 2 virtual devices each (8 global devices):
    python -m distributed_tensorflow_guide_tpu.launch \
        --num-processes 4 --devices-per-process 2 --platform cpu \
        examples/mnist_sync_dp.py --steps 100

    # On a TPU pod each host runs the SAME command (no launcher needed);
    # this CLI is for single-host multi-process development and CI.

The launched script needs no flags parsing for topology: it just calls
``distributed_tensorflow_guide_tpu.core.dist.initialize()``, which reads the
env this launcher sets (core/dist.py DistConfig.from_env).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

from .runtime.multiprocess import free_port, supervise


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m distributed_tensorflow_guide_tpu.launch",
        description="Spawn and supervise an N-process SPMD run on one host.",
    )
    p.add_argument("--num-processes", "-n", type=int, default=2)
    p.add_argument(
        "--devices-per-process", type=int, default=1,
        help="virtual CPU devices per process (cpu platform only)",
    )
    p.add_argument(
        "--platform", choices=["cpu", "tpu", "auto"], default="cpu",
        help="cpu: force JAX_PLATFORMS=cpu with virtual devices (default, "
        "for dev/CI); tpu/auto: leave device selection to JAX",
    )
    p.add_argument("--timeout", type=float, default=600.0,
                   help="wall-clock limit for the whole run (seconds)")
    p.add_argument("--failure-grace", type=float, default=10.0,
                   help="seconds survivors get after the first failure")
    p.add_argument("--coordinator-port", type=int, default=0,
                   help="0 = pick a free port")
    p.add_argument("--log-dir", type=Path, default=None,
                   help="also write per-process logs to DIR/p{k}.log")
    p.add_argument("--module", "-m", action="store_true",
                   help="treat the target as a module name (python -m)")
    p.add_argument("target", help="script path (or module with -m)")
    p.add_argument("args", nargs=argparse.REMAINDER,
                   help="arguments forwarded to the target")
    return p


def _child_env(ns: argparse.Namespace, coordinator: str, pid: int) -> dict:
    env = dict(os.environ)
    env["JAX_COORDINATOR_ADDRESS"] = coordinator
    env["JAX_NUM_PROCESSES"] = str(ns.num_processes)
    env["JAX_PROCESS_ID"] = str(pid)
    if ns.platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_NUM_CPU_DEVICES"] = str(ns.devices_per_process)
        # Scrub a parent XLA_FLAGS device-count override that would fight
        # the per-process count above.
        env.pop("XLA_FLAGS", None)
    return env


def _stream(proc: subprocess.Popen, pid: int, log_file, lock: threading.Lock):
    """Tee one child's combined output to our stdout with a [p{k}] prefix."""
    for raw in proc.stdout:
        line = raw.decode("utf-8", "replace")
        with lock:
            sys.stdout.write(f"[p{pid}] {line}")
            sys.stdout.flush()
            if log_file is not None:
                log_file.write(line)
                log_file.flush()


def main(argv: list[str] | None = None) -> int:
    ns = _build_parser().parse_args(argv)
    if ns.args and ns.args[0] == "--":
        ns.args = ns.args[1:]
    port = ns.coordinator_port or free_port()
    coordinator = f"localhost:{port}"
    base_cmd = [sys.executable]
    base_cmd += ["-m", ns.target] if ns.module else [ns.target]
    base_cmd += ns.args

    if ns.log_dir is not None:
        ns.log_dir.mkdir(parents=True, exist_ok=True)

    procs: list[subprocess.Popen] = []
    logs = []
    lock = threading.Lock()
    threads = []
    print(
        f"launch: {ns.num_processes} processes, coordinator {coordinator}, "
        f"cmd: {' '.join(base_cmd)}",
        flush=True,
    )
    def _announce(bad: int, code: int) -> None:
        print(
            f"launch: process {bad} exited {code}; giving survivors "
            f"{ns.failure_grace:.0f}s grace",
            file=sys.stderr, flush=True,
        )

    timed_out = False
    try:
        # Spawning inside the try: if any open()/Popen in this loop fails
        # (e.g. unwritable --log-dir entry), the finally below reaps the
        # children already started instead of leaking them unsupervised.
        # Log file is opened BEFORE its child so a failure leaves no extra
        # untracked process.
        for pid in range(ns.num_processes):
            log_file = (
                open(ns.log_dir / f"p{pid}.log", "w", encoding="utf-8")
                if ns.log_dir is not None else None
            )
            logs.append(log_file)
            proc = subprocess.Popen(
                base_cmd,
                env=_child_env(ns, coordinator, pid),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
            procs.append(proc)
            t = threading.Thread(
                target=_stream, args=(proc, pid, log_file, lock), daemon=True
            )
            t.start()
            threads.append(t)

        timed_out = supervise(
            procs, timeout=ns.timeout, failure_grace=ns.failure_grace,
            on_first_failure=_announce,
        )
        if timed_out:
            print("launch: timeout; killed all", file=sys.stderr, flush=True)
    except KeyboardInterrupt:
        print("launch: interrupted; killing all", file=sys.stderr, flush=True)
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        time.sleep(1.0)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        for t in threads:
            t.join(timeout=5.0)
        for f in logs:
            if f is not None:
                f.close()

    codes = [p.returncode for p in procs]
    ok = not timed_out and all(c == 0 for c in codes)
    print(f"launch: exit codes {codes}" + (" (timeout)" if timed_out else ""),
          flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
