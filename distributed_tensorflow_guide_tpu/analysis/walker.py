"""The sub-jaxpr-complete walker every analysis rule is built on.

One recursive traversal replaces the per-test scanners that grew in
tests/pin_utils.py. Two blind spots of the old pattern are fixed here and
pinned by positive controls in tests/test_analysis.py:

* **dict-valued / nested-container eqn params** — the old loop only
  looked inside tuple/list param values, so a sub-jaxpr carried in a dict
  (or a dict nested in a tuple, e.g. a branches table keyed by name) was
  silently skipped. :func:`iter_subjaxprs` recurses arbitrary dict /
  tuple / list nests.
* **``eqn.invars`` aliasing** — the old walkers never read invars at all,
  so a donated buffer consumed twice by one equation (``dot(x, x)``)
  counted as one use. :func:`input_use_counts` counts list occurrences.

Everything duck-types on the ``jax.extend.core`` surface (``eqns`` /
``jaxpr`` / ``invars`` / ``outvars`` / ``primitive.name``) so the walker
keeps working across the 0.4/0.5/0.7 lines core/compat.py spans — and so
tests can feed it hand-built equation shells as positive controls.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Any, Iterator

import numpy as np

# ---- traversal --------------------------------------------------------------


def _as_open_jaxpr(j):
    """ClosedJaxpr -> its open jaxpr; open jaxprs pass through. (ClosedJaxpr
    also *forwards* ``eqns``, so test on the ``jaxpr`` attribute alone.)"""
    return j.jaxpr if hasattr(j, "jaxpr") else j


def _is_jaxpr_like(v) -> bool:
    return hasattr(v, "eqns") or (
        hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"), "eqns")
    )


def iter_subjaxprs(value: Any) -> Iterator[Any]:
    """Every jaxpr reachable inside one eqn-param *value*, however nested.

    Handles the containers real primitives use today — ``scan``'s bare
    ClosedJaxpr, ``cond``'s tuple of branches, ``custom_vjp``'s
    dict-free params — plus dict- and mixed-nested containers, which the
    pin_utils-era loop missed entirely.
    """
    if _is_jaxpr_like(value):
        yield _as_open_jaxpr(value)
    elif isinstance(value, dict):
        for v in value.values():
            yield from iter_subjaxprs(v)
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from iter_subjaxprs(v)


def walk(jaxpr) -> Iterator[Any]:
    """Depth-first over every equation of ``jaxpr`` and all sub-jaxprs
    (scan/while/pjit/cond/custom_vjp/shard_map bodies included)."""
    jaxpr = _as_open_jaxpr(jaxpr)
    for eqn in jaxpr.eqns:
        yield eqn
        for p in eqn.params.values():
            for sub in iter_subjaxprs(p):
                yield from walk(sub)


# ---- census helpers ---------------------------------------------------------


def count_primitives(jaxpr, name: str) -> int:
    """Occurrences of one primitive across the jaxpr and every sub-jaxpr
    — e.g. how many ``psum`` binds a bucketed backward emits."""
    return sum(1 for eqn in walk(jaxpr) if eqn.primitive.name == name)


def primitive_census(jaxpr) -> Counter:
    """primitive name -> equation count, across every sub-jaxpr."""
    return Counter(eqn.primitive.name for eqn in walk(jaxpr))


#: Cross-device communication primitives the collective audit reports.
#: ``pmean`` lowers to ``psum`` + divide and ``cc.reduce_scatter`` binds
#: jax's scatter primitive (spelled ``reduce_scatter`` on this line,
#: ``psum_scatter`` on others — both are listed), so expectations are
#: written in primitive spelling, not wrapper spelling.
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "all_gather", "psum_scatter", "reduce_scatter",
    "ppermute", "pbroadcast", "all_to_all",
})

#: shard_map's replication-checking rewrite (``check_rep=True``) rebinds
#: ``psum`` as the distinct ``psum2`` primitive; the data movement is
#: identical, so censuses and cost pricing spell both as ``psum``.
_PRIM_ALIASES = {"psum2": "psum"}


def prim_name(eqn) -> str:
    """``eqn``'s primitive name with rewrite aliases normalized."""
    name = eqn.primitive.name
    return _PRIM_ALIASES.get(name, name)


def eqn_axis_names(eqn) -> tuple[str, ...]:
    """The *named* mesh axes one collective equation reduces over (its
    positional integer axes, if any, are dropped)."""
    p = eqn.params
    axes = p.get("axes", p.get("axis_name", p.get("axis_names", ())))
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def collective_census(jaxpr) -> Counter:
    """``"prim[axis,...]" -> count`` over every sub-jaxpr — the static
    counterpart of ``collectives.trace_comm`` (which counts Python call
    sites during tracing and can see shard_map bodies traced twice; an
    equation census of the final jaxpr is single-valued)."""
    census: Counter = Counter()
    for eqn in walk(jaxpr):
        name = prim_name(eqn)
        if name in COLLECTIVE_PRIMS:
            key = f"{name}[{','.join(eqn_axis_names(eqn))}]"
            census[key] += 1
    return census


# ---- shape / dtype scans ----------------------------------------------------


def _f32_elems(aval) -> int:
    import jax.numpy as jnp

    if getattr(aval, "dtype", None) != jnp.float32:
        return 0
    return int(np.prod(getattr(aval, "shape", ()) or (1,)))


def largest_f32_intermediate(jaxpr) -> tuple[int, tuple[int, ...]]:
    """(elements, shape) of the biggest f32 value any equation produces —
    the single-tensor lower bound on live memory the memory rule reports."""
    worst, shape = 0, ()
    for eqn in walk(jaxpr):
        for var in eqn.outvars:
            n = _f32_elems(var.aval)
            if n > worst:
                worst, shape = n, tuple(var.aval.shape)
    return worst, shape


def max_f32_elems_with_vocab_dim(jaxpr, n: int, v: int) -> int:
    """Largest f32 intermediate of shape (..., V) with >= n rows, walked
    through every sub-jaxpr — the fused-CE "no full logits" instrument
    (the ``n`` floor excludes the legitimate (D, V) head weight/grad)."""
    import jax.numpy as jnp

    worst = 0
    for eqn in walk(jaxpr):
        for var in eqn.outvars:
            aval = var.aval
            shape = getattr(aval, "shape", ())
            if (getattr(aval, "dtype", None) == jnp.float32
                    and len(shape) >= 2 and shape[-1] == v
                    and int(np.prod(shape[:-1])) >= n):
                worst = max(worst, int(np.prod(shape)))
    return worst


# ---- input-use analysis (donation rule) -------------------------------------

#: Call-like primitives whose eqn.invars map positionally onto their
#: sub-jaxpr's invars, letting use-analysis see through the call boundary.
_CALL_PRIMS = frozenset({
    "pjit", "jit", "closed_call", "core_call", "xla_call", "shard_map",
    "remat", "remat2", "checkpoint", "custom_jvp_call", "custom_vjp_call",
    "custom_vjp_call_jaxpr",
})


def _single_subjaxpr(eqn):
    subs = [s for p in eqn.params.values() for s in iter_subjaxprs(p)]
    return subs[0] if len(subs) == 1 else None


def input_use_counts(jaxpr) -> list[int]:
    """Per input position: how many times the top-level equations (and the
    jaxpr's own outputs) reference that variable — *list* occurrences, so
    ``dot(x, x)`` counts x twice (the invar-aliasing blind spot)."""
    jaxpr = _as_open_jaxpr(jaxpr)
    refs = Counter()
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            refs[id(v)] += 1
    for v in jaxpr.outvars:
        refs[id(v)] += 1
    return [refs[id(v)] for v in jaxpr.invars]


def deep_input_used(jaxpr) -> list[bool]:
    """Per input position: is the value *actually read* by any compute —
    resolved recursively through call-like equations (a buffer that only
    flows into a ``pjit`` whose body ignores it is dead, and donating a
    dead buffer is a contract violation the flat count can't see)."""
    jaxpr = _as_open_jaxpr(jaxpr)
    used: set[int] = {id(v) for v in jaxpr.outvars}
    for eqn in jaxpr.eqns:
        sub = (_single_subjaxpr(eqn)
               if eqn.primitive.name in _CALL_PRIMS else None)
        if sub is not None and len(sub.invars) == len(eqn.invars):
            inner = deep_input_used(sub)
            for v, u in zip(eqn.invars, inner):
                if u:
                    used.add(id(v))
        else:
            for v in eqn.invars:
                used.add(id(v))
    return [id(v) in used for v in jaxpr.invars]


# ---- byte-identity instrument ----------------------------------------------


def traced_text(fn, *args) -> str:
    """The full textual trace of ``fn`` at ``args`` (every sub-jaxpr
    printed) — the byte-identity instrument: two code paths that must
    trace the same program compare equal here. Variable naming is
    deterministic within a process, so equal programs compare equal and
    any structural drift shows as a diff. Raw object addresses (repr'd
    closures/meshes in eqn params) are normalized away — they differ per
    Python instance, not per program."""
    import jax

    return re.sub(r"0x[0-9a-f]+", "0x•", str(jax.make_jaxpr(fn)(*args)))
