"""Program-contract static analysis (round 13).

The pin idiom of tests/pin_utils.py — walk a jaxpr, count something,
assert — grown into a subsystem, the way every kernel registers into
``ops/autotune.py``: each judged entry point registers a declarative
:class:`~.contracts.ProgramContract`, and ``python -m
distributed_tensorflow_guide_tpu.analysis.lint`` traces every registered
program on CPU fake devices and audits it against five rule families
(memory, precision, collectives, donation, determinism). Trace-time only
— the linter observes programs, it never rewrites them (docs/analysis.md).

Import discipline: this package must stay importable before jax device
configuration happens (the CLI sets up fake CPU devices itself), so this
module performs no jax work at import time.
"""

from distributed_tensorflow_guide_tpu.analysis.contracts import (  # noqa: F401
    DonationSpec,
    ProgramContract,
    register,
    registered_contracts,
)
