"""Declarative program contracts + the global registry.

A :class:`ProgramContract` is what a subsystem *promises* about one of its
compiled entry programs — which collectives it emits on which axes, what
its precision policy is, what it donates, that it never materializes a
(rows, V) logits tensor, that it is host-callback-free. Subsystems expose
their contracts from a ``lint_contracts()`` module function (the autotune
pattern: the subsystem owns its table entries); ``analysis.programs``
aggregates them into the registry the CLI and tier-1 audit run over.

Import discipline: no jax at module import — ``build`` callables do all
jax work lazily, so the lint CLI can configure fake CPU devices first.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

#: build() -> (fn, args): the traceable program and example (or abstract
#: ShapeDtypeStruct) arguments jax.make_jaxpr is called with.
BuildFn = Callable[[], tuple[Callable, tuple]]


@dataclasses.dataclass(frozen=True)
class DonationSpec:
    """What the program declares about buffer donation.

    ``argnums`` are positions in the *pre-flattening* argument list.
    ``mode``:

    * ``"alias"`` — every donated leaf must be shape/dtype-matchable to
      an output leaf (XLA input-output alias feasibility), the train-step
      state->state pattern.
    * ``"scratch"`` — the donated buffer never comes back out (the decode
      KV cache: the program returns tokens only, donation frees the input
      for in-place reuse); only the liveness checks apply — the buffer
      must be read at least once and referenced at most once at top level.
    """

    argnums: tuple[int, ...]
    mode: str = "alias"

    def __post_init__(self):
        if self.mode not in ("alias", "scratch"):
            raise ValueError(
                f"donation mode must be 'alias' or 'scratch', "
                f"got {self.mode!r}")


@dataclasses.dataclass(frozen=True)
class CostPin:
    """One audited quantity: the cost interpreter's derived number must
    match ``expect`` within ``rel_tol``.

    ``quantity`` is a :meth:`analysis.cost.CostVector.quantity` spelling:
    a scalar field (``"flops"``, ``"hbm_bytes"``, ``"hbm_bytes_read"``,
    ``"hbm_bytes_written"``, ``"peak_live_bytes"``,
    ``"collective_bytes_total"``) or one census-keyed entry spelled
    ``"collective_bytes[psum[data]]"``.

    ``expect`` is a number or a ZERO-ARG CALLABLE evaluated at rule time —
    the callable form is the point of the subsystem: providers pass
    ``lambda: common.dp_allreduce_bytes(...)`` so the pin IS the
    ``benchmarks/common.py`` closed form, and a drifted byte model fails
    lint instead of going stale. ``rel_tol=0`` means exact.
    """

    quantity: str
    expect: Any
    rel_tol: float = 0.0
    note: str = ""


@dataclasses.dataclass(frozen=True)
class CostSpec:
    """The contract's quantitative promises: closed-form pins plus an
    optional hard ceiling on per-device peak live bytes (the linear-scan
    liveness number — a dead donation or a new whole-program live buffer
    pushes it up and fails the budget)."""

    pins: tuple[CostPin, ...] = ()
    max_peak_live_bytes: int | None = None


@dataclasses.dataclass(frozen=True)
class ProgramContract:
    """One judged entry program and everything the rules check it against.

    ``collectives`` maps ``"prim[axis,...]"`` census keys (walker
    spelling: ``psum``, ``all_gather``, ``psum_scatter``, ``ppermute``)
    to an exact count or an inclusive ``(lo, hi)`` range. With
    ``strict_collectives`` (default) any *unlisted* collective observed
    in the trace is a violation — the "extra stray psum" failure mode.
    An empty dict therefore declares a collective-free program.

    ``policy`` names a core/precision.py preset (or is a Policy): matmul
    operands must be in its compute dtype and large contractions /
    reductions must accumulate in its accum dtype.

    ``vocab_dim`` arms the vocab-materialization rule: no f32
    (rows >= vocab_rows, ..., vocab_dim) intermediate bigger than
    ``max_vocab_f32_elems`` may exist anywhere in the trace.

    ``sources`` lists the module names whose edits should re-trigger this
    contract under ``lint --changed-only``.
    """

    name: str
    build: BuildFn
    policy: Any = "f32"
    collectives: dict[str, Any] | None = None
    strict_collectives: bool = True
    vocab_dim: int | None = None
    vocab_rows: int = 1
    max_vocab_f32_elems: int = 0
    max_f32_intermediate_elems: int | None = None
    donation: DonationSpec | None = None
    allowed_callbacks: tuple[str, ...] = ()
    sources: tuple[str, ...] = ()
    notes: str = ""
    #: quantitative promises (round 17); None = observe-only — the cost
    #: vector is still derived and fingerprinted, just not pinned.
    cost: CostSpec | None = None
    #: opt-in for integer matmul operands (round 19): quantized programs
    #: (weight-only decode, AQT training steps) legally contract int8
    #: operands — but ONLY int8, only into an int32 accumulator, and the
    #: result must be rescaled by an f32 scale (the dequant chain the
    #: precision rule walks). Default False: an integer dot in any other
    #: program is a finding, not a silent pass.
    quantized_matmuls: bool = False

    #: Opt-in for fp8 contractions (round 21): the precision rule accepts
    #: dot_generals with float8 operands — but ONLY e4m3fn, only into the
    #: policy's accum dtype (preferred_element_type), and the result must
    #: feed an f32 dequant mul (the same chain the int8 gate walks).
    #: Default False: an fp8 dot in any other program is a finding.
    fp8_matmuls: bool = False


_REGISTRY: dict[str, ProgramContract] = {}


def register(contract: ProgramContract) -> ProgramContract:
    """Add one contract to the global registry (idempotent per name —
    re-registering the same name replaces, so provider modules can be
    re-imported in long-lived test processes)."""
    _REGISTRY[contract.name] = contract
    return contract


def registered_contracts(
    names: tuple[str, ...] | list[str] | None = None,
) -> list[ProgramContract]:
    """Registry contents (deterministic registration order). ``names``
    filters — an unknown name is an error, not an empty result."""
    if names is None:
        return list(_REGISTRY.values())
    unknown = [n for n in names if n not in _REGISTRY]
    if unknown:
        raise KeyError(
            f"unknown program(s) {unknown}; registered: "
            f"{sorted(_REGISTRY)}")
    return [_REGISTRY[n] for n in names]


def clear_registry() -> None:
    """Test isolation hook (tests/test_analysis.py scratch registries)."""
    _REGISTRY.clear()
