"""The contract linter: trace every registered program, run every rule.

``python -m distributed_tensorflow_guide_tpu.analysis.lint`` (or the
``dtg-lint`` console script) configures 8 fake CPU devices, imports the
provider modules (``analysis/programs.py``), traces each registered
:class:`~.contracts.ProgramContract` with ``jax.make_jaxpr`` — trace-time
only, nothing is compiled or executed, so lint is perf-neutral by
construction — and audits the jaxpr with the five rule families in
``analysis/rules.py``. Exit status 1 on any violation; the report (text
or ``--json``) carries the expected-vs-observed diff per finding.

``--changed-only`` maps ``git diff --name-only <base>`` (plus the working
tree) onto each contract's ``sources`` so a small edit lints in seconds;
any edit under ``analysis/`` re-lints everything, and when git state is
unreadable the mode falls back to the full audit rather than passing
vacuously.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import traceback
from typing import Any

LINT_DEVICES = 8  # the tier-1 fake-mesh size every expectation is pinned at


def _ensure_cpu_devices(n: int = LINT_DEVICES) -> None:
    """Fake CPU devices for standalone runs. Importing this package already
    imports jax, but the *backend* only materializes at the first
    ``jax.devices()`` — until then the device count is still configurable
    (0.4.x reads the XLA flag at client creation; ≥0.5 has the config).
    If a backend is already live (pytest / bench harness), that caller's
    device setup wins — contracts are pinned at 8 devices either way
    (tests/conftest.py uses 8 too)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("JAX_NUM_CPU_DEVICES", str(n))
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    import jax

    from distributed_tensorflow_guide_tpu.core import compat

    try:
        from jax._src import xla_bridge
        if xla_bridge.backends_are_initialized():
            return
    except Exception:
        pass
    jax.config.update("jax_platforms", "cpu")
    compat.set_cpu_device_count(n)


# ---- tracing + rule execution ----------------------------------------------


@dataclasses.dataclass
class ProgramReport:
    name: str
    ok: bool
    rules: list
    error: str | None = None
    notes: str = ""

    def to_dict(self) -> dict:
        return {"name": self.name, "ok": self.ok,
                "rules": [r.to_dict() for r in self.rules],
                "error": self.error, "notes": self.notes}


@dataclasses.dataclass
class LintReport:
    programs: list

    @property
    def ok(self) -> bool:
        return all(p.ok for p in self.programs)

    @property
    def n_findings(self) -> int:
        return sum(len(r.findings) for p in self.programs for r in p.rules)

    def to_dict(self) -> dict:
        return {"ok": self.ok, "n_programs": len(self.programs),
                "n_pass": sum(p.ok for p in self.programs),
                "n_findings": self.n_findings,
                "programs": [p.to_dict() for p in self.programs]}


def _leaf_avals(arg: Any) -> list:
    import jax
    import jax.numpy as jnp

    return [jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))
            for x in jax.tree.leaves(arg)]


def lint_contract(contract) -> ProgramReport:
    """Trace one contract's program and run every rule family over it."""
    import jax

    from distributed_tensorflow_guide_tpu.analysis import rules

    try:
        fn, args = contract.build()
        jaxpr = jax.make_jaxpr(fn)(*args)
        traced = rules.TracedProgram(
            name=contract.name, jaxpr=jaxpr,
            arg_leaf_avals=[_leaf_avals(a) for a in args])
    except Exception:  # a broken build must FAIL lint, not crash it
        return ProgramReport(contract.name, ok=False, rules=[],
                             error=traceback.format_exc(limit=8),
                             notes=contract.notes)
    reports = [rule(traced, contract) for rule in rules.ALL_RULES]
    return ProgramReport(contract.name,
                         ok=all(r.ok for r in reports),
                         rules=reports, notes=contract.notes)


def run_contracts(contracts) -> LintReport:
    return LintReport([lint_contract(c) for c in contracts])


# ---- registry + --changed-only selection ------------------------------------


def _registered(names=None):
    from distributed_tensorflow_guide_tpu.analysis import (  # noqa: F401
        programs,  # import for side effect: providers register
    )
    from distributed_tensorflow_guide_tpu.analysis.contracts import (
        registered_contracts,
    )

    return registered_contracts(names)


def _changed_files(base: str) -> list[str] | None:
    """Repo-relative changed paths (committed-vs-base + working tree), or
    None when git can't answer (then the caller lints everything)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    out: set[str] = set()
    for cmd in (["git", "diff", "--name-only", base],
                ["git", "status", "--porcelain"]):
        try:
            r = subprocess.run(cmd, cwd=root, capture_output=True,
                               text=True, timeout=30)
        except Exception:
            return None
        if r.returncode != 0:
            return None
        for line in r.stdout.splitlines():
            path = line[3:] if cmd[1] == "status" else line
            if path.strip():
                out.add(path.strip().split(" -> ")[-1])
    return sorted(out)


def _module_path(mod_name: str) -> str | None:
    import importlib.util

    try:
        spec = importlib.util.find_spec(mod_name)
    except (ImportError, ValueError):
        return None
    return spec.origin if spec else None


def select_changed(contracts, base: str) -> tuple[list, str]:
    """The subset of ``contracts`` whose ``sources`` intersect the changed
    files; an analysis/-layer change (or unreadable git) selects all."""
    changed = _changed_files(base)
    if changed is None:
        return list(contracts), "git unreadable -> full lint"
    changed_abs = {os.path.basename(c): c for c in changed}
    if any("/analysis/" in c or c.startswith("analysis/") for c in changed):
        return list(contracts), "analysis/ changed -> full lint"
    picked = []
    for c in contracts:
        hit = False
        for mod in c.sources:
            path = _module_path(mod)
            if path and os.path.basename(path) in changed_abs:
                hit = True
                break
        if hit:
            picked.append(c)
    return picked, f"{len(changed)} changed file(s)"


def run_lint(names=None, changed_only: bool = False,
             base: str = "HEAD") -> LintReport:
    contracts = _registered(tuple(names) if names else None)
    if changed_only:
        contracts, _why = select_changed(contracts, base)
    return run_contracts(contracts)


# ---- rendering --------------------------------------------------------------


def render_text(report: LintReport, verbose: bool = False) -> str:
    lines = []
    for p in report.programs:
        status = "PASS" if p.ok else "FAIL"
        lines.append(f"{status:4}  {p.name}")
        if p.error:
            lines.append("      trace error:")
            lines.extend("      | " + ln
                         for ln in p.error.strip().splitlines()[-6:])
            continue
        for r in p.rules:
            if verbose or not r.ok:
                obs = ", ".join(f"{k}={v}" for k, v in r.observed.items())
                lines.append(f"      {r.rule:12} {'ok' if r.ok else 'FAIL'}"
                             f"  [{obs}]")
            for f in r.findings:
                lines.append(f"        - {f.message}")
                lines.append(f"          expected: {f.expected!r}   "
                             f"observed: {f.observed!r}")
    lines.append(
        f"{'PASS' if report.ok else 'FAIL'}: "
        f"{sum(p.ok for p in report.programs)}/{len(report.programs)} "
        f"programs clean, {report.n_findings} finding(s)")
    return "\n".join(lines)


# ---- CLI --------------------------------------------------------------------


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dtg-lint",
        description="Audit every registered compiled program against its "
                    "declared contract (trace-only, CPU fake devices).")
    parser.add_argument("--programs", default=None,
                        help="comma-separated program names (default: all)")
    parser.add_argument("--changed-only", action="store_true",
                        help="lint only contracts whose source modules "
                             "changed vs --base / the working tree")
    parser.add_argument("--base", default="HEAD",
                        help="git ref --changed-only diffs against")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--list", action="store_true",
                        help="list registered programs and exit")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="show per-rule observations for passing rules")
    args = parser.parse_args(argv)

    _ensure_cpu_devices()
    names = args.programs.split(",") if args.programs else None
    if args.list:
        for c in _registered(None):
            print(f"{c.name:32} sources={','.join(c.sources)}")
        return 0
    contracts = _registered(tuple(names) if names else None)
    if args.changed_only:
        contracts, why = select_changed(contracts, args.base)
        if not args.json:
            print(f"--changed-only: {why}; linting "
                  f"{len(contracts)}/{len(_registered(None))} program(s)")
        if not contracts:
            print("nothing to lint")
            return 0
    report = run_contracts(contracts)
    if args.json:
        print(json.dumps(report.to_dict()))
    else:
        print(render_text(report, verbose=args.verbose))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
