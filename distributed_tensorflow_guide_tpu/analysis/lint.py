"""The contract linter: trace every registered program, run every rule.

``python -m distributed_tensorflow_guide_tpu.analysis.lint`` (or the
``dtg-lint`` console script) configures 8 fake CPU devices, imports the
provider modules (``analysis/programs.py``), traces each registered
:class:`~.contracts.ProgramContract` with ``jax.make_jaxpr`` — trace-time
only, nothing is compiled or executed, so lint is perf-neutral by
construction — and audits the jaxpr with the five rule families in
``analysis/rules.py``. Exit status 1 on any violation; the report (text
or ``--json``) carries the expected-vs-observed diff per finding.

``--changed-only`` maps ``git diff --name-only <base>`` (plus the working
tree) onto each contract's ``sources`` so a small edit lints in seconds;
any edit under ``analysis/`` — or to ``benchmarks/common.py``, whose
closed forms the cost pins audit — re-lints everything, and when git
state is unreadable the mode falls back to the full audit rather than
passing vacuously.

Round 17 adds the drift gate: every linted program's normalized trace +
derived cost vector is hashed (``analysis/fingerprint.py``) and compared
to the blessed ``analysis/golden_fingerprints.json``; an unblessed
change exits 1. ``--cost`` prints the per-program cost table;
``--bless --reason "why"`` rewrites the goldens.

Round 21 adds ``--regress``: selftest the continuous regression gate
(``analysis/regress.py``), then join the persisted bench history
(``bench_history/history.jsonl``) against the cost model's roofline and
exit 1 on unexplained measured/modeled ratio drift.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import traceback
from typing import Any

LINT_DEVICES = 8  # the tier-1 fake-mesh size every expectation is pinned at


def _ensure_cpu_devices(n: int = LINT_DEVICES) -> None:
    """Fake CPU devices for standalone runs. Importing this package already
    imports jax, but the *backend* only materializes at the first
    ``jax.devices()`` — until then the device count is still configurable
    (0.4.x reads the XLA flag at client creation; ≥0.5 has the config).
    If a backend is already live (pytest / bench harness), that caller's
    device setup wins — contracts are pinned at 8 devices either way
    (tests/conftest.py uses 8 too)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("JAX_NUM_CPU_DEVICES", str(n))
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    import jax

    from distributed_tensorflow_guide_tpu.core import compat

    try:
        from jax._src import xla_bridge
        if xla_bridge.backends_are_initialized():
            return
    except Exception:
        pass
    jax.config.update("jax_platforms", "cpu")
    compat.set_cpu_device_count(n)


# ---- tracing + rule execution ----------------------------------------------


@dataclasses.dataclass
class ProgramReport:
    name: str
    ok: bool
    rules: list
    error: str | None = None
    notes: str = ""
    fingerprint: Any = None  # analysis.fingerprint.Fingerprint | None

    def to_dict(self) -> dict:
        return {"name": self.name, "ok": self.ok,
                "rules": [r.to_dict() for r in self.rules],
                "error": self.error, "notes": self.notes,
                "fingerprint": (self.fingerprint.to_json()
                                if self.fingerprint else None)}


@dataclasses.dataclass
class LintReport:
    programs: list
    #: fingerprint-vs-golden drift lines (empty = clean); populated by
    #: check_fingerprints, part of ``ok`` — drift without a bless fails.
    fingerprint_drift: list = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return (all(p.ok for p in self.programs)
                and not self.fingerprint_drift)

    @property
    def n_findings(self) -> int:
        return sum(len(r.findings) for p in self.programs for r in p.rules)

    @property
    def n_cost_pass(self) -> int:
        return sum(1 for p in self.programs for r in p.rules
                   if r.rule == "cost" and r.ok)

    def to_dict(self) -> dict:
        return {"ok": self.ok, "n_programs": len(self.programs),
                "n_pass": sum(p.ok for p in self.programs),
                "n_findings": self.n_findings,
                "n_cost_pass": self.n_cost_pass,
                "fingerprint_drift": list(self.fingerprint_drift),
                "programs": [p.to_dict() for p in self.programs]}


def _leaf_avals(arg: Any) -> list:
    import jax
    import jax.numpy as jnp

    return [jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))
            for x in jax.tree.leaves(arg)]


def lint_contract(contract) -> ProgramReport:
    """Trace one contract's program and run every rule family over it."""
    import jax

    from distributed_tensorflow_guide_tpu.analysis import rules

    try:
        fn, args = contract.build()
        jaxpr = jax.make_jaxpr(fn)(*args)
        traced = rules.TracedProgram(
            name=contract.name, jaxpr=jaxpr,
            arg_leaf_avals=[_leaf_avals(a) for a in args])
    except Exception:  # a broken build must FAIL lint, not crash it
        return ProgramReport(contract.name, ok=False, rules=[],
                             error=traceback.format_exc(limit=8),
                             notes=contract.notes)
    reports = [rule(traced, contract) for rule in rules.ALL_RULES]
    fp = None
    if traced.cost_vector is not None:  # set by rule_cost
        from distributed_tensorflow_guide_tpu.analysis import fingerprint
        fp = fingerprint.fingerprint(
            contract.name, traced.jaxpr, traced.cost_vector)
    return ProgramReport(contract.name,
                         ok=all(r.ok for r in reports),
                         rules=reports, notes=contract.notes,
                         fingerprint=fp)


def run_contracts(contracts) -> LintReport:
    return LintReport([lint_contract(c) for c in contracts])


# ---- registry + --changed-only selection ------------------------------------


def _registered(names=None):
    from distributed_tensorflow_guide_tpu.analysis import (  # noqa: F401
        programs,  # import for side effect: providers register
    )
    from distributed_tensorflow_guide_tpu.analysis.contracts import (
        registered_contracts,
    )

    return registered_contracts(names)


def _changed_files(base: str) -> list[str] | None:
    """Repo-relative changed paths (committed-vs-base + working tree), or
    None when git can't answer (then the caller lints everything)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    out: set[str] = set()
    for cmd in (["git", "diff", "--name-only", base],
                ["git", "status", "--porcelain"]):
        try:
            r = subprocess.run(cmd, cwd=root, capture_output=True,
                               text=True, timeout=30)
        except Exception:
            return None
        if r.returncode != 0:
            return None
        for line in r.stdout.splitlines():
            path = line[3:] if cmd[1] == "status" else line
            if path.strip():
                out.add(path.strip().split(" -> ")[-1])
    return sorted(out)


def _module_path(mod_name: str) -> str | None:
    import importlib.util

    try:
        spec = importlib.util.find_spec(mod_name)
    except (ImportError, ValueError):
        return None
    return spec.origin if spec else None


def select_changed(contracts, base: str) -> tuple[list, str]:
    """The subset of ``contracts`` whose ``sources`` intersect the changed
    files; an analysis/-layer change (or unreadable git) selects all."""
    changed = _changed_files(base)
    if changed is None:
        return list(contracts), "git unreadable -> full lint"
    changed_abs = {os.path.basename(c): c for c in changed}
    if any("/analysis/" in c or c.startswith("analysis/") for c in changed):
        return list(contracts), "analysis/ changed -> full lint"
    # the closed forms under cost audit: an edit there can invalidate any
    # contract's pins, so it re-lints everything just like analysis/
    if any(c.endswith("benchmarks/common.py") for c in changed):
        return list(contracts), "benchmarks/common.py changed -> full lint"
    picked = []
    for c in contracts:
        hit = False
        for mod in c.sources:
            path = _module_path(mod)
            if path and os.path.basename(path) in changed_abs:
                hit = True
                break
        if hit:
            picked.append(c)
    return picked, f"{len(changed)} changed file(s)"


def check_fingerprints(report: LintReport, *, full_registry: bool,
                       golden_path=None) -> None:
    """The drift gate: diff every linted program's live fingerprint
    against the blessed goldens; mismatch / missing-golden lines land in
    ``report.fingerprint_drift`` (part of ``ok``). Stale goldens — a
    golden whose program no longer exists — only fail on full-registry
    runs (a ``--programs`` subset says nothing about the rest)."""
    from distributed_tensorflow_guide_tpu.analysis import fingerprint

    goldens = fingerprint.load_goldens(golden_path)
    drift: list[str] = []
    for p in report.programs:
        if p.fingerprint is None:
            continue  # trace error: already a FAIL via p.ok
        drift.extend(fingerprint.diff_fingerprint(p.fingerprint, goldens))
    if full_registry:
        live = {p.name for p in report.programs}
        drift.extend(fingerprint.stale_goldens(live, goldens))
    report.fingerprint_drift = drift


def bless_fingerprints(report: LintReport, reason: str,
                       golden_path=None):
    """Rewrite the goldens from the live fingerprints. Refuses when any
    rule failed — blessed numbers must come from a clean registry."""
    from distributed_tensorflow_guide_tpu.analysis import fingerprint

    broken = [p.name for p in report.programs
              if not p.ok or p.fingerprint is None]
    if broken:
        raise RuntimeError(
            f"refusing to bless with failing/untraceable programs: "
            f"{broken} — fix the contracts first")
    return fingerprint.save_goldens(
        [p.fingerprint for p in report.programs], reason, golden_path)


def run_lint(names=None, changed_only: bool = False,
             base: str = "HEAD", fingerprints: bool = True) -> LintReport:
    contracts = _registered(tuple(names) if names else None)
    full = names is None and not changed_only
    if changed_only:
        contracts, _why = select_changed(contracts, base)
    report = run_contracts(contracts)
    if fingerprints:
        check_fingerprints(report, full_registry=full)
    return report


# ---- rendering --------------------------------------------------------------


def _fmt_bytes(x: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(x) < 1024 or unit == "GiB":
            return f"{x:,.1f}{unit}" if unit != "B" else f"{x:,.0f}B"
        x /= 1024
    return f"{x:,.1f}GiB"


def render_cost_table(report: LintReport) -> str:
    """The ``--cost`` table: one row per program from the cost rule's
    observations (present whether or not the contract pins anything)."""
    rows = [("program", "MXU flops", "HBM read", "HBM write",
             "collective", "peak live")]
    for p in report.programs:
        obs = next((r.observed for r in p.rules if r.rule == "cost"), None)
        if not obs or "flops" not in obs:
            rows.append((p.name, "-", "-", "-", "-", "-"))
            continue
        coll = sum(obs.get("collective_bytes", {}).values())
        rows.append((p.name, f"{obs['flops']:,.0f}",
                     _fmt_bytes(obs["hbm_bytes_read"]),
                     _fmt_bytes(obs["hbm_bytes_written"]),
                     _fmt_bytes(coll),
                     _fmt_bytes(obs["peak_live_bytes"])))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    out = []
    for i, r in enumerate(rows):
        out.append("  ".join(
            c.ljust(w) if j == 0 else c.rjust(w)
            for j, (c, w) in enumerate(zip(r, widths))))
        if i == 0:
            out.append("  ".join("-" * w for w in widths))
    detail = []
    for p in report.programs:
        obs = next((r.observed for r in p.rules if r.rule == "cost"), None)
        for key, v in sorted((obs or {}).get(
                "collective_bytes", {}).items()):
            detail.append(f"    {p.name}: {key} = {_fmt_bytes(v)}")
    if detail:
        out.append("  per-axis collective bytes:")
        out.extend(detail)
    return "\n".join(out)


def render_text(report: LintReport, verbose: bool = False) -> str:
    lines = []
    for p in report.programs:
        status = "PASS" if p.ok else "FAIL"
        lines.append(f"{status:4}  {p.name}")
        if p.error:
            lines.append("      trace error:")
            lines.extend("      | " + ln
                         for ln in p.error.strip().splitlines()[-6:])
            continue
        for r in p.rules:
            if verbose or not r.ok:
                obs = ", ".join(f"{k}={v}" for k, v in r.observed.items())
                lines.append(f"      {r.rule:12} {'ok' if r.ok else 'FAIL'}"
                             f"  [{obs}]")
            for f in r.findings:
                lines.append(f"        - {f.message}")
                lines.append(f"          expected: {f.expected!r}   "
                             f"observed: {f.observed!r}")
    if report.fingerprint_drift:
        lines.append("FAIL  golden fingerprints (unblessed trace drift — "
                     "run dtg-lint --bless --reason '...'):")
        lines.extend(f"        - {d}" for d in report.fingerprint_drift)
    lines.append(
        f"{'PASS' if report.ok else 'FAIL'}: "
        f"{sum(p.ok for p in report.programs)}/{len(report.programs)} "
        f"programs clean, {report.n_findings} finding(s), "
        f"{len(report.fingerprint_drift)} fingerprint drift(s)")
    return "\n".join(lines)


# ---- CLI --------------------------------------------------------------------


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dtg-lint",
        description="Audit every registered compiled program against its "
                    "declared contract (trace-only, CPU fake devices).")
    parser.add_argument("--programs", default=None,
                        help="comma-separated program names (default: all)")
    parser.add_argument("--changed-only", action="store_true",
                        help="lint only contracts whose source modules "
                             "changed vs --base / the working tree")
    parser.add_argument("--base", default="HEAD",
                        help="git ref --changed-only diffs against")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--list", action="store_true",
                        help="list registered programs and exit")
    parser.add_argument("--cost", action="store_true",
                        help="print the derived cost table (FLOPs, HBM "
                             "bytes, collective bytes, peak live) per "
                             "program")
    parser.add_argument("--bless", action="store_true",
                        help="rewrite analysis/golden_fingerprints.json "
                             "from the live traces (requires --reason)")
    parser.add_argument("--reason", default=None,
                        help="why the fingerprints changed — stored in "
                             "the golden file; required with --bless")
    parser.add_argument("--no-fingerprints", action="store_true",
                        help="skip the golden-fingerprint drift gate")
    parser.add_argument("--regress", action="store_true",
                        help="also gate the persisted bench history "
                             "(bench_history/) against the cost model: "
                             "selftest the gate, then flag rows whose "
                             "measured/modeled ratio drifted past "
                             "--regress-tol (analysis/regress.py)")
    parser.add_argument("--regress-tol", type=float, default=None,
                        help="drift tolerance for --regress (default "
                             "0.25)")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="show per-rule observations for passing rules")
    args = parser.parse_args(argv)

    if args.bless and not args.reason:
        parser.error("--bless requires --reason 'why the traces changed'")
    if args.bless and (args.programs or args.changed_only):
        parser.error("--bless rewrites ALL goldens: run it on the full "
                     "registry (no --programs / --changed-only)")

    _ensure_cpu_devices()
    names = args.programs.split(",") if args.programs else None
    if args.list:
        for c in _registered(None):
            print(f"{c.name:32} sources={','.join(c.sources)}")
        return 0
    contracts = _registered(tuple(names) if names else None)
    full = names is None and not args.changed_only
    if args.changed_only:
        contracts, why = select_changed(contracts, args.base)
        if not args.json:
            print(f"--changed-only: {why}; linting "
                  f"{len(contracts)}/{len(_registered(None))} program(s)")
        if not contracts:
            print("nothing to lint")
            return 0
    report = run_contracts(contracts)
    if args.bless:
        try:
            path = bless_fingerprints(report, args.reason)
        except RuntimeError as e:
            print(f"BLESS REFUSED: {e}", file=sys.stderr)
            print(render_text(report, verbose=args.verbose))
            return 1
        print(f"blessed {len(report.programs)} fingerprint(s) -> {path}")
        return 0
    if not args.no_fingerprints:
        check_fingerprints(report, full_registry=full)
    regress_ok = True
    regress_out: dict | None = None
    if args.regress:
        from distributed_tensorflow_guide_tpu.analysis import regress

        tol = (args.regress_tol if args.regress_tol is not None
               else regress.DEFAULT_TOL)
        st = regress.selftest(tol)
        hist = regress.check_history(tol=tol)
        regress_ok = bool(st["ok"]) and bool(hist["ok"])
        regress_out = {"selftest_ok": st["ok"], **hist}
    if args.json:
        d = report.to_dict()
        if regress_out is not None:
            d["regress"] = regress_out
        print(json.dumps(d))
    else:
        if args.cost:
            print(render_cost_table(report))
            print()
        print(render_text(report, verbose=args.verbose))
        if regress_out is not None:
            from distributed_tensorflow_guide_tpu.analysis import regress

            print(f"regress selftest: "
                  f"{'PASS' if regress_out['selftest_ok'] else 'FAIL'}")
            print(regress.render_report(regress_out))
    return 0 if report.ok and regress_ok else 1


if __name__ == "__main__":
    sys.exit(main())
