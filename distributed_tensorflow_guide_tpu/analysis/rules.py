"""The five rule families the linter runs over every traced program.

Each rule is a pure function ``(traced, contract) -> RuleReport``: it
reads the jaxpr (never rewrites it), records what it *observed* — so a
passing report is still evidence, not silence — and emits a
:class:`Finding` per violation with the expected/observed pair the report
renderer turns into a diff.

Rule families (docs/analysis.md has the catalog with rationale):

1. **memory**    — largest live f32 intermediate + the vocab-dim
   materialization cap generalizing the fused-CE "no full logits" pin.
2. **precision** — matmul operand dtypes and accumulation dtypes must
   conform to the contract's core/precision.py Policy.
3. **collectives** — census of communication primitives per mesh axis vs
   the declared expectations; strict mode flags unlisted collectives.
4. **donation**  — declared donated buffers are actually donatable
   (alias-feasible or scratch), read at least once, referenced at most
   once at top level (invar aliasing counted).
5. **determinism** — no host callbacks / nondeterministic-lowering
   primitives inside step functions.
6. **cost**      — the abstract cost interpreter (``analysis/cost.py``)
   derives FLOPs / HBM bytes / per-axis collective bytes / peak live
   bytes from the trace and diffs them against the contract's
   :class:`~.contracts.CostSpec` pins — closed-form models from
   ``benchmarks/common.py``, now machine-checked at trace time.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from distributed_tensorflow_guide_tpu.analysis import walker
from distributed_tensorflow_guide_tpu.analysis.contracts import (
    ProgramContract,
)


@dataclasses.dataclass
class Finding:
    """One violation: ``expected`` vs ``observed`` renders as the diff."""

    rule: str
    message: str
    expected: Any = None
    observed: Any = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class RuleReport:
    rule: str
    observed: dict
    findings: list[Finding]

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {"rule": self.rule, "ok": self.ok, "observed": self.observed,
                "findings": [f.to_dict() for f in self.findings]}


@dataclasses.dataclass
class TracedProgram:
    """A contract's program after tracing: the closed jaxpr plus the
    per-argument flat input avals the donation rule needs (jaxpr invars
    are flat; ``arg_leaf_avals[i]`` is argument i's slice of them)."""

    name: str
    jaxpr: Any  # jax.extend.core.ClosedJaxpr
    arg_leaf_avals: list[list[Any]]
    #: set by rule_cost as a side effect so the linter can fingerprint
    #: the program without interpreting the trace twice
    cost_vector: Any = None


# ---- 1. memory --------------------------------------------------------------


def rule_memory(traced: TracedProgram,
                contract: ProgramContract) -> RuleReport:
    elems, shape = walker.largest_f32_intermediate(traced.jaxpr)
    observed = {"largest_f32_elems": elems, "largest_f32_shape": list(shape)}
    findings = []
    cap = contract.max_f32_intermediate_elems
    if cap is not None and elems > cap:
        findings.append(Finding(
            "memory",
            f"largest f32 intermediate {shape} has {elems} elements, over "
            f"the declared cap",
            expected=f"<= {cap} elements", observed=elems))
    if contract.vocab_dim is not None:
        worst = walker.max_f32_elems_with_vocab_dim(
            traced.jaxpr, contract.vocab_rows, contract.vocab_dim)
        observed["vocab_materialized_elems"] = worst
        if worst > contract.max_vocab_f32_elems:
            findings.append(Finding(
                "memory",
                f"f32 (rows>={contract.vocab_rows}, ..., "
                f"V={contract.vocab_dim}) logits-shaped intermediate "
                "materialized",
                expected=f"<= {contract.max_vocab_f32_elems} elements",
                observed=worst))
    return RuleReport("memory", observed, findings)


# ---- 2. precision -----------------------------------------------------------

#: Contractions/reductions at or above this many reduced elements must
#: accumulate in the policy's accum dtype; tiny ones (scalar bookkeeping,
#: metric averages) are noise, not a numerics hazard.
ACCUM_MIN_REDUCED = 64

_MATMUL_PRIMS = ("dot_general", "conv_general_dilated")


def _reduced_elems_dot(eqn) -> int:
    dims = eqn.params.get("dimension_numbers")
    if not dims:
        return 0
    (lhs_c, _), _ = dims
    shape = eqn.invars[0].aval.shape
    n = 1
    for d in lhs_c:
        n *= int(shape[d])
    return n


def _int_dot_dequant_ok(eqn, consumers) -> bool:
    """Walk the dequant chain of one integer dot: its int32 accumulator
    must reach a ``mul`` whose other operand is f32 — the scale — possibly
    through converts (``acc.astype(f32) * scale`` is the idiom both
    ops/quant paths emit). A quantized product that is never rescaled is
    numerically meaningless output, not an optimization."""
    import jax.numpy as jnp

    var = eqn.outvars[0]
    for _ in range(4):  # tolerate a short convert/reshape chain
        for c in consumers.get(id(var), ()):
            cname = c.primitive.name
            if cname == "mul":
                others = [v for v in c.invars if v is not var]
                return any(
                    hasattr(v, "aval")
                    and jnp.dtype(v.aval.dtype) == jnp.dtype(jnp.float32)
                    for v in others)
            if cname in ("convert_element_type", "reshape", "broadcast_in_dim"):  # noqa: E501
                var = c.outvars[0]
                break
        else:
            return False
    return False


def rule_precision(traced: TracedProgram,
                   contract: ProgramContract) -> RuleReport:
    import jax.numpy as jnp

    from distributed_tensorflow_guide_tpu.core import precision

    policy = precision.resolve(contract.policy)
    compute = jnp.dtype(policy.compute_dtype)
    accum = jnp.dtype(policy.accum_dtype)
    observed: dict = {"policy": policy.name, "matmuls": 0,
                      "bad_operand_matmuls": 0, "bad_accum_ops": 0,
                      "int_matmuls": 0, "fp8_matmuls": 0}
    # fp8 grids are jnp.floating subtypes, so without this carve-out every
    # e4m3 dot would trip the compute-dtype check below. The gate mirrors
    # the int8 one: contract.fp8_matmuls opts in, and opted-in dots must be
    # e4m3-only (e5m2 is the gradient wire format, never a contraction
    # operand here), accumulate at the policy's accum dtype via
    # preferred_element_type, and feed an f32 dequant mul.
    fp8_ok = jnp.dtype(jnp.float8_e4m3fn)
    fp8_all = {fp8_ok, jnp.dtype(jnp.float8_e5m2)}
    findings = []
    eqns = list(walker.walk(traced.jaxpr))
    # var -> consuming eqns (vars are per-jaxpr objects, so identity keys
    # are exact; a dot and its dequant chain share one enclosing jaxpr)
    consumers: dict[int, list] = {}
    for eqn in eqns:
        for v in eqn.invars:
            if hasattr(v, "aval"):
                consumers.setdefault(id(v), []).append(eqn)
    for eqn in eqns:
        name = eqn.primitive.name
        if name in _MATMUL_PRIMS:
            observed["matmuls"] += 1
            int_dtypes = {jnp.dtype(v.aval.dtype) for v in eqn.invars
                          if jnp.issubdtype(v.aval.dtype, jnp.integer)}
            if int_dtypes:
                observed["int_matmuls"] += 1
                if not contract.quantized_matmuls:
                    findings.append(Finding(
                        "precision",
                        f"{name} with integer operands "
                        f"{sorted(d.name for d in int_dtypes)} in a program "
                        "whose contract does not opt in via "
                        "quantized_matmuls",
                        expected="float operands (or "
                                 "contract.quantized_matmuls=True)",
                        observed=sorted(d.name for d in int_dtypes)))
                else:
                    if int_dtypes != {jnp.dtype(jnp.int8)}:
                        findings.append(Finding(
                            "precision",
                            f"quantized {name} operands must be int8, got "
                            f"{sorted(d.name for d in int_dtypes)}",
                            expected="int8",
                            observed=sorted(d.name for d in int_dtypes)))
                    out = jnp.dtype(eqn.outvars[0].aval.dtype)
                    if out != jnp.dtype(jnp.int32):
                        findings.append(Finding(
                            "precision",
                            f"quantized {name} accumulates in {out.name} — "
                            "int8 contractions must widen to int32 (set "
                            "preferred_element_type), the integer analog "
                            "of the accum-dtype contract",
                            expected="int32", observed=out.name))
                    elif not _int_dot_dequant_ok(eqn, consumers):
                        findings.append(Finding(
                            "precision",
                            f"quantized {name} result is never rescaled by "
                            "an f32 scale (no dequant mul found on its "
                            "accumulator)",
                            expected="acc.astype(f32) * f32_scale",
                            observed="no f32 mul in the consumer chain"))
                continue  # the float checks below don't apply to int dots
            op_dtypes = {jnp.dtype(v.aval.dtype) for v in eqn.invars
                         if jnp.issubdtype(v.aval.dtype, jnp.floating)}
            fp8_dtypes = op_dtypes & fp8_all
            if fp8_dtypes:
                observed["fp8_matmuls"] += 1
                if not contract.fp8_matmuls:
                    findings.append(Finding(
                        "precision",
                        f"{name} with fp8 operands "
                        f"{sorted(d.name for d in fp8_dtypes)} in a program "
                        "whose contract does not opt in via fp8_matmuls",
                        expected="policy-dtype operands (or "
                                 "contract.fp8_matmuls=True)",
                        observed=sorted(d.name for d in fp8_dtypes)))
                else:
                    if op_dtypes != {fp8_ok}:
                        findings.append(Finding(
                            "precision",
                            f"fp8 {name} operands must all be "
                            "float8_e4m3fn, got "
                            f"{sorted(d.name for d in op_dtypes)}",
                            expected="float8_e4m3fn",
                            observed=sorted(d.name for d in op_dtypes)))
                    out = jnp.dtype(eqn.outvars[0].aval.dtype)
                    if out != accum:
                        findings.append(Finding(
                            "precision",
                            f"fp8 {name} accumulates in {out.name} — fp8 "
                            "contractions must widen to the accum dtype "
                            "(set preferred_element_type)",
                            expected=accum.name, observed=out.name))
                    elif not _int_dot_dequant_ok(eqn, consumers):
                        findings.append(Finding(
                            "precision",
                            f"fp8 {name} result is never rescaled by an "
                            "f32 scale (no dequant mul found on its "
                            "accumulator)",
                            expected="acc * f32_scale",
                            observed="no f32 mul in the consumer chain"))
                continue  # the policy-dtype checks below don't apply
            bad = op_dtypes - {compute}
            if bad:
                observed["bad_operand_matmuls"] += 1
                findings.append(Finding(
                    "precision",
                    f"{name} operands in {sorted(d.name for d in bad)} "
                    f"violate the {policy.name} policy's compute dtype",
                    expected=compute.name,
                    observed=sorted(d.name for d in bad)))
            out = jnp.dtype(eqn.outvars[0].aval.dtype)
            if (_reduced_elems_dot(eqn) >= ACCUM_MIN_REDUCED
                    and jnp.issubdtype(out, jnp.floating)
                    and out != accum):
                observed["bad_accum_ops"] += 1
                findings.append(Finding(
                    "precision",
                    f"{name} contracting {_reduced_elems_dot(eqn)} "
                    f"elements accumulates in {out.name} (set "
                    "preferred_element_type)",
                    expected=accum.name, observed=out.name))
        elif name == "reduce_sum":
            inv, out = eqn.invars[0].aval, eqn.outvars[0].aval
            if not jnp.issubdtype(out.dtype, jnp.floating):
                continue
            import numpy as np

            reduced = int(np.prod(inv.shape or (1,))) // max(
                1, int(np.prod(out.shape or (1,))))
            if (reduced >= ACCUM_MIN_REDUCED
                    and jnp.dtype(out.dtype) != accum):
                observed["bad_accum_ops"] += 1
                findings.append(Finding(
                    "precision",
                    f"reduce_sum over {reduced} elements accumulates in "
                    f"{jnp.dtype(out.dtype).name}",
                    expected=accum.name,
                    observed=jnp.dtype(out.dtype).name))
    return RuleReport("precision", observed, findings)


# ---- 3. collectives ---------------------------------------------------------


def rule_collectives(traced: TracedProgram,
                     contract: ProgramContract) -> RuleReport:
    census = walker.collective_census(traced.jaxpr)
    observed = {"census": dict(sorted(census.items()))}
    findings = []
    if contract.collectives is None:  # census-only program: observe, allow
        return RuleReport("collectives", observed, findings)
    for key, want in sorted(contract.collectives.items()):
        got = census.get(key, 0)
        lo, hi = want if isinstance(want, tuple) else (want, want)
        if not lo <= got <= hi:
            findings.append(Finding(
                "collectives",
                f"{key}: expected "
                + (f"{lo}" if lo == hi else f"{lo}..{hi}")
                + f", traced {got}",
                expected=want, observed=got))
    if contract.strict_collectives:
        for key in sorted(set(census) - set(contract.collectives)):
            findings.append(Finding(
                "collectives",
                f"undeclared collective {key} x{census[key]} in the trace "
                "(stray communication)",
                expected="absent", observed=census[key]))
    return RuleReport("collectives", observed, findings)


# ---- 4. donation ------------------------------------------------------------


def rule_donation(traced: TracedProgram,
                  contract: ProgramContract) -> RuleReport:
    spec = contract.donation
    if spec is None:
        return RuleReport("donation", {"declared": None}, [])
    jaxpr = traced.jaxpr.jaxpr
    observed = {"declared": list(spec.argnums), "mode": spec.mode}
    findings = []

    # flat invar index ranges per argument
    starts, pos = [], 0
    for leaves in traced.arg_leaf_avals:
        starts.append(pos)
        pos += len(leaves)
    use_counts = walker.input_use_counts(jaxpr)
    deep_used = walker.deep_input_used(jaxpr)

    donated: list[tuple[int, Any]] = []  # (flat index, aval)
    for argnum in spec.argnums:
        if argnum >= len(traced.arg_leaf_avals):
            findings.append(Finding(
                "donation", f"donate argnum {argnum} out of range",
                expected=f"< {len(traced.arg_leaf_avals)} args",
                observed=argnum))
            continue
        for k, aval in enumerate(traced.arg_leaf_avals[argnum]):
            donated.append((starts[argnum] + k, aval))

    for idx, aval in donated:
        if not deep_used[idx]:
            findings.append(Finding(
                "donation",
                f"donated buffer (arg leaf {idx}, "
                f"{getattr(aval, 'dtype', '?')}{list(aval.shape)}) is never "
                "read — dead donation",
                expected="buffer read at least once", observed="unused"))
        elif use_counts[idx] > 1:
            findings.append(Finding(
                "donation",
                f"donated buffer (arg leaf {idx}) referenced "
                f"{use_counts[idx]}x at top level — still live after the "
                "donating call, XLA cannot alias it",
                expected="exactly one reference", observed=use_counts[idx]))

    if spec.mode == "alias":
        # XLA input-output alias feasibility: every donated leaf must find
        # a same-shape/dtype output leaf, each output used at most once.
        from collections import Counter

        sig = lambda a: (tuple(a.shape), str(a.dtype))  # noqa: E731
        outs = Counter(sig(v.aval) for v in jaxpr.outvars)
        unmatched = 0
        for _, aval in donated:
            if outs[sig(aval)] > 0:
                outs[sig(aval)] -= 1
            else:
                unmatched += 1
                findings.append(Finding(
                    "donation",
                    f"donated {str(aval.dtype)}{list(aval.shape)} leaf has "
                    "no matching output to alias — the donation is dropped "
                    "(XLA warns 'donated buffer not usable')",
                    expected="a same-shape/dtype output leaf",
                    observed="no match"))
        observed["alias_unmatched"] = unmatched
    observed["donated_leaves"] = len(donated)
    return RuleReport("donation", observed, findings)


# ---- 5. determinism ---------------------------------------------------------

#: Host-callback / side-channel primitives: anything here inside a step
#: function breaks replay determinism (callbacks observe host state and
#: order) and stalls the TPU on a host round-trip.
HOST_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed", "outside_call", "host_callback_call",
})

#: Primitives whose lowering is nondeterministic across runs (XLA's
#: stateful RNG — unlike the threefry/counter path jax.random uses).
NONDETERMINISTIC_PRIMS = frozenset({"rng_uniform"})


def rule_determinism(traced: TracedProgram,
                     contract: ProgramContract) -> RuleReport:
    hits: dict[str, int] = {}
    for eqn in walker.walk(traced.jaxpr):
        name = eqn.primitive.name
        if name in HOST_CALLBACK_PRIMS or name in NONDETERMINISTIC_PRIMS:
            hits[name] = hits.get(name, 0) + 1
    observed = {"hits": hits}
    findings = [
        Finding(
            "determinism",
            f"{'host callback' if n in HOST_CALLBACK_PRIMS else 'nondeterministic'}"  # noqa: E501
            f" primitive {n} x{c} inside the step function",
            expected="absent", observed=c)
        for n, c in sorted(hits.items())
        if n not in contract.allowed_callbacks
    ]
    return RuleReport("determinism", observed, findings)


# ---- 6. cost ----------------------------------------------------------------


def rule_cost(traced: TracedProgram,
              contract: ProgramContract) -> RuleReport:
    """Derive the program's cost vector and hold it to the contract's
    :class:`~.contracts.CostSpec`. With no spec the rule is observe-only
    (the vector still feeds the fingerprint gate); with one, every pin is
    diffed against its closed-form expectation and the optional peak-live
    budget is enforced."""
    from distributed_tensorflow_guide_tpu.analysis import cost as cost_mod

    spec = contract.cost
    try:
        vec = cost_mod.program_cost(traced, contract)
    except Exception as e:  # pragma: no cover - exercised via fake jaxprs
        # Un-interpretable trace: fine to observe (micro-programs in
        # tests), fatal when the contract declares pins it can't verify.
        findings = [] if spec is None else [Finding(
            "cost", f"cost interpreter failed on the trace: {e!r}",
            expected="interpretable trace", observed=type(e).__name__)]
        return RuleReport("cost", {"error": repr(e)}, findings)
    traced.cost_vector = vec
    observed = vec.to_dict()
    if spec is None:
        return RuleReport("cost", observed, [])
    findings = []
    for pin in spec.pins:
        want = float(pin.expect() if callable(pin.expect) else pin.expect)
        got = vec.quantity(pin.quantity)
        if abs(got - want) > pin.rel_tol * max(abs(want), 1.0):
            findings.append(Finding(
                "cost",
                f"{pin.quantity} drifted from the closed-form model"
                + (f" ({pin.note})" if pin.note else ""),
                expected=(f"{want:g}" if pin.rel_tol == 0
                          else f"{want:g} ±{pin.rel_tol:.1%}"),
                observed=got))
    cap = spec.max_peak_live_bytes
    if cap is not None and vec.peak_live_bytes > cap:
        findings.append(Finding(
            "cost",
            f"peak live bytes {vec.peak_live_bytes} over the declared "
            "per-device budget",
            expected=f"<= {cap} bytes", observed=vec.peak_live_bytes))
    return RuleReport("cost", observed, findings)


#: Registry the linter iterates — order is the report order.
ALL_RULES: tuple[Callable[[TracedProgram, ProgramContract], RuleReport],
                 ...] = (
    rule_memory,
    rule_precision,
    rule_collectives,
    rule_donation,
    rule_determinism,
    rule_cost,
)
