"""Tiny shared programs the contract providers trace.

Lint runs inside tier-1's wall-clock budget, so every registered program
is built at toy scale: a 4-leaf MLP for the DP/FSDP/multislice strategy
contracts (dims chosen so FSDP shards the two matrices over 8 devices and
replicates the two biases) and an 8-token 2-layer transformer for the
pipeline/decode contracts. The contracts audit *structure* — collectives,
dtypes, donation, shapes — which is scale-invariant; correctness at real
scale stays with the subsystem test suites.
"""

from __future__ import annotations


def tiny_mlp():
    """(loss_fn, state, batch): 4 param leaves — w1 (16,32) and w2 (32,16)
    shard over 8 devices at min_shard_size=64; b1 (32,) and b2 (16,) stay
    replicated — with an SGD+momentum optimizer so the optimizer state
    carries float leaves (what the multislice outer sync pmeans)."""
    import jax
    import jax.numpy as jnp
    import optax
    from flax.training import train_state

    def loss_fn(params, batch):
        h = jnp.tanh(batch["x"] @ params["w1"] + params["b1"])
        pred = h @ params["w2"] + params["b2"]
        err = pred - batch["y"]
        return jnp.mean(err ** 2), {"mae": jnp.mean(jnp.abs(err))}

    k = jax.random.PRNGKey(0)
    ks = jax.random.split(k, 4)
    params = {
        "w1": jax.random.normal(ks[0], (16, 32), jnp.float32) * 0.1,
        "b1": jnp.zeros((32,), jnp.float32),
        "w2": jax.random.normal(ks[1], (32, 16), jnp.float32) * 0.1,
        "b2": jnp.zeros((16,), jnp.float32),
    }
    state = train_state.TrainState.create(
        apply_fn=lambda *a, **kw: None, params=params,
        tx=optax.sgd(0.1, momentum=0.9))
    batch = {
        "x": jax.random.normal(ks[2], (8, 16), jnp.float32),
        "y": jax.random.normal(ks[3], (8, 16), jnp.float32),
    }
    return loss_fn, state, batch


def tiny_lm_cfg(**overrides):
    """The toy TransformerConfig the pipeline/decode contracts trace
    (f32 on CPU, dense attention at this length)."""
    import jax.numpy as jnp

    from distributed_tensorflow_guide_tpu.models.transformer import (
        TransformerConfig,
    )

    kw = dict(vocab_size=64, num_layers=2, num_heads=2, d_model=16,
              d_ff=32, max_len=8, causal=True, dtype=jnp.float32)
    kw.update(overrides)
    return TransformerConfig(**kw)
