"""Quantitative cost semantics over traced programs (round 17).

PR 8 gave every judged program *predicate* checks (a psum census, a
donation audit); this module extends the same walker into an abstract
cost interpreter, so the numbers the repo's closed-form models promise
(``benchmarks/common.py``) are DERIVED from the jaxpr that will actually
compile — and drift between model and program becomes a lint failure
instead of a stale doc. Four quantities per program:

* **MXU FLOPs** — ``dot_general``/``conv_general_dilated`` contraction
  shapes, ``lax.scan`` bodies multiplied by their static trip count,
  ``cond``/``switch`` charged at the max branch (exact when the expensive
  branch is taken), ``while`` bodies once (documented undercount — no hot
  path here uses a raw while_loop for compute), ``pallas_call`` charged
  through the kernel cost-model registry (:func:`register_kernel_cost`)
  with an inner-jaxpr × grid fallback. Rematerialized regions are charged
  on recompute by construction: the remat body appears again inside the
  backward, and the interpreter charges equations as scheduled.

* **HBM bytes read/written** — the fusion-boundary byte model: only
  memory-bound equations (matmul-class, gather/scatter, dynamic slices,
  sort/top_k, pallas) touch HBM; elementwise/shape/convert chains are
  assumed XLA-fused (zero traffic), which makes this MINIMAL algorithmic
  traffic exactly like the closed forms it is diffed against.
  Matmul operand reads are *narrow-origin* aware (round 19): a
  ``convert_element_type`` chain carries the smallest storage the value
  ever had, so a weight stored int8 and upcast inside the fused matmul
  is charged 1 byte/elem — the widening cast is compute, not traffic.
  Narrowing converts (f32 -> bf16) are the identity under the ``min``,
  so every pre-existing program's bytes are unchanged.
  Gather charges the *touched rows* (output size), not the whole table —
  the ``decode_hbm_bytes_per_step`` "gathered embedding rows" convention
  — and ``dynamic_update_slice`` charges the update size, in-place.
  Donation-awareness at the program boundary: an output leaf that is a
  bare passthrough of an input costs a defensive copy UNLESS that input
  is donated in alias mode (XLA aliases it — zero bytes), so an
  undonated state->state program is visibly more expensive than the
  donated one.

* **Collective bytes** — every census key (``"prim[axis,...]"``) priced
  per participating device with the same ring accounting as
  ``benchmarks/common.py``: psum 2·P·(n−1)/n, all_gather (n−1)/n of the
  gathered output, reduce/psum_scatter (n−1)/n of the scattered input,
  all_to_all (n−1)/n of the buffer, ppermute one ring-averaged hop with
  the wrap pair carrying no payload. Axis sizes come from the enclosing
  ``shard_map`` equation's mesh, so the interpreter needs no device
  globals.

* **Peak live bytes** — a linear scan over the equation schedule with
  last-use liveness: non-donated inputs and constants are live for the
  whole program (the caller owns those buffers), donated inputs die at
  their last use — and a donated-but-DEAD input never dies (XLA drops
  the unusable donation and the buffer sits allocated), which is how a
  dead donation shows up as a peak-live regression, not just a warning.

Import discipline matches the package: no jax at module import.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable

from distributed_tensorflow_guide_tpu.analysis import walker

# ---- the cost vector ---------------------------------------------------------


@dataclasses.dataclass
class CostVector:
    """One program's derived costs. ``collective_bytes`` is keyed exactly
    like the walker census (``"psum[data]"``) so a contract can pin the
    bytes of the same collective family it already counts."""

    flops: float = 0.0
    hbm_bytes_read: float = 0.0
    hbm_bytes_written: float = 0.0
    collective_bytes: dict[str, float] = dataclasses.field(
        default_factory=dict)
    peak_live_bytes: int = 0

    @property
    def hbm_bytes(self) -> float:
        return self.hbm_bytes_read + self.hbm_bytes_written

    @property
    def collective_bytes_total(self) -> float:
        return float(sum(self.collective_bytes.values()))

    def quantity(self, name: str) -> float:
        """Resolve a CostPin quantity string: a scalar field name
        (``"flops"``, ``"hbm_bytes"``, ``"peak_live_bytes"``,
        ``"collective_bytes_total"``) or one census-keyed entry spelled
        ``"collective_bytes[psum[data]]"`` (0.0 when the key never
        traced — an absent collective moved zero bytes)."""
        if name.startswith("collective_bytes[") and name.endswith("]"):
            return float(self.collective_bytes.get(name[17:-1], 0.0))
        if not hasattr(self, name) and name not in (
                "hbm_bytes", "collective_bytes_total"):
            raise KeyError(f"unknown cost quantity {name!r}")
        return float(getattr(self, name))

    def to_dict(self) -> dict:
        return {
            "flops": self.flops,
            "hbm_bytes_read": self.hbm_bytes_read,
            "hbm_bytes_written": self.hbm_bytes_written,
            "hbm_bytes": self.hbm_bytes,
            "collective_bytes": dict(sorted(self.collective_bytes.items())),
            "peak_live_bytes": self.peak_live_bytes,
        }


# ---- kernel cost-model registry ---------------------------------------------

#: kernel name (pallas_call ``name_and_src_info.name``) -> model.
#: A model maps one pallas_call equation to
#: ``{"flops": f, "read": r, "write": w}``; kernels register next to
#: their implementation (the autotune pattern), e.g.
#: ops/decode_attention.py registers the paged decode kernel's model.
_KERNEL_COST_MODELS: dict[str, Callable[[Any], dict]] = {}


def register_kernel_cost(name: str, model: Callable[[Any], dict]) -> None:
    """Register (idempotently) the cost model for one Pallas kernel."""
    _KERNEL_COST_MODELS[name] = model


def _pallas_name(eqn) -> str:
    nsi = eqn.params.get("name_and_src_info")
    return getattr(nsi, "name", None) or eqn.params.get("name") or "?"


def _pallas_grid(eqn) -> int:
    gm = eqn.params.get("grid_mapping")
    grid = getattr(gm, "grid", None) or ()
    return int(math.prod(int(g) for g in grid)) or 1


def _pallas_cost(eqn) -> dict:
    """Registered model, else the fallback: kernel-body FLOPs × grid
    cells, operands read once, outputs written once (the minimal-DMA
    ceiling — BlockSpec revisits push real traffic above it, which is
    the same "spills push the fraction down" convention as every
    roofline model in benchmarks/common.py)."""
    model = _KERNEL_COST_MODELS.get(_pallas_name(eqn))
    if model is not None:
        return model(eqn)
    body = walker.iter_subjaxprs(eqn.params.get("jaxpr"))
    flops = sum(_jaxpr_flops(b) for b in body) * _pallas_grid(eqn)
    return {
        "flops": flops,
        "read": sum(_aval_bytes(v.aval) for v in eqn.invars),
        "write": sum(_aval_bytes(v.aval) for v in eqn.outvars),
    }


# ---- aval helpers ------------------------------------------------------------


def _aval_bytes(aval) -> int:
    import numpy as np

    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:  # tokens / abstract refs
        return 0
    try:
        itemsize = int(np.dtype(dtype).itemsize)
    except TypeError:  # extended dtypes (PRNG keys: fry = 2 x uint32)
        itemsize = int(getattr(dtype, "itemsize", 8))
    return int(math.prod(shape) or 1) * itemsize


def _dot_general_flops(eqn) -> float:
    lhs, rhs = (v.aval for v in eqn.invars[:2])
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    batch = math.prod(lhs.shape[i] for i in lb)
    contract = math.prod(lhs.shape[i] for i in lc)
    lhs_free = math.prod(
        d for i, d in enumerate(lhs.shape) if i not in set(lb) | set(lc))
    rhs_free = math.prod(
        d for i, d in enumerate(rhs.shape) if i not in set(rb) | set(rc))
    return 2.0 * batch * contract * lhs_free * rhs_free


def _conv_flops(eqn) -> float:
    lhs, rhs = (v.aval for v in eqn.invars[:2])
    out = eqn.outvars[0].aval
    dn = eqn.params["dimension_numbers"]
    k_spatial = math.prod(rhs.shape[i] for i in dn.rhs_spec[2:])
    c_in = rhs.shape[dn.rhs_spec[1]]
    c_out = out.shape[dn.out_spec[1]]
    batch = out.shape[dn.out_spec[0]]
    out_spatial = math.prod(out.shape[i] for i in dn.out_spec[2:])
    groups = eqn.params.get("feature_group_count", 1)
    return 2.0 * batch * out_spatial * c_out * c_in * k_spatial / groups


def _jaxpr_flops(jaxpr) -> float:
    """FLOPs only (the pallas fallback needs this without the rest of the
    interpreter — kernel bodies have no collectives or HBM boundary)."""
    vec = CostVector()
    _interpret(jaxpr, vec, mult=1.0, axis_sizes={}, flops_only=True)
    return vec.flops


# ---- the HBM fusion-boundary classification ---------------------------------

#: Equations that move HBM bytes themselves. Everything else is assumed
#: fused by XLA (elementwise chains, reshapes, converts, broadcasts) and
#: charged zero — the byte totals are MINIMAL algorithmic traffic by
#:  construction, same convention as the closed forms they're diffed with.
_MATMUL_PRIMS = frozenset({"dot_general", "conv_general_dilated"})
_TOUCHED_ROWS_PRIMS = frozenset({"gather", "take", "take_along_axis"})
_INPLACE_UPDATE_PRIMS = frozenset({"dynamic_update_slice", "scatter",
                                   "scatter-add", "scatter_add"})
_SLICE_PRIMS = frozenset({"dynamic_slice"})
_REORDER_PRIMS = frozenset({"sort", "top_k", "argmax", "argmin",
                            "cumsum", "cumlogsumexp", "cummax"})

#: Branch/loop primitives the interpreter schedules explicitly.
_SCAN, _WHILE = "scan", "while"
_BRANCH_PRIMS = frozenset({"cond", "switch", "platform_index"})


def _eqn_hbm(eqn, narrow: dict[int, int] | None = None,
             ) -> tuple[float, float]:
    """(read, write) bytes one memory-bound equation moves; (0, 0) for
    fused-class equations. ``narrow`` maps ``id(var)`` to the smallest
    storage bytes the value had anywhere on its convert chain — applied
    ONLY to matmul operand reads (the weight-only-quant case: the int8
    buffer in HBM is what the MXU pipeline actually streams; the f32
    upcast lives in registers)."""
    name = eqn.primitive.name
    in_b = sum(_aval_bytes(v.aval) for v in eqn.invars)
    out_b = sum(_aval_bytes(v.aval) for v in eqn.outvars)
    if name in _MATMUL_PRIMS or name in _REORDER_PRIMS:
        if name in _MATMUL_PRIMS and narrow:
            in_b = sum(narrow.get(id(v), _aval_bytes(v.aval))
                       for v in eqn.invars)
        return float(in_b), float(out_b)
    if name in _TOUCHED_ROWS_PRIMS:
        # the touched rows, not the whole table (decode counts GATHERED
        # embedding rows); indices are noise next to the rows
        return float(out_b), float(out_b)
    if name in _INPLACE_UPDATE_PRIMS:
        upd = sum(_aval_bytes(v.aval) for v in eqn.invars[1:])
        return float(upd), float(upd)
    if name in _SLICE_PRIMS:
        return float(out_b), float(out_b)
    return 0.0, 0.0


# ---- the interpreter ---------------------------------------------------------


def _merge_collectives(dst: dict, src: dict, mult: float) -> None:
    for k, v in src.items():
        dst[k] = dst.get(k, 0.0) + v * mult


def _collective_bytes(eqn, axis_sizes: dict[str, int]) -> float:
    """Per-device ring bytes of one collective equation — the SAME
    accounting as benchmarks/common.py's closed forms, derived from the
    equation instead of hand-fed."""
    name = walker.prim_name(eqn)
    n = 1
    for ax in walker.eqn_axis_names(eqn):
        n *= int(axis_sizes.get(ax, 1))
    if n <= 1:
        return 0.0  # compiles to a no-op on a 1-device axis
    frac = (n - 1) / n
    in_b = sum(_aval_bytes(v.aval) for v in eqn.invars)
    out_b = sum(_aval_bytes(v.aval) for v in eqn.outvars)
    if name in ("psum", "pmax", "pmin"):
        return 2.0 * in_b * frac        # ring = reduce-scatter + all-gather
    if name == "all_gather":
        return out_b * frac             # receive everyone else's shard
    if name in ("psum_scatter", "reduce_scatter"):
        return in_b * frac              # send everyone else's shard
    if name == "all_to_all":
        return in_b * frac              # keep own 1/n, exchange the rest
    if name == "ppermute":
        # one hop per non-wrap pair, ring-averaged over the axis — the
        # pipeline model's (P-1)/P with the wrap carrying no payload
        perm = eqn.params.get("perm", ())
        hops = max(0, len(perm) - 1) if len(perm) == n else len(perm)
        return in_b * hops / n
    return in_b * frac  # pbroadcast and friends: one pass


def _subjaxprs(eqn) -> list:
    return [s for p in eqn.params.values() for s in walker.iter_subjaxprs(p)]


def _interpret(jaxpr, vec: CostVector, *, mult: float,
               axis_sizes: dict[str, int], flops_only: bool = False) -> None:
    """Accumulate ``jaxpr``'s costs into ``vec`` with multiplier ``mult``
    (scan trip counts compose multiplicatively through nesting)."""
    jaxpr = walker._as_open_jaxpr(jaxpr)
    # narrow-origin storage bytes, per jaxpr: convert_element_type chains
    # carry min(chain, own aval) forward — monotone, so a pure-widening
    # chain (int8 weight -> f32 matmul operand) remembers the 1-byte HBM
    # buffer it streams from, while narrowing (f32 -> bf16) is a no-op
    # relative to the plain aval bytes. Chain-breaking ops (the int4
    # unpack's shifts/concats) deliberately reset to aval bytes: once the
    # program *computes* a wider value, that value is what moves.
    narrow: dict[int, int] = {}
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "convert_element_type" and eqn.invars and eqn.outvars:
            inv, outv = eqn.invars[0], eqn.outvars[0]
            narrow[id(outv)] = min(
                narrow.get(id(inv), _aval_bytes(inv.aval)),
                _aval_bytes(outv.aval))
        if name == "pallas_call":
            cost = _pallas_cost(eqn)
            vec.flops += cost.get("flops", 0.0) * mult
            if not flops_only:
                vec.hbm_bytes_read += cost.get("read", 0.0) * mult
                vec.hbm_bytes_written += cost.get("write", 0.0) * mult
            continue
        if name in _MATMUL_PRIMS:
            vec.flops += (_dot_general_flops(eqn) if name == "dot_general"
                          else _conv_flops(eqn)) * mult
        if not flops_only:
            r, w = _eqn_hbm(eqn, narrow)
            vec.hbm_bytes_read += r * mult
            vec.hbm_bytes_written += w * mult
            cname = walker.prim_name(eqn)
            if cname in walker.COLLECTIVE_PRIMS:
                key = (f"{cname}"
                       f"[{','.join(walker.eqn_axis_names(eqn))}]")
                b = _collective_bytes(eqn, axis_sizes)
                vec.collective_bytes[key] = (
                    vec.collective_bytes.get(key, 0.0) + b * mult)
        # -- recurse ----------------------------------------------------------
        if name == _SCAN:
            trips = int(eqn.params.get("length", 1))
            for sub in _subjaxprs(eqn):
                _interpret(sub, vec, mult=mult * trips,
                           axis_sizes=axis_sizes, flops_only=flops_only)
        elif name == _WHILE:
            # dynamic trip count: body charged once (documented undercount)
            for key in ("cond_jaxpr", "body_jaxpr"):
                for sub in walker.iter_subjaxprs(eqn.params.get(key)):
                    _interpret(sub, vec, mult=mult,
                               axis_sizes=axis_sizes, flops_only=flops_only)
        elif name in _BRANCH_PRIMS:
            # runtime takes ONE branch: charge the max (exact when the
            # expensive branch is the taken one)
            best, best_vec = -1.0, None
            for sub in walker.iter_subjaxprs(eqn.params.get("branches")):
                bv = CostVector()
                _interpret(sub, bv, mult=1.0, axis_sizes=axis_sizes,
                           flops_only=flops_only)
                score = bv.flops + bv.hbm_bytes
                if score > best:
                    best, best_vec = score, bv
            if best_vec is not None:
                vec.flops += best_vec.flops * mult
                if not flops_only:
                    vec.hbm_bytes_read += best_vec.hbm_bytes_read * mult
                    vec.hbm_bytes_written += (
                        best_vec.hbm_bytes_written * mult)
                    _merge_collectives(vec.collective_bytes,
                                       best_vec.collective_bytes, mult)
        else:
            if name == "shard_map":
                mesh = eqn.params.get("mesh")
                shape = getattr(mesh, "shape", None)
                if shape:
                    axis_sizes = {**axis_sizes,
                                  **{str(k): int(v)
                                     for k, v in dict(shape).items()}}
            for sub in _subjaxprs(eqn):
                _interpret(sub, vec, mult=mult,
                           axis_sizes=axis_sizes, flops_only=flops_only)


# ---- program boundary (donation-aware) --------------------------------------


_TRIVIAL_CALLS = frozenset({"pjit", "closed_call", "core_call", "xla_call",
                            "remat", "checkpoint", "custom_jvp_call",
                            "custom_vjp_call", "shard_map"})


def _unwrap_trivial(jaxpr):
    """Descend through whole-program wrappers (``make_jaxpr`` of a jitted
    shard_map program traces as one ``pjit`` eqn around one ``shard_map``
    eqn). Only unwraps when the wrapper consumes the program inputs in
    order and returns the body outputs unchanged, so flat invar positions
    (and therefore donation indices) carry through positionally. Inside
    ``shard_map`` the avals are the per-device block shapes — peak-live
    and boundary bytes become PER-DEVICE quantities, which is what one
    TPU core's HBM actually holds and what every closed form models."""
    jaxpr = walker._as_open_jaxpr(jaxpr)
    while (len(jaxpr.eqns) == 1
           and jaxpr.eqns[0].primitive.name in _TRIVIAL_CALLS):
        eqn = jaxpr.eqns[0]
        subs = [walker._as_open_jaxpr(s) for s in _subjaxprs(eqn)]
        if len(subs) != 1:
            break
        if [id(v) for v in eqn.invars] != [id(v) for v in jaxpr.invars]:
            break
        if [id(v) for v in eqn.outvars] != [id(v) for v in jaxpr.outvars]:
            break
        if len(subs[0].invars) != len(eqn.invars):
            break
        jaxpr = subs[0]
    return jaxpr


def _boundary_bytes(closed_jaxpr, donated_flat: set[int],
                    donation_mode: str | None) -> tuple[float, float]:
    """Copy traffic at the program boundary: an output leaf that is a bare
    passthrough of an input materializes a defensive copy — UNLESS the
    input is donated in alias mode, where XLA aliases it in place and the
    copy costs zero (the "donations charge zero for the aliased buffer"
    semantics). Computed traffic is charged by the producing equations;
    only passthroughs can hide at the boundary."""
    jaxpr = _unwrap_trivial(closed_jaxpr)
    invar_pos = {id(v): i for i, v in enumerate(jaxpr.invars)}
    read = written = 0.0
    for out in jaxpr.outvars:
        pos = invar_pos.get(id(out))
        if pos is None:
            continue  # produced by an equation — already charged
        if donation_mode == "alias" and pos in donated_flat:
            continue  # aliased in place: zero
        b = _aval_bytes(out.aval)
        read += b
        written += b
    return read, written


# ---- peak live bytes ---------------------------------------------------------


def _inner_peak(eqn, axis_sizes: dict[str, int]) -> int:
    """Internal peak of one equation's sub-jaxpr bodies (intermediates the
    body allocates beyond the operands the outer scan already counts)."""
    peak = 0
    for sub in _subjaxprs(eqn):
        peak = max(peak, peak_live_bytes(sub, donated_flat=frozenset()))
    return peak


def peak_live_bytes(closed_jaxpr, *,
                    donated_flat: frozenset[int] | set[int] = frozenset(),
                    ) -> int:
    """Linear-scan peak over the equation schedule.

    Liveness rules: constants and NON-donated inputs are live for the
    whole program (the caller owns those buffers; XLA cannot free them).
    Donated inputs die after their last use — and a donated input with NO
    use never dies: XLA drops the unusable donation and the buffer sits
    allocated to the end, which is exactly the dead-donation hazard the
    donation rule flags and this scan *prices*. Equation outputs are live
    from their equation to their last use (program outputs to the end).
    Sub-jaxpr bodies contribute their own internal peak at the equation
    that runs them.
    """
    jaxpr = _unwrap_trivial(closed_jaxpr)
    eqns = list(jaxpr.eqns)
    n = len(eqns)
    last_use: dict[int, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            last_use[id(v)] = i
    for v in jaxpr.outvars:
        last_use[id(v)] = n  # program outputs survive the whole schedule

    live = sum(_aval_bytes(v.aval)
               for v in getattr(jaxpr, "constvars", ()))
    # inputs: donated-and-USED die after their last use; everything else
    # is whole-program — non-donated because the caller owns the buffer,
    # donated-but-DEAD because XLA drops the unusable donation
    deaths: dict[int, float] = {}
    for pos, v in enumerate(jaxpr.invars):
        b = _aval_bytes(v.aval)
        live += b
        death = last_use.get(id(v), n)
        if pos in donated_flat and death < n:
            deaths[death] = deaths.get(death, 0.0) + b
    peak = live
    out_death: dict[int, float] = {}
    seen_out: set[int] = set()
    for i, eqn in enumerate(eqns):
        alloc = 0
        for v in eqn.outvars:
            if id(v) in seen_out:
                continue
            seen_out.add(id(v))
            b = _aval_bytes(v.aval)
            alloc += b
            death = last_use.get(id(v), i)
            if death < n:
                out_death[death] = out_death.get(death, 0.0) + b
        live += alloc
        peak = max(peak, int(live + _inner_peak(eqn, {})))
        live -= out_death.pop(i, 0.0)
        live -= deaths.get(i, 0.0)
    return int(peak)


# ---- public entry ------------------------------------------------------------


def closed_forms():
    """``benchmarks.common`` — the closed-form models the CostSpec pins
    diff against. The benchmarks tree lives NEXT to the package (repo
    root), not inside it, so the CLI run from an arbitrary cwd needs the
    path fallback."""
    try:
        import benchmarks.common as common
    except ImportError:
        import pathlib
        import sys
        root = str(pathlib.Path(__file__).resolve().parents[2])
        if root not in sys.path:
            sys.path.insert(0, root)
        import benchmarks.common as common
    return common


def donated_flat_indices(contract, arg_leaf_avals) -> frozenset[int]:
    """Flat invar positions of the contract's donated argument leaves
    (same flattening the donation rule uses)."""
    spec = getattr(contract, "donation", None)
    if spec is None:
        return frozenset()
    starts, pos = [], 0
    for leaves in arg_leaf_avals:
        starts.append(pos)
        pos += len(leaves)
    idx: set[int] = set()
    for argnum in spec.argnums:
        if argnum < len(arg_leaf_avals):
            idx.update(starts[argnum] + k
                       for k in range(len(arg_leaf_avals[argnum])))
    return frozenset(idx)


def program_cost(traced, contract) -> CostVector:
    """The full cost vector of one traced contract program."""
    vec = CostVector()
    _interpret(traced.jaxpr, vec, mult=1.0, axis_sizes={})
    donated = donated_flat_indices(contract, traced.arg_leaf_avals)
    mode = getattr(getattr(contract, "donation", None), "mode", None)
    r, w = _boundary_bytes(traced.jaxpr, set(donated), mode)
    vec.hbm_bytes_read += r
    vec.hbm_bytes_written += w
    vec.peak_live_bytes = peak_live_bytes(
        traced.jaxpr, donated_flat=donated)
    return vec
