"""Registry population: import every provider, register every contract.

Each subsystem that ships a judged entry point exposes a module-level
``lint_contracts() -> list[ProgramContract]`` next to the code it audits
(the contract lives WITH the program, not in a central manifest — adding
a subsystem means adding a provider function, not editing this package).
This module is the one aggregation point: importing it (which
``lint._registered`` does for its side effect) registers everything.

Provider failures are deliberately NOT swallowed: a provider that cannot
even build its contract list is a lint failure in its own right, and the
ImportError propagating out of ``dtg-lint`` is the report.
"""

from __future__ import annotations

import importlib

from distributed_tensorflow_guide_tpu.analysis.contracts import register

PROVIDER_MODULES = (
    "distributed_tensorflow_guide_tpu.parallel.data_parallel",
    "distributed_tensorflow_guide_tpu.parallel.fsdp",
    "distributed_tensorflow_guide_tpu.parallel.pipeline",
    "distributed_tensorflow_guide_tpu.parallel.multislice",
    "distributed_tensorflow_guide_tpu.ops.fused_ce",
    "distributed_tensorflow_guide_tpu.ops.quant",
    "distributed_tensorflow_guide_tpu.models.moe_lm",
    "distributed_tensorflow_guide_tpu.models.generation",
    "distributed_tensorflow_guide_tpu.serve.engine",
)


def load_all() -> None:
    for mod_name in PROVIDER_MODULES:
        mod = importlib.import_module(mod_name)
        for contract in mod.lint_contracts():
            register(contract)


load_all()
