"""Golden program fingerprints: the drift gate over registered traces.

A fingerprint is two stable hashes per registered program:

* ``structure`` — sha256 of the *normalized* jaxpr text: the walker's
  address-normalization (``0x1234abcd`` → ``0x•``) plus var-numbering
  left intact (jaxpr printing is deterministic per trace), so the hash
  moves exactly when the traced program's structure moves — a new eqn,
  a changed shape, a different collective — and never with process
  ASLR.
* ``cost`` — the derived :class:`analysis.cost.CostVector`, rounded, so
  a pure cost-model change (say a new kernel cost model making the same
  structure price differently) is ALSO a gated change: the blessed
  numbers are the repo's numbers of record.

Goldens persist to ``analysis/golden_fingerprints.json`` next to this
module — committed, human-diffable (sorted keys, one program per entry,
the bless ``reason`` stored inline), no timestamps so re-blessing an
unchanged registry is a no-op diff. The gate runs inside every default
``dtg-lint``: a program whose fingerprint differs from its golden — or
a registered program with no golden at all — is a lint failure until
``dtg-lint --bless --reason "why"`` rewrites the file. That is the whole
point: trace drift needs a *stated reason* in the commit that carries
it, not a reviewer noticing a silent diff.

Import discipline matches the package: no jax at module import.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
from pathlib import Path

GOLDEN_PATH = Path(__file__).resolve().parent / "golden_fingerprints.json"

#: Cost entries are rounded to this many significant-ish decimals before
#: hashing/storing so float formatting can never flap the gate.
_ROUND = 3


@dataclasses.dataclass(frozen=True)
class Fingerprint:
    program: str
    structure: str           # sha256 hex of the normalized jaxpr text
    cost: dict               # rounded CostVector.to_dict()

    def to_json(self) -> dict:
        return {"structure": self.structure, "cost": self.cost}


def _round(value):
    if isinstance(value, dict):
        return {k: _round(v) for k, v in sorted(value.items())}
    if isinstance(value, float):
        return round(value, _ROUND)
    return value


_ADDR = re.compile(r"0x[0-9a-f]+")


def structure_hash(jaxpr) -> str:
    """Stable hash of the normalized trace text — the same
    address-scrubbing normalization as ``walker.traced_text`` (repr'd
    closures/meshes in eqn params carry object addresses that differ per
    process, not per program), applied to an already-traced jaxpr."""
    text = _ADDR.sub("0x•", str(jaxpr))
    return hashlib.sha256(text.encode()).hexdigest()


def fingerprint(name: str, jaxpr, cost_vector) -> Fingerprint:
    return Fingerprint(
        program=name,
        structure=structure_hash(jaxpr),
        cost=_round(cost_vector.to_dict()),
    )


# ---- golden store ------------------------------------------------------------


def load_goldens(path: Path | None = None) -> dict:
    """{program: {"structure": ..., "cost": {...}, "reason": ...}} — empty
    when no golden file exists yet (every program then reports
    ``missing-golden`` until the first bless)."""
    p = path or GOLDEN_PATH
    if not p.exists():
        return {}
    return json.loads(p.read_text())


def save_goldens(fingerprints: list[Fingerprint], reason: str,
                 path: Path | None = None) -> Path:
    """Bless: rewrite the golden file from live fingerprints. ``reason``
    is stored per program so the blame trail lives in the artifact, not
    just the commit message."""
    p = path or GOLDEN_PATH
    payload = {
        fp.program: {**fp.to_json(), "reason": reason}
        for fp in sorted(fingerprints, key=lambda f: f.program)
    }
    p.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")
    return p


def diff_fingerprint(fp: Fingerprint, goldens: dict) -> list[str]:
    """Human-readable drift lines for one program; [] when clean."""
    gold = goldens.get(fp.program)
    if gold is None:
        return [f"{fp.program}: no golden fingerprint "
                f"(new program? bless it with --bless --reason)"]
    out = []
    if gold.get("structure") != fp.structure:
        out.append(f"{fp.program}: structure hash drifted "
                   f"{gold.get('structure', '?')[:12]} -> "
                   f"{fp.structure[:12]}")
    gcost, lcost = gold.get("cost", {}), fp.cost
    for key in sorted(set(gcost) | set(lcost)):
        if key == "collective_bytes":
            g, l = gcost.get(key, {}), lcost.get(key, {})
            for ck in sorted(set(g) | set(l)):
                if g.get(ck) != l.get(ck):
                    out.append(f"{fp.program}: cost[{key}[{ck}]] "
                               f"{g.get(ck)} -> {l.get(ck)}")
        elif gcost.get(key) != lcost.get(key):
            out.append(f"{fp.program}: cost[{key}] "
                       f"{gcost.get(key)} -> {lcost.get(key)}")
    return out


def stale_goldens(live_names: set[str], goldens: dict) -> list[str]:
    """Goldens for programs that no longer exist (renamed/removed without
    a bless) — also drift: the registry and the record must agree."""
    return [f"{name}: golden exists but program is not registered "
            f"(removed/renamed? re-bless)"
            for name in sorted(set(goldens) - live_names)]
