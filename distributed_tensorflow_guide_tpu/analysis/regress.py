"""Continuous regression gate: persisted bench history vs the cost model.

The static auditor (``analysis/cost.py``) derives what a program SHOULD
cost; the benches measure what it DOES cost; ``obs/recon.py`` joins the
two for one run. This module makes that join *longitudinal*: every
battery row appends one entry to ``bench_history/history.jsonl`` —
metric, measured value, device_kind, git sha, and the
measured-vs-modeled ratio with its binding resource — and
``check_history`` flags a row whose latest ratio drifted past tolerance
against its own per-device baseline. Attribution rides along for free:
the entry's ``bound`` field names which roofline term (compute / memory
/ comm / pcie) the drifted measurement is limited by, and when the row
maps to a registered program the report joins the golden-fingerprint
bless ``reason`` that last changed that program's trace — the first
suspect for "the model moved" vs "the machine moved".

Deliberately jax-free at import (like ``obs/recon.py``): the history
store must be writable from the battery driver and readable from CI
without bringing up a backend. ``detect_device_kind`` imports jax
lazily and degrades to a host label.

Non-guarantees: ``append_entry`` is best-effort (a read-only checkout
must never fail a bench run over bookkeeping), and the gate compares a
row only against ITS OWN history on the SAME device_kind — there is no
cross-device normalization, so a history seeded on one chip says
nothing about another.
"""

from __future__ import annotations

import json
import math
import os
import statistics
import subprocess
import time
from pathlib import Path

#: Env override for the history location: a ``.jsonl`` file path, or a
#: directory (the rolling ``history.jsonl`` lands inside it).
HISTORY_ENV = "DTG_BENCH_HISTORY"

#: Default directory under the repo root; gitignored — history is
#: machine-local evidence, not source.
DEFAULT_DIRNAME = "bench_history"

HISTORY_FILENAME = "history.jsonl"

#: Default drift tolerance: the latest measured/modeled ratio may sit up
#: to 25% above the row's own baseline before the gate flags it. Wide on
#: purpose — bench noise on shared hosts is real; the gate exists to
#: catch step-function regressions (a lost fusion, a new copy), not 3%
#: jitter.
DEFAULT_TOL = 0.25

#: result-line roofline fractions -> the recon/CostVector resource names
#: they reconcile against (the ``bound`` vocabulary).
_FRAC_KEYS = (
    ("flop_roofline_frac", "compute"),
    ("hbm_roofline_frac", "memory"),
    ("ici_roofline_frac", "comm"),
    ("dcn_roofline_frac", "comm"),
    ("pcie_roofline_frac", "pcie"),
)


def _repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def history_path() -> Path:
    """The active history file: :data:`HISTORY_ENV` override (file, or a
    directory to hold ``history.jsonl``), else
    ``<repo>/bench_history/history.jsonl``."""
    raw = os.environ.get(HISTORY_ENV, "").strip()
    if raw:
        p = Path(raw)
        if p.suffix == ".jsonl":
            return p
        return p / HISTORY_FILENAME
    return _repo_root() / DEFAULT_DIRNAME / HISTORY_FILENAME


def detect_device_kind() -> str:
    """``jax.devices()[0].device_kind`` when a backend is importable,
    else a host-arch label — the grouping key must never raise."""
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:
        import platform

        return f"host-{platform.machine() or 'unknown'}"


def git_sha() -> str | None:
    """Short HEAD sha, or None outside a readable git checkout."""
    try:
        r = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                           cwd=_repo_root(), capture_output=True,
                           text=True, timeout=15)
    except Exception:
        return None
    sha = r.stdout.strip()
    return sha if r.returncode == 0 and sha else None


def make_entry(row: str, result: dict | None, *,
               device_kind: str | None = None,
               git_rev: str | None = None,
               program: str | None = None,
               ts: float | None = None) -> dict:
    """One history entry from a bench's JSON result line.

    ``result`` is the line :func:`benchmarks.common.report` printed (or
    None / a ``{"skipped": ...}`` stub). The measured-vs-modeled ratio
    comes from whichever evidence the line carries, best first:
    ``efficiency`` + ``bound`` (an ``obs.recon.reconcile`` output
    embedded in the line), else the roofline fractions
    (``*_roofline_frac`` / ``mfu``) — efficiency is then the binding
    fraction and ``bound`` its resource. Lines with neither are recorded
    (continuity: the row RAN) but carry no ratio and are never flagged.
    """
    entry: dict = {
        "ts": round(time.time() if ts is None else ts, 3),
        "row": row,
        "device_kind": device_kind or detect_device_kind(),
        "git_sha": git_rev if git_rev is not None else git_sha(),
    }
    if program:
        entry["program"] = program
    r = result or {}
    if r.get("skipped"):
        entry["skipped"] = str(r["skipped"])
        return entry
    for k in ("metric", "value", "unit"):
        if k in r:
            entry[k] = r[k]
    if "measured_s" in r:
        entry["measured_s"] = r["measured_s"]
    if "model_time_s" in r:
        entry["model_time_s"] = r["model_time_s"]
    fracs = {}
    for key, resource in _FRAC_KEYS:
        v = r.get(key)
        if isinstance(v, (int, float)) and math.isfinite(v) and v > 0:
            entry[key] = v
            # keep the LARGEST fraction per resource (ici vs dcn)
            fracs[resource] = max(v, fracs.get(resource, 0.0))
    if isinstance(r.get("mfu"), (int, float)) and r["mfu"] > 0:
        entry["mfu"] = r["mfu"]
        fracs.setdefault("compute", r["mfu"])
    if isinstance(r.get("efficiency"), (int, float)) and r["efficiency"] > 0:
        entry["efficiency"] = r["efficiency"]
        if r.get("bound"):
            entry["bound"] = r["bound"]
    elif fracs:
        bound = max(fracs, key=lambda k: fracs[k])
        entry["efficiency"] = round(fracs[bound], 6)
        entry["bound"] = bound
    return entry


def append_entry(entry: dict, path: Path | str | None = None) -> bool:
    """Append one entry to the history file. Best-effort by contract:
    any OS/serialization failure returns False instead of raising — a
    full disk or read-only checkout must not fail the bench that was
    only trying to leave a breadcrumb."""
    p = Path(path) if path else history_path()
    try:
        p.parent.mkdir(parents=True, exist_ok=True)
        line = json.dumps(entry, sort_keys=True)
        with open(p, "a") as f:
            f.write(line + "\n")
        return True
    except Exception:
        return False


def load_history(path: Path | str | None = None) -> list[dict]:
    """Entries from the history file, oldest first; unparseable lines
    are dropped (a truncated tail from a crashed run must not poison
    the readable majority). Missing file -> []."""
    p = Path(path) if path else history_path()
    entries: list[dict] = []
    try:
        text = p.read_text()
    except OSError:
        return entries
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and obj.get("row"):
            entries.append(obj)
    return entries


def _ratio(entry: dict) -> float | None:
    """measured/modeled time ratio (>= ~1.0 on the roofline's terms;
    drift UP = slower than the model says this device can go)."""
    m, t = entry.get("measured_s"), entry.get("model_time_s")
    if (isinstance(m, (int, float)) and isinstance(t, (int, float))
            and m > 0 and t > 0):
        return m / t
    eff = entry.get("efficiency")
    if isinstance(eff, (int, float)) and eff > 0:
        return 1.0 / eff
    return None


def _bless_reason(program: str) -> str | None:
    """The golden-fingerprint bless reason for ``program`` — the last
    recorded "why did this trace change", i.e. the first suspect when a
    row's measured/modeled ratio moved."""
    try:
        from distributed_tensorflow_guide_tpu.analysis import fingerprint

        goldens = fingerprint.load_goldens()
    except Exception:
        return None
    g = goldens.get(program)
    if isinstance(g, dict):
        return g.get("reason")
    return None


def check_history(entries: list[dict] | None = None, *,
                  tol: float = DEFAULT_TOL,
                  path: Path | str | None = None) -> dict:
    """The gate: per (row, device_kind) group, compare the LATEST
    measured/modeled ratio against the median of the prior entries'
    ratios; flag when it drifted more than ``tol`` above baseline.

    Returns ``{"ok", "n_entries", "n_groups", "n_checked", "flags"}``
    where each flag carries the drift arithmetic, the binding resource,
    both git shas, and — when the row names a registered program — the
    golden bless reason that last changed its trace.
    """
    if entries is None:
        entries = load_history(path)
    groups: dict[tuple, list[dict]] = {}
    for e in entries:
        if e.get("skipped"):
            continue
        groups.setdefault((e.get("row"), e.get("device_kind")),
                          []).append(e)
    flags: list[dict] = []
    n_checked = 0
    for (row, kind), group in sorted(groups.items()):
        ratios = [(e, _ratio(e)) for e in group]
        ratios = [(e, r) for e, r in ratios if r is not None]
        if len(ratios) < 2:
            continue  # nothing to drift against yet
        n_checked += 1
        *prior, (latest, latest_r) = ratios
        baseline = statistics.median(r for _, r in prior)
        if baseline <= 0 or latest_r <= baseline * (1.0 + tol):
            continue
        flag = {
            "row": row,
            "device_kind": kind,
            "baseline_ratio": round(baseline, 4),
            "latest_ratio": round(latest_r, 4),
            "drift": round(latest_r / baseline - 1.0, 4),
            "tol": tol,
            "bound": latest.get("bound"),
            "baseline_git_sha": prior[-1][0].get("git_sha"),
            "latest_git_sha": latest.get("git_sha"),
        }
        program = latest.get("program")
        if program:
            flag["program"] = program
            reason = _bless_reason(program)
            if reason:
                flag["last_bless"] = reason
        flags.append(flag)
    return {"ok": not flags, "n_entries": len(entries),
            "n_groups": len(groups), "n_checked": n_checked,
            "flags": flags}


def selftest(tol: float = DEFAULT_TOL) -> dict:
    """Prove the gate end-to-end on synthetic history, no file I/O:
    a clean two-entry row must pass, and the same row with its latest
    measurement inflated past tolerance must flag with the right
    binding resource and program join. Returns ``{"ok": ...}`` plus
    both sub-reports — wired into ``dtg-lint --regress`` and the smoke
    battery so the gate itself is under test wherever it gates."""
    def entry(ratio: float, sha: str) -> dict:
        return make_entry(
            "synthetic_decode", {
                "metric": "synthetic_decode_throughput",
                "value": 100.0 / ratio, "unit": "tokens/sec",
                # memory-bound decode at 1/ratio of the HBM roofline
                "hbm_roofline_frac": 1.0 / ratio,
                "flop_roofline_frac": 0.05,
            },
            device_kind="synthetic-v0", git_rev=sha,
            program="serve_decode_step", ts=0.0)

    clean = check_history([entry(1.25, "aaaa111"), entry(1.30, "bbb2222")],
                          tol=tol)
    # latest ratio 1.25 * (1 + tol) * 1.6 over baseline: unambiguous
    inflated = check_history(
        [entry(1.25, "aaaa111"), entry(1.25 * (1 + tol) * 1.6, "ccc3333")],
        tol=tol)
    flag = inflated["flags"][0] if inflated["flags"] else {}
    ok = (clean["ok"] and not inflated["ok"]
          and flag.get("bound") == "memory"
          and flag.get("program") == "serve_decode_step"
          and flag.get("latest_git_sha") == "ccc3333")
    return {"ok": ok, "clean": clean, "inflated": inflated}


def render_report(rep: dict) -> str:
    lines = [f"regress: {rep['n_entries']} entr(ies), "
             f"{rep['n_groups']} row group(s), "
             f"{rep['n_checked']} with enough history to gate"]
    for f in rep["flags"]:
        lines.append(
            f"FAIL  {f['row']} on {f['device_kind']}: measured/modeled "
            f"{f['baseline_ratio']} -> {f['latest_ratio']} "
            f"(+{f['drift']:.0%}, tol {f['tol']:.0%}), "
            f"bound by {f['bound'] or 'unknown'} "
            f"[{f['baseline_git_sha']} -> {f['latest_git_sha']}]")
        if f.get("last_bless"):
            lines.append(f"        last trace bless for {f['program']}: "
                         f"{f['last_bless']!r}")
    lines.append("PASS: no unexplained drift" if rep["ok"]
                 else f"FAIL: {len(rep['flags'])} row(s) drifted")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="dtg-regress",
        description="Gate persisted bench history against the cost "
                    "model's roofline (measured/modeled ratio drift).")
    ap.add_argument("--tol", type=float, default=DEFAULT_TOL)
    ap.add_argument("--path", default=None,
                    help=f"history file (default: ${HISTORY_ENV} or "
                         f"<repo>/{DEFAULT_DIRNAME}/{HISTORY_FILENAME})")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--selftest", action="store_true",
                    help="run the synthetic-history selftest only")
    args = ap.parse_args(argv)

    if args.selftest:
        st = selftest(args.tol)
        print(json.dumps(st) if args.json
              else f"regress selftest: {'PASS' if st['ok'] else 'FAIL'}")
        return 0 if st["ok"] else 1
    rep = check_history(tol=args.tol, path=args.path)
    print(json.dumps(rep) if args.json else render_report(rep))
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
