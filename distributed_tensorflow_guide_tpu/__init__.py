"""distributed_tensorflow_guide_tpu — a TPU-native distributed-training framework.

A ground-up JAX/XLA/Pallas re-design of every capability taught by the
reference repo (salemmohammed/Distributed-TensorFlow-Guide): async
parameter-server training (Hogwild, DOWNPOUR, ADAG), synchronous
data-parallel SGD, and multi-device single-host training — plus the tensor-,
pipeline-, and sequence-parallel extensions the judged configs require.

Architecture inversion vs. the reference (see SURVEY.md §7): the reference is
built on role-typed processes (PS vs worker, ``tf.train.Server`` /
``tf.train.ClusterSpec``, tensorflow/python/training/server_lib.py:96,:243)
with implicit gRPC parameter traffic. Here there are no roles: ONE SPMD
program runs on every host, parallelism is an explicit
``jax.sharding.Mesh`` with named axes, and all communication is explicit XLA
collectives (``psum`` / ``all_gather`` / ``ppermute`` / ``all_to_all``) riding
the ICI fabric.

Package layout:
    core/        mesh construction, distributed init, config
    collectives/ the NCCL/gRPC-equivalent comm layer (traced + counted)
    parallel/    strategies: sync DP, async-PS equivalents, TP, PP, SP
    ops/         compute kernels (Pallas flash/ring attention, fused ops)
    models/      Flax model zoo: MNIST CNN, ResNet-50, BERT, GPT-2, Wide&Deep
    train/       MonitoredTrainingSession-equivalent loop + hooks
    data/        sharded synthetic/host data pipelines (+ native C++ loader)
    utils/       profiling, determinism checks, logging
    runtime/     native (C++) host-side runtime pieces
"""

__version__ = "0.1.0"

from distributed_tensorflow_guide_tpu.core.mesh import (  # noqa: F401
    AXES,
    MeshSpec,
    build_mesh,
)
