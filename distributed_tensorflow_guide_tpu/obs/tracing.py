"""Span API + Chrome/Perfetto trace-event JSON exporter.

Spans are just paired events (``span.begin`` / ``span.end``) in the same
flight-recorder stream — no second bookkeeping path. The exporter maps
the serve engine's event vocabulary onto the Chrome trace-event format
(`chrome://tracing` / https://ui.perfetto.dev, "Open trace file"):

* ``span.begin`` / ``span.end``   -> ``B``/``E`` duration events on the
  track named in the payload (train data-wait / dispatch timelines).
* ``prefill.launch`` / ``decode.launch`` -> ``X`` complete events on one
  track per engine slot (``slot0``, ``slot1``, ...), so a request reads
  as queued -> admitted -> prefill chunk(s) -> decode on its slot lane.
* ``req.admit``                   -> an ``X`` on the ``queue`` track
  spanning arrival -> admission (the queue-wait bar).
* everything else                 -> ``i`` instant events (lifecycle
  terminals, prefix hits/evictions, chaos faults, snapshots, ...).

Timestamps: the exporter prefers the semantic clock ``t`` (the engine's
virtual ``now``) and falls back to ``mono`` when ``t`` is None (train
spans). Events whose resolved timestamp is non-finite are skipped —
``ServeEngine.run()`` drains with ``now=inf``, which is meaningful to
the scheduler but not to a timeline. ``pid`` is the event category,
``tid`` the track; both are stable small integers with ``M`` metadata
records carrying the human names.
"""

from __future__ import annotations

import json
import math
from contextlib import contextmanager
from typing import Iterable

from distributed_tensorflow_guide_tpu.obs.events import ObsEvent


@contextmanager
def span(rec, name: str, *, track: str = "main", cat: str = "train",
         actor: str = ""):
    """Emit ``span.begin``/``span.end`` around a block. Payload carries
    the (name, track) pair the exporter turns into a B/E lane."""
    if not rec.enabled:
        yield
        return
    rec.emit("span.begin", cat=cat, actor=actor,
             payload={"name": name, "track": track})
    try:
        yield
    finally:
        rec.emit("span.end", cat=cat, actor=actor,
                 payload={"name": name, "track": track})


def _fields(e) -> tuple[str, str, str, float | None, float, dict]:
    """(kind, cat, actor, t, mono, payload) from an ObsEvent or a dict
    (the shape ``events_from_dump`` round-trips)."""
    if isinstance(e, dict):
        return (e["kind"], e["cat"], e["actor"], e.get("t"),
                e.get("mono", 0.0), e.get("payload", {}))
    return e.kind, e.cat, e.actor, e.t, e.mono, e.payload


def _ts(t: float | None, mono: float) -> float | None:
    """Microsecond timestamp: semantic clock first, wall fallback;
    None = skip this event (non-finite virtual time)."""
    base = t if t is not None else mono
    if base is None or not math.isfinite(base):
        return None
    return base * 1e6


class _Ids:
    """Stable first-seen-order pid/tid assignment + metadata records."""

    def __init__(self):
        self.pids: dict[str, int] = {}
        self.tids: dict[tuple[int, str], int] = {}
        self.meta: list[dict] = []

    def pid(self, cat: str) -> int:
        if cat not in self.pids:
            self.pids[cat] = len(self.pids) + 1
            self.meta.append({"ph": "M", "name": "process_name",
                              "pid": self.pids[cat], "tid": 0,
                              "args": {"name": cat}})
        return self.pids[cat]

    def tid(self, pid: int, track: str) -> int:
        key = (pid, track)
        if key not in self.tids:
            self.tids[key] = len(self.tids) + 1
            self.meta.append({"ph": "M", "name": "thread_name",
                              "pid": pid, "tid": self.tids[key],
                              "args": {"name": track}})
        return self.tids[key]


def to_chrome_trace(events: Iterable) -> dict:
    """Events (ObsEvent objects or dump dicts) -> Chrome trace JSON."""
    ids = _Ids()
    out: list[dict] = []
    for e in events:
        kind, cat, actor, t, mono, payload = _fields(e)
        ts = _ts(t, mono)
        if ts is None:
            continue
        pid = ids.pid(cat)
        if kind in ("span.begin", "span.end"):
            tid = ids.tid(pid, str(payload.get("track", "main")))
            out.append({"ph": "B" if kind == "span.begin" else "E",
                        "name": str(payload.get("name", kind)),
                        "pid": pid, "tid": tid, "ts": ts})
        elif kind == "prefill.launch":
            tid = ids.tid(pid, f"slot{payload.get('slot', 0)}")
            out.append({"ph": "X", "name": f"prefill rid{payload.get('rid')}",
                        "pid": pid, "tid": tid, "ts": ts,
                        "dur": max(payload.get("dur_s", 0.0), 0.0) * 1e6,
                        "args": {k: v for k, v in payload.items()
                                 if k not in ("slot",)}})
        elif kind == "decode.launch":
            dur = max(payload.get("dur_s", 0.0), 0.0) * 1e6
            slots = payload.get("slots", [])
            rids = payload.get("rids", [])
            for slot, rid in zip(slots, rids):
                tid = ids.tid(pid, f"slot{slot}")
                out.append({"ph": "X", "name": f"decode rid{rid}",
                            "pid": pid, "tid": tid, "ts": ts, "dur": dur,
                            "args": {"tick": payload.get("tick")}})
        elif kind == "req.admit":
            wait = payload.get("queue_wait_s")
            tid = ids.tid(pid, "queue")
            if wait is not None and math.isfinite(wait) and wait >= 0:
                out.append({"ph": "X",
                            "name": f"rid{payload.get('rid')} queued",
                            "pid": pid, "tid": tid, "ts": ts - wait * 1e6,
                            "dur": wait * 1e6, "args": dict(payload)})
            else:
                out.append({"ph": "i", "s": "t", "name": kind, "pid": pid,
                            "tid": tid, "ts": ts,
                            "args": dict(payload)})
        else:
            tid = ids.tid(pid, "events")
            out.append({"ph": "i", "s": "t", "name": kind, "pid": pid,
                        "tid": tid, "ts": ts,
                        "args": {"actor": actor, **payload}})
    return {"traceEvents": ids.meta + out,
            "displayTimeUnit": "ms"}


def ttft_breakdown(events: Iterable) -> dict[int, dict[str, float]]:
    """Per-request TTFT split from the serve event stream.

    For every rid that reached a first token:
    ``queue_wait_s`` (arrival -> admission, from ``req.admit``),
    ``prefill_s`` (sum of its prefill launch durations), and
    ``first_decode_s`` (duration of the first decode launch carrying the
    rid; 0.0 when the final prefill chunk itself produced the first
    token). Durations are measured launch wall times — real numbers
    under the bench's virtual clock."""
    queue_wait: dict[int, float] = {}
    prefill: dict[int, float] = {}
    first_decode: dict[int, float] = {}
    first_token: set[int] = set()
    for e in events:
        kind, _cat, _actor, _t, _mono, payload = _fields(e)
        rid = payload.get("rid")
        if kind == "req.admit" and rid is not None:
            w = payload.get("queue_wait_s")
            if w is not None and math.isfinite(w):
                queue_wait.setdefault(rid, w)
        elif kind == "prefill.launch" and rid is not None:
            prefill[rid] = prefill.get(rid, 0.0) + payload.get("dur_s", 0.0)
        elif kind == "decode.launch":
            for r in payload.get("rids", []):
                if r not in first_token:
                    first_decode.setdefault(r, payload.get("dur_s", 0.0))
        elif kind == "req.first_token" and rid is not None:
            first_token.add(rid)
    return {rid: {"queue_wait_s": queue_wait.get(rid, 0.0),
                  "prefill_s": prefill.get(rid, 0.0),
                  "first_decode_s": first_decode.get(rid, 0.0)}
            for rid in sorted(first_token)}


def events_from_dump(path: str) -> list[ObsEvent]:
    """Load a :meth:`FlightRecorder.dump` file back into events."""
    with open(path) as f:
        data = json.load(f)
    return [ObsEvent(seq=d.get("seq", i), t=d.get("t"),
                     mono=d.get("mono", 0.0), kind=d["kind"],
                     cat=d.get("cat", "misc"), actor=d.get("actor", ""),
                     payload=d.get("payload", {}))
            for i, d in enumerate(data.get("events", []))]
