"""Modeled-vs-measured reconciliation: CostVector x measured seconds.

The runtime consumer the PR-13 cost auditor never had: join a program's
statically derived cost vector (``analysis/cost.py`` — FLOPs, HBM bytes,
collective bytes) with a live measured duration and report achieved
GF/s / GB/s, per-resource roofline fractions, and which resource the
measurement says the program is bound by.

Deliberately jax-free: ``reconcile`` duck-types its ``cost`` argument —
a real :class:`~distributed_tensorflow_guide_tpu.analysis.cost.CostVector`,
or any dict with the same keys (e.g. one loaded from a lint ``--json``
report) — so the obs package stays stdlib-only at import.

Non-guarantees: the cost vector is the *algorithmic* model (fusion
boundaries, undercounted while-bodies — see docs/analysis.md); the
roofline peaks are whatever the caller supplies. Fractions are evidence
for "where did the time go", not a compiler-grade profile.
"""

from __future__ import annotations

import dataclasses
import math
import os


@dataclasses.dataclass(frozen=True)
class Roofline:
    """Peak rates to reconcile against (bytes and flops per second).

    ``from_env`` reads ``DTG_PEAK_FLOPS`` / ``DTG_PEAK_HBM_BPS`` /
    ``DTG_PEAK_ICI_BPS`` / ``DTG_PEAK_PCIE_BPS`` with v5e-class
    defaults — callers with a real device table (benchmarks/common.py)
    should pass explicit numbers.
    """

    peak_flops_s: float
    peak_hbm_bytes_s: float
    peak_ici_bytes_s: float | None = None
    #: host<->device transfer peak (KV spill tier d2h/h2d traffic);
    #: optional like ICI — absent means "don't reconcile swap bytes".
    peak_pcie_bytes_s: float | None = None

    @classmethod
    def from_env(cls) -> "Roofline":
        ici = os.environ.get("DTG_PEAK_ICI_BPS")
        pcie = os.environ.get("DTG_PEAK_PCIE_BPS")
        return cls(
            peak_flops_s=float(os.environ.get("DTG_PEAK_FLOPS", 1.97e14)),
            peak_hbm_bytes_s=float(
                os.environ.get("DTG_PEAK_HBM_BPS", 8.19e11)),
            peak_ici_bytes_s=float(ici) if ici else None,
            peak_pcie_bytes_s=float(pcie) if pcie else None)


def _get(cost, name: str) -> float:
    if isinstance(cost, dict):
        if name == "hbm_bytes" and "hbm_bytes" not in cost:
            return (float(cost.get("hbm_bytes_read", 0.0))
                    + float(cost.get("hbm_bytes_written", 0.0)))
        if name == "collective_bytes_total" and name not in cost:
            cb = cost.get("collective_bytes", {})
            return float(sum(cb.values())) if isinstance(cb, dict) \
                else float(cb or 0.0)
        return float(cost.get(name, 0.0))
    return float(getattr(cost, name))


def reconcile(cost, measured_s: float, roof: Roofline) -> dict:
    """One program execution's modeled-vs-measured reconciliation.

    Returns achieved rates, per-resource roofline fractions, the
    roofline model's predicted time (max over resources), efficiency
    (model time / measured time — 1.0 means the measurement sits ON the
    roofline), and the binding resource."""
    if not (measured_s > 0 and math.isfinite(measured_s)):
        raise ValueError(f"measured_s must be finite > 0, "
                         f"got {measured_s!r}")
    flops = _get(cost, "flops")
    hbm = _get(cost, "hbm_bytes")
    coll = _get(cost, "collective_bytes_total")
    times = {"compute": flops / roof.peak_flops_s,
             "memory": hbm / roof.peak_hbm_bytes_s}
    ici_frac = None
    if roof.peak_ici_bytes_s:
        times["comm"] = coll / roof.peak_ici_bytes_s
        ici_frac = coll / measured_s / roof.peak_ici_bytes_s
    # pcie term (round 21, additive): only when the cost dict carries
    # swap/offload bytes AND the roofline has a pcie peak — absent either,
    # the output dict is unchanged key-for-key from the round-20 shape.
    pcie_frac = None
    pcie_bytes = (float(cost.get("pcie_bytes", 0.0) or 0.0)
                  if isinstance(cost, dict)
                  else float(getattr(cost, "pcie_bytes", 0.0) or 0.0))
    if roof.peak_pcie_bytes_s and pcie_bytes:
        times["pcie"] = pcie_bytes / roof.peak_pcie_bytes_s
        pcie_frac = pcie_bytes / measured_s / roof.peak_pcie_bytes_s
    model_time_s = max(times.values())
    bound = max(times, key=lambda k: times[k])
    out = {
        "measured_s": measured_s,
        "achieved_gflops_s": flops / measured_s / 1e9,
        "achieved_hbm_gb_s": hbm / measured_s / 1e9,
        "achieved_ici_gb_s": coll / measured_s / 1e9,
        "flops_frac": flops / measured_s / roof.peak_flops_s,
        "hbm_frac": hbm / measured_s / roof.peak_hbm_bytes_s,
        "ici_frac": ici_frac,
        "model_time_s": model_time_s,
        "efficiency": model_time_s / measured_s,
        "bound": bound,
    }
    if pcie_frac is not None:
        out["pcie_frac"] = pcie_frac
    return out
