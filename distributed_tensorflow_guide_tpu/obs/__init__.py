"""Runtime observability plane: flight recorder, spans, metrics, recon.

Strictly observe-only and stdlib-only at import: nothing in this package
imports jax, and no instrumentation site ever reaches inside a compiled
program — the recorder on/off leaves every trace byte-identical and every
engine/train output bitwise-identical (pinned in tests/test_obs.py).
"""

from distributed_tensorflow_guide_tpu.obs.events import (
    NULL_RECORDER,
    FlightRecorder,
    NullRecorder,
    ObsEvent,
    current,
    install,
    signature,
)
from distributed_tensorflow_guide_tpu.obs.metrics import Registry
from distributed_tensorflow_guide_tpu.obs.recon import Roofline, reconcile
from distributed_tensorflow_guide_tpu.obs.tracing import (
    events_from_dump,
    span,
    to_chrome_trace,
    ttft_breakdown,
)

__all__ = [
    "FlightRecorder",
    "NullRecorder",
    "NULL_RECORDER",
    "ObsEvent",
    "Registry",
    "Roofline",
    "current",
    "events_from_dump",
    "install",
    "reconcile",
    "signature",
    "span",
    "to_chrome_trace",
    "ttft_breakdown",
]
