"""Typed structured events + the bounded thread-safe FlightRecorder.

The event model every other obs layer builds on:

* :class:`ObsEvent` — one immutable record: a ``kind`` string
  (dot-namespaced, e.g. ``req.admit`` / ``ckpt.save`` / ``span.begin``),
  a ``cat`` egory (``serve`` / ``train`` / ``ckpt`` / ``chaos`` / ...),
  an ``actor`` (which component emitted it), a ``payload`` dict, and TWO
  timestamps: ``mono`` (``time.monotonic()``, always wall) and ``t`` (the
  *semantic* clock — the serve engine passes its virtual ``now``
  explicitly, a recorder-level injectable ``clock`` covers everything
  else, and ``None`` means "no semantic clock here").
* :class:`FlightRecorder` — a bounded ring buffer (``deque(maxlen=...)``)
  under one lock; ``dump(path)`` writes the tail as JSON and
  ``crash_dump(...)`` is the black-box hook the watchdog/recovery paths
  call on trip: emit the terminal event, then dump.
* :class:`NullRecorder` / :func:`current` — the disabled default. Every
  instrumentation site resolves its recorder ONCE at construction
  (``recorder if recorder is not None else current()``) and guards each
  emission with ``if rec.enabled:`` so a disabled recorder costs one
  attribute check (benchmarks/bench_obs.py pins <1% of a step).
* :func:`signature` — the determinism instrument: a stable tuple view of
  an event sequence that drops the wall clock (``mono``), the semantic
  clock by default, and :data:`VOLATILE` payload keys (wall-measured
  durations), so two seeded runs compare exactly.

Inertness contract: nothing here imports jax and no emission site feeds
a compiled program; the recorder cannot change what the runtime computes,
only what it remembers. Non-guarantees: the ring drops the OLDEST events
under overflow (``dropped`` counts them), ``emit`` ordering across
threads is lock-acquisition order, and payloads are stored by reference
(emitters must pass fresh dicts, which every call site here does).
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
import time
from collections import deque
from typing import Any, Callable, Iterable

SCHEMA = "dtg-flight-recorder-v1"

#: payload keys that carry wall-measured durations — real data, but noise
#: for the reproducibility signature (two identical seeded runs measure
#: different launch times; everything else they emit is identical).
VOLATILE = frozenset({"dur_s", "waited_s", "queue_wait_s", "ttft_s"})


def _jsonable(v: Any) -> Any:
    """Strict-JSON-safe scalar view: non-finite floats become None."""
    if isinstance(v, float) and not math.isfinite(v):
        return None
    return v


@dataclasses.dataclass(frozen=True)
class ObsEvent:
    """One structured event; immutable once emitted."""

    seq: int
    t: float | None  # semantic clock (virtual serve time, injected, ...)
    mono: float      # time.monotonic() at emission — always present
    kind: str
    cat: str
    actor: str
    payload: dict

    def to_dict(self) -> dict:
        return {
            "seq": self.seq,
            "t": _jsonable(self.t),
            "mono": self.mono,
            "kind": self.kind,
            "cat": self.cat,
            "actor": self.actor,
            "payload": {k: _jsonable(v) for k, v in self.payload.items()},
        }


class FlightRecorder:
    """Bounded, ordered, thread-safe ring of :class:`ObsEvent`.

    ``capacity`` bounds memory; overflow drops the oldest event and
    counts it in ``dropped``. ``clock`` (optional zero-arg callable)
    supplies ``t`` when the emitter doesn't pass one — bench_serving's
    virtual clock and the chaos harness pass explicit ``t`` instead.
    ``crash_dump_path`` is where :meth:`crash_dump` writes the tail.
    """

    enabled = True

    def __init__(self, capacity: int = 4096, *,
                 clock: Callable[[], float] | None = None,
                 crash_dump_path: str | None = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.clock = clock
        self.crash_dump_path = crash_dump_path
        self.dropped = 0
        self._seq = 0
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=capacity)

    def emit(self, kind: str, *, cat: str = "misc", actor: str = "",
             payload: dict | None = None,
             t: float | None = None) -> ObsEvent:
        mono = time.monotonic()
        if t is None and self.clock is not None:
            t = self.clock()
        with self._lock:
            if len(self._buf) == self.capacity:
                self.dropped += 1
            ev = ObsEvent(self._seq, t, mono, kind, cat, actor,
                          payload if payload is not None else {})
            self._seq += 1
            self._buf.append(ev)
        return ev

    def events(self) -> list[ObsEvent]:
        """Snapshot of the ring, oldest first."""
        with self._lock:
            return list(self._buf)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    @property
    def total(self) -> int:
        """Events ever emitted (ring contents + dropped)."""
        return self._seq

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def dump(self, path: str) -> str:
        """Write the ring tail as strict JSON; returns ``path``."""
        with self._lock:
            events = list(self._buf)
            meta = {"schema": SCHEMA, "capacity": self.capacity,
                    "dropped": self.dropped, "total": self._seq}
        with open(path, "w") as f:
            json.dump({**meta, "events": [e.to_dict() for e in events]},
                      f)
        return path

    def crash_dump(self, kind: str, *, cat: str = "crash",
                   actor: str = "", payload: dict | None = None,
                   t: float | None = None,
                   path: str | None = None) -> str | None:
        """The black-box protocol: emit the terminal event, then dump
        the tail to ``path`` / ``crash_dump_path`` (no-op dump when
        neither is set — the event still lands in the ring)."""
        self.emit(kind, cat=cat, actor=actor, payload=payload, t=t)
        path = path if path is not None else self.crash_dump_path
        if path is None:
            return None
        return self.dump(path)


class NullRecorder:
    """The disabled default: every method is a no-op; ``enabled`` is
    False so guarded call sites skip even building the payload dict."""

    enabled = False
    capacity = 0
    dropped = 0
    clock = None
    crash_dump_path = None
    total = 0

    def emit(self, kind: str, *, cat: str = "misc", actor: str = "",
             payload: dict | None = None, t: float | None = None) -> None:
        return None

    def events(self) -> list:
        return []

    def __len__(self) -> int:
        return 0

    def clear(self) -> None:
        return None

    def dump(self, path: str) -> None:
        return None

    def crash_dump(self, kind: str, *, cat: str = "crash", actor: str = "",
                   payload: dict | None = None, t: float | None = None,
                   path: str | None = None) -> None:
        return None


NULL_RECORDER = NullRecorder()
_current: FlightRecorder | NullRecorder = NULL_RECORDER


def install(rec: FlightRecorder | NullRecorder | None):
    """Install a process-global recorder (``None`` resets to the null
    recorder); returns the previous one so callers can restore it."""
    global _current
    prev = _current
    _current = rec if rec is not None else NULL_RECORDER
    return prev


def current() -> FlightRecorder | NullRecorder:
    """The process-global recorder components resolve at construction."""
    return _current


def signature(events: Iterable, *, include_t: bool = False,
              volatile: frozenset = VOLATILE) -> list[tuple]:
    """Stable comparison view of an event sequence.

    Drops ``seq``/``mono`` always, ``t`` unless ``include_t``, and the
    ``volatile`` payload keys; accepts :class:`ObsEvent` objects or the
    dicts a :meth:`FlightRecorder.dump` round-trips. Two seeded runs of
    the same storm must produce equal signatures (pinned)."""
    out = []
    for e in events:
        if isinstance(e, dict):
            kind, cat, actor = e["kind"], e["cat"], e["actor"]
            t, payload = e.get("t"), e.get("payload", {})
        else:
            kind, cat, actor = e.kind, e.cat, e.actor
            t, payload = e.t, e.payload
        items = tuple(sorted(
            (k, tuple(v) if isinstance(v, list) else v)
            for k, v in payload.items() if k not in volatile))
        row: tuple = (kind, cat, actor)
        if include_t:
            row += (t,)
        out.append(row + (items,))
    return out
