"""Process-local Counter/Gauge/Histogram registry + text exposition.

One namespaced scheme (``dtg_<area>_<what>[_total]``) absorbing the
counters that today live on scattered objects: engine ``health()``,
block-pool occupancy/refcounts, prefix-index size, per-tenant DRR stats,
dispatch/prefetch host-gap accounting, train-loop step/ckpt/anomaly
counts. ``snapshot()`` gives a flat dict (histograms as
``{count, sum, buckets}``), :meth:`Registry.to_prometheus` the
Prometheus text exposition format.

Strictly passive: absorbing reads host-side numbers that already exist;
nothing here is consulted by any scheduler or compiled program.
"""

from __future__ import annotations

import math
import threading
from typing import Iterable

#: default histogram buckets: exponential, micro-seconds to minutes —
#: wide enough for step times and launch latencies alike.
DEFAULT_BUCKETS = tuple(1e-6 * 4 ** i for i in range(14))


def _label_str(labels: dict | None) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    """Monotonically increasing; ``set_total`` absorbs an externally
    maintained cumulative count (engine health counters)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labels: dict | None = None):
        self.name, self.help, self.labels = name, help, labels or {}
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        self.value += n

    def set_total(self, v: float) -> None:
        self.value = float(v)


class Gauge:
    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labels: dict | None = None):
        self.name, self.help, self.labels = name, help, labels or {}
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self.value += n


class Histogram:
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labels: dict | None = None,
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        self.name, self.help, self.labels = name, help, labels or {}
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self.count = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        self.count += 1
        self.sum += v
        for i, le in enumerate(self.buckets):
            if v <= le:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def snapshot_value(self) -> dict:
        cum, out = 0, {}
        for le, c in zip(self.buckets, self.counts):
            cum += c
            out[le] = cum
        return {"count": self.count, "sum": self.sum, "buckets": out}


class Registry:
    """Get-or-create metric registry, keyed (name, labels)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[tuple, object] = {}

    def _get(self, cls, name: str, help: str, labels: dict | None,
             **kw):
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help, labels, **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"{name} already registered as {m.kind}")
            return m

    def counter(self, name: str, help: str = "",
                labels: dict | None = None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: dict | None = None) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: dict | None = None,
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def snapshot(self) -> dict:
        """Flat dict: ``name{label="v"}`` -> scalar, histograms ->
        ``{count, sum, buckets}``."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = {}
        for m in metrics:
            key = m.name + _label_str(m.labels)
            out[key] = (m.snapshot_value() if isinstance(m, Histogram)
                        else m.value)
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (one HELP/TYPE block per family)."""
        with self._lock:
            metrics = list(self._metrics.values())
        seen_family: set[str] = set()
        lines: list[str] = []
        for m in sorted(metrics, key=lambda m: (m.name,
                                                _label_str(m.labels))):
            if m.name not in seen_family:
                seen_family.add(m.name)
                if m.help:
                    lines.append(f"# HELP {m.name} {m.help}")
                lines.append(f"# TYPE {m.name} {m.kind}")
            ls = _label_str(m.labels)
            if isinstance(m, Histogram):
                cum = 0
                for le, c in zip(m.buckets, m.counts):
                    cum += c
                    lab = dict(m.labels)
                    lab["le"] = f"{le:g}"
                    lines.append(
                        f"{m.name}_bucket{_label_str(lab)} {cum}")
                lab = dict(m.labels)
                lab["le"] = "+Inf"
                lines.append(
                    f"{m.name}_bucket{_label_str(lab)} {m.count}")
                lines.append(f"{m.name}_sum{ls} {m.sum:g}")
                lines.append(f"{m.name}_count{ls} {m.count}")
            else:
                v = m.value if math.isfinite(m.value) else float("nan")
                lines.append(f"{m.name}{ls} {v:g}")
        return "\n".join(lines) + "\n"


# ---- absorbers: existing host-side stats -> the one namespace -------------


def absorb_engine(reg: Registry, health: dict) -> None:
    """``ServeEngine.health()`` -> ``dtg_serve_*`` metrics (gauges for
    instantaneous occupancy, counters for cumulative event counts,
    per-tenant DRR stats as labeled counters)."""
    for k in ("resident", "queued", "live_blocks", "prefix_nodes"):
        if k in health:
            reg.gauge(f"dtg_serve_{k}").set(health[k])
    # host spill tier (PR 16): instantaneous occupancy of the host
    # BlockStore under the device pool
    for k in ("host_blocks", "host_bytes"):
        if k in health:
            reg.gauge(f"dtg_serve_spill_{k}").set(health[k])
    if "last_tick_s" in health:
        reg.gauge("dtg_serve_last_tick_s").set(health["last_tick_s"])
    for k in ("completed", "shed", "cancelled", "expired", "preemptions",
              "prefix_hit_tokens", "prefill_tokens_saved",
              "prefix_evictions", "spill_out_blocks", "spill_in_blocks",
              "spill_d2h_bytes", "spill_h2d_bytes",
              "spill_prefetched_blocks", "spill_resumes",
              "swapin_tokens_saved", "launch_failures"):
        if k in health:
            reg.counter(f"dtg_serve_{k}_total").set_total(health[k])
    if "ticks" in health:
        reg.counter("dtg_serve_ticks_total").set_total(health["ticks"])
    for tenant, c in (health.get("tenants") or {}).items():
        for k, v in c.items():
            reg.counter(f"dtg_serve_tenant_{k}_total",
                        labels={"tenant": str(tenant)}).set_total(v)
    # expert-parallel decode (PR 19): per-expert routed/overflowed token
    # counts as labeled counters, stall ticks as engine-wide counters —
    # the load/overflow skew is THE capacity-tuning signal
    moe = health.get("moe")
    if moe:
        for e, v in enumerate(moe.get("expert_load", ())):
            reg.counter("dtg_moe_expert_load_total",
                        labels={"expert": str(e)}).set_total(v)
        for e, v in enumerate(moe.get("expert_overflow", ())):
            reg.counter("dtg_moe_expert_overflow_total",
                        labels={"expert": str(e)}).set_total(v)
        for k in ("stall_slot_ticks", "stall_ticks"):
            if k in moe:
                reg.counter(f"dtg_moe_{k}_total").set_total(moe[k])


def absorb_fleet(reg: Registry, health: dict) -> None:
    """``FleetScheduler.health()`` -> ``dtg_fleet_*`` metrics: fleet
    counters and the global tenant aggregation at the top level,
    per-replica engine healths re-absorbed through
    :func:`absorb_engine`'s scheme under a ``replica`` label."""
    reg.gauge("dtg_fleet_queued").set(health.get("queued", 0))
    reg.gauge("dtg_fleet_live_replicas").set(
        health.get("live_replicas", 0))
    reg.gauge("dtg_fleet_generation").set(health.get("generation", 0))
    for k in ("shed", "completed", "migrations", "migration_bytes",
              "replicas_shed", "replicas_regrown", "prefix_route_hits",
              "prefix_route_hit_tokens",
              # the PR-20 reliability plane: crash/stall recoveries, the
              # breaker's eject/probe/recover cycle, step-boundary
              # faults, exactly-once adoption drops, autoscale actions
              "replica_crashes", "replica_stalls", "breaker_ejections",
              "breaker_probes", "breaker_recoveries", "replica_faults",
              "launch_failures", "migration_dups_dropped",
              "autoscale_added", "autoscale_retired"):
        if k in health:
            reg.counter(f"dtg_fleet_{k}_total").set_total(health[k])
    if "migration_secs" in health:
        reg.gauge("dtg_fleet_migration_s").set(health["migration_secs"])
    if "stalled" in health:
        reg.gauge("dtg_fleet_stalled_replicas").set(
            len(health["stalled"]))
    if "draining" in health:
        reg.gauge("dtg_fleet_draining_replicas").set(
            len(health["draining"]))
    autoscale = health.get("autoscale")
    if autoscale:
        reg.gauge("dtg_fleet_autoscale_target").set(
            autoscale.get("target_replicas", 0))
    for tenant, c in (health.get("tenants") or {}).items():
        for k, v in c.items():
            reg.counter(f"dtg_fleet_tenant_{k}_total",
                        labels={"tenant": str(tenant)}).set_total(v)
    for i, h in enumerate(health.get("replicas") or []):
        labels = {"replica": str(i), "role": str(h.get("role", ""))}
        reg.gauge("dtg_fleet_replica_live", labels=labels).set(
            1.0 if h.get("live") else 0.0)
        br = h.get("breaker")
        if br:
            reg.gauge("dtg_fleet_replica_breaker_open",
                      labels=labels).set(
                0.0 if br.get("state") == "closed" else 1.0)
        for k in ("resident", "queued", "live_blocks"):
            if k in h:
                reg.gauge(f"dtg_fleet_replica_{k}",
                          labels=labels).set(h[k])
        for k in ("completed", "shed", "preemptions",
                  "migrated_out", "migrated_in", "launch_failures"):
            if k in h:
                reg.counter(f"dtg_fleet_replica_{k}_total",
                            labels=labels).set_total(h[k])


def absorb_pool(reg: Registry, stats: dict) -> None:
    """``BlockPool.stats()`` -> ``dtg_serve_pool_*`` gauges."""
    for k, v in stats.items():
        reg.gauge(f"dtg_serve_pool_{k}").set(v)


def absorb_prefix(reg: Registry, stats: dict) -> None:
    """``PrefixIndex.stats()`` -> ``dtg_serve_prefix_*`` gauges."""
    for k, v in stats.items():
        reg.gauge(f"dtg_serve_prefix_{k}").set(v)


def absorb_spill_store(reg: Registry, stats: dict) -> None:
    """``BlockStore.stats()`` -> ``dtg_serve_spill_store_*`` gauges."""
    for k, v in stats.items():
        reg.gauge(f"dtg_serve_spill_store_{k}").set(v)


def absorb_dispatch(reg: Registry, stats) -> None:
    """``utils.profiling.DispatchStats`` -> ``dtg_train_*`` — the
    host-gap numbers that were only reachable by attribute-poking."""
    reg.counter("dtg_train_dispatches_total").set_total(stats.dispatches)
    reg.counter("dtg_train_opt_steps_total").set_total(stats.steps)
    reg.gauge("dtg_train_host_gap_s").set(stats.host_gap_s)
    reg.gauge("dtg_train_dispatch_enqueue_s").set(stats.dispatch_s)
    if stats.dispatches:
        reg.gauge("dtg_train_host_gap_ms_per_dispatch").set(
            1e3 * stats.host_gap_s / stats.dispatches)


def absorb_prefetch(reg: Registry, stats) -> None:
    """``data.prefetch.PrefetchStats`` -> ``dtg_data_prefetch_*``."""
    reg.counter("dtg_data_prefetch_batches_total").set_total(stats.batches)
    reg.gauge("dtg_data_prefetch_host_wait_s").set(stats.host_wait_s)
    reg.gauge("dtg_data_prefetch_max_host_wait_s").set(
        stats.max_host_wait_s)
    reg.gauge("dtg_data_prefetch_put_s").set(stats.put_s)
    reg.gauge("dtg_data_prefetch_peak_ahead").set(stats.peak_ahead)
