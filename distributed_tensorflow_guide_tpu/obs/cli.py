"""``dtg-obs``: inspect a flight-recorder dump, convert to Chrome trace.

    dtg-obs crash.json                    # pretty-print the event tail
    dtg-obs crash.json --kind req.        # only request-lifecycle events
    dtg-obs crash.json --chrome out.json  # -> chrome://tracing / Perfetto
"""

from __future__ import annotations

import argparse
import json
import sys

from distributed_tensorflow_guide_tpu.obs.tracing import (
    events_from_dump,
    to_chrome_trace,
)


def _fmt(e) -> str:
    t = "-" if e.t is None else f"{e.t:.6f}"
    payload = json.dumps(e.payload, sort_keys=True, default=str)
    return (f"{e.seq:6d}  t={t:>12}  {e.cat:<9} {e.kind:<20} "
            f"{e.actor:<14} {payload}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dtg-obs",
        description="Pretty-print a flight-recorder dump or convert it "
                    "to Chrome/Perfetto trace-event JSON.")
    ap.add_argument("dump", help="path to a FlightRecorder.dump() file")
    ap.add_argument("--chrome", metavar="OUT", default=None,
                    help="write Chrome trace-event JSON to OUT instead "
                         "of printing events")
    ap.add_argument("--kind", default=None,
                    help="only events whose kind contains this substring")
    ap.add_argument("--limit", type=int, default=0,
                    help="print only the last N events (0 = all)")
    args = ap.parse_args(argv)

    try:
        with open(args.dump) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"dtg-obs: cannot read {args.dump}: {e}", file=sys.stderr)
        return 1
    events = events_from_dump(args.dump)

    if args.chrome:
        trace = to_chrome_trace(events)
        with open(args.chrome, "w") as f:
            json.dump(trace, f)
        print(f"wrote {len(trace['traceEvents'])} trace event(s) from "
              f"{len(events)} recorder event(s) -> {args.chrome}")
        return 0

    if args.kind:
        events = [e for e in events if args.kind in e.kind]
    if args.limit > 0:
        events = events[-args.limit:]
    print(f"# {data.get('schema', '?')}  total={data.get('total', '?')} "
          f"dropped={data.get('dropped', '?')} "
          f"capacity={data.get('capacity', '?')}  "
          f"showing={len(events)}")
    for e in events:
        print(_fmt(e))
    return 0


if __name__ == "__main__":
    sys.exit(main())
