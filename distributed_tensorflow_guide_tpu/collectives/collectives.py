"""The communication layer — NCCL/gRPC equivalent, in one traceable place.

Reference equivalents:
  * async PS traffic: implicit gRPC send/recv inserted by the TF graph
    partitioner between /job:worker and /job:ps
    (tensorflow/python/training/server_lib.py:96; placement via
    tensorflow/python/training/device_setter.py:129) — the guide never calls a
    collective explicitly.
  * sync traffic (modern surface): ``NcclAllReduce`` / ``CollectiveAllReduce``
    (tensorflow/python/distribute/cross_device_ops.py:961,:1045) selected via
    ``CommunicationImplementation.NCCL``
    (tensorflow/python/distribute/collective_util.py).

Here communication is *explicit and named*: every collective the framework
issues goes through these wrappers, so a single ``trace_comm()`` context can
count ops and bytes for any jitted program (the observability the reference
lacks entirely). All functions must be called under ``shard_map`` (or a
``pjit`` body with manual axes) where ``axis`` is a mesh axis name.

On hardware these lower to XLA ICI collectives: psum → all-reduce ring,
all_gather → bidirectional ring gather, ppermute → neighbor ICI hop,
all_to_all → ICI transpose. Over multi-slice deployments XLA routes the DCN
legs automatically from the mesh's device assignment.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import functools
from collections import defaultdict
from typing import Any

import jax
from jax import lax

_TRACE: contextvars.ContextVar["CommTrace | None"] = contextvars.ContextVar(
    "dtg_comm_trace", default=None
)


@dataclasses.dataclass
class CommTrace:
    """Counts collective *call sites* (per trace) and traced payload bytes."""

    calls: dict[str, int] = dataclasses.field(
        default_factory=lambda: defaultdict(int)
    )
    bytes: dict[str, int] = dataclasses.field(
        default_factory=lambda: defaultdict(int)
    )

    def record(self, op: str, axis: Any, tree: Any) -> None:
        key = f"{op}[{axis}]"
        self.calls[key] += 1
        n = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            if hasattr(leaf, "size") and hasattr(leaf, "dtype"):
                n += int(leaf.size) * leaf.dtype.itemsize
        self.bytes[key] += n

    def total_calls(self) -> int:
        return sum(self.calls.values())


@contextlib.contextmanager
def trace_comm():
    """Record all collectives issued while tracing code under this context.

    Counts are per-trace (graph-level), like counting NCCL launch sites —
    re-executing a compiled function does not re-count.
    """
    rec = CommTrace()
    token = _TRACE.set(rec)
    try:
        yield rec
    finally:
        _TRACE.reset(token)


def _record(op: str, axis: Any, tree: Any) -> None:
    rec = _TRACE.get()
    if rec is not None:
        rec.record(op, axis, tree)


def record_event(op: str, axis: Any, tree: Any = None) -> None:
    """Record a named NON-collective event into the active ``trace_comm``.

    For decisions that change the comm/compute profile without issuing a
    collective themselves — e.g. a kernel auto-policy silently taking a
    differently-shaped path (ops/flash_attention's blockwise fallback
    registry is the package-wide sibling). Shows up in
    ``CommTrace.calls`` under ``op[axis]`` like any collective, so a test
    (or a user auditing a trace) sees the degradation instead of guessing
    from throughput."""
    _record(op, axis, tree)


def axis_size(axis: str) -> int:
    """Size of a mesh axis from inside shard_map (NCCL world-size analogue).

    ``lax.axis_size`` only exists on newer JAX; ``psum(1, axis)`` is the
    portable spelling and constant-folds to the same Python int at trace
    time (it is not a collective — no wire traffic, nothing recorded)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


def psum(x, axis: str | tuple[str, ...]):
    """All-reduce sum — replaces NcclAllReduce
    (tensorflow/python/distribute/cross_device_ops.py:961) and the reference's
    SyncReplicasOptimizer accumulator+token-queue barrier
    (tensorflow/python/training/sync_replicas_optimizer.py:42)."""
    _record("psum", axis, x)
    return lax.psum(x, axis)


def pmean(x, axis: str | tuple[str, ...]):
    """All-reduce mean — gradient averaging across the data axis."""
    _record("pmean", axis, x)
    return lax.pmean(x, axis)


def pmax(x, axis: str | tuple[str, ...]):
    """All-reduce max — the stabilizer of vocab-parallel log-softmax."""
    _record("pmax", axis, x)
    return lax.pmax(x, axis)


def all_gather(x, axis: str, *, tiled: bool = False, gather_axis: int = 0):
    """All-gather — replaces NCCL allgather per the north-star mapping."""
    _record("all_gather", axis, x)
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_axis: int = 0):
    """Reduce-scatter (psum_scatter) — the memory-optimal half of an
    all-reduce; used for ZeRO/FSDP-style sharded gradient reduction."""
    _record("reduce_scatter", axis, x)
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def ppermute(x, axis: str, perm):
    """Point-to-point permutation over the mesh axis — the ICI-neighbor hop
    used by pipeline stages and ring attention."""
    _record("ppermute", axis, x)
    return lax.ppermute(x, axis, perm)


def ring_shift(x, axis: str, *, shift: int = 1):
    """Rotate values one (or `shift`) steps around the axis ring.

    Device i sends to device (i+shift) mod n — the KV-rotation primitive of
    ring attention and the activation hand-off of pipeline parallelism.
    """
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return ppermute(x, axis, perm)


# -- Megatron tensor-parallel conjugate operators (f / g) ---------------------
#
# Manual-SPMD tensor parallelism (TP inside shard_map, e.g. TP-sharded
# pipeline stages) cannot use bare ``lax.psum`` around the row-parallel
# matmuls: under ``shard_map`` with replication-checking off, the transpose
# of psum is another psum, so autodiff would multiply cotangents by the TP
# degree. Megatron (Shoeybi et al. 2019, §3) defines the conjugate pair
#   f: identity forward, all-reduce backward   (at the parallel block input)
#   g: all-reduce forward, identity backward   (after the row-parallel matmul)
# which is exactly the VJP structure pinned here with ``jax.custom_vjp``.
# With f at each sub-layer input and g at each sub-layer output, parameter
# gradients of the sharded weights stay local (matching their shard specs)
# and every replicated tensor's gradient (LayerNorm, embeddings, residual
# stream) arrives correctly summed over the TP shards.


def tp_allreduce(x, axis: str):
    """Megatron's ``g``: psum forward, identity backward."""
    _record("psum", axis, x)  # wire traffic is the forward psum
    return _tp_g(x, axis)


def tp_identity(x, axis: str):
    """Megatron's ``f``: identity forward, psum backward."""
    _record("psum_bwd", axis, x)  # wire traffic happens in the backward pass
    return _tp_f(x, axis)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tp_g(x, axis):
    return lax.psum(x, axis)


def _tp_g_fwd(x, axis):
    return lax.psum(x, axis), None


def _tp_g_bwd(axis, _, ct):
    return (ct,)


_tp_g.defvjp(_tp_g_fwd, _tp_g_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tp_f(x, axis):
    return x


def _tp_f_fwd(x, axis):
    return x, None


def _tp_f_bwd(axis, _, ct):
    return (lax.psum(ct, axis),)


_tp_f.defvjp(_tp_f_fwd, _tp_f_bwd)


def all_to_all(x, axis: str, *, split_axis: int, concat_axis: int):
    """All-to-all resharding — the Ulysses sequence↔heads exchange and the
    MoE token-routing primitive."""
    _record("all_to_all", axis, x)
    return lax.all_to_all(x, axis, split_axis, concat_axis, tiled=True)
