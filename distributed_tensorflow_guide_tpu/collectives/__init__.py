from distributed_tensorflow_guide_tpu.collectives.collectives import (  # noqa: F401
    CommTrace,
    all_gather,
    all_to_all,
    axis_size,
    pmean,
    ppermute,
    psum,
    reduce_scatter,
    ring_shift,
    tp_allreduce,
    tp_identity,
    trace_comm,
)
