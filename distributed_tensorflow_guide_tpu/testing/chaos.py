"""Deterministic chaos harness — seeded fault injection for the resilience
layer.

The reference guide's failure story was untested because failures were
unproducible: you killed a PS by hand and watched workers hang. This module
makes every failure class the resilience layer claims to handle injectable
*deterministically* — a seeded :class:`FaultSchedule` fires the same faults
at the same points on every run — so tests can assert the strongest
property there is: a faulted supervised run ends **bitwise identical** to an
uninterrupted one (tests/test_chaos.py), and ``benchmarks/bench_resilience.py``
can measure recovery MTTR and goodput under a reproducible storm.

Fault classes and where they fire:

==================  =========================================================
``step_exception``  raised from inside the step function at the Nth step-fn
                    invocation (``wrap_step``) — a host-visible step crash
``nan_batch``       the batch at absolute stream position N is replaced with
                    NaNs (``inject_data``) — data poison for the sentinel
``iterator_stall``  the fetch of position N sleeps ``param`` seconds
                    (``inject_data``) — the watchdog's prey
``ckpt_truncate``   the newest committed checkpoint's largest payload file is
                    truncated when position N is reached (``inject_data``) —
                    post-commit corruption for the restore ladder
``ckpt_corrupt``    same, but bytes are flipped in place (size unchanged —
                    only the CRC manifest can catch it)
==================  =========================================================

World-kind faults are *process-group-targeted* — they change the capacity
of the job instead of poisoning its stream — and fire through the elastic
supervisor (train/elastic_world.py) rather than the step/data injectors:

==================  =========================================================
``slice_loss``      every process of slice ``param`` exits abruptly after
                    completing step N — a lost slice (maintenance, preempted
                    capacity); survivors resume at reduced world size
``slice_return``    slice ``param`` becomes schedulable again at step N —
                    the supervisor regrows to full world at that boundary
==================  =========================================================

Serve-kind faults target the continuous-batching engine
(serve/engine.py) and fire at deterministic engine *tick* positions —
the engine drains them via :meth:`FaultSchedule.take_serve` at the top
of each ``step`` call.  Like the world kinds they are excluded from
:meth:`FaultSchedule.random`'s default draw (the training-side
injectors would silently swallow them and existing storms would hang
waiting); draw a serving storm with :meth:`random_serve` instead:

=======================  ====================================================
``serve_step_exception``  the next jitted program launch raises (a transient
                          launch failure); the engine's retry re-runs the
                          SAME tick bitwise
``client_abandon``        a live request is cancelled (``param`` indexes the
                          sorted live rids); slot+blocks free at the next
                          step boundary
``arrival_burst``         ``param`` extra requests arrive at once through the
                          engine's ``burst_factory`` — the admission gate's
                          prey
``pool_pressure``         ``param`` KV blocks vanish from the pool for a few
                          ticks (a co-tenant spike); residents get evicted
                          and must re-prefill
``snapshot_truncate``     the newest committed engine snapshot is truncated
                          (caught by the manifest size check)
``snapshot_corrupt``      same, but bytes flip in place (only the CRC
                          catches it) — the restore ladder falls back
=======================  ====================================================

Fleet-kind faults target the scale-out tier (serve/fleet.py) and fire at
deterministic FLEET tick positions — the fleet drains them via
:meth:`FaultSchedule.take_fleet` at the top of each fleet ``step``.  They
model the failure domain ABOVE one engine: a whole replica dying or
wedging, and the migration seam tearing mid-handoff.  Like the world and
serve kinds they are excluded from :meth:`FaultSchedule.random`'s default
draw and pass through ``wrap_step``/``inject_data`` untouched; draw a
fleet storm with :meth:`random_fleet`:

==================  =========================================================
``replica_crash``   replica ``param`` dies mid-tick with NO orderly
                    ``detach_stream`` (its KV and engine object are gone);
                    the fleet reconstructs its residents from the fleet's
                    own admission ledger and re-anchors them queue-front
``replica_stall``   replica ``param`` wedges (the watchdog's tick-deadline
                    verdict, delivered deterministically); it is excluded
                    from routing while its streams re-anchor host-side,
                    and rejoins after the fleet's stall-recovery window
``migration_torn``  the NEXT migration / re-anchor handoff record is
                    duplicated in flight (a torn handoff: the sender
                    cannot know the record landed, so it resends) — the
                    fleet's (rid, generation) adoption ledger must swallow
                    the duplicate exactly once
==================  =========================================================

Mid-save process kills are process-level, not stream-level: use
``runtime.multiprocess.MultiProcessRunner.kill`` directly (see the chaos
tests). Every fault is one-shot — after it fires once it never fires again,
which is what makes replay-after-recovery converge (and is also how real
transients behave; persistent data poison is modeled by the underlying
stream itself, plus the sentinel's ``skip_offending``).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from pathlib import Path
from typing import Any, Callable, Iterable, Iterator, Sequence

import numpy as np

from distributed_tensorflow_guide_tpu.obs import events as obs_events

log = logging.getLogger("dtg.chaos")

DATA_KINDS = ("nan_batch", "iterator_stall", "ckpt_truncate", "ckpt_corrupt")
STEP_KINDS = ("step_exception",)
# in-process injectable kinds — what wrap_step/inject_data (and
# FaultSchedule.random's default draw) cover
INJECTABLE_KINDS = STEP_KINDS + DATA_KINDS
# world kinds change job capacity; they are applied by the elastic
# supervisor (train/elastic_world.py), which marks them fired via fire()
WORLD_KINDS = ("slice_loss", "slice_return")
# serve storm kinds fire inside ServeEngine.step at engine-tick
# positions; the snapshot kinds additionally need an engine snapshot
# directory to damage, so random_serve leaves them out of its default
# draw the same way random() leaves out the ckpt-less-safe split
SERVE_STORM_KINDS = ("serve_step_exception", "client_abandon",
                     "arrival_burst", "pool_pressure")
SERVE_SNAPSHOT_KINDS = ("snapshot_truncate", "snapshot_corrupt")
SERVE_KINDS = SERVE_STORM_KINDS + SERVE_SNAPSHOT_KINDS
# fleet kinds fire inside FleetScheduler.step at fleet-tick positions —
# the replica-targeted ones carry a replica index in param (mod'd by the
# fleet width, mirroring the world kinds' slice targeting)
FLEET_KINDS = ("replica_crash", "replica_stall", "migration_torn")
KINDS = INJECTABLE_KINDS + WORLD_KINDS + SERVE_KINDS + FLEET_KINDS


class ChaosInjectedError(RuntimeError):
    """The injected step exception (recoverable by design)."""


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scheduled fault. ``position`` is the absolute stream position for
    data-kind faults, the (1-based counting from 0) step-fn invocation index
    for ``step_exception``. ``param`` is kind-specific: stall seconds for
    ``iterator_stall``, unused otherwise."""

    kind: str
    position: int
    param: float = 0.0
    # arrival_burst only: direct the burst at one tenant so fair-share
    # admission (not just queue shedding) is what absorbs it
    tenant: int | None = None

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(choose from {KINDS})")
        if self.tenant is not None:
            if self.kind != "arrival_burst":
                raise ValueError(
                    f"tenant= only applies to arrival_burst, not "
                    f"{self.kind!r}")
            if self.tenant != int(self.tenant) or self.tenant < 0:
                raise ValueError(
                    f"arrival_burst tenant must be a non-negative id, "
                    f"got {self.tenant!r}")
        if self.kind in WORLD_KINDS:
            # param targets the process group: the slice index
            if self.param != int(self.param) or self.param < 0:
                raise ValueError(
                    f"{self.kind} needs param = a non-negative slice "
                    f"index, got {self.param!r}")
        if self.kind == "client_abandon":
            # param indexes the engine's sorted live rids (mod count)
            if self.param != int(self.param) or self.param < 0:
                raise ValueError(
                    f"client_abandon needs param = a non-negative live-rid "
                    f"index, got {self.param!r}")
        if self.kind in ("arrival_burst", "pool_pressure"):
            if self.param != int(self.param) or self.param < 1:
                raise ValueError(
                    f"{self.kind} needs param = a positive count "
                    f"(requests / blocks), got {self.param!r}")
        if self.kind in ("replica_crash", "replica_stall"):
            # param targets the replica index (mod fleet width at fire)
            if self.param != int(self.param) or self.param < 0:
                raise ValueError(
                    f"{self.kind} needs param = a non-negative replica "
                    f"index, got {self.param!r}")

    @property
    def slice_id(self) -> int:
        """The targeted slice of a world-kind fault."""
        if self.kind not in WORLD_KINDS:
            raise ValueError(f"{self.kind!r} targets no slice")
        return int(self.param)


def _poison(batch: Any) -> Any:
    """Replace every float leaf with NaNs (ints/bools pass through)."""
    import jax

    def bad(x):
        arr = np.asarray(x)
        if np.issubdtype(arr.dtype, np.floating):
            return np.full_like(arr, np.nan)
        return x

    return jax.tree.map(bad, batch)


def _interruptible_sleep(seconds: float) -> None:
    """Sleep in small slices so a watchdog ``interrupt_main`` can land
    between bytecodes — a single long C-level sleep would be opaque to it
    (the honest limitation utils/watchdog.py documents)."""
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        time.sleep(0.02)


def corrupt_checkpoint(directory: str | Path, step: int | None = None, *,
                       mode: str = "truncate") -> tuple[int, str]:
    """Damage a committed checkpoint in place — the post-commit corruption
    (partial fsync loss, bit rot, an overzealous cleanup job) the manifest
    + restore ladder exist for.

    ``mode="truncate"`` halves the largest payload file (size changes —
    caught by the manifest's size check); ``mode="flip"`` inverts its
    middle bytes (size unchanged — only the CRC catches it). ``step=None``
    targets the newest committed step. Returns ``(step, relative_path)``.
    """
    directory = Path(directory)
    steps = sorted(
        int(p.name) for p in directory.iterdir()
        if p.is_dir() and p.name.isdigit()
    )
    if not steps:
        raise FileNotFoundError(f"no committed checkpoints under {directory}")
    step = steps[-1] if step is None else step
    step_dir = directory / str(step)
    target = max(
        (p for p in step_dir.rglob("*") if p.is_file()),
        key=lambda p: p.stat().st_size,
    )
    data = target.read_bytes()
    if mode == "truncate":
        target.write_bytes(data[: len(data) // 2])
    elif mode == "flip":
        mid = len(data) // 2
        span = max(1, min(64, len(data) - mid))
        mutated = bytes(b ^ 0xFF for b in data[mid:mid + span])
        target.write_bytes(data[:mid] + mutated + data[mid + span:])
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    rel = str(target.relative_to(step_dir))
    log.warning("chaos: %s checkpoint step %d file %s", mode, step, rel)
    return step, rel


class FaultSchedule:
    """A seeded, one-shot fault plan shared across a supervised run.

    The SAME instance must wrap both the step function and the data maker
    of every restart attempt (``run_with_recovery`` re-calls ``make_data``
    per attempt; the schedule's fired-set persists across them) — that is
    what makes each fault fire exactly once per run.
    """

    def __init__(self, faults: Sequence[Fault] = ()):
        self.faults = sorted(faults, key=lambda f: (f.position, f.kind))
        self.fired: list[Fault] = []
        self._pending = set(self.faults)
        self._step_calls = 0
        # observability (PR 14): every firing lands in the flight
        # recorder as a ``chaos.fault`` instant. The serve engine stamps
        # ``recorder``/``obs_now`` with its own recorder and semantic
        # clock each tick; standalone schedules use the process-global
        # recorder (disabled by default) with no semantic timestamp.
        self.recorder = obs_events.current()
        self.obs_now: float | None = None

    @classmethod
    def random(cls, seed: int, *, max_position: int,
               kinds: Sequence[str] = INJECTABLE_KINDS, n_faults: int = 3,
               min_position: int = 1,
               stall_s: float = 0.5) -> "FaultSchedule":
        """Deterministic-in-``seed`` schedule: ``n_faults`` distinct
        positions in ``[min_position, max_position)``, kinds drawn
        uniformly from the INJECTABLE kinds (world kinds need a slice
        target — use :meth:`random_world`). Same seed → identical
        schedule, always."""
        if max_position - min_position < n_faults:
            raise ValueError(
                f"cannot place {n_faults} faults in "
                f"[{min_position}, {max_position})")
        rng = np.random.RandomState(seed)
        positions = rng.choice(
            np.arange(min_position, max_position), size=n_faults,
            replace=False,
        )
        chosen = rng.choice(len(kinds), size=n_faults)
        return cls([
            Fault(kinds[int(k)], int(p),
                  stall_s if kinds[int(k)] == "iterator_stall" else 0.0)
            for p, k in zip(positions, chosen)
        ])

    @classmethod
    def random_world(cls, seed: int, *, n_slices: int, max_position: int,
                     min_position: int = 1, min_gap: int = 2,
                     ) -> "FaultSchedule":
        """Deterministic-in-``seed`` capacity storm: one ``slice_loss`` /
        ``slice_return`` pair targeting a random slice, the loss at a
        random step and the return at least ``min_gap`` steps later (the
        reduced-world window has to contain real training for the elastic
        pins to mean anything). Same seed → identical schedule, always."""
        if n_slices < 2:
            raise ValueError(
                f"need >= 2 slices to lose one, got {n_slices}")
        if max_position - min_position <= min_gap:
            raise ValueError(
                f"cannot place a loss/return pair {min_gap} apart in "
                f"[{min_position}, {max_position})")
        rng = np.random.RandomState(seed)
        target = int(rng.randint(0, n_slices))
        loss_at = int(rng.randint(min_position, max_position - min_gap))
        return_at = int(rng.randint(loss_at + min_gap, max_position))
        return cls([
            Fault("slice_loss", loss_at, float(target)),
            Fault("slice_return", return_at, float(target)),
        ])

    @classmethod
    def random_serve(cls, seed: int, *, max_position: int,
                     kinds: Sequence[str] = SERVE_STORM_KINDS,
                     n_faults: int = 4, min_position: int = 1,
                     burst_n: int = 2, pressure_blocks: int = 4,
                     abandon_span: int = 4,
                     burst_tenants: int | None = None) -> "FaultSchedule":
        """Deterministic-in-``seed`` serving storm: ``n_faults`` distinct
        engine-tick positions in ``[min_position, max_position)``, kinds
        drawn uniformly from ``kinds`` (defaults to the storm kinds — the
        snapshot kinds need ``ServeEngine(snapshot_dir=...)``, so pass
        ``SERVE_KINDS`` explicitly to include them). Params: bursts are
        ``burst_n`` requests, pressure spikes grab ``pressure_blocks``,
        abandons index the live rids in ``[0, abandon_span)``. With
        ``burst_tenants`` set, each arrival_burst additionally targets a
        tenant drawn from ``[0, burst_tenants)`` (rng draws happen only
        for burst faults, so schedules without bursts — or with
        ``burst_tenants=None`` — are byte-identical to pre-tenancy ones).
        Same seed → identical schedule, always."""
        bad = [k for k in kinds if k not in SERVE_KINDS]
        if bad:
            raise ValueError(f"non-serve kinds in random_serve: {bad}")
        if max_position - min_position < n_faults:
            raise ValueError(
                f"cannot place {n_faults} faults in "
                f"[{min_position}, {max_position})")
        rng = np.random.RandomState(seed)
        positions = rng.choice(
            np.arange(min_position, max_position), size=n_faults,
            replace=False,
        )
        chosen = rng.choice(len(kinds), size=n_faults)
        params = {"serve_step_exception": lambda: 0.0,
                  "snapshot_truncate": lambda: 0.0,
                  "snapshot_corrupt": lambda: 0.0,
                  "client_abandon": lambda: float(
                      rng.randint(0, abandon_span)),
                  "arrival_burst": lambda: float(burst_n),
                  "pool_pressure": lambda: float(pressure_blocks)}

        def _tenant(kind):
            if kind != "arrival_burst" or burst_tenants is None:
                return None
            return int(rng.randint(0, burst_tenants))

        faults = []
        for p, k in zip(positions, chosen):
            kind = kinds[int(k)]
            faults.append(Fault(kind, int(p), params[kind](),
                                tenant=_tenant(kind)))
        return cls(faults)

    @classmethod
    def random_fleet(cls, seed: int, *, max_position: int,
                     replicas: int,
                     kinds: Sequence[str] = FLEET_KINDS,
                     n_faults: int = 3,
                     min_position: int = 1) -> "FaultSchedule":
        """Deterministic-in-``seed`` fleet storm: ``n_faults`` distinct
        FLEET-tick positions in ``[min_position, max_position)``, kinds
        drawn uniformly from ``kinds`` (defaults to all three fleet
        kinds).  Replica-targeted kinds draw their target from
        ``[0, replicas)``; ``migration_torn`` is param-free (rng draws
        happen only for replica-targeted faults, keeping schedules with
        different kind mixes independently stable).  Same seed →
        identical schedule, always."""
        bad = [k for k in kinds if k not in FLEET_KINDS]
        if bad:
            raise ValueError(f"non-fleet kinds in random_fleet: {bad}")
        if replicas < 1:
            raise ValueError(f"need >= 1 replica to target, "
                             f"got {replicas}")
        if max_position - min_position < n_faults:
            raise ValueError(
                f"cannot place {n_faults} faults in "
                f"[{min_position}, {max_position})")
        rng = np.random.RandomState(seed)
        positions = rng.choice(
            np.arange(min_position, max_position), size=n_faults,
            replace=False,
        )
        chosen = rng.choice(len(kinds), size=n_faults)
        faults = []
        for p, k in zip(positions, chosen):
            kind = kinds[int(k)]
            param = (0.0 if kind == "migration_torn"
                     else float(rng.randint(0, replicas)))
            faults.append(Fault(kind, int(p), param))
        return cls(faults)

    @property
    def pending(self) -> list[Fault]:
        return sorted(self._pending, key=lambda f: (f.position, f.kind))

    def world_events(self) -> list[Fault]:
        """Pending world-kind faults, soonest first — the elastic
        supervisor's work queue."""
        return [f for f in self.pending if f.kind in WORLD_KINDS]

    def serve_events(self) -> list[Fault]:
        """Pending serve-kind faults, soonest first — what the engine has
        yet to absorb (tests assert this drains to [] at run end)."""
        return [f for f in self.pending if f.kind in SERVE_KINDS]

    def take_serve(self, tick: int) -> list[Fault]:
        """Consume (one-shot) the serve-kind faults due at engine tick
        ``tick``. The engine calls this at the top of every ``step`` and
        applies what comes back — the mechanism lives in the engine, the
        schedule only decides *when*, mirroring the world-kind split."""
        return self._take(tick, SERVE_KINDS)

    def fleet_events(self) -> list[Fault]:
        """Pending fleet-kind faults, soonest first — what the fleet has
        yet to absorb (tests assert this drains to [] at run end)."""
        return [f for f in self.pending if f.kind in FLEET_KINDS]

    def take_fleet(self, tick: int) -> list[Fault]:
        """Consume (one-shot) the fleet-kind faults due at fleet tick
        ``tick``.  :class:`~..serve.fleet.FleetScheduler` calls this at
        the top of every fleet ``step`` — the mechanism (crash
        reconstruction, stall exclusion, torn-handoff duplication) lives
        in the fleet, the schedule only decides *when*."""
        return self._take(tick, FLEET_KINDS)

    def fire(self, fault: Fault) -> None:
        """Mark an externally-applied fault fired (one-shot bookkeeping
        for the world kinds, whose mechanism lives in the supervisor, not
        in wrap_step/inject_data)."""
        if fault not in self._pending:
            raise ValueError(f"fault {fault} is not pending (already "
                             "fired, or never scheduled)")
        self._pending.discard(fault)
        self.fired.append(fault)
        self._record(fault)

    def _take(self, position: int, kinds: Sequence[str]) -> list[Fault]:
        # kind-sorted, NOT set-iteration order: _pending is a set and
        # Fault.kind is a str, so under hash randomization two faults
        # due at the same position would fire in a process-dependent
        # order (a torn handoff armed before vs after a same-tick crash
        # is a different storm) — sorting makes co-positioned faults
        # deterministic across processes
        due = sorted(
            (f for f in self._pending
             if f.position == position and f.kind in kinds),
            key=lambda f: (f.kind, f.param))
        for f in due:
            self._pending.discard(f)
            self.fired.append(f)
            self._record(f)
        return due

    def _record(self, fault: Fault) -> None:
        if self.recorder.enabled:
            self.recorder.emit(
                "chaos.fault", cat="chaos", actor="schedule",
                payload={"kind": fault.kind, "position": fault.position,
                         "param": fault.param, "tenant": fault.tenant},
                t=self.obs_now)

    # ---- injectors ---------------------------------------------------------

    def wrap_step(self, step_fn: Callable) -> Callable:
        """Raise :class:`ChaosInjectedError` at the scheduled step-fn
        invocation indices (counting every invocation, replays included —
        execution order under a fixed schedule is deterministic, so the
        whole faulted run is too)."""

        def chaotic_step(state, batch):
            call = self._step_calls
            self._step_calls += 1
            for f in self._take(call, STEP_KINDS):
                log.warning("chaos: injected step exception at call %d",
                            call)
                raise ChaosInjectedError(
                    f"chaos: injected step exception (call {call})")
            return step_fn(state, batch)

        return chaotic_step

    def inject_data(self, make_data: Callable[[int], Iterable], *,
                    checkpoint_dir: str | Path | None = None,
                    ) -> Callable[[int], Iterator]:
        """Wrap a ``make_data(start)`` maker: data-kind faults fire when the
        stream reaches their absolute position. Checkpoint-corruption kinds
        need ``checkpoint_dir`` (they damage the newest committed save at
        that moment — i.e. *after* the checkpoints earlier positions
        produced, which is what makes the ladder's fallback observable)."""

        def wrapped(start: int) -> Iterator:
            def gen():
                pos = start
                for batch in make_data(start):
                    for f in self._take(pos, DATA_KINDS):
                        batch = self._fire_data(f, batch, checkpoint_dir)
                    yield batch
                    pos += 1

            return gen()

        return wrapped

    def _fire_data(self, fault: Fault, batch: Any,
                   checkpoint_dir: str | Path | None) -> Any:
        log.warning("chaos: firing %s at position %d",
                    fault.kind, fault.position)
        if fault.kind == "nan_batch":
            return _poison(batch)
        if fault.kind == "iterator_stall":
            _interruptible_sleep(fault.param)
            return batch
        # ckpt_truncate / ckpt_corrupt
        if checkpoint_dir is None:
            raise ValueError(
                f"{fault.kind} fault needs inject_data(checkpoint_dir=...)")
        try:
            corrupt_checkpoint(
                checkpoint_dir,
                mode="truncate" if fault.kind == "ckpt_truncate" else "flip",
            )
        except FileNotFoundError:
            log.warning("chaos: %s at position %d found no committed "
                        "checkpoint to damage", fault.kind, fault.position)
        return batch
