from distributed_tensorflow_guide_tpu.testing.chaos import (  # noqa: F401
    ChaosInjectedError,
    Fault,
    FaultSchedule,
    corrupt_checkpoint,
)
