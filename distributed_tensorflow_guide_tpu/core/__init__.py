from distributed_tensorflow_guide_tpu.core.mesh import AXES, MeshSpec, build_mesh  # noqa: F401
from distributed_tensorflow_guide_tpu.core.dist import initialize  # noqa: F401
