"""Unified run configuration — one dataclass + CLI, identical on every host.

Reference equivalent: per-script ``tf.app.flags``/argparse with per-PROCESS
role flags (``--job_name=ps --task_index=0``) plus ports hardcoded in each
``run.sh``, and on the modern surface the ``TF_CONFIG`` env JSON parsed by
TFConfigClusterResolver
(tensorflow/python/distribute/cluster_resolver/tfconfig_cluster_resolver.py:48).

SPMD inverts this (SURVEY.md §5 config row): there are no roles, so the WHOLE
topology is ordinary config — the MeshSpec — and every host runs the same
command line. The only per-host state is what ``jax.distributed.initialize``
needs (core/dist.py), which stays in env vars because launchers own it.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from pathlib import Path
from typing import Any, Sequence

from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec


@dataclasses.dataclass(frozen=True)
class RunConfig:
    """Everything a training run needs, serializable, mesh included."""

    mesh: MeshSpec = MeshSpec()
    steps: int = 1000
    global_batch: int = 256
    lr: float = 1e-3
    seed: int = 0
    log_every: int = 100
    ckpt_dir: str | None = None
    ckpt_every: int = 500
    metrics_path: str | None = None
    tb_logdir: str | None = None
    profile_dir: str | None = None
    fake_devices: int = 0  # >0: force CPU with N virtual devices (tests/dev)
    # 1: apply the async-collective / latency-hiding libtpu flag set before
    # backend init (parallel/overlap.py XLA_OVERLAP_FLAGS) — the compiler-
    # side half of the ICI overlap layer; echoed by benches like BENCH_MODE
    xla_overlap: int = 0

    # -- CLI --------------------------------------------------------------

    @classmethod
    def parser(cls, parser: argparse.ArgumentParser | None = None
               ) -> argparse.ArgumentParser:
        p = parser or argparse.ArgumentParser(
            description="dtg-tpu run config (SPMD: same flags on every host)")
        for f in dataclasses.fields(cls):
            if f.name == "mesh":
                continue
            # `from __future__ import annotations` makes f.type a string
            typ = {"int": int, "float": float}.get(str(f.type), str)
            p.add_argument(f"--{f.name.replace('_', '-')}", type=typ,
                           default=f.default, dest=f.name)
        for ax in dataclasses.fields(MeshSpec):
            p.add_argument(f"--mesh-{ax.name}", type=int, default=ax.default,
                           dest=f"mesh_{ax.name}",
                           help=f"mesh axis {ax.name!r} size (-1 = fill)")
        return p

    @classmethod
    def from_argv(cls, argv: Sequence[str] | None = None) -> "RunConfig":
        ns = cls.parser().parse_args(argv)
        mesh = MeshSpec(**{ax.name: getattr(ns, f"mesh_{ax.name}")
                           for ax in dataclasses.fields(MeshSpec)})
        kw = {f.name: getattr(ns, f.name) for f in dataclasses.fields(cls)
              if f.name != "mesh"}
        # optional paths parse as str; treat explicit ""/"None" as unset
        for k in ("ckpt_dir", "metrics_path", "tb_logdir", "profile_dir"):
            if kw[k] in (None, "", "None"):
                kw[k] = None
        return cls(mesh=mesh, **kw)

    # -- (de)serialization -------------------------------------------------

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "RunConfig":
        d = dict(d)
        mesh = MeshSpec(**d.pop("mesh", {}))
        names = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - names
        if unknown:
            raise ValueError(f"unknown RunConfig keys: {sorted(unknown)}")
        return cls(mesh=mesh, **d)

    def save(self, path: str | Path) -> None:
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        Path(path).write_text(self.to_json())

    @classmethod
    def load(cls, path: str | Path) -> "RunConfig":
        return cls.from_dict(json.loads(Path(path).read_text()))

    # -- environment application ------------------------------------------

    def apply_platform(self) -> None:
        """Honor ``fake_devices`` and ``xla_overlap`` BEFORE importing/
        initializing jax devices."""
        if self.xla_overlap:
            from distributed_tensorflow_guide_tpu.parallel.overlap import (
                apply_xla_overlap_flags,
            )

            apply_xla_overlap_flags(True)
        if self.fake_devices:
            import os

            os.environ["JAX_PLATFORMS"] = "cpu"
            flags = os.environ.get("XLA_FLAGS", "")
            opt = f"--xla_force_host_platform_device_count={self.fake_devices}"
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = f"{flags} {opt}".strip()
