"""Multi-host bootstrap — replaces the reference's cluster-bootstrap stack.

Reference call stack (SURVEY.md §3.1): ``bash run.sh`` spawns N+1 processes,
each builds ``tf.train.ClusterSpec`` and starts an in-process gRPC
``tf.train.Server`` (tensorflow/python/training/server_lib.py:96); PS
processes block in ``server.join()`` forever; the modern surface discovers
peers from the ``TF_CONFIG`` env JSON
(tensorflow/python/distribute/cluster_resolver/tfconfig_cluster_resolver.py:48).

TPU-native: that entire stack collapses to ``jax.distributed.initialize()``
per host (jax/_src/distributed.py) — a coordinator handshake over DCN after
which every host sees the global device set and runs the *same* SPMD program.
There is no PS process and no role flag.
"""

from __future__ import annotations

import dataclasses
import logging
import os

import jax

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class DistConfig:
    """Multi-host coordination config.

    All fields optional: on TPU pods JAX auto-detects everything from the
    metadata server; on CPU/GPU clusters pass them explicitly or set
    JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID (the latter
    two are parsed by this framework via :meth:`from_env` and forwarded as
    kwargs — JAX itself only reads the coordinator address).
    """

    coordinator_address: str | None = None
    num_processes: int | None = None
    process_id: int | None = None

    @classmethod
    def from_env(cls) -> "DistConfig":
        """Read JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID.

        JAX itself only reads JAX_COORDINATOR_ADDRESS; the other two are
        this framework's convention and are parsed here and passed through
        as explicit kwargs.
        """
        nproc = os.environ.get("JAX_NUM_PROCESSES")
        pid = os.environ.get("JAX_PROCESS_ID")
        return cls(
            coordinator_address=os.environ.get("JAX_COORDINATOR_ADDRESS"),
            num_processes=int(nproc) if nproc is not None else None,
            process_id=int(pid) if pid is not None else None,
        )


_initialized = False


def retry_with_backoff(
    fn,
    *,
    attempts: int = 3,
    base_delay_s: float = 1.0,
    max_delay_s: float = 30.0,
    retry_on: tuple[type[BaseException], ...] = (RuntimeError, OSError),
    sleep=None,
    what: str = "operation",
):
    """Call ``fn()`` up to ``attempts`` times with exponential backoff.

    The coordinator handshake is the classic transient: process 0's
    listener may come up seconds after the peers dial in (the reference's
    run.sh had the same race and simply hung). Delay doubles per attempt
    from ``base_delay_s`` up to ``max_delay_s`` — deterministic, no
    jitter, so multi-process retries stay in lockstep with each other.
    The last failure re-raises unchanged.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    import time

    sleep = time.sleep if sleep is None else sleep
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:
            if attempt + 1 >= attempts:
                raise
            delay = min(base_delay_s * 2 ** attempt, max_delay_s)
            log.warning(
                "%s failed (attempt %d/%d): %s — retrying in %.1fs",
                what, attempt + 1, attempts, e, delay,
            )
            sleep(delay)


def ensure_platform_from_env(*, strict: bool = True) -> None:
    """Re-assert JAX_PLATFORMS / JAX_NUM_CPU_DEVICES from the environment.

    ``JAX_PLATFORMS=cpu python script.py`` is NOT sufficient on a machine
    with an out-of-tree PJRT plugin: plugin registration during ``import
    jax`` can override the requested platform via ``jax.config`` (config
    beats env), silently routing a "CPU" run to the accelerator — or
    hanging it when the accelerator transport is down. Measured on the
    axon-tunnel chip: only a post-import ``jax.config.update`` reliably
    pins the platform. JAX_NUM_CPU_DEVICES is re-asserted for the same
    reason (jax reads it as a flag default at import; the launcher sets it).

    Precedence: the environment wins over an in-process
    ``jax.config.update`` made before this call (matching the established
    behavior of the env-driven multi-host path). A caller that wants a
    programmatic platform choice to survive should not export
    JAX_PLATFORMS, or should re-apply its choice after initialize().
    Applied changes are logged at INFO so the reroute is visible.

    ``strict=False`` degrades an un-applicable update (a backend is already
    live) to a debug log — for opportunistic callers like the single-process
    path of :func:`initialize`, which must stay a no-op for callers that
    already touched devices.
    """
    plat = os.environ.get("JAX_PLATFORMS")
    ndev = os.environ.get("JAX_NUM_CPU_DEVICES")
    # Parse the env var OUTSIDE the config-update try block so its named
    # error can only ever mean the env var (a ValueError from the config
    # updates below would otherwise be mislabeled as a bad device count).
    ndev_int = None
    if ndev:
        try:
            ndev_int = int(ndev)
        except ValueError as e:
            # Malformed JAX_NUM_CPU_DEVICES (e.g. "4,4"): name the env var
            # in strict mode; best-effort callers ignore it like any other
            # un-applicable setting.
            if strict:
                raise ValueError(
                    f"JAX_NUM_CPU_DEVICES={ndev!r} is not an integer"
                ) from e
            log.debug("platform env not applied (malformed): %s", e)
    from distributed_tensorflow_guide_tpu.core import compat

    try:
        if plat and jax.config.jax_platforms != plat:
            log.info(
                "honoring JAX_PLATFORMS=%s from env (was %r in config)",
                plat, jax.config.jax_platforms,
            )
            jax.config.update("jax_platforms", plat)
        if ndev_int is not None:
            # compat owns the version split (config on >= 0.5, XLA flag on
            # 0.4.x) AND the failure contract: RuntimeError when a live
            # backend already fixed a different count — which the
            # strict/best-effort handling below turns into the actionable
            # message or a debug log.
            compat.apply_cpu_device_count(ndev_int)
    except RuntimeError as e:
        if strict:
            raise RuntimeError(
                "initialize() must run before any JAX backend is used: the "
                "environment requests JAX_PLATFORMS/JAX_NUM_CPU_DEVICES "
                "settings that cannot be applied after jax.devices() (or any "
                "computation) has initialized a backend. Call "
                "distributed_tensorflow_guide_tpu.core.dist.initialize() "
                "first, or clear those env vars."
            ) from e
        log.debug("platform env not applied (backend already live): %s", e)


def initialize(config: DistConfig | None = None) -> None:
    """Idempotent multi-host init. No-op for single-process runs.

    Single-process is detected when no coordinator is configured anywhere —
    the common case for tests and single-host benches.
    """
    global _initialized
    if _initialized:
        return
    # An explicitly passed config wins wholesale — env vars are only read
    # when no config is given (so stale JAX_* exports can't leak into an
    # explicit setup, and an explicit all-None config can't be promoted to a
    # multi-host handshake by the environment).
    explicit = config is not None
    config = config if explicit else DistConfig.from_env()
    coord, nproc, pid = (
        config.coordinator_address,
        config.num_processes,
        config.process_id,
    )
    # num_processes == 1 with no coordinator means "force single-process".
    # TPU_WORKER_HOSTNAMES with a single entry (e.g. "localhost" on a
    # single-host slice) is also a single-process run.
    multi_host_tpu = (not explicit) and "," in os.environ.get(
        "TPU_WORKER_HOSTNAMES", ""
    )
    if (coord is None and nproc is None and not multi_host_tpu) or (
        coord is None and nproc == 1
    ):
        # Single-process: still honor an env-requested platform, best-effort
        # (strict would break callers that already touched devices — those
        # keep the historical pure-no-op behavior). This is what makes
        # ``JAX_PLATFORMS=cpu python examples/non_distributed.py`` actually
        # run on CPU instead of being silently rerouted by the plugin.
        if not explicit:
            ensure_platform_from_env(strict=False)
        log.debug("single-process run; skipping jax.distributed.initialize")
        return
    # Env-driven multi-host path: the platform env MUST apply (the launcher
    # depends on it), so failures raise with an actionable message. An
    # explicit config keeps its no-env-leakage guarantee (comment above).
    if not explicit:
        ensure_platform_from_env(strict=True)
    from distributed_tensorflow_guide_tpu.core import compat

    if (os.environ.get("JAX_PLATFORMS", "") or "").startswith("cpu") or (
            jax.config.jax_platforms or "").startswith("cpu"):
        # CPU multi-process needs Gloo collectives, an opt-in flag on the
        # 0.4.x JAX line (the default elsewhere) — without it every
        # cross-process psum dies at dispatch
        compat.enable_cpu_cross_process_collectives()
    kwargs = {}
    if coord is not None:
        kwargs["coordinator_address"] = coord
    if nproc is not None:
        kwargs["num_processes"] = nproc
    if pid is not None:
        kwargs["process_id"] = pid
    # The handshake is retried with backoff: a coordinator that boots a few
    # seconds late (restarted chief, slow container) must not be fatal.
    # DTG_INIT_RETRIES=1 restores the old fail-immediately behavior.
    retry_with_backoff(
        lambda: jax.distributed.initialize(**kwargs),
        attempts=int(os.environ.get("DTG_INIT_RETRIES", "3")),
        base_delay_s=float(os.environ.get("DTG_INIT_BACKOFF_S", "1.0")),
        what="jax.distributed.initialize",
    )
    _initialized = True
    from distributed_tensorflow_guide_tpu.core.mesh import num_slices

    n_slices = num_slices()
    log.info(
        "distributed init: process %d/%d, %d local / %d global devices, "
        "%d slice(s)%s",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
        n_slices,
        "" if n_slices == 1 else
        " — build_mesh will lay dcn_axis across slices (DCN), all other "
        "axes within-slice (ICI)",
    )


def reinitialize(config: DistConfig | None = None) -> None:
    """Tear down and re-run the coordinator handshake — the IN-PROCESS
    elastic-resize path: after a slice loss the surviving hosts re-form
    the cluster at the new (smaller) world size, and on slice return at
    the full one. (The relaunch-based resize — fresh processes per
    generation — lives in train/elastic_world.py and does not need this;
    this is for deployments that resize without relaunching.)

    Shutdown + initialize is *more* racy than first boot — the new
    coordinator only comes up after the old incarnation's port is
    released, and peers re-dial at slightly different times — so the
    whole cycle goes through :func:`retry_with_backoff`, governed by
    ``DTG_REINIT_RETRIES`` / ``DTG_REINIT_BACKOFF_S`` (mirroring the
    ``DTG_INIT_RETRIES`` / ``DTG_INIT_BACKOFF_S`` pair of first init).
    Unlike :func:`initialize` this is NOT idempotent: every call cycles
    the handshake, because a resize by definition changes the answer.

    With no coordinator configured anywhere (single-process), the cycle
    degrades to a best-effort shutdown — there is no cluster to re-form.
    """
    global _initialized
    explicit = config is not None
    config = config if explicit else DistConfig.from_env()
    coord, nproc, pid = (
        config.coordinator_address,
        config.num_processes,
        config.process_id,
    )

    def _shutdown() -> None:
        try:
            jax.distributed.shutdown()
        except Exception as e:  # not initialized / already torn down
            log.debug("jax.distributed.shutdown before reinit: %s", e)

    # Single-process detection MUST mirror initialize(): an env-driven TPU
    # pod (auto-detected coordinator, multi-entry TPU_WORKER_HOSTNAMES)
    # re-forms the cluster too — treating it as single-process would tear
    # the cluster down and never rebuild it. An explicit all-None config
    # keeps initialize()'s no-env-promotion guarantee.
    multi_host_tpu = (not explicit) and "," in os.environ.get(
        "TPU_WORKER_HOSTNAMES", ""
    )
    if (coord is None and nproc is None and not multi_host_tpu) or (
        coord is None and nproc == 1
    ):
        _shutdown()
        _initialized = False
        log.debug("single-process reinitialize: shutdown only")
        return
    # The flag drops BEFORE the cycle: if every retry fails, a caller that
    # catches and falls back to initialize() must not hit its idempotent
    # guard while the runtime is actually torn down.
    _initialized = False
    # Same pre-handshake setup as initialize()'s multi-host path: the
    # platform env must apply for env-driven launches, and CPU
    # multi-process needs the Gloo collectives opt-in on the 0.4.x line —
    # a relaunched survivor whose FIRST distributed call is reinitialize()
    # would otherwise re-form the cluster and die on its first psum.
    if not explicit:
        ensure_platform_from_env(strict=True)
    from distributed_tensorflow_guide_tpu.core import compat

    if (os.environ.get("JAX_PLATFORMS", "") or "").startswith("cpu") or (
            jax.config.jax_platforms or "").startswith("cpu"):
        compat.enable_cpu_cross_process_collectives()
    kwargs = {}
    if coord is not None:
        kwargs["coordinator_address"] = coord
    if nproc is not None:
        kwargs["num_processes"] = nproc
    if pid is not None:
        kwargs["process_id"] = pid

    def _cycle() -> None:
        _shutdown()
        jax.distributed.initialize(**kwargs)

    retry_with_backoff(
        _cycle,
        attempts=int(os.environ.get("DTG_REINIT_RETRIES", "3")),
        base_delay_s=float(os.environ.get("DTG_REINIT_BACKOFF_S", "1.0")),
        what="coordinator re-initialize (elastic resize)",
    )
    _initialized = True
    log.info(
        "elastic reinitialize: process %d/%d, %d global devices",
        jax.process_index(), jax.process_count(), jax.device_count(),
    )


def is_chief() -> bool:
    """Process 0 — the one that writes checkpoints/logs.

    Reference equivalent: ``is_chief=(task_index == 0)`` passed to
    ``MonitoredTrainingSession`` (tensorflow/python/training/monitored_session.py:428).
    Unlike the reference, chief-ness here affects only host-side IO; the
    device program is identical on every host.
    """
    return jax.process_index() == 0
