"""Multi-host bootstrap — replaces the reference's cluster-bootstrap stack.

Reference call stack (SURVEY.md §3.1): ``bash run.sh`` spawns N+1 processes,
each builds ``tf.train.ClusterSpec`` and starts an in-process gRPC
``tf.train.Server`` (tensorflow/python/training/server_lib.py:96); PS
processes block in ``server.join()`` forever; the modern surface discovers
peers from the ``TF_CONFIG`` env JSON
(tensorflow/python/distribute/cluster_resolver/tfconfig_cluster_resolver.py:48).

TPU-native: that entire stack collapses to ``jax.distributed.initialize()``
per host (jax/_src/distributed.py) — a coordinator handshake over DCN after
which every host sees the global device set and runs the *same* SPMD program.
There is no PS process and no role flag.
"""

from __future__ import annotations

import dataclasses
import logging
import os

import jax

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class DistConfig:
    """Multi-host coordination config.

    All fields optional: on TPU pods JAX auto-detects everything from the
    metadata server; on CPU/GPU clusters pass them explicitly or set
    JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID (the latter
    two are parsed by this framework via :meth:`from_env` and forwarded as
    kwargs — JAX itself only reads the coordinator address).
    """

    coordinator_address: str | None = None
    num_processes: int | None = None
    process_id: int | None = None

    @classmethod
    def from_env(cls) -> "DistConfig":
        """Read JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID.

        JAX itself only reads JAX_COORDINATOR_ADDRESS; the other two are
        this framework's convention and are parsed here and passed through
        as explicit kwargs.
        """
        nproc = os.environ.get("JAX_NUM_PROCESSES")
        pid = os.environ.get("JAX_PROCESS_ID")
        return cls(
            coordinator_address=os.environ.get("JAX_COORDINATOR_ADDRESS"),
            num_processes=int(nproc) if nproc is not None else None,
            process_id=int(pid) if pid is not None else None,
        )


_initialized = False


def initialize(config: DistConfig | None = None) -> None:
    """Idempotent multi-host init. No-op for single-process runs.

    Single-process is detected when no coordinator is configured anywhere —
    the common case for tests and single-host benches.
    """
    global _initialized
    if _initialized:
        return
    # An explicitly passed config wins wholesale — env vars are only read
    # when no config is given (so stale JAX_* exports can't leak into an
    # explicit setup, and an explicit all-None config can't be promoted to a
    # multi-host handshake by the environment).
    explicit = config is not None
    config = config if explicit else DistConfig.from_env()
    coord, nproc, pid = (
        config.coordinator_address,
        config.num_processes,
        config.process_id,
    )
    # num_processes == 1 with no coordinator means "force single-process".
    # TPU_WORKER_HOSTNAMES with a single entry (e.g. "localhost" on a
    # single-host slice) is also a single-process run.
    multi_host_tpu = (not explicit) and "," in os.environ.get(
        "TPU_WORKER_HOSTNAMES", ""
    )
    if (coord is None and nproc is None and not multi_host_tpu) or (
        coord is None and nproc == 1
    ):
        log.debug("single-process run; skipping jax.distributed.initialize")
        return
    # Re-assert the env-requested platform/device-count post-import: PJRT
    # plugins (e.g. the local axon TPU plugin) can override JAX_PLATFORMS
    # during `import jax`, and JAX_NUM_CPU_DEVICES is this framework's env
    # convention (the launcher sets it), not a flag JAX reads itself. Done
    # only on the env-driven multi-host path: single-process calls stay pure
    # no-ops (config.update raises once backends are live), and an explicit
    # config keeps its no-env-leakage guarantee (comment above).
    if not explicit:
        # config.update raises RuntimeError once any backend is live (e.g.
        # user code touched jax.devices() before calling initialize()). Skip
        # updates that already match, and turn the remaining failure into an
        # actionable message instead of a bare RuntimeError.
        plat = os.environ.get("JAX_PLATFORMS")
        ndev = os.environ.get("JAX_NUM_CPU_DEVICES")
        try:
            if plat and jax.config.jax_platforms != plat:
                jax.config.update("jax_platforms", plat)
            if ndev and jax.config.jax_num_cpu_devices != int(ndev):
                jax.config.update("jax_num_cpu_devices", int(ndev))
        except RuntimeError as e:
            raise RuntimeError(
                "initialize() must run before any JAX backend is used: the "
                "environment requests JAX_PLATFORMS/JAX_NUM_CPU_DEVICES "
                "settings that cannot be applied after jax.devices() (or any "
                "computation) has initialized a backend. Call "
                "distributed_tensorflow_guide_tpu.core.dist.initialize() "
                "first, or clear those env vars."
            ) from e
    kwargs = {}
    if coord is not None:
        kwargs["coordinator_address"] = coord
    if nproc is not None:
        kwargs["num_processes"] = nproc
    if pid is not None:
        kwargs["process_id"] = pid
    jax.distributed.initialize(**kwargs)
    _initialized = True
    log.info(
        "distributed init: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def is_chief() -> bool:
    """Process 0 — the one that writes checkpoints/logs.

    Reference equivalent: ``is_chief=(task_index == 0)`` passed to
    ``MonitoredTrainingSession`` (tensorflow/python/training/monitored_session.py:428).
    Unlike the reference, chief-ness here affects only host-side IO; the
    device program is identical on every host.
    """
    return jax.process_index() == 0
