"""JAX version compatibility seam.

The framework targets the JAX 0.9 surface (``jax.shard_map`` with
``check_vma``, the ``jax_num_cpu_devices`` config) but must also run on the
0.4.x line installed in some environments, where the same capabilities live
under ``jax.experimental.shard_map.shard_map(check_rep=...)`` and the CPU
device count is only settable via ``XLA_FLAGS=--xla_force_host_platform_
device_count`` before backend init. Every version-sensitive call goes
through this module so the rest of the codebase is written once, against
the modern names.
"""

from __future__ import annotations

import logging
import os

import jax

log = logging.getLogger("dtg.compat")

_HAS_SHARD_MAP = hasattr(jax, "shard_map")        # jax >= 0.6
_HAS_NUM_CPU_CONFIG = hasattr(jax.config, "jax_num_cpu_devices")  # >= 0.5

if not _HAS_SHARD_MAP:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the modern keyword surface on every JAX.

    ``check_vma`` (varying-manual-axes checking, 0.9) and ``check_rep``
    (replication checking, 0.4) gate the same machinery — static validation
    of per-axis replication of shard_map outputs; the framework always
    passes ``check_vma=False`` where collectives are explicit, which maps
    to ``check_rep=False`` exactly.
    """
    if _HAS_SHARD_MAP:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=check_vma)


def num_cpu_devices_config_supported() -> bool:
    """Whether ``jax.config.update("jax_num_cpu_devices", n)`` exists."""
    return _HAS_NUM_CPU_CONFIG


def set_cpu_device_count(n: int, *, pre_import_env: bool = True) -> None:
    """Request ``n`` virtual CPU devices, whichever way this JAX supports.

    On ≥0.5 this is the ``jax_num_cpu_devices`` config (appliable any time
    before backend init). On 0.4.x the only mechanism is the
    ``--xla_force_host_platform_device_count`` XLA flag, which the CPU
    client reads from the environment when it is created — so this must run
    before the first ``jax.devices()``/computation. Callers that can set
    the environment before ``import jax`` (launchers, conftest) should
    still do that too (``pre_import_env``); this function is the
    post-import half.
    """
    if _HAS_NUM_CPU_CONFIG:
        jax.config.update("jax_num_cpu_devices", n)
        return
    if pre_import_env:
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        flag = f"--xla_force_host_platform_device_count={n}"
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags)
        else:
            flags = (flags + " " + flag).strip()
        os.environ["XLA_FLAGS"] = flags


def apply_cpu_device_count(n: int) -> None:
    """Like :func:`set_cpu_device_count`, but with the modern config's
    failure contract on every JAX: raises ``RuntimeError`` when a live
    backend has already fixed a DIFFERENT device count (on ≥0.5 the config
    update itself raises; on 0.4.x the XLA flag would just be silently
    ignored, so the liveness check reproduces the error).
    """
    if _HAS_NUM_CPU_CONFIG:
        if jax.config.jax_num_cpu_devices != n:
            jax.config.update("jax_num_cpu_devices", n)
        return
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        if len(jax.devices()) != n:
            raise RuntimeError(
                f"cannot apply a CPU device count of {n}: a backend with "
                f"{len(jax.devices())} devices is already initialized and "
                "this JAX has no jax_num_cpu_devices config")
        return
    set_cpu_device_count(n)


def device_put_global(tree, shardings):
    """``jax.device_put`` onto shardings that may span NON-addressable
    devices (multi-process global meshes).

    Newer JAX accepts such shardings directly; the 0.4.x jaxlib refuses
    ("must represent addressable devices"). The fallback rebuilds each leaf
    as a global array via ``make_array_from_callback``, which materializes
    only this process's addressable shards — requiring the leaf to be
    host-materializable (host value, or an array this process can read),
    true for the replicated init/state flows that need this. A leaf that
    already IS a global array with an equivalent sharding passes through
    untouched (re-placement would be a no-op anyway).

    ``shardings`` is a single sharding (applied to every leaf) or a
    matching pytree, as with ``jax.device_put``.
    """
    import numpy as np

    one_sharding = isinstance(shardings, jax.sharding.Sharding)

    def _one(x, s):
        try:
            return jax.device_put(x, s)
        except ValueError:
            if (isinstance(x, jax.Array)
                    and x.sharding.is_equivalent_to(s, x.ndim)):
                return x
            arr = np.asarray(x)
            return jax.make_array_from_callback(
                arr.shape, s, lambda idx: arr[idx])

    if one_sharding:
        return jax.tree.map(lambda x: _one(x, shardings), tree)
    return jax.tree.map(_one, tree, shardings)


def enable_cpu_cross_process_collectives() -> None:
    """Gloo-backed cross-process CPU collectives.

    Newer JAX wires these up by itself; the 0.4.x line ships them behind
    ``jax_cpu_collectives_implementation`` (default "none" — a
    multi-process psum then fails with "Multiprocess computations aren't
    implemented on the CPU backend"). Must run before the CPU client is
    created. No-op where the config has been removed.
    """
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass
    # the env var reaches CHILD processes that build their own client
    os.environ.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")
