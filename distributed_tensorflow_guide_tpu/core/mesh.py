"""Device-mesh construction — the TPU-native replacement for the reference's
cluster topology.

Reference equivalent: ``tf.train.ClusterSpec({"ps": [...], "worker": [...]})``
(tensorflow/python/training/server_lib.py:243) plus device placement via
``tf.train.replica_device_setter`` (tensorflow/python/training/device_setter.py:129).
The reference wires up a *role-typed* cluster: parameter-server tasks hold
variables, worker tasks compute.

On TPU there are no roles. Topology is a single ``jax.sharding.Mesh`` with
five named logical axes:

    data     — data parallelism (sync allreduce; replaces PS/worker split)
    model    — tensor parallelism (param sharding; Megatron-style)
    pipe     — pipeline parallelism (stage sharding + ppermute microbatches)
    context  — sequence/context parallelism (ring attention KV rotation)
    expert   — expert parallelism (MoE all_to_all token routing)

Axis sizes are *config*, not process roles: every host runs the same program
with the same MeshSpec (SPMD), and XLA lays collectives onto the ICI torus.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical logical axis order. Order matters for ICI locality under
# create_device_mesh: later (inner) axes — pipe/context/expert here — get the
# tightest physical rings. model sits second-outermost; configs that need
# nearest-neighbor tensor-parallel rings should keep the trailing axes at 1
# (size-1 dims are free) so model becomes the effective innermost axis.
AXES = ("data", "model", "pipe", "context", "expert")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. ``-1`` means "fill with the remaining devices".

    The reference encodes topology as per-process CLI flags
    (``--job_name=ps --task_index=0`` ...) plus a bash launcher; here the
    whole topology is this one value, identical on every host.
    """

    data: int = -1
    model: int = 1
    pipe: int = 1
    context: int = 1
    expert: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        """Resolve -1 entries against the device count; validate the product."""
        sizes = {a: getattr(self, a) for a in AXES}
        for a, s in sizes.items():
            if s != -1 and s < 1:
                raise ValueError(f"axis {a!r} size must be -1 or >= 1, got {s}")
        fills = [a for a, s in sizes.items() if s == -1]
        if len(fills) > 1:
            raise ValueError(f"at most one axis may be -1, got {fills}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if fills:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[fills[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {math.prod(sizes.values())} devices, "
                f"have {n_devices}"
            )
        return sizes


def build_mesh(
    spec: MeshSpec | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` over ``devices`` (default: all).

    Uses ``mesh_utils.create_device_mesh`` when possible so the logical mesh
    maps onto the physical ICI torus with nearest-neighbor rings per axis
    (critical for ppermute/psum bandwidth); falls back to a plain reshape on
    backends with no topology info (CPU fake devices in tests).
    """
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXES)
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception as e:
        # On a real pod slice this fallback loses ICI-neighbor placement, so
        # warn loudly there; on CPU ordering is meaningless, so log quietly.
        import logging

        lg = logging.getLogger(__name__)
        level = (
            logging.DEBUG
            if devices and devices[0].platform == "cpu"
            else logging.WARNING
        )
        lg.log(
            level,
            "create_device_mesh failed (%s); falling back to reshape "
            "ordering — logical axes may not map to ICI neighbors",
            e,
        )
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    """An all-ones (1x1x1x1x1) mesh — the Non-Distributed-Setup control
    (reference R2)."""
    device = device or jax.devices()[0]
    return Mesh(np.asarray([device]).reshape((1,) * len(AXES)), AXES)


def axis_sizes(mesh: Mesh) -> dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))
