"""Device-mesh construction — the TPU-native replacement for the reference's
cluster topology.

Reference equivalent: ``tf.train.ClusterSpec({"ps": [...], "worker": [...]})``
(tensorflow/python/training/server_lib.py:243) plus device placement via
``tf.train.replica_device_setter`` (tensorflow/python/training/device_setter.py:129).
The reference wires up a *role-typed* cluster: parameter-server tasks hold
variables, worker tasks compute.

On TPU there are no roles. Topology is a single ``jax.sharding.Mesh`` with
five named logical axes:

    data     — data parallelism (sync allreduce; replaces PS/worker split)
    model    — tensor parallelism (param sharding; Megatron-style)
    pipe     — pipeline parallelism (stage sharding + ppermute microbatches)
    context  — sequence/context parallelism (ring attention KV rotation)
    expert   — expert parallelism (MoE all_to_all token routing)

Axis sizes are *config*, not process roles: every host runs the same program
with the same MeshSpec (SPMD), and XLA lays collectives onto the ICI torus.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical logical axis order. Order matters for ICI locality under
# create_device_mesh: later (inner) axes — pipe/context/expert here — get the
# tightest physical rings. model sits second-outermost; configs that need
# nearest-neighbor tensor-parallel rings should keep the trailing axes at 1
# (size-1 dims are free) so model becomes the effective innermost axis.
AXES = ("data", "model", "pipe", "context", "expert")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. ``-1`` means "fill with the remaining devices".

    The reference encodes topology as per-process CLI flags
    (``--job_name=ps --task_index=0`` ...) plus a bash launcher; here the
    whole topology is this one value, identical on every host.
    """

    data: int = -1
    model: int = 1
    pipe: int = 1
    context: int = 1
    expert: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        """Resolve -1 entries against the device count; validate the product."""
        sizes = {a: getattr(self, a) for a in AXES}
        for a, s in sizes.items():
            if s != -1 and s < 1:
                raise ValueError(f"axis {a!r} size must be -1 or >= 1, got {s}")
        fills = [a for a, s in sizes.items() if s == -1]
        if len(fills) > 1:
            raise ValueError(f"at most one axis may be -1, got {fills}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if fills:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[fills[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {math.prod(sizes.values())} devices, "
                f"have {n_devices}"
            )
        return sizes


def num_slices(devices: Sequence[jax.Device] | None = None) -> int:
    """Number of TPU slices the devices span (1 on single-slice / CPU).

    Multi-slice (Megascale / multi-pod) deployments expose
    ``device.slice_index``; within a slice links are ICI, across slices
    they are DCN — orders of magnitude slower, so the mesh layout must put
    exactly one low-traffic axis across that boundary."""
    devices = list(devices if devices is not None else jax.devices())
    return len({getattr(d, "slice_index", 0) for d in devices})


def _slice_groups(devices: Sequence) -> list[list]:
    groups: dict[int, list] = {}
    for d in devices:
        groups.setdefault(getattr(d, "slice_index", 0), []).append(d)
    return [groups[k] for k in sorted(groups)]


def valid_slice_counts(sizes: dict[str, int], dcn_axis: str = "data") -> list[int]:
    """Slice counts a ``dcn_axis`` of this size can span: its divisors.

    The programmatic answer to :func:`hybrid_device_array`'s divisibility
    error — callers picking a deployment shape (or an elastic supervisor
    deciding which reduced worlds are reachable) can query instead of
    parsing an exception message."""
    if dcn_axis not in AXES:
        raise ValueError(f"dcn_axis must be one of {AXES}, got {dcn_axis!r}")
    n = sizes[dcn_axis]
    return [k for k in range(1, n + 1) if n % k == 0]


def hybrid_device_array(
    sizes: dict[str, int],
    devices: Sequence,
    n_slices: int,
    dcn_axis: str = "data",
):
    """Device array for a multi-slice mesh: ``dcn_axis`` factors as
    (slice, within-slice) with the slice-spanning part OUTERMOST, every
    other axis entirely within a slice — so ``model``/``pipe``/``context``
    /``expert`` neighbors (and the within-slice part of ``data``) ride
    ICI, and only ``dcn_axis``'s outer loop crosses DCN.

    Prefers ``mesh_utils.create_hybrid_device_mesh`` (ICI-aware per-slice
    layout); falls back to per-slice reshape + stack when topology info is
    unavailable (fake/test devices) — slice grouping is preserved either
    way, which is the property that matters for DCN traffic.
    """
    if dcn_axis not in AXES:
        raise ValueError(f"dcn_axis must be one of {AXES}, got {dcn_axis!r}")
    if sizes[dcn_axis] % n_slices:
        raise ValueError(
            f"{n_slices} slices need axis {dcn_axis!r} divisible by the "
            f"slice count, got {sizes[dcn_axis]} — either resize "
            f"{dcn_axis!r} or pick another dcn_axis (axis {dcn_axis!r} "
            f"supports slice counts {valid_slice_counts(sizes, dcn_axis)}; "
            "see valid_slice_counts())"
        )
    per_slice = dict(sizes)
    per_slice[dcn_axis] //= n_slices
    inner = tuple(per_slice[a] for a in AXES)
    dcn = tuple(n_slices if a == dcn_axis else 1 for a in AXES)
    try:
        from jax.experimental import mesh_utils

        return mesh_utils.create_hybrid_device_mesh(
            inner, dcn, devices=list(devices)
        )
    except Exception as e:
        import logging

        logging.getLogger(__name__).warning(
            "create_hybrid_device_mesh failed (%s); falling back to "
            "per-slice reshape — slice grouping kept, per-slice ICI "
            "ordering may be suboptimal", e,
        )
        groups = _slice_groups(devices)
        arrs = [np.asarray(g, dtype=object).reshape(inner) for g in groups]
        return np.concatenate(arrs, axis=AXES.index(dcn_axis))


def build_mesh(
    spec: MeshSpec | None = None,
    devices: Sequence[jax.Device] | None = None,
    *,
    dcn_axis: str = "data",
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` over ``devices`` (default: all).

    Uses ``mesh_utils.create_device_mesh`` when possible so the logical mesh
    maps onto the physical ICI torus with nearest-neighbor rings per axis
    (critical for ppermute/psum bandwidth); falls back to a plain reshape on
    backends with no topology info (CPU fake devices in tests).

    Multi-slice deployments (``num_slices() > 1``) get the hybrid layout:
    ``dcn_axis`` (default ``data`` — one gradient allreduce per step is
    the cheapest thing to put on the slow network) spans slices, all other
    axes stay inside a slice on ICI. Without this, a naive reshape would
    silently scatter ``model``/``pipe`` neighbors across DCN.
    """
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.resolve(len(devices))
    n_slices = num_slices(devices)
    if n_slices > 1:
        return Mesh(
            hybrid_device_array(sizes, devices, n_slices, dcn_axis), AXES
        )
    shape = tuple(sizes[a] for a in AXES)
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception as e:
        # On a real pod slice this fallback loses ICI-neighbor placement, so
        # warn loudly there; on CPU ordering is meaningless, so log quietly.
        import logging

        lg = logging.getLogger(__name__)
        level = (
            logging.DEBUG
            if devices and devices[0].platform == "cpu"
            else logging.WARNING
        )
        lg.log(
            level,
            "create_device_mesh failed (%s); falling back to reshape "
            "ordering — logical axes may not map to ICI neighbors",
            e,
        )
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    """An all-ones (1x1x1x1x1) mesh — the Non-Distributed-Setup control
    (reference R2)."""
    device = device or jax.devices()[0]
    return Mesh(np.asarray([device]).reshape((1,) * len(AXES)), AXES)


def axis_sizes(mesh: Mesh) -> dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))
