"""Explicit mixed-precision policy — one object instead of scattered dtypes.

Until round 8 every model picked its dtypes ad hoc: the transformer configs
hand-set ``dtype=jnp.bfloat16`` (or f32 for CPU tests), the LM heads pinned
their Dense to f32, ResNet passed ``dtype`` separately, and remat was a bare
bool. This module names the contract those choices were all approximating —
**params f32 / activations bf16 / loss+accum f32** (the TPU-native mixed
precision every judged config trains under) — and threads it through the
strategy classes as ONE value:

* :class:`Policy` — (param_dtype, compute_dtype, accum_dtype, remat), where
  ``remat`` is the SELECTIVE knob: ``"none"`` stores every intermediate,
  ``"attention"`` checkpoints only the attention sub-layer (recompute the
  cheap/high-traffic part, keep the MLP activations), ``"block"`` is the
  classic full-block checkpoint (max HBM relief, +1 forward of re-FLOPs).
  ``models/transformer.py`` consumes it via ``TransformerConfig.remat_mode``;
  the pipeline's ``_stage_apply`` applies the block-level variant per
  schedule (1F1B already recomputes per stage, so "block" stays a no-op
  there — the existing contract).
* presets (:data:`PRESETS`) so benches/examples say ``--precision bf16``
  instead of re-deriving dtype tuples: ``f32``, ``bf16``, ``bf16_remat``,
  ``bf16_remat_attn``.
* :func:`resolve` accepts a preset name, a Policy, or None-with-default —
  the strategy-class entry point (``PipelinedLM(precision=...)``,
  ``SwitchLM(precision=...)``).

The policy deliberately does NOT touch the loss/accumulation dtype of the
existing paths — those are already f32 by construction (f32 head Dense,
f32 grad accumulators in the 1F1B tick loop, f32 ``preferred_element_type``
in the fused CE chunks); ``accum_dtype`` names that contract in one place.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp

REMAT_MODES = ("none", "attention", "block")


@dataclasses.dataclass(frozen=True)
class Policy:
    """One mixed-precision + rematerialization contract."""

    name: str
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    accum_dtype: Any = jnp.float32
    remat: str = "none"
    # AQT-style int8 matmuls (ops/quant.int8_ste_dot): the projection
    # contractions quantize both operands per-tensor dynamically each
    # step, run int8 x int8 -> int32, and backpropagate straight-through.
    # Params stay f32 masters (param_dtype), the head/loss stays
    # accum_dtype — only the MXU-bound dots change representation.
    quantized_matmuls: bool = False
    # fp8 matmuls (ops/quant.fp8_ste_dot, round 21): same STE discipline
    # as int8 — per-tensor dynamic scales, f32 accumulation, straight-
    # through backward — with e4m3 operands instead of int8. Only the
    # MXU-native mode on fp8-capable TPU generations; see require_fp8.
    fp8_matmuls: bool = False

    def __post_init__(self):
        if self.remat not in REMAT_MODES:
            raise ValueError(
                f"remat must be one of {REMAT_MODES}, got {self.remat!r}")
        if self.quantized_matmuls and self.fp8_matmuls:
            raise ValueError(
                "quantized_matmuls and fp8_matmuls are exclusive — one "
                "quantized representation per policy")

    def apply_to_transformer(self, cfg):
        """A TransformerConfig re-expressed under this policy: activation
        dtype = compute_dtype, remat mode threaded through ``remat_mode``
        (with the legacy bool kept consistent for old call sites), int8 /
        fp8 training matmuls through ``quantized_matmuls`` /
        ``fp8_matmuls``."""
        import dataclasses as _dc

        return _dc.replace(
            cfg, dtype=self.compute_dtype,
            remat=self.remat == "block", remat_mode=self.remat,
            quantized_matmuls=self.quantized_matmuls,
            fp8_matmuls=self.fp8_matmuls)


PRESETS: dict[str, Policy] = {
    # everything f32 — the CPU-test / numerics-oracle policy
    "f32": Policy("f32", compute_dtype=jnp.float32),
    # the TPU default every judged config already trains under
    "bf16": Policy("bf16"),
    # + full-block checkpointing (the old remat=True)
    "bf16_remat": Policy("bf16_remat", remat="block"),
    # + attention-only checkpointing: recompute the high-traffic sub-layer,
    # keep the MLP activations resident — the middle of the HBM/FLOP trade
    "bf16_remat_attn": Policy("bf16_remat_attn", remat="attention"),
    # AQT-style int8 training matmuls: f32 masters, f32 non-matmul compute
    # (so CPU parity runs isolate the quantizer — the only delta vs "f32"
    # is the int8 contraction), per-tensor dynamic scales, straight-through
    # gradients. The loss-parity pins in tests/test_quant.py train this
    # preset against "f32" step-for-step.
    "int8": Policy("int8", compute_dtype=jnp.float32,
                   quantized_matmuls=True),
    # fp8 training matmuls (round 21): the same isolation discipline as
    # "int8" — f32 masters, f32 non-matmul compute, so the only delta vs
    # "f32" is the e4m3 contraction — with the fp8_ste_dot quantizer.
    # Gate with require_fp8() before building device programs: pre-fp8
    # TPU generations silently emulate e4m3 through f32 convert pairs,
    # which costs MORE than bf16 while looking like a win.
    "fp8": Policy("fp8", compute_dtype=jnp.float32, fp8_matmuls=True),
}


def resolve(policy, default: str = "bf16") -> Policy:
    """A Policy from a preset name, a Policy, or None (-> ``default``)."""
    if policy is None:
        policy = default
    if isinstance(policy, Policy):
        return policy
    try:
        return PRESETS[str(policy)]
    except KeyError:
        raise ValueError(
            f"unknown precision policy {policy!r} "
            f"(presets: {sorted(PRESETS)})") from None


# --------------------------------------------------------------------------
# fp8 capability gate (round 21)
# --------------------------------------------------------------------------

#: device_kind substrings of TPU generations with native fp8 MXU modes.
#: Older generations (and CPU/GPU backends this repo doesn't model) still
#: EXECUTE e4m3 programs — XLA legalizes through f32 convert pairs — but
#: that emulation reads the same bytes as f32 and burns extra converts, so
#: "runs" must not be confused with "capable". Matched case-insensitively
#: against ``jax.devices()[0].device_kind``.
FP8_DEVICE_KINDS = ("v6", "v7", "trillium")

#: Escape hatch: set to force fp8_capable() True — for numerics work on
#: CPU/older hardware where the (slow) emulated semantics are the point.
FP8_EMULATE_ENV = "DTG_FP8_EMULATE"


def fp8_capable(device_kind: str | None = None) -> bool:
    """Whether ``device_kind`` (default: this process's device 0) has a
    native fp8 MXU mode. With :data:`FP8_EMULATE_ENV` set truthy, always
    True — the explicit "I want the emulation" override."""
    import os

    raw = os.environ.get(FP8_EMULATE_ENV, "").strip().lower()
    if raw not in ("", "0", "false", "no", "off"):
        return True
    if device_kind is None:
        import jax

        device_kind = jax.devices()[0].device_kind
    kind = device_kind.lower()
    return any(s in kind for s in FP8_DEVICE_KINDS)


def require_fp8(device_kind: str | None = None) -> None:
    """Fail fast (ValueError) when fp8 is requested on a device generation
    without native fp8 matmuls — emulated fp8 is a net loss there, so
    silently proceeding would invert the point of the preset."""
    if fp8_capable(device_kind):
        return
    if device_kind is None:
        import jax

        device_kind = jax.devices()[0].device_kind
    raise ValueError(
        f"fp8 requested but device_kind {device_kind!r} has no native fp8 "
        f"matmul mode (capable kinds match {FP8_DEVICE_KINDS}). XLA would "
        "emulate e4m3 through f32 converts — same HBM bytes as f32 plus "
        "extra convert work, strictly worse than bf16. Use precision "
        "'bf16'/'int8' (or weight_dtype='int8' for decode) here, or set "
        f"{FP8_EMULATE_ENV}=1 to force the emulation for numerics work.")
