"""Explicit mixed-precision policy — one object instead of scattered dtypes.

Until round 8 every model picked its dtypes ad hoc: the transformer configs
hand-set ``dtype=jnp.bfloat16`` (or f32 for CPU tests), the LM heads pinned
their Dense to f32, ResNet passed ``dtype`` separately, and remat was a bare
bool. This module names the contract those choices were all approximating —
**params f32 / activations bf16 / loss+accum f32** (the TPU-native mixed
precision every judged config trains under) — and threads it through the
strategy classes as ONE value:

* :class:`Policy` — (param_dtype, compute_dtype, accum_dtype, remat), where
  ``remat`` is the SELECTIVE knob: ``"none"`` stores every intermediate,
  ``"attention"`` checkpoints only the attention sub-layer (recompute the
  cheap/high-traffic part, keep the MLP activations), ``"block"`` is the
  classic full-block checkpoint (max HBM relief, +1 forward of re-FLOPs).
  ``models/transformer.py`` consumes it via ``TransformerConfig.remat_mode``;
  the pipeline's ``_stage_apply`` applies the block-level variant per
  schedule (1F1B already recomputes per stage, so "block" stays a no-op
  there — the existing contract).
* presets (:data:`PRESETS`) so benches/examples say ``--precision bf16``
  instead of re-deriving dtype tuples: ``f32``, ``bf16``, ``bf16_remat``,
  ``bf16_remat_attn``.
* :func:`resolve` accepts a preset name, a Policy, or None-with-default —
  the strategy-class entry point (``PipelinedLM(precision=...)``,
  ``SwitchLM(precision=...)``).

The policy deliberately does NOT touch the loss/accumulation dtype of the
existing paths — those are already f32 by construction (f32 head Dense,
f32 grad accumulators in the 1F1B tick loop, f32 ``preferred_element_type``
in the fused CE chunks); ``accum_dtype`` names that contract in one place.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp

REMAT_MODES = ("none", "attention", "block")


@dataclasses.dataclass(frozen=True)
class Policy:
    """One mixed-precision + rematerialization contract."""

    name: str
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    accum_dtype: Any = jnp.float32
    remat: str = "none"
    # AQT-style int8 matmuls (ops/quant.int8_ste_dot): the projection
    # contractions quantize both operands per-tensor dynamically each
    # step, run int8 x int8 -> int32, and backpropagate straight-through.
    # Params stay f32 masters (param_dtype), the head/loss stays
    # accum_dtype — only the MXU-bound dots change representation.
    quantized_matmuls: bool = False

    def __post_init__(self):
        if self.remat not in REMAT_MODES:
            raise ValueError(
                f"remat must be one of {REMAT_MODES}, got {self.remat!r}")

    def apply_to_transformer(self, cfg):
        """A TransformerConfig re-expressed under this policy: activation
        dtype = compute_dtype, remat mode threaded through ``remat_mode``
        (with the legacy bool kept consistent for old call sites), int8
        training matmuls through ``quantized_matmuls``."""
        import dataclasses as _dc

        return _dc.replace(
            cfg, dtype=self.compute_dtype,
            remat=self.remat == "block", remat_mode=self.remat,
            quantized_matmuls=self.quantized_matmuls)


PRESETS: dict[str, Policy] = {
    # everything f32 — the CPU-test / numerics-oracle policy
    "f32": Policy("f32", compute_dtype=jnp.float32),
    # the TPU default every judged config already trains under
    "bf16": Policy("bf16"),
    # + full-block checkpointing (the old remat=True)
    "bf16_remat": Policy("bf16_remat", remat="block"),
    # + attention-only checkpointing: recompute the high-traffic sub-layer,
    # keep the MLP activations resident — the middle of the HBM/FLOP trade
    "bf16_remat_attn": Policy("bf16_remat_attn", remat="attention"),
    # AQT-style int8 training matmuls: f32 masters, f32 non-matmul compute
    # (so CPU parity runs isolate the quantizer — the only delta vs "f32"
    # is the int8 contraction), per-tensor dynamic scales, straight-through
    # gradients. The loss-parity pins in tests/test_quant.py train this
    # preset against "f32" step-for-step.
    "int8": Policy("int8", compute_dtype=jnp.float32,
                   quantized_matmuls=True),
}


def resolve(policy, default: str = "bf16") -> Policy:
    """A Policy from a preset name, a Policy, or None (-> ``default``)."""
    if policy is None:
        policy = default
    if isinstance(policy, Policy):
        return policy
    try:
        return PRESETS[str(policy)]
    except KeyError:
        raise ValueError(
            f"unknown precision policy {policy!r} "
            f"(presets: {sorted(PRESETS)})") from None
