"""Tensor parallelism via pjit/NamedSharding — judged config 3: "BERT-base
GLUE under ParameterServerStrategy → pjit param-sharded" (BASELINE.md).

Reference context: ParameterServerStrategyV2
(tensorflow/python/distribute/parameter_server_strategy_v2.py:77) shards
*whole variables* round-robin across PS tasks and moves them over gRPC every
step. The TPU inversion shards *inside* each tensor over the ``model`` mesh
axis (Megatron factorization, annotated in models/transformer.py), keeps
every shard pinned in its chip's HBM, and lets XLA insert the allreduces
where the math needs them — communication becomes a property of the program,
not of parameter placement.

The GSPMD contract: we only (1) lay out params per the logical rules,
(2) shard the batch over ``data``, (3) constrain activations inside the
model; the compiler derives every collective.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
from flax.linen import spmd
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_guide_tpu.utils.activation_sharding import (
    activation_mesh,
)
from distributed_tensorflow_guide_tpu.utils.spec_utils import assign_by_shape

# logical axis name -> mesh axis (None = replicated)
#
# Activation constraints are BINDING here (round-3 verdict weak 4 fixed):
# the model's constraint sites route through models/transformer.py
# ``_constrain``, and make_train_step traces the loss inside
# ``activation_mesh(self.mesh)`` — with an explicit mesh,
# nn.with_logical_constraint lowers to a real
# jax.lax.with_sharding_constraint even under the legacy `with mesh:`
# context this strategy must use. (jax.set_mesh would also bind them, but
# it breaks flax's DenseGeneral + with_logical_partitioning boxing —
# rank-2 flat kernel vs rank-4 logical names — which is why the legacy
# context stays.) tests/test_tensor_parallel.py pins bindingness: a rules
# change alters the compiled HLO.
DEFAULT_RULES = (
    ("batch", "data"),
    ("seq", None),       # residual-stream sequence: unsharded under pure
                         # TP; MEGATRON_SP_RULES maps it to "model"
    ("seq_inner", None), # sequence INSIDE attn/mlp sub-layers: always
                         # full (attention needs every key position)
    ("embed", None),
    ("qkv", None),
    ("mlp", "model"),
    ("heads", "model"),
    ("kv", None),
    ("vocab", "model"),
)

# Megatron sequence parallelism (Korthikanti et al. 2022): between the
# TP-parallel sub-layers the residual stream — and with it LayerNorm and
# the residual adds — is sharded along SEQUENCE over the same "model"
# axis; GSPMD places the all-gather (into the column-parallel matmuls)
# and reduce-scatter (out of the row-parallel ones) at the boundaries,
# replacing DEFAULT_RULES' allreduce with an equal-bytes gather/scatter
# pair while cutting residual/LN activation memory by the TP degree.
# "seq" -> "model" binds the stream; "seq_inner" keeps attention math on
# the full sequence per head shard.
MEGATRON_SP_RULES = tuple(
    ("seq", "model") if name == "seq" else (name, axis)
    for name, axis in DEFAULT_RULES
)

LossFn = Callable[[Any, Any], tuple[jax.Array, dict]]


class TensorParallel:
    """Parameter-sharded training over the ``model`` mesh axis."""

    def __init__(self, mesh: Mesh, rules=DEFAULT_RULES):
        self.mesh = mesh
        self.rules = list(rules)

    # -- layout ---------------------------------------------------------------
    def init_params(self, model: nn.Module, rng, *sample_args):
        """Initialize with every param materialized directly into its shard
        layout (no host-side full copy — how 100B-param states fit).

        attn_impl='flash' composes: the Pallas kernel carries a
        ``custom_partitioning`` rule (ops/flash_attention.py) that shards
        batch/heads and replicates seq/head_dim, so GSPMD partitions it like
        any other op (heads map to the ``model`` axis under DEFAULT_RULES).
        """

        def init_fn():
            return model.init(rng, *sample_args)

        abstract = jax.eval_shape(init_fn)
        specs = nn.get_partition_spec(abstract)
        shardings = spmd.logical_to_mesh_sharding(specs, self.mesh, self.rules)
        with self.mesh:
            variables = jax.jit(init_fn, out_shardings=shardings)()
        params = nn.meta.unbox(variables)["params"]
        param_shardings = nn.meta.unbox(shardings)["params"]
        return params, param_shardings

    def state_shardings(self, state: Any, param_shardings: Any) -> Any:
        """Shardings for a full TrainState: optimizer moments inherit their
        param's sharding (matched by shape+dtype), scalars replicate."""
        return assign_by_shape(
            state.params, param_shardings, state,
            NamedSharding(self.mesh, P()),
        )

    # -- compiled steps -------------------------------------------------------
    def make_train_step(self, loss_fn: LossFn, state_shardings: Any,
                        *, donate: bool = True, steps_per_call: int = 1,
                        stacked_batch: bool = False):
        """jit the step with explicit in/out shardings; GSPMD derives the
        collectives (the reference's gRPC push/pull has no analogue here —
        nothing moves except the math's own allreduces).

        ``steps_per_call`` / ``stacked_batch``: the same dispatch-
        amortization knob as :meth:`DataParallel._compile_step` and
        :meth:`PipelinedLM.make_train_step` — K optimizer steps inside one
        compiled program via ``lax.scan``; stacked mode consumes a leading
        ``steps_per_call`` batch axis, otherwise the same batch repeats.
        Metrics are the LAST inner step's."""
        if steps_per_call < 1:
            raise ValueError(
                f"steps_per_call must be >= 1, got {steps_per_call}")
        if stacked_batch and steps_per_call == 1:
            raise ValueError(
                "stacked_batch requires steps_per_call > 1 (a stacked "
                "batch's leading axis is consumed one slice per inner step)")
        batch_sharding = NamedSharding(
            self.mesh, P(None, "data") if stacked_batch else P("data"))

        def step(state, batch):
            # activation_mesh makes the model's logical constraints binding
            # (real with_sharding_constraint ops) — required for layouts
            # the params alone can't imply, e.g. MEGATRON_SP_RULES'
            # sequence-sharded residual stream
            with nn.logical_axis_rules(self.rules), activation_mesh(self.mesh):
                (loss, mets), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(state.params, batch)
            state = state.apply_gradients(grads=grads)
            return state, {"loss": loss, **mets}

        if steps_per_call == 1:
            body = step
        else:
            from jax import lax

            def body(state, batch):
                if stacked_batch:
                    lead = {jax.tree.leaves(batch)[0].shape[0]}
                    if lead != {steps_per_call}:
                        raise ValueError(
                            f"stacked batch leading axis {lead} != "
                            f"steps_per_call={steps_per_call}; the scan "
                            "would silently run a different number of "
                            "optimizer steps")

                def inner(st, xs):
                    st, m = step(st, batch if xs is None else xs)
                    return st, m

                state, ms = lax.scan(
                    inner, state, batch if stacked_batch else None,
                    length=None if stacked_batch else steps_per_call)
                return state, jax.tree.map(lambda x: x[-1], ms)

        jitted = jax.jit(
            body,
            in_shardings=(state_shardings, batch_sharding),
            out_shardings=(state_shardings, NamedSharding(self.mesh, P())),
            donate_argnums=(0,) if donate else (),
        )

        # Trace-time mesh context: ops that dispatch on the ambient mesh
        # (the flash kernel's custom_partitioning path) must see this pjit
        # program's mesh, which jit alone does not establish. The LEGACY
        # `with mesh:` context — NOT jax.set_mesh — because set_mesh turns
        # flax's global_mesh_defined() on and eagerly applies every logical
        # constraint, breaking DenseGeneral+with_logical_partitioning (flat
        # rank-2 kernel init vs rank-4 logical names).
        def step_in_mesh(state, batch):
            with self.mesh:
                return jitted(state, batch)

        # expose the raw jitted step for AOT consumers (lower/compile/
        # memory_analysis) — the wrapper itself is a plain function
        step_in_mesh.jitted = jitted
        return step_in_mesh

    def make_eval_step(self, metric_fn, state_shardings: Any):
        """``(state, batch) -> metrics`` — the no-grad half for
        :class:`~distributed_tensorflow_guide_tpu.train.evaluation.Evaluator`:
        same shardings and logical-rule context as the train step, GSPMD
        collectives only, state untouched. ``metric_fn(params, batch) ->
        {name: scalar}`` (e.g. built from
        ``models.transformer.make_cls_loss_fn`` by dropping the grad)."""
        batch_sharding = NamedSharding(self.mesh, P("data"))
        param_shardings = state_shardings.params

        def step(params, batch):
            with nn.logical_axis_rules(self.rules), activation_mesh(self.mesh):
                return metric_fn(params, batch)

        jitted = jax.jit(
            step,
            in_shardings=(param_shardings, batch_sharding),
            out_shardings=NamedSharding(self.mesh, P()),
        )

        def step_in_mesh(state, batch):
            with self.mesh:
                return jitted(state.params, batch)

        step_in_mesh.jitted = jitted
        return step_in_mesh
