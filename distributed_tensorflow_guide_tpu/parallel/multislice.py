"""Two-tier multi-slice training — dense DP inside a slice over ICI,
infrequent outer parameter sync across slices over DCN.

The reference guide's answer to "more capacity than one machine" was the
PS/worker cluster; this framework's answer so far was one ICI slice. DCN —
the data-center network between slices — is orders of magnitude slower than
ICI (benchmarks/common.py `_TPU_DCN_PEAK` vs `_TPU_ICI_PEAK`), so a naive
mesh that runs the per-step gradient all-reduce across slices is
wire-bound. The DiLoCo-style composition (Douillard et al. 2023; the same
bandwidth economics as DOWNPOUR, see :class:`~.async_ps.LocalSGD`) keeps
the dense per-step collective entirely on ICI and crosses DCN once every
``sync_period`` steps with a parameter *delta*:

  * **inner tier** — each slice runs ``sync_period`` synchronous DP steps:
    per-step gradient ``pmean`` over the within-slice ``data`` axis only
    (pure ICI), local optimizer update.
  * **outer tier** — slices average the round's parameter delta
    ``anchor - params`` over the ``dcn`` axis (the only collective that
    touches DCN) and apply it through a Nesterov-style outer optimizer;
    float inner-optimizer state is pmean'd across slices alongside so
    every slice re-enters the next round bit-identical.

With ``sync_period=1``, ``outer_lr=1`` and ``outer_momentum=0`` the outer
update collapses to ``params = mean_slices(params_s)`` — plain sync DP
split into a two-level reduction (pinned against :class:`DataParallel` in
tests/test_multislice.py, the same parity LocalSGD pins at period 1).

The mesh is explicit about the two tiers: :func:`two_tier_mesh` builds a
``(dcn, data, model, pipe, context, expert)`` mesh whose leading ``dcn``
axis is the slice index — the slice-spanning factor that
``core.mesh.build_mesh`` folds into one logical axis is a *named axis*
here, so shard_map can address "across slices" and "within a slice" as
different collectives.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

import distributed_tensorflow_guide_tpu.collectives as cc
from distributed_tensorflow_guide_tpu.core.compat import shard_map
from distributed_tensorflow_guide_tpu.core.mesh import (
    AXES,
    MeshSpec,
    _slice_groups,
    axis_sizes,
    num_slices,
)

DCN_AXIS = "dcn"

LossFn = Callable[[Any, Any], tuple[jax.Array, dict]]


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def _pmean_floats(tree: Any, axis: str) -> Any:
    """pmean float leaves; pass through ints (identical across replicas —
    e.g. optax step counts), which integer pmean would corrupt."""
    return jax.tree.map(
        lambda x: cc.pmean(x, axis) if _is_float(x) else x, tree
    )


def two_tier_mesh(
    spec: MeshSpec | None = None,
    devices=None,
    *,
    n_slices: int | None = None,
) -> Mesh:
    """Build a ``(dcn, data, model, pipe, context, expert)`` mesh: the
    leading ``dcn`` axis indexes slices, ``spec`` describes the PER-SLICE
    (ICI) mesh and is resolved against ``len(devices) / n_slices``.

    Real multi-slice deployments group by ``device.slice_index`` so only
    the ``dcn`` axis crosses DCN. Backends with no slice info (CPU fake
    devices — the test/bench harness) are split into ``n_slices``
    contiguous groups ordered by ``(process_index, id)``: each fake
    "slice" is a contiguous block of processes, which is exactly the
    process→slice mapping the elastic harness (train/elastic_world.py)
    assigns, and keeps batch sharding under ``P((dcn, data))``
    process-contiguous for ``make_array_from_process_local_data``.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_slices is None:
        n_slices = max(num_slices(devices), 1)
    if n_slices < 1:
        raise ValueError(f"n_slices must be >= 1, got {n_slices}")
    if len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices do not split into {n_slices} slices"
        )
    per = len(devices) // n_slices
    spec = spec or MeshSpec()
    sizes = spec.resolve(per)
    inner_shape = tuple(sizes[a] for a in AXES)
    groups = _slice_groups(devices)
    if len(groups) != n_slices:
        if len(groups) > 1:
            # devices DO expose slice topology and it disagrees: chunking
            # would silently straddle real DCN boundaries, putting the
            # per-step inner pmean on the slow wire — the exact mistake
            # this module exists to prevent. Refuse.
            raise ValueError(
                f"devices span {len(groups)} real slice(s) but "
                f"n_slices={n_slices} was requested; pass n_slices="
                f"{len(groups)} (or omit it) so slice boundaries stay on "
                "DCN")
        # no slice info (CPU fake devices): contiguous fake slices
        devices = sorted(
            devices,
            key=lambda d: (getattr(d, "process_index", 0), d.id),
        )
        groups = [devices[i * per:(i + 1) * per] for i in range(n_slices)]
    arrs = []
    for g in groups:
        if len(g) != per:
            raise ValueError(
                f"uneven slice sizes {[len(x) for x in groups]}; every "
                f"slice must contribute {per} devices"
            )
        try:
            from jax.experimental import mesh_utils

            arrs.append(
                mesh_utils.create_device_mesh(inner_shape, devices=list(g))
            )
        except Exception:
            arrs.append(np.asarray(g, dtype=object).reshape(inner_shape))
    return Mesh(np.stack(arrs), (DCN_AXIS, *AXES))


@dataclasses.dataclass
class TwoTierState:
    """Carried state of one outer round: the per-slice inner TrainState
    plus the outer optimizer's momentum (a float-params-shaped tree).
    Registered as a pytree so it checkpoints/shard_maps like any state."""

    inner: Any
    outer_momentum: Any


jax.tree_util.register_pytree_node(
    TwoTierState,
    lambda s: ((s.inner, s.outer_momentum), None),
    lambda _, kids: TwoTierState(*kids),
)


class MultiSliceLocalSGD:
    """DiLoCo-style two-tier strategy over a :func:`two_tier_mesh`.

    One call of the compiled train step = one outer round:
    ``sync_period`` inner sync-DP steps (``lax.scan``; gradient pmean over
    ``inner_axis`` — within-slice ICI) followed by the one DCN collective:
    the round's parameter delta pmean'd over ``outer_axis`` and applied
    through the Nesterov outer optimizer

        m   <- outer_momentum * m + delta_mean
        upd <- delta_mean + outer_momentum * m        (nesterov)
               m                                      (heavy-ball)
        params <- anchor - outer_lr * upd

    plus a pmean of the float inner-optimizer state. ``outer="off"``
    emits NO DCN collective at all — outer sync, opt-state sync, and the
    metric scalar (slices train fully independently — numerically wrong
    on purpose; the timing control benchmarks use to measure the exposed
    DCN cost must not pay even one latency-bound round-trip per round).

    The super-batch contract matches LocalSGD: leaves shaped
    ``(sync_period, global_batch, ...)``, global batch sharded over
    ``(dcn, data)`` jointly — slices take contiguous row blocks, the
    within-slice data axis subdivides them.
    """

    def __init__(
        self,
        mesh: Mesh,
        sync_period: int,
        *,
        outer_lr: float = 1.0,
        outer_momentum: float = 0.0,
        nesterov: bool = True,
        inner_axis: str = "data",
        outer_axis: str = DCN_AXIS,
        outer: str = "on",
        compress: str | None = None,
    ):
        from distributed_tensorflow_guide_tpu.parallel.overlap import (
            resolve_compress,
        )

        sizes = axis_sizes(mesh)
        for ax in (inner_axis, outer_axis):
            if ax not in sizes:
                raise ValueError(
                    f"mesh has no axis {ax!r} (axes: {tuple(sizes)}); build "
                    "it with two_tier_mesh()"
                )
        if sync_period < 1:
            raise ValueError(f"sync_period must be >= 1, got {sync_period}")
        if outer not in ("on", "off"):
            raise ValueError(f"outer must be 'on' or 'off', got {outer!r}")
        # int8-compressed outer sync (ops/quant.int8_pmean): the delta and
        # float opt-state cross DCN at 1 byte/elem with one shared-scale
        # f32 pmax each — outer_sync_bytes(..., compress="int8") is the
        # closed form. The round's OUTER delta is exactly the signal that
        # tolerates coarse quantization (DiLoCo's premise: it is already
        # an average of sync_period updates); inner ICI grads stay f32.
        self.compress = resolve_compress(compress)
        self.mesh = mesh
        self.sync_period = sync_period
        self.outer_lr = float(outer_lr)
        self.outer_momentum = float(outer_momentum)
        self.nesterov = nesterov
        self.inner_axis = inner_axis
        self.outer_axis = outer_axis
        self.outer = outer
        self.n_slices = sizes[outer_axis]
        self.slice_world = sizes[inner_axis]
        self.world = self.n_slices * self.slice_world

    # ---- state / data placement -------------------------------------------

    def init(self, state: Any) -> TwoTierState:
        """Wrap an inner TrainState with zeroed outer momentum (same
        structure and dtypes as ``params``; non-float leaves stay zeros
        and are never updated — the outer optimizer only moves floats)."""
        momentum = jax.tree.map(jnp.zeros_like, state.params)
        return TwoTierState(inner=state, outer_momentum=momentum)

    def replicate(self, tt_state: TwoTierState) -> TwoTierState:
        from distributed_tensorflow_guide_tpu.core.compat import (
            device_put_global,
        )

        sharding = NamedSharding(self.mesh, P())
        return device_put_global(
            tt_state, jax.tree.map(lambda _: sharding, tt_state)
        )

    def batch_spec(self, *, leading_time_axis: bool = True) -> P:
        axes = (self.outer_axis, self.inner_axis)
        return P(None, axes) if leading_time_axis else P(axes)

    def shard_batch(self, batch: Any, *, leading_time_axis: bool = True):
        """Place a host super-batch. Single-process: the full global
        super-batch. Multi-process: this process's contiguous row block
        (see :func:`~.elastic_world.shard_bounds`)."""
        sharding = NamedSharding(
            self.mesh, self.batch_spec(leading_time_axis=leading_time_axis)
        )
        if jax.process_count() > 1:
            return jax.tree.map(
                lambda x: jax.make_array_from_process_local_data(sharding, x),
                batch,
            )
        return jax.device_put(batch, sharding)

    # ---- accounting --------------------------------------------------------

    def outer_float_bytes(self, tt_state: TwoTierState) -> int:
        """Bytes the outer sync moves per slice per round: the float param
        delta plus the float inner-optimizer state (what the two DCN
        pmeans carry). Feed to ``benchmarks.common.outer_sync_bytes`` for
        the ring-model per-device wire traffic."""
        total = 0
        for tree in (tt_state.inner.params, tt_state.inner.opt_state):
            for leaf in jax.tree.leaves(tree):
                if hasattr(leaf, "dtype") and _is_float(leaf):
                    total += int(leaf.size) * leaf.dtype.itemsize
        return total

    # ---- the compiled outer round -----------------------------------------

    def _outer_pmean(self, tree: Any) -> Any:
        """The outer-tier float pmean: int8 wire format when compressed,
        the historical per-leaf f32 pmean otherwise (byte-identical
        default trace)."""
        if self.compress == "int8":
            from distributed_tensorflow_guide_tpu.ops import quant

            return quant.int8_pmean(tree, self.outer_axis)
        return _pmean_floats(tree, self.outer_axis)

    def make_train_step(self, loss_fn: LossFn, *, donate: bool = True):
        mu = self.outer_momentum

        def sm_step(tt, batches):
            state = tt.inner
            anchor = state.params

            def inner_step(carry, sub):
                params, opt_state = carry
                (loss, _), g = jax.value_and_grad(loss_fn, has_aux=True)(
                    params, sub
                )
                # dense sync DP *within the slice*: ICI-only collective
                g = cc.pmean(g, self.inner_axis)
                updates, opt_state = state.tx.update(g, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            (params, opt_state), losses = lax.scan(
                inner_step, (anchor, state.opt_state), batches
            )
            momentum = tt.outer_momentum
            if self.outer == "on":
                delta = jax.tree.map(jnp.subtract, anchor, params)
                # the ONLY collectives on the DCN tier: one param-delta
                # pmean + the float opt-state pmean, per round
                delta = self._outer_pmean(delta)
                momentum = jax.tree.map(
                    lambda m, d: mu * m + d if _is_float(d) else m,
                    tt.outer_momentum,
                    delta,
                )
                if self.nesterov:
                    update = jax.tree.map(
                        lambda d, m: d + mu * m if _is_float(d) else d,
                        delta,
                        momentum,
                    )
                else:
                    update = jax.tree.map(
                        lambda d, m: m if _is_float(d) else d,
                        delta,
                        momentum,
                    )
                params = jax.tree.map(
                    lambda a, u: a - self.outer_lr * u
                    if _is_float(a) else a,
                    anchor,
                    update,
                )
                opt_state = self._outer_pmean(opt_state)
            new_inner = state.replace(
                step=state.step + self.sync_period,
                params=params,
                opt_state=opt_state,
            )
            # outer="off" must be genuinely DCN-free — including the
            # metric scalar (on real DCN one latency-bound round-trip per
            # round would contaminate the bench's exposed-frac control),
            # so its loss is the within-slice mean only
            met_axes = ((self.outer_axis, self.inner_axis)
                        if self.outer == "on" else self.inner_axis)
            mets = {"loss": cc.pmean(losses.mean(), met_axes)}
            return TwoTierState(new_inner, momentum), mets

        sharded = shard_map(
            sm_step,
            mesh=self.mesh,
            in_specs=(P(), self.batch_spec()),
            out_specs=(P(), P()),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_kv_block_transfer(mesh: Mesh, *, src_slice: int = 0,
                           dst_slice: int = 1):
    """Compiled-side model of the fleet's disaggregated prefill->decode
    KV handoff (PR 18): ship a ``(blocks, payload)`` buffer from the
    prefill-role slice to the decode-role slice with ONE single-hop
    ``lax.ppermute`` over the ``dcn`` axis — a point-to-point send, not
    a ring, because a migration has exactly one producer and one
    consumer.  The buffer is donated (alias mode: the transfer replaces
    it in place on the wire's far side).  The host-side fleet moves the
    same bytes through its d2h/h2d path today; this program is what the
    cost walker prices so the `collective_bytes` pin can hold the
    closed-form migration model (``kv_migration_bytes``) against an
    auditable trace, and what a future device-to-device DCN fast path
    compiles to."""
    n = axis_sizes(mesh)[DCN_AXIS]
    if n < 2:
        raise ValueError(
            f"kv block transfer needs >= 2 slices on {DCN_AXIS!r}, "
            f"got {n}")
    perm = [(src_slice % n, dst_slice % n)]

    def xfer(buf):
        return lax.ppermute(buf, DCN_AXIS, perm)

    sharded = shard_map(xfer, mesh=mesh, in_specs=P(DCN_AXIS),
                        out_specs=P(DCN_AXIS), check_vma=False)
    return jax.jit(sharded, donate_argnums=(0,))


# ---- program contracts (analysis/) ------------------------------------------


def lint_contracts():
    """Contracts for one outer round at both outer-sync settings. The
    load-bearing expectation is the ``outer="off"`` program: ZERO
    collectives on the dcn axis — including the metric scalar — because
    the bench that measures exposed DCN cost uses it as the no-DCN timing
    control; one stray latency-bound round-trip per round would poison
    the measurement. ``outer="on"`` pins the full DCN budget: one delta
    pmean per float param leaf + per float optimizer leaf, and the metric
    pmean over (dcn, data)."""
    from distributed_tensorflow_guide_tpu.analysis.contracts import (
        CostPin,
        CostSpec,
        DonationSpec,
        ProgramContract,
    )
    from distributed_tensorflow_guide_tpu.analysis.cost import closed_forms

    # tiny_mlp float state the outer sync pmeans: params + SGD momentum
    float_state_bytes = 2 * 4288
    sync_period, n_slices = 2, 2

    def _dcn_expect():
        return closed_forms().outer_sync_bytes(float_state_bytes, n_slices)

    def _inner_expect(n_metric_pmeans):
        def expect():
            import jax

            common = closed_forms()
            ici_world = jax.device_count() // n_slices
            return sync_period * common.dp_allreduce_bytes(
                4288, ici_world) + n_metric_pmeans * \
                common.dp_allreduce_bytes(4, ici_world)

        return expect

    def build(outer):
        def _build():
            from distributed_tensorflow_guide_tpu.analysis.fixtures import (
                tiny_mlp,
            )
            from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec

            loss_fn, state, batch = tiny_mlp()
            mesh = two_tier_mesh(MeshSpec(data=-1), n_slices=2)
            ms = MultiSliceLocalSGD(mesh, sync_period=2, outer=outer)
            tt = ms.init(state)
            step = ms.make_train_step(loss_fn, donate=True)
            batches = jax.tree.map(
                lambda x: jnp.stack([x, x]), batch)  # (sync_period, B, ...)
            return step, (tt, batches)

        return _build

    sources = ("distributed_tensorflow_guide_tpu.parallel.multislice",
               "distributed_tensorflow_guide_tpu.collectives.collectives")
    # tiny_mlp: 4 float param leaves (delta pmean) + 4 float momentum
    # leaves in the SGD trace state (opt-state pmean)
    n_dcn = 4 + 4
    return [
        ProgramContract(
            name="multislice_outer_on_round",
            build=build("on"),
            policy="f32",
            collectives={
                "psum[data]": 1,       # the inner grad pmean (scan body)
                "psum[dcn]": n_dcn,    # delta + float-opt-state sync
                "psum[dcn,data]": 1,   # the metric pmean over both tiers
            },
            donation=DonationSpec(argnums=(0,)),
            sources=sources,
            cost=CostSpec(
                pins=(
                    CostPin("collective_bytes[psum[dcn]]", _dcn_expect,
                            note="outer_sync_bytes over the float state "
                                 "(params + momentum), once per round"),
                    CostPin("collective_bytes[psum[data]]",
                            _inner_expect(0),
                            note="sync_period inner grad allreduces over "
                                 "the within-slice data axis; the metric "
                                 "pmean rides psum[dcn,data]"),
                ),
                max_peak_live_bytes=49152),
            notes="two-tier round: dense ICI inner steps, one DCN sync"),
        ProgramContract(
            name="multislice_outer_off_round",
            build=build("off"),
            policy="f32",
            # strict census: the inner grad pmean + the within-slice
            # metric pmean and NOTHING else — any dcn-axis collective
            # showing up here fails the lint
            collectives={"psum[data]": 2},
            donation=DonationSpec(argnums=(0,)),
            sources=sources,
            cost=CostSpec(
                pins=(
                    # the byte-level version of the DCN-free promise: the
                    # quantity resolves to 0.0 when the key is absent
                    CostPin("collective_bytes[psum[dcn]]", 0.0,
                            note="outer=off moves ZERO bytes over DCN"),
                    CostPin("collective_bytes[psum[data]]",
                            _inner_expect(1),
                            note="inner grad allreduces + the one "
                                 "within-slice scalar metric pmean"),
                ),
                max_peak_live_bytes=49152),
            notes="outer=off is DCN-free by contract (bench timing "
                  "control)"),
        _kv_transfer_contract(),
    ]


def _kv_transfer_contract():
    """Contract for the fleet KV-block migration program (PR 18): one
    point-to-point ppermute on the dcn axis and NOTHING else (strict
    census — a stray psum here would mean the migration path grew a
    synchronization it must not have), with an EXACT ``collective_bytes``
    pin against the closed-form migration model: the fixture's
    ``kv_migration_bytes`` divided by the slice count (the cost walker's
    per-device ppermute convention — bytes x hops / n_devices, one hop
    for a point-to-point send)."""
    from distributed_tensorflow_guide_tpu.analysis.contracts import (
        CostPin,
        CostSpec,
        DonationSpec,
        ProgramContract,
    )
    from distributed_tensorflow_guide_tpu.analysis.cost import closed_forms

    # fixture geometry mirrors the serve lint fixtures (L=2 layers, H=2
    # heads, 8-token blocks, head_dim 8) with 4 migrated blocks per
    # slice; payload rows are f32 here (the lint policy dtype), so the
    # closed form is evaluated at 4 bytes/elem
    L, H, BS, HD, NB = 2, 2, 8, 8, 4
    n_slices = 2

    def _xfer_expect():
        return closed_forms().kv_migration_bytes(
            NB, L, H, BS, HD, activation_dtype_bytes=4) / n_slices

    def _build():
        from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec

        mesh = two_tier_mesh(MeshSpec(data=-1), n_slices=n_slices)
        fn = make_kv_block_transfer(mesh)
        elems_per_block = 2 * L * H * BS * HD  # k and v rows
        buf = jnp.zeros((n_slices * NB, elems_per_block), jnp.float32)
        return fn, (buf,)

    return ProgramContract(
        name="serve_kv_block_transfer_dcn",
        build=_build,
        policy="f32",
        collectives={"ppermute[dcn]": 1},
        donation=DonationSpec(argnums=(0,)),
        sources=("distributed_tensorflow_guide_tpu.parallel.multislice",),
        cost=CostSpec(
            pins=(CostPin(
                "collective_bytes[ppermute[dcn]]", _xfer_expect,
                note="kv_migration_bytes(4 blocks, f32) / n_slices — "
                     "the closed-form migration model at the walker's "
                     "per-device single-hop convention"),),
            max_peak_live_bytes=49152),
        notes="point-to-point KV block handoff over DCN: the compiled "
              "model the fleet's migration counters reconcile against")
