"""Synchronous data parallelism — the Synchronous-SGD / MirroredStrategy track.

Reference equivalents:
  * ``SyncReplicasOptimizer``
    (tensorflow/python/training/sync_replicas_optimizer.py:42): workers push
    grads to per-variable accumulators on the PS; the chief applies once
    ``replicas_to_aggregate`` arrive and releases workers via a token queue.
  * Modern surface: ``MirroredStrategy``
    (tensorflow/python/distribute/mirrored_strategy.py:200) /
    ``CollectiveAllReduceStrategy``
    (tensorflow/python/distribute/collective_all_reduce_strategy.py:57) with
    NCCL allreduce (cross_device_ops.py:961).

TPU-native inversion: the accumulator + token-queue barrier *is* ``psum`` on
the ICI ring — hardware-synchronous, no chief, no PS. One compiled SPMD step:
per-shard forward/backward, explicit ``pmean`` of grads over the ``data``
axis, identical optimizer update everywhere. ``check_vma=False`` because the
collective is explicit (with vma checking on, jax.grad w.r.t. replicated
params already inserts the psum and an explicit pmean would double-reduce).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

import distributed_tensorflow_guide_tpu.collectives as cc
from distributed_tensorflow_guide_tpu.core.compat import shard_map
from distributed_tensorflow_guide_tpu.core.mesh import axis_sizes
from distributed_tensorflow_guide_tpu.parallel.grad_accum import (
    accumulate_grads,
)

# loss_fn(params, batch) -> (scalar loss, dict of scalar metrics)
LossFn = Callable[[Any, Any], tuple[jax.Array, dict]]


class DataParallel:
    """Build compiled sync-DP train/eval steps over a mesh's ``data`` axis.

    ``overlap`` ("auto"|True|False, default off) routes the gradient
    all-reduce through the bucketed backward path (parallel/overlap.py):
    per-bucket ``custom_vjp`` boundary markers on the parameter tree emit
    each bucket's pmean mid-backward — where XLA's latency-hiding
    scheduler can hide it under the remaining backward compute — instead
    of one monolithic pmean after the full gradient tree. Bitwise-
    identical gradients (all-reduce is elementwise per leaf; pinned in
    tests/test_overlap.py); ``auto`` resolves on only for TPU, so CPU
    tier-1 traces stay byte-identical to the overlap-off program.
    ``bucket_bytes`` overrides the autotune-table bucket budget.
    """

    def __init__(self, mesh: Mesh, axis: str = "data", *,
                 overlap="off", bucket_bytes: int | None = None,
                 compress: str | None = None):
        from distributed_tensorflow_guide_tpu.parallel import (
            overlap as overlap_mod,
        )

        self.mesh = mesh
        self.axis = axis
        self.world = axis_sizes(mesh)[axis]
        self.overlap = overlap_mod.resolve_overlap(overlap)
        self.bucket_bytes = bucket_bytes
        # int8-compressed gradient all-reduce (ops/quant.int8_pmean):
        # rides the bucket seams, so it requires the bucketed backward —
        # the mono pmean stays the bitwise-exact historical program.
        self.compress = overlap_mod.resolve_compress(compress)
        if self.compress and not self.overlap:
            raise ValueError(
                "compress='int8' rides the bucketed backward — it "
                "requires overlap=True (the monolithic pmean path stays "
                "bitwise-exact by contract)")

    # ---- data placement ----------------------------------------------------
    def shard_batch(self, batch: Any) -> Any:
        """Place a host batch onto the mesh, sharded along the leading axis.

        Single-process: ``batch`` is the global batch. Multi-process SPMD:
        ``batch`` is this process's equal share (global/process_count rows,
        e.g. from a process-sharded data loader) and the global array is
        assembled shard-wise — each host's rows land on its own devices, no
        cross-host transfer (the TF analogue is per-worker input pipelines
        under MultiWorkerMirroredStrategy, not one host scattering to all).
        """
        sharding = NamedSharding(self.mesh, P(self.axis))
        if jax.process_count() > 1:
            return jax.tree.map(
                lambda x: jax.make_array_from_process_local_data(sharding, x),
                batch,
            )
        return jax.device_put(batch, sharding)

    def batch_sharding(self, stacked: bool = False) -> NamedSharding:
        """The placement of a step's batch argument: leading axis sharded
        over ``data`` — or, for a ``stacked_batch`` multi-step super-batch,
        the SECOND axis (the leading one is the inner-step index)."""
        spec = P(None, self.axis) if stacked else P(self.axis)
        return NamedSharding(self.mesh, spec)

    def shard_packed_batch(self, packed: Any) -> Any:
        """Place one ``steps_per_call`` super-batch (leading axis = inner
        step, from data/prefetch.py ``pack_batches``) onto the mesh."""
        sharding = self.batch_sharding(stacked=True)
        if jax.process_count() > 1:
            return jax.tree.map(
                lambda x: jax.make_array_from_process_local_data(sharding, x),
                packed,
            )
        return jax.device_put(packed, sharding)

    def prefetch(self, source, *, depth: int = 2, steps_per_call: int = 1,
                 drop_remainder: bool = True):
        """Wrap a host-batch iterable in the device-prefetch overlap stage
        (data/prefetch.py), placed with this strategy's sharding. With
        ``steps_per_call > 1`` each yielded item is a packed super-batch
        ready for the multi-step compiled step."""
        from distributed_tensorflow_guide_tpu.data.prefetch import (
            prefetch_to_device,
        )

        put = (self.shard_packed_batch if steps_per_call > 1
               else self.shard_batch)
        return prefetch_to_device(source, depth=depth, put_fn=put,
                                  steps_per_call=steps_per_call,
                                  drop_remainder=drop_remainder)

    def replicate(self, state: Any) -> Any:
        """Replicate a state pytree across every device (params live
        everywhere — the anti-PS: no parameter server holds them).
        Multi-process meshes include non-addressable devices, which
        compat.device_put_global handles on every JAX line."""
        from distributed_tensorflow_guide_tpu.core.compat import (
            device_put_global,
        )

        return device_put_global(state, NamedSharding(self.mesh, P()))

    # ---- compiled steps ----------------------------------------------------
    def _compile_step(self, sm_step, donate: bool, steps_per_call: int = 1,
                      stacked_batch: bool = False,
                      per_step_metrics: bool = False):
        """shard_map + jit a per-device ``(state, batch) -> (state, metrics)``
        body: state replicated, batch sharded on its leading axis,
        explicit collectives (hence check_vma=False).

        ``steps_per_call > 1`` runs that many optimizer steps inside ONE
        compiled program (a ``lax.scan`` around the sharded step) — the TF
        ``steps_per_run`` / Keras ``steps_per_execution`` knob. On a
        remote-attached chip each executable dispatch costs milliseconds of
        host/tunnel latency; measured on the axon v5e, the ResNet-50 device
        step is 46.9 ms but wall-clock was 62 ms — ~15 ms/step of dispatch
        overhead that this knob amortizes away. With ``stacked_batch`` the
        batch carries a leading ``steps_per_call`` axis (one microbatch per
        inner step — the real-training mode); otherwise the same batch is
        re-used every inner step (synthetic benchmarking mode). Metrics
        returned are the LAST inner step's, unless ``per_step_metrics``:
        then every metric keeps the scan's leading ``steps_per_call`` axis,
        one slice per inner step — what lets TrainLoop keep hooks observing
        every optimizer step across a fused dispatch.
        """
        if steps_per_call < 1:
            raise ValueError(
                f"steps_per_call must be >= 1, got {steps_per_call}"
            )
        if steps_per_call == 1:
            if stacked_batch:
                raise ValueError(
                    "stacked_batch requires steps_per_call > 1 (a stacked "
                    "batch's leading axis is consumed one slice per inner "
                    "step)"
                )
            sharded = shard_map(
                sm_step,
                mesh=self.mesh,
                in_specs=(P(), P(self.axis)),
                out_specs=(P(), P()),
                check_vma=False,
            )
            return jax.jit(sharded, donate_argnums=(0,) if donate else ())

        def pick(ms):
            return ms if per_step_metrics else jax.tree.map(
                lambda x: x[-1], ms)

        if stacked_batch:
            def multi(state, batch):
                lead = {jax.tree.leaves(batch)[0].shape[0]}
                if lead != {steps_per_call}:
                    raise ValueError(
                        f"stacked batch leading axis {lead} != "
                        f"steps_per_call={steps_per_call}; the scan would "
                        "silently run a different number of optimizer steps"
                    )

                state, ms = lax.scan(sm_step, state, batch)
                return state, pick(ms)
        else:
            def multi(state, batch):
                def body(st, _):
                    st, m = sm_step(st, batch)
                    return st, m

                state, ms = lax.scan(
                    body, state, None, length=steps_per_call
                )
                return state, pick(ms)

        batch_spec = (P(None, self.axis) if stacked_batch
                      else P(self.axis))
        multi_sharded = shard_map(
            multi,
            mesh=self.mesh,
            in_specs=(P(), batch_spec),
            out_specs=(P(), P()),
            check_vma=False,
        )
        return jax.jit(multi_sharded, donate_argnums=(0,) if donate else ())

    def _pmean_metrics(self, mets: dict) -> dict:
        return {k: cc.pmean(v, self.axis) for k, v in mets.items()}

    def _grad_loss_fn(self, loss_fn):
        """The loss the backward differentiates: with overlap on, params
        are wrapped in per-bucket sync markers so gradients come out
        already pmean-ed (the call sites then skip the monolithic pmean);
        with overlap off it is ``loss_fn`` itself — the identical object,
        so the traced program cannot drift byte-wise."""
        if not self.overlap:
            return loss_fn
        from distributed_tensorflow_guide_tpu.parallel import (
            overlap as overlap_mod,
        )

        return overlap_mod.bucketed_loss_fn(
            loss_fn, self.axis, self.bucket_bytes, compress=self.compress)

    def make_train_step(self, loss_fn: LossFn, *, donate: bool = True,
                        accum_steps: int = 1, steps_per_call: int = 1,
                        stacked_batch: bool = False,
                        per_step_metrics: bool = False):
        """Compile ``(state, batch) -> (state, metrics)``.

        ``state`` is a flax TrainState (replicated); ``batch`` a pytree
        sharded on its leading axis. Gradients are explicitly pmean-ed: the
        update is bit-identical on every device, which is what keeps replicas
        in lockstep without ever broadcasting parameters.

        ``accum_steps > 1`` splits each device's shard into that many
        microbatches and accumulates gradients over a ``lax.scan`` before the
        single pmean + update — the DOWNPOUR 'fetch_period' knob reborn as a
        memory knob: identical numerics to the full batch (mean-of-means over
        equal microbatches), activation memory divided by ``accum_steps``,
        and still exactly one collective per step. The per-device shard
        length must divide by ``accum_steps``.
        """
        if self.overlap and accum_steps > 1:
            # pmean-per-microbatch then mean != mean then pmean bitwise
            # (summation order), and per-microbatch collectives would
            # multiply the wire traffic by accum_steps — the knobs solve
            # different problems (memory vs exposure); pick one.
            raise ValueError(
                "overlap=True is incompatible with accum_steps > 1: the "
                "bucketed backward reduces per microbatch backward, which "
                "breaks the bitwise-identity contract with the single "
                "post-accumulation pmean and multiplies collective traffic "
                f"by accum_steps={accum_steps}")
        grad_loss_fn = self._grad_loss_fn(loss_fn)

        def sm_step(state, batch):
            if accum_steps == 1:
                (loss, mets), grads = jax.value_and_grad(
                    grad_loss_fn, has_aux=True
                )(state.params, batch)
            else:
                shard_len = jax.tree.leaves(batch)[0].shape[0]
                if shard_len % accum_steps:
                    raise ValueError(
                        f"per-device batch shard of {shard_len} rows is not "
                        f"divisible by accum_steps={accum_steps}; pick a "
                        "global batch size that is a multiple of "
                        f"data_parallel_size * accum_steps"
                    )
                micro = jax.tree.map(
                    lambda x: x.reshape(
                        accum_steps, x.shape[0] // accum_steps, *x.shape[1:]
                    ),
                    batch,
                )
                grads, (losses, metas) = accumulate_grads(
                    loss_fn, state.params, micro, accum_steps
                )
                loss = jnp.mean(losses)
                mets = jax.tree.map(jnp.mean, metas)
            if not self.overlap:  # bucketed bwd already reduced them
                grads = cc.pmean(grads, self.axis)
            state = state.apply_gradients(grads=grads)
            return state, self._pmean_metrics({"loss": loss, **mets})

        return self._compile_step(sm_step, donate, steps_per_call,
                                  stacked_batch, per_step_metrics)

    def make_train_step_with_stats(self, loss_fn, *, donate: bool = True,
                                   steps_per_call: int = 1,
                                   stacked_batch: bool = False,
                                   per_step_metrics: bool = False):
        """Like :meth:`make_train_step` for models with non-trainable state
        (BatchNorm running stats).

        ``loss_fn(params, model_state, batch) ->
        (loss, (metrics, new_model_state))``; ``state`` is a
        :class:`~distributed_tensorflow_guide_tpu.train.state.TrainStateWithStats`.
        New model state is pmean-ed across replicas — synchronized running
        statistics, matching MultiWorkerMirroredStrategy's aggregation of
        BN updates rather than the reference PS examples' last-writer-wins
        race on PS-resident stats.
        """

        grad_loss_fn = self._grad_loss_fn(loss_fn)

        def sm_step(state, batch):
            (loss, (mets, new_ms)), grads = jax.value_and_grad(
                grad_loss_fn, has_aux=True
            )(state.params, state.model_state, batch)
            if not self.overlap:  # bucketed bwd already reduced them
                grads = cc.pmean(grads, self.axis)
            new_ms = cc.pmean(new_ms, self.axis)
            state = state.apply_gradients(grads=grads, model_state=new_ms)
            return state, self._pmean_metrics({"loss": loss, **mets})

        return self._compile_step(sm_step, donate, steps_per_call,
                                  stacked_batch, per_step_metrics)

    def make_eval_step(self, metric_fn: Callable[[Any, Any], dict]):
        """Compile ``(state, batch) -> metrics`` with pmean-ed metrics."""

        def sm_eval(state, batch):
            mets = metric_fn(state.params, batch)
            return {k: cc.pmean(v, self.axis) for k, v in mets.items()}

        sharded = shard_map(
            sm_eval,
            mesh=self.mesh,
            in_specs=(P(), P(self.axis)),
            out_specs=P(),
            check_vma=False,
        )
        return jax.jit(sharded)

    def make_eval_step_with_stats(self, metric_fn):
        """:meth:`make_eval_step` for models with non-trainable state:
        ``metric_fn(params, model_state, batch) -> {name: scalar}``.
        Evaluation reads the (already replica-synchronized) running stats
        — BatchNorm in inference mode — and never writes them back."""

        def sm_eval(state, batch):
            mets = metric_fn(state.params, state.model_state, batch)
            return {k: cc.pmean(v, self.axis) for k, v in mets.items()}

        sharded = shard_map(
            sm_eval,
            mesh=self.mesh,
            in_specs=(P(), P(self.axis)),
            out_specs=P(),
            check_vma=False,
        )
        return jax.jit(sharded)


# ---- program contracts (analysis/) ------------------------------------------


def lint_contracts():
    """Contracts for the static-analysis linter: the mono train step
    (one grad pmean + one pmean per metric) and the bucketed-overlap step,
    whose collective count is DERIVED from the bucket partition — N
    buckets must mean exactly N mid-backward grad psums, the structure
    the latency-hiding scheduler needs."""
    import dataclasses

    import numpy as np

    from distributed_tensorflow_guide_tpu.analysis.contracts import (
        CostPin,
        CostSpec,
        DonationSpec,
        ProgramContract,
    )
    from distributed_tensorflow_guide_tpu.analysis.cost import closed_forms
    from distributed_tensorflow_guide_tpu.core.mesh import (
        MeshSpec,
        build_mesh,
    )
    from distributed_tensorflow_guide_tpu.parallel import overlap

    def build(overlap_on, compress=None):
        def _build():
            from distributed_tensorflow_guide_tpu.analysis.fixtures import (
                tiny_mlp,
            )

            loss_fn, state, batch = tiny_mlp()
            mesh = build_mesh(MeshSpec(data=-1))
            dp = DataParallel(mesh, overlap=overlap_on,
                              bucket_bytes=1 if overlap_on else None,
                              compress=compress)
            step = dp.make_train_step(loss_fn, donate=True)
            return step, (state, batch)

        return _build

    sources = ("distributed_tensorflow_guide_tpu.parallel.data_parallel",
               "distributed_tensorflow_guide_tpu.parallel.overlap",
               "distributed_tensorflow_guide_tpu.collectives.collectives")
    # the tiny_mlp param tree at bucket_bytes=1: one bucket per leaf
    leaf_shapes = [(16, 32), (32,), (32, 16), (16,)]
    grad_bytes = sum(int(np.prod(s)) * 4 for s in leaf_shapes)  # 4288
    n_buckets = len(overlap.bucket_assignment(
        [np.zeros(s, np.float32) for s in leaf_shapes], bucket_bytes=1))

    def _grad_allreduce_expect():
        # grad-tree ring allreduce + the loss and mae scalar metric pmeans
        import jax

        common = closed_forms()
        world = jax.device_count()
        return (common.dp_allreduce_bytes(grad_bytes, world)
                + 2 * common.dp_allreduce_bytes(4, world))

    def _int8_allreduce_expect():
        # the same grad tree at 1 byte/elem on the wire (the int8 payload
        # of the compressed buckets) + the 2 f32 scalar metric pmeans
        import jax

        common = closed_forms()
        world = jax.device_count()
        return (common.dp_allreduce_bytes(grad_bytes, world,
                                          compress="int8")
                + 2 * common.dp_allreduce_bytes(4, world))

    def _scale_sidechannel_expect():
        # one f32 amax scalar rides a ring pmax per bucket
        import jax

        common = closed_forms()
        world = jax.device_count()
        return n_buckets * common.dp_allreduce_bytes(4, world)

    def _flops_expect():
        # the 3x-forward MFU convention counts 6 forward-equivalent
        # matmuls per step; the real backward of a 2-layer MLP skips the
        # first layer's input-grad matmul, so the trace holds 5 of them —
        # and the auditor sees PER-DEVICE shapes inside shard_map
        import jax

        from distributed_tensorflow_guide_tpu.analysis.fixtures import (
            tiny_mlp,
        )

        loss_fn, state, batch = tiny_mlp()
        common = closed_forms()
        full = common.model_flops_per_step(loss_fn, state.params, batch)
        return full / jax.device_count() * 5.0 / 6.0

    dp_cost = CostSpec(
        pins=(
            CostPin("collective_bytes[psum[data]]", _grad_allreduce_expect,
                    note="comm_bytes_model: 2*G*(n-1)/n grad ring + 2 "
                         "scalar metric pmeans"),
            CostPin("flops", _flops_expect,
                    note="5/6 of the 3x-fwd convention (no input-grad "
                         "matmul at layer 0), per device"),
        ),
        max_peak_live_bytes=20480)
    return [
        ProgramContract(
            name="dp_train_step",
            build=build(False),
            policy="f32",
            # 1 grad-tree pmean + the loss and mae metric pmeans
            collectives={"psum[data]": 3},
            donation=DonationSpec(argnums=(0,)),
            sources=sources,
            cost=dp_cost,
            notes="sync-DP mono step: one gradient collective per step"),
        ProgramContract(
            name="dp_overlap_train_step",
            build=build(True),
            policy="f32",
            # one psum per gradient bucket (emitted mid-backward) + the
            # 2 metric pmeans — the bucket partition IS the expectation
            collectives={"psum[data]": n_buckets + 2},
            donation=DonationSpec(argnums=(0,)),
            sources=sources,
            # same bytes as the mono step (bucketing changes WHEN psums
            # fire, not how much they move); buckets die mid-backward so
            # the peak sits ~2KiB below the mono step's
            cost=dataclasses.replace(dp_cost, max_peak_live_bytes=18432),
            notes=f"bucketed backward: {n_buckets} buckets -> "
                  f"{n_buckets} grad psums"),
        ProgramContract(
            name="dp_overlap_int8_round",
            build=build(True, compress="int8"),
            policy="f32",
            # one int8 psum per gradient bucket + the 2 f32 metric pmeans,
            # plus one scalar pmax per bucket — the shared-scale f32
            # side-channel of the compressed wire format
            collectives={"psum[data]": n_buckets + 2,
                         "pmax[data]": n_buckets},
            donation=DonationSpec(argnums=(0,)),
            sources=sources,
            cost=dataclasses.replace(
                dp_cost,
                pins=(
                    CostPin("collective_bytes[psum[data]]",
                            _int8_allreduce_expect,
                            note="grad ring at 1 byte/elem "
                                 "(compress='int8') + 2 scalar metric "
                                 "pmeans at f32"),
                    CostPin("collective_bytes[pmax[data]]",
                            _scale_sidechannel_expect,
                            note="one f32 amax scalar per bucket: the "
                                 "shared-scale side-channel"),
                    dp_cost.pins[1],  # same matmul flops: only the wire
                                      # representation changed
                ),
                # measured 21212: the f32 bucket budget (18432) plus the
                # transient int8 shadow buffers + f32 scales the quantize/
                # dequant seam holds while the wire copy is in flight
                max_peak_live_bytes=22528),
            notes=f"int8-compressed bucketed backward: {n_buckets} "
                  "buckets at a quarter of the grad bytes + "
                  f"{n_buckets} scale pmaxes"),
    ]
