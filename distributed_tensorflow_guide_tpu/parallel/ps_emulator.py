"""Host-side emulation of TRUE async parameter-server semantics.

The device strategies in :mod:`.async_ps` are the production TPU mappings;
they are synchronous by construction. This module preserves the reference's
*actual* semantics — stale parameter reads, interleaved writes, per-worker
pacing — so tests can quantify the semantic delta (SURVEY.md §2c: "keep a
host-side async-PS emulation for parity testing").

Model (one "event" = one worker micro-step, order given by a seeded
pseudorandom schedule — the emulated nondeterminism of N racing processes):

  * ``hogwild``  (⚠ Hogwild/):  worker pulls fresh PS params, computes a
    gradient, applies it directly to PS params (SGD on the PS, lock-free;
    fetch_period is forced to 1).
  * ``downpour`` (⚠ DOWNPOUR/): worker keeps a local replica, trains it
    locally each event, and every ``fetch_period`` of ITS events pushes the
    accumulated parameter delta to the PS and pulls fresh params.
  * ``adag``     (⚠ ADAG/):     worker accumulates raw gradients on stale
    params; every ``fetch_period`` events pushes them; the PS applies an
    adaptive optax optimizer (the PS-resident Adam).

Gradients run through jitted JAX; the PS itself is plain host state —
exactly the reference's architecture, scaled down to one process.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np
import optax

GradFn = Callable[[Any, Any], tuple[jax.Array, Any]]  # (loss, grads)


class AsyncPSEmulator:
    def __init__(
        self,
        loss_fn: Callable[[Any, Any], jax.Array],
        params: Any,
        *,
        n_workers: int,
        mode: str = "hogwild",
        lr: float = 0.1,
        fetch_period: int = 1,
        tx: optax.GradientTransformation | None = None,
        seed: int = 0,
    ):
        if mode not in ("hogwild", "downpour", "adag"):
            raise ValueError(f"unknown mode {mode!r}")
        self.mode = mode
        self.lr = lr
        self.fetch_period = 1 if mode == "hogwild" else fetch_period
        self.n_workers = n_workers
        self.ps_params = jax.tree.map(jnp.asarray, params)
        self._grad = jax.jit(jax.value_and_grad(loss_fn))
        self._rng = np.random.RandomState(seed)
        if mode == "adag":
            self.tx = tx or optax.adam(lr)
            self.tx_state = self.tx.init(self.ps_params)
        # per-worker local replicas / accumulators / event counts
        self.local = [self.ps_params for _ in range(n_workers)]
        self.accum = [
            jax.tree.map(jnp.zeros_like, self.ps_params) for _ in range(n_workers)
        ]
        self.events = [0] * n_workers
        self.pushes = 0

    # -- PS ops ---------------------------------------------------------------
    def _push_pull(self, k: int) -> None:
        """Worker k pushes its accumulated delta/grads; pulls fresh params."""
        self.pushes += 1
        if self.mode == "adag":
            g = jax.tree.map(lambda a: a / self.fetch_period, self.accum[k])
            updates, self.tx_state = self.tx.update(
                g, self.tx_state, self.ps_params
            )
            self.ps_params = optax.apply_updates(self.ps_params, updates)
        else:
            self.ps_params = jax.tree.map(
                jnp.add, self.ps_params, self.accum[k]
            )
        self.accum[k] = jax.tree.map(jnp.zeros_like, self.accum[k])
        self.local[k] = self.ps_params

    def _event(self, k: int, batch: Any) -> float:
        """One micro-step of worker k."""
        if self.mode == "hogwild":
            # fresh read of PS-resident params (no local replica at all) +
            # direct racing write back — staleness comes only from the
            # interleaving of other workers' events, as in true Hogwild
            loss, g = self._grad(self.ps_params, batch)
            delta = jax.tree.map(lambda gg: -self.lr * gg, g)
            self.accum[k] = delta
            self._push_pull(k)
            return float(loss)
        loss, g = self._grad(self.local[k], batch)
        if self.mode == "downpour":
            delta = jax.tree.map(lambda gg: -self.lr * gg, g)
            self.local[k] = optax.apply_updates(self.local[k], delta)
            self.accum[k] = jax.tree.map(jnp.add, self.accum[k], delta)
            self.events[k] += 1
            if self.events[k] % self.fetch_period == 0:
                self._push_pull(k)
        else:  # adag: accumulate raw grads on stale params
            self.accum[k] = jax.tree.map(jnp.add, self.accum[k], g)
            self.events[k] += 1
            if self.events[k] % self.fetch_period == 0:
                self._push_pull(k)
        return float(loss)

    def run(self, data: Iterator[Any], n_events: int) -> list[float]:
        """Interleave ``n_events`` worker micro-steps in pseudorandom order."""
        losses = []
        for _ in range(n_events):
            k = int(self._rng.randint(self.n_workers))
            losses.append(self._event(k, next(data)))
        return losses
