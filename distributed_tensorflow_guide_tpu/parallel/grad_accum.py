"""Shared gradient-accumulation core.

One implementation of accumulate-over-``lax.scan`` used by both the sync-DP
``accum_steps`` knob (data_parallel.py) and the ADAG-descendant
``AccumulatedAdaptive`` strategy (async_ps.py) — the numerics (mean of
per-microbatch mean-gradients over equal microbatches == full-batch
gradient) must stay identical in both, so they share this function.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

# loss_fn(params, microbatch) -> (scalar loss, dict of scalar metrics)
LossFn = Callable[[Any, Any], tuple[jax.Array, dict]]


def accumulate_grads(loss_fn: LossFn, params: Any, microbatches: Any,
                     accum_steps: int):
    """Mean gradient over stacked microbatches, activations freed per micro.

    ``microbatches``: pytree whose leaves lead with ``accum_steps``. Returns
    ``(mean_grads, (losses, metrics))`` with per-microbatch stacked aux
    (shape ``(accum_steps,)`` per scalar).
    """
    zeros = jax.tree.map(jnp.zeros_like, params)

    def body(acc, mb):
        (loss, mets), g = jax.value_and_grad(loss_fn, has_aux=True)(
            params, mb
        )
        return jax.tree.map(jnp.add, acc, g), (loss, mets)

    summed, aux = lax.scan(body, zeros, microbatches)
    return jax.tree.map(lambda g: g / accum_steps, summed), aux
