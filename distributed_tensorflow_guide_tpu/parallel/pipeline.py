"""Pipeline parallelism — judged config 5: "GPT-2 124M pipeline-parallel
across a v5e-16 pod slice" (BASELINE.md).

No pipeline exists in the reference (SURVEY.md §2c). Design: GPipe microbatch
schedule (Huang et al. 2019) expressed as ONE compiled SPMD program — the
pipeline "stages" are not processes (the reference's only composition
mechanism) but shards of a stacked-layer parameter tree over the ``pipe``
mesh axis, and the stage-to-stage hand-off is a single ICI-neighbor
``lax.ppermute`` per tick inside a ``lax.scan``:

    tick t:  stage 0 injects microbatch t | stage s runs layers on the
             activation it received at t-1 | everyone ppermutes output to s+1

    M microbatches, P stages → M+P-1 ticks; bubble fraction (P-1)/(M+P-1).

Differentiating *through* the scan+ppermute gives the backward pipeline for
free (ppermute's transpose is the reverse ppermute) — no hand-written
backward schedule, no send/recv pairs to keep in sync.

Embedding params live logically on stage 0 and head params on stage P-1:
every stage holds a copy, but only the owning stage's compute reaches the
loss, so the others' grads are structurally zero and one ``psum`` over
``pipe`` reconstitutes the true gradient. Composes with data parallelism
(``data`` axis pmean) in the same shard_map.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

import distributed_tensorflow_guide_tpu.collectives as cc
from distributed_tensorflow_guide_tpu.core.compat import shard_map
from distributed_tensorflow_guide_tpu.core.mesh import axis_sizes
from distributed_tensorflow_guide_tpu.utils.spec_utils import expand_prefix
from distributed_tensorflow_guide_tpu.models.transformer import (
    Block,
    TransformerConfig,
)


def _freeze_tables(fn):
    """Cache a schedule generator on its (M, P, v) key and mark the numpy
    tables read-only. The generators are trace-time Python (greedy
    simulations, O(T*P)); at judged scale (P=16, M=64, v=2) regenerating
    them on every retrace — new microbatch count, new donate configuration,
    eval vs train variant — is pure waste, and the cache makes a retrace's
    schedule cost one dict lookup. Freezing makes sharing safe: a caller
    mutating a cached table would silently corrupt every later trace."""

    @functools.lru_cache(maxsize=64)
    def cached(*key):
        out = fn(*key)
        for v_ in out.values():
            if hasattr(v_, "flags"):
                v_.flags.writeable = False
        return out

    return functools.wraps(fn)(cached)


@_freeze_tables
def _make_1f1b_schedule(M: int, P: int):
    """Static 1F1B schedule (Narayanan et al. 2019, PipeDream-flush).

    Returns numpy tables driving the SPMD tick loop:
      op[t, s] in {0 idle, 1 forward, 2 backward}; mb[t, s] = microbatch.
      sa/sam[t, s]: stage s must store the activation that arrived this tick
        (sent by s-1 at t-1) into slot ``sam % R``; sc/scm likewise for
        cotangents from s+1.
      R: ring-buffer depth (max in-flight microbatches + safety check that no
        slot is overwritten before consumption).
      T: total ticks.

    Greedy simulation: each stage forwards through its warmup window
    (min(P-s, M) microbatches), then strictly alternates backward-preferred /
    forward — the classic 1F1B steady state that caps in-flight activations
    at ~P-s instead of GPipe's M.
    """
    import numpy as np

    next_f = [0] * P
    next_b = [0] * P
    f_tick = [[-1] * M for _ in range(P)]
    b_tick = [[-1] * M for _ in range(P)]
    op_rows: list[list[int]] = []
    mb_rows: list[list[int]] = []
    t = 0
    max_inflight = 1
    while any(next_b[s] < M for s in range(P)):
        row_op = [0] * P
        row_mb = [0] * P
        for s in range(P):
            cap = min(P - s, M)  # 1F1B in-flight bound for stage s
            can_f = (
                next_f[s] < M
                and next_f[s] - next_b[s] < cap
                and (s == 0 or 0 <= f_tick[s - 1][next_f[s]] < t)
            )
            can_b = next_b[s] < next_f[s] and (
                s == P - 1 or 0 <= b_tick[s + 1][next_b[s]] < t
            )
            if s == P - 1 and can_b and not (0 <= f_tick[s][next_b[s]] < t):
                can_b = False
            in_warmup = next_f[s] < cap
            if can_f and in_warmup:
                row_op[s], row_mb[s] = 1, next_f[s]
            elif can_b:
                row_op[s], row_mb[s] = 2, next_b[s]
            elif can_f:
                row_op[s], row_mb[s] = 1, next_f[s]
        for s in range(P):
            if row_op[s] == 1:
                f_tick[s][row_mb[s]] = t
                next_f[s] += 1
            elif row_op[s] == 2:
                b_tick[s][row_mb[s]] = t
                next_b[s] += 1
            max_inflight = max(max_inflight, next_f[s] - next_b[s])
        op_rows.append(row_op)
        mb_rows.append(row_mb)
        t += 1
        if t > 6 * (M + P) + 16:
            raise RuntimeError("1F1B schedule generation did not converge")
    T = t
    op = np.array(op_rows, np.int32)
    mb = np.array(mb_rows, np.int32)

    # receive bookkeeping: arrival at tick t is what the neighbor sent at t-1
    sa = np.zeros((T, P), np.int32)
    sam = np.zeros((T, P), np.int32)
    sc = np.zeros((T, P), np.int32)
    scm = np.zeros((T, P), np.int32)
    for tt in range(1, T):
        for s in range(P):
            if s > 0 and op[tt - 1, s - 1] == 1:
                sa[tt, s], sam[tt, s] = 1, mb[tt - 1, s - 1]
            if s < P - 1 and op[tt - 1, s + 1] == 2:
                sc[tt, s], scm[tt, s] = 1, mb[tt - 1, s + 1]

    def slots_ok(R: int) -> bool:
        """No buffer slot may be overwritten before its consumer runs."""
        for s in range(P):
            # act_buf: arrival (t from sa) .. consumption (F at stage s);
            # resid:   store (F) .. consumption (B); cot_buf: arrival .. B.
            intervals: dict[int, list[tuple[int, int]]] = {}

            def add(slot, t0, t1):
                intervals.setdefault(slot, []).append((t0, t1))

            for m in range(M):
                if s > 0:
                    add(m % R, f_tick[s - 1][m] + 1, f_tick[s][m])
                add((m % R) + R, f_tick[s][m], b_tick[s][m])  # resid
                if s < P - 1:
                    add((m % R) + 2 * R, b_tick[s + 1][m] + 1, b_tick[s][m])
            for spans in intervals.values():
                spans.sort()
                for (a0, a1), (b0, _b1) in zip(spans, spans[1:]):
                    if b0 <= a1:
                        return False
        return True

    R = max_inflight
    while not slots_ok(R):  # pragma: no cover - safety margin
        R += 1
    return {"op": op, "mb": mb, "sa": sa, "sam": sam, "sc": sc, "scm": scm,
            "R": R, "T": T}


@_freeze_tables
def _make_interleaved_1f1b_schedule(M: int, P: int, v: int):
    """Static interleaved-1F1B schedule (Megatron-LM's combined schedule:
    Narayanan et al. 2021 §2.2) — BOTH the 1F1B O(P) in-flight memory cap
    and interleaving's ~v-fold bubble shrink in one table.

    D = v*P chunk-stages; chunk-stage k = j*P + s runs on device s as local
    chunk row j, so every k -> k+1 hand-off is one forward ``ppermute`` hop
    and every cotangent hand-off one backward hop. Each device executes a
    FIXED op sequence (warmup forwards, then 1F/1B pairs, then cooldown
    backwards), stalling when an op's input has not yet arrived — exactly
    how Megatron's executor behaves, here pre-simulated into per-tick
    tables so the SPMD loop stays a static ``lax.scan``.

    Per-device op order (requires ``M % P == 0``, as in Megatron):
      - forwards are chunk-grouped: chunk 0 takes microbatches 0..P-1, then
        chunk 1 takes 0..P-1, ... chunk v-1, then chunk 0 takes P..2P-1, …
      - backwards mirror it with chunks reversed (v-1 first).
      - warmup length W(s) = min(v*M, 2*(P-s-1) + (v-1)*P).

    Returns tables (T, P): ``op`` (0 idle / 1 fwd / 2 bwd), ``jr`` (local
    chunk row), ``mb``; arrival tables ``sa``/``saj``/``sam`` (an
    activation sent by device s-1 at t-1 lands this tick, destined for
    local chunk row ``saj``, microbatch ``sam``) and ``sc``/``scj``/``scm``
    for cotangents; ring depth ``R`` (slot = (j*M + m) % R, interval-
    checked); ``f_done``/``b_done`` tick stamps; ``T``.
    """
    import numpy as np

    if P < 2 or v < 2:
        raise ValueError(f"interleaved 1F1B needs P >= 2, v >= 2 (got {P}, {v})")
    if M % P:
        raise ValueError(
            f"interleaved 1F1B requires num_microbatches % pipe == 0 "
            f"(got M={M}, P={P}) — the chunk-grouped issue order rides "
            "groups of P microbatches"
        )
    D = v * P
    TF = v * M  # forward (and backward) ops per device

    def f_index(i):
        group, pos = divmod(i, P)
        rnd, j = divmod(group, v)
        return j, rnd * P + pos

    def b_index(i):
        group, pos = divmod(i, P)
        rnd, jr = divmod(group, v)
        return v - 1 - jr, rnd * P + pos

    seqs: list[list[tuple[int, int, int]]] = []
    for s in range(P):
        W = min(TF, 2 * (P - s - 1) + (v - 1) * P)
        ops: list[tuple[int, int, int]] = []
        nf = nb = 0
        while nf < W:
            ops.append((1, *f_index(nf)))
            nf += 1
        while nf < TF:
            ops.append((1, *f_index(nf)))
            nf += 1
            ops.append((2, *b_index(nb)))
            nb += 1
        while nb < TF:
            ops.append((2, *b_index(nb)))
            nb += 1
        seqs.append(ops)

    ptr = [0] * P
    f_done = [[-1] * M for _ in range(D)]
    b_done = [[-1] * M for _ in range(D)]
    rows: list[list[tuple[int, int, int]]] = []
    t = 0
    while any(ptr[s] < 2 * TF for s in range(P)):
        row: list[tuple[int, int, int]] = []
        for s in range(P):
            if ptr[s] >= 2 * TF:
                row.append((0, 0, 0))
                continue
            op, j, m = seqs[s][ptr[s]]
            k = j * P + s
            if op == 1:
                ready = k == 0 or 0 <= f_done[k - 1][m] < t
            else:
                ready = 0 <= f_done[k][m] < t and (
                    k == D - 1 or 0 <= b_done[k + 1][m] < t
                )
            row.append((op, j, m) if ready else (0, 0, 0))
        progress = False
        for s, (op, j, m) in enumerate(row):
            if op == 1:
                f_done[j * P + s][m] = t
                ptr[s] += 1
                progress = True
            elif op == 2:
                b_done[j * P + s][m] = t
                ptr[s] += 1
                progress = True
        if not progress:  # pragma: no cover - the fixed order is deadlock-free
            raise RuntimeError(
                f"interleaved 1F1B schedule deadlocked at tick {t} "
                f"(M={M}, P={P}, v={v})"
            )
        rows.append(row)
        t += 1
        if t > 8 * (TF + P) + 16:  # pragma: no cover - safety
            raise RuntimeError("interleaved 1F1B schedule did not converge")
    T = t

    op = np.zeros((T, P), np.int32)
    jr = np.zeros((T, P), np.int32)
    mb = np.zeros((T, P), np.int32)
    for tt, row in enumerate(rows):
        for s, (o, j, m) in enumerate(row):
            op[tt, s], jr[tt, s], mb[tt, s] = o, j, m

    # Arrivals: what device s-1 forwarded at t-1 lands on s at t (destined
    # for chunk-stage k+1, unless k was the tap D-1); what device s+1
    # backwarded at t-1 lands on s as the cotangent for chunk-stage k-1.
    sa = np.zeros((T, P), np.int32)
    saj = np.zeros((T, P), np.int32)
    sam = np.zeros((T, P), np.int32)
    sc = np.zeros((T, P), np.int32)
    scj = np.zeros((T, P), np.int32)
    scm = np.zeros((T, P), np.int32)
    for tt in range(1, T):
        for s in range(P):
            o, j, m = rows[tt - 1][(s - 1) % P]
            if o == 1:
                k = j * P + (s - 1) % P
                if k + 1 < D:
                    assert (k + 1) % P == s
                    sa[tt, s], saj[tt, s], sam[tt, s] = 1, (k + 1) // P, m
            o, j, m = rows[tt - 1][(s + 1) % P]
            if o == 2:
                k = j * P + (s + 1) % P
                if k - 1 >= 0:
                    assert (k - 1) % P == s
                    sc[tt, s], scj[tt, s], scm[tt, s] = 1, (k - 1) // P, m

    def slots_ok(R: int) -> bool:
        """No (j*M+m) % R slot overwritten before its consumer runs."""
        for s in range(P):
            intervals: dict[int, list[tuple[int, int]]] = {}

            def add(slot, t0, t1):
                intervals.setdefault(slot, []).append((t0, t1))

            for j in range(v):
                k = j * P + s
                for m in range(M):
                    u = (j * M + m) % R
                    if k > 0:
                        add(u, f_done[k - 1][m] + 1, f_done[k][m])
                    add(u + R, f_done[k][m], b_done[k][m])  # resid
                    if k < D - 1:
                        add(u + 2 * R, b_done[k + 1][m] + 1, b_done[k][m])
            for spans in intervals.values():
                spans.sort()
                for (a0, a1), (b0, _b1) in zip(spans, spans[1:]):
                    if b0 <= a1:
                        return False
        return True

    max_inflight = 1
    for s in range(P):
        events = []
        for j in range(v):
            k = j * P + s
            for m in range(M):
                events.append((f_done[k][m], 1))
                events.append((b_done[k][m], -1))
        cur = 0
        for _, d in sorted(events):
            cur += d
            max_inflight = max(max_inflight, cur)
    R = max_inflight
    while not slots_ok(R):
        R += 1
    return {"op": op, "jr": jr, "mb": mb, "sa": sa, "saj": saj, "sam": sam,
            "sc": sc, "scj": scj, "scm": scm, "R": R, "T": T,
            "f_done": f_done, "b_done": b_done,
            "max_inflight": max_inflight}


@_freeze_tables
def _make_interleaved_schedule(M: int, P: int, v: int):
    """Forward schedule for interleaved GPipe (Megatron virtual stages):
    D = v*P chunk-stages laid round-robin on P devices (chunk-stage k lives
    on device k % P as local chunk row j = k // P). One op per device per
    tick; drain priority (deepest ready chunk first); chunk-stage k of
    microbatch m runs strictly after k-1 of m. Shrinks the pipeline bubble
    from (P-1)/(M+P-1) toward (P-1)/(vM+P-1): each fill/drain slot costs a
    1/v-stage chunk instead of a full stage.

    Returns numpy tables (T, P): ``jrow``/``mbrow`` (op, -1 = idle),
    ``rflag``/``rj``/``rm`` (landing slot for the activation that arrives
    this tick), plus ``done[k][m]`` tick stamps and ``T``.
    """
    import numpy as np

    D = v * P
    done = [[-1] * M for _ in range(D)]
    nxt = [0] * D
    t = 0
    ops: list[list[tuple[int, int]]] = []
    while any(nxt[k] < M for k in range(D)):
        row = [(-1, -1)] * P
        for s in range(P):
            for j in reversed(range(v)):
                k = j * P + s
                m = nxt[k]
                if m >= M:
                    continue
                if k == 0 or 0 <= done[k - 1][m] < t:
                    row[s] = (j, m)
                    break
        for s in range(P):
            j, m = row[s]
            if j >= 0:
                done[j * P + s][m] = t
                nxt[j * P + s] += 1
        ops.append(row)
        t += 1
        if t > 10 * (M * v + P) + 16:  # pragma: no cover - safety
            raise RuntimeError("interleaved schedule did not converge")
    T = t
    jrow = np.full((T, P), -1, np.int32)
    mbrow = np.zeros((T, P), np.int32)
    for tt, row in enumerate(ops):
        for s in range(P):
            j, m = row[s]
            jrow[tt, s] = j
            mbrow[tt, s] = m if j >= 0 else 0
    # Arrivals: what device s-1 (mod P) ran at t-1 lands on s at t, destined
    # for chunk-stage k+1 = same local row j (or j+1 when wrapping P-1 -> 0).
    # The last chunk-stage's output never lands anywhere (it is the tap).
    rflag = np.zeros((T, P), np.int32)
    rj = np.zeros((T, P), np.int32)
    rm = np.zeros((T, P), np.int32)
    for tt in range(1, T):
        for s in range(P):
            sp = (s - 1) % P
            j, m = ops[tt - 1][sp]
            if j < 0:
                continue
            k_next = j * P + sp + 1
            if k_next >= D:
                continue  # tap, not a hand-off
            assert k_next % P == s
            rflag[tt, s] = 1
            rj[tt, s] = k_next // P
            rm[tt, s] = m
    return {"jrow": jrow, "mbrow": mbrow, "rflag": rflag, "rj": rj,
            "rm": rm, "done": done, "T": T}


class _Embedder(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                     name="tok_emb")(tokens)
        pos = nn.Embed(cfg.max_len, cfg.d_model, dtype=cfg.dtype,
                       name="pos_emb")(jnp.arange(tokens.shape[1])[None, :])
        return x + pos


class _Head(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=self.cfg.dtype, name="ln_f")(x)
        return nn.Dense(self.cfg.vocab_size, dtype=jnp.float32, use_bias=False,
                        name="lm_head")(x)


class PipelinedLM:
    """GPipe LM training over the ``pipe`` (× ``data``) mesh axes."""

    def __init__(self, mesh: Mesh, cfg: TransformerConfig,
                 num_microbatches: int, schedule: str = "gpipe",
                 virtual_chunks: int = 1, fused_ce="auto",
                 ce_chunk: int | None = None, precision=None):
        if schedule not in ("auto", "gpipe", "1f1b"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}")
        if precision is not None:
            # core/precision.py policy: one object sets activation dtype +
            # the selective-remat mode instead of per-call-site dtypes
            from distributed_tensorflow_guide_tpu.core import (
                precision as precision_mod,
            )

            cfg = precision_mod.resolve(precision).apply_to_transformer(cfg)
        sizes = axis_sizes(mesh)
        if schedule == "auto":
            # Measured policy (round-5 on-chip battery): at pipe=1 the 1F1B
            # manual-VJP machinery is pure overhead — GPipe 99,737 vs 1F1B
            # 87,901 tok/s at the judged shape (~12%); at pipe>=2 the 1F1B
            # O(P) in-flight activation cap is what pipelining is for.
            schedule = "gpipe" if sizes["pipe"] == 1 else "1f1b"
        elif schedule == "1f1b" and sizes["pipe"] == 1:
            import logging

            logging.getLogger("dtg.parallel.pipeline").warning(
                "schedule='1f1b' on a single-stage mesh (pipe=1): the "
                "manual-VJP tick machinery is pure overhead with no "
                "in-flight activations to cap (round-5 battery: GPipe "
                "99,737 vs 1F1B 87,901 tok/s). schedule='auto' picks "
                "GPipe here.")
        self.mesh = mesh
        self.cfg = cfg
        self.schedule = schedule
        self.n_stages = sizes["pipe"]
        self.n_data = sizes["data"]
        self.num_microbatches = num_microbatches
        # Interleaved schedules (Megatron virtual stages): each device holds
        # ``virtual_chunks`` non-contiguous layer chunks; chunk-stage
        # k = j*P + s lives on device s as local row j. Fill/drain slots
        # cost a 1/v stage, shrinking the bubble ~v-fold. Under gpipe the
        # autodiff produces the reversed drain (_make_interleaved_schedule);
        # under 1f1b the combined Megatron schedule
        # (_make_interleaved_1f1b_schedule) ALSO keeps the O(P) in-flight
        # memory cap — the production pairing.
        if virtual_chunks < 1:
            raise ValueError(f"virtual_chunks must be >= 1, got {virtual_chunks}")
        if virtual_chunks > 1 and schedule == "1f1b":
            if sizes["pipe"] < 2:
                raise ValueError(
                    "interleaved 1F1B needs pipe >= 2 (got "
                    f"{sizes['pipe']}); gpipe handles the degenerate case"
                )
            if num_microbatches % sizes["pipe"]:
                raise ValueError(
                    f"interleaved 1F1B requires num_microbatches divisible "
                    f"by pipe ({num_microbatches} % {sizes['pipe']} != 0)"
                )
        self.virtual_chunks = virtual_chunks
        n_chunk_stages = self.n_stages * virtual_chunks
        if cfg.num_layers % n_chunk_stages:
            raise ValueError(
                f"{cfg.num_layers} layers not divisible by "
                f"{n_chunk_stages} chunk-stages "
                f"({self.n_stages} stages x {virtual_chunks} chunks)"
            )
        self.layers_per_stage = cfg.num_layers // self.n_stages
        self.layers_per_chunk = cfg.num_layers // n_chunk_stages
        self.embedder = _Embedder(cfg)
        self.head = _Head(cfg)
        self.block = Block(cfg)
        # Chunked fused cross-entropy (ops/fused_ce.py): the loss and its
        # grad-of-logits run per vocab chunk, so the last stage never
        # materializes (mb, S, V) logits — fwd OR bwd. One implementation
        # serves tp=1 and tp>1 (where it subsumes the vocab-parallel path:
        # same chunk loop per shard + the Megatron collective triple).
        # Resolution is per resolve_fused_ce ("auto": TPU + chunkable
        # vocab); the schedules all dispatch through _mb_loss, so the
        # gradient-identity contract is preserved by construction.
        from distributed_tensorflow_guide_tpu.ops.fused_ce import (
            resolve_fused_ce,
        )

        self.fused_ce = resolve_fused_ce(fused_ce,
                                         vocab_size=cfg.vocab_size)
        self.ce_chunk = ce_chunk
        # raw LN for the explicit-params head paths (fused CE at any tp;
        # vocab-parallel CE at tp>1) — the _Head module computes full-vocab
        # logits, which is exactly what those paths avoid
        self._head_ln = nn.LayerNorm(dtype=cfg.dtype)
        # 3D parallelism (dp x tp x pp): when the mesh's ``model`` axis is
        # >1, each pipeline stage's blocks are Megatron-TP-sharded over it —
        # qkv/up kernels column-parallel (heads / d_ff dims), proj/down
        # row-parallel, with the f/g conjugate operators inside the block
        # (models/transformer.py ``tp_axis``) keeping values AND gradients
        # exact inside this strategy's manual-SPMD shard_map. Params are
        # initialized at global shapes and sharded by per-leaf specs
        # (:meth:`param_specs`); each device applies a LOCAL-config block on
        # its (heads/tp, d_ff/tp) shard. The vocab-sized tables shard too:
        # the token embedding is a Megatron parallel embedding
        # (:meth:`_embed_tokens`) and the LM head computes vocab-parallel
        # cross-entropy (:meth:`_mb_loss_fused` with axis="model", or the
        # naive :meth:`_mb_loss_vocab_parallel` when fused CE is off) — no
        # device holds a full-vocab table or materializes full-vocab
        # logits.
        self.tp = sizes["model"]
        if self.tp > 1:
            if cfg.vocab_size % self.tp:
                raise ValueError(
                    f"vocab_size {cfg.vocab_size} must divide by tp "
                    f"{self.tp} (vocab-parallel head)"
                )
            self.block_apply = Block(cfg.tp_local(self.tp, axis="model"))
            abs_block = jax.eval_shape(
                self.block.init,
                jax.random.PRNGKey(0),
                jnp.zeros((1, cfg.max_len, cfg.d_model), cfg.dtype),
            )["params"]
            self._stage_specs_tp = jax.tree_util.tree_map_with_path(
                lambda path, _: self._stage_leaf_spec(path),
                nn.meta.unbox(abs_block),
            )
        else:
            self.block_apply = self.block

    # -- params ---------------------------------------------------------------
    def init_params(self, rng) -> dict:
        """Initialize and lay out onto the mesh. Single-controller path;
        multi-controller callers build global arrays from
        :meth:`init_host_params` (device_put cannot target another
        process's shards)."""
        return jax.device_put(
            self.init_host_params(rng), self.param_shardings()
        )

    def init_params_multihost(self, rng) -> dict:
        """Multi-controller init: every process computes the identical
        host tree (deterministic in ``rng``) and materializes ONLY its
        own shards via ``make_array_from_callback`` — the layout
        ``device_put`` cannot produce when shards live on another
        process's devices. Used by the cross-process pipeline test; the
        entry point for real multi-host training."""
        import numpy as np

        host = jax.tree.map(np.asarray, self.init_host_params(rng))
        full_specs = expand_prefix(self.param_specs(), host)
        return jax.tree.map(
            lambda h, spec: jax.make_array_from_callback(
                h.shape, NamedSharding(self.mesh, spec),
                lambda idx, h=h: h[idx],
            ),
            host, full_specs,
            is_leaf=lambda x: isinstance(x, np.ndarray),
        )

    def init_host_params(self, rng) -> dict:
        """The un-laid-out param tree (deterministic in ``rng`` — every
        process computes identical values, which is what lets
        :meth:`init_params_multihost` slice out per-process shards)."""
        cfg = self.cfg
        r_emb, r_blocks, r_head = jax.random.split(rng, 3)
        dummy_tok = jnp.zeros((1, cfg.max_len), jnp.int32)
        emb = self.embedder.init(r_emb, dummy_tok)["params"]
        dummy_x = jnp.zeros((1, cfg.max_len, cfg.d_model), cfg.dtype)

        keys = jax.random.split(r_blocks, cfg.num_layers)
        stacked = jax.vmap(
            lambda k: self.block.init(k, dummy_x)["params"]
        )(keys)
        v = self.virtual_chunks
        if v == 1:
            stacked = jax.tree.map(
                lambda x: x.reshape(
                    self.n_stages, self.layers_per_stage, *x.shape[1:]
                ),
                stacked,
            )
        else:
            # interleaved chunk order: global row r = s*v + j (the row the
            # contiguous pipe-shard hands device s as local row j) holds the
            # layers of chunk-stage k = j*P + s
            P_, Lc = self.n_stages, self.layers_per_chunk
            order = []
            for r in range(P_ * v):
                s, j = divmod(r, v)
                k = j * P_ + s
                order.extend(range(k * Lc, (k + 1) * Lc))
            idx = jnp.asarray(order)
            stacked = jax.tree.map(
                lambda x: x[idx].reshape(P_ * v, Lc, *x.shape[1:]),
                stacked,
            )
        head = self.head.init(r_head, dummy_x)["params"]
        return {"embed": emb, "stages": stacked, "head": head}

    @staticmethod
    def _stage_leaf_spec(path) -> P:
        """Megatron placement for one stacked stage leaf (dims: row, layer,
        *param). Column-parallel kernels shard their output dim (heads /
        d_ff), row-parallel their input dim; everything else replicates
        over ``model``."""
        names = tuple(
            k.key for k in path if isinstance(k, jax.tree_util.DictKey)
        )
        table = {
            ("attn", "qkv", "kernel"): P("pipe", None, None, None, "model"),
            ("attn", "proj", "kernel"): P("pipe", None, "model"),
            ("mlp", "up", "kernel"): P("pipe", None, None, "model"),
            ("mlp", "up", "bias"): P("pipe", None, "model"),
            ("mlp", "down", "kernel"): P("pipe", None, "model"),
        }
        return table.get(names[-3:], P("pipe"))

    def layout_metadata(self) -> dict:
        """Layout identity for checkpoints (``Checkpointer.save(layout=)``).

        The interleaved stacking permutes layer order inside
        ``params['stages']`` — a (P=2, v=2) tree is shape-identical to a
        (P=4, v=1) tree, so orbax would silently restore one into the
        other with the wrong layer order. This dict pins the layout so
        restore can refuse the mismatch."""
        return {
            "format": "pipelined_lm_stages",
            "n_stages": self.n_stages,
            "virtual_chunks": self.virtual_chunks,
            "layers_per_chunk": self.layers_per_chunk,
            "tp": self.tp,
        }

    def ppermute_bytes_per_step(self, microbatch_size: int) -> float:
        """Closed-form per-device ICI ``ppermute`` traffic of ONE train
        step: every microbatch's activation crosses each of the P−1
        stage boundaries once forward and its gradient once backward, so
        the ring-averaged per-device bytes are

            2 · M · (mb · S · d_model · itemsize) · (P − 1) / P

        — the pipeline leg of the interconnect roofline
        (``benchmarks/common.pipeline_ppermute_bytes`` is the same
        formula; equality pinned in tests/test_overlap.py). Zero at
        P = 1: a single stage hands nothing off."""
        import numpy as np

        act = (microbatch_size * self.cfg.max_len * self.cfg.d_model
               * np.dtype(self.cfg.dtype).itemsize)
        if self.n_stages <= 1:
            return 0.0
        return (2.0 * self.num_microbatches * act
                * (self.n_stages - 1) / self.n_stages)

    def param_specs(self) -> dict:
        """Spec tree: stage stack sharded over pipe (and, when the mesh has
        a ``model`` axis, Megatron-TP over it per leaf; the LM-head kernel
        vocab-sharded over it), rest replicated."""
        if self.tp > 1:
            return {
                "embed": {"tok_emb": P("model"), "pos_emb": P()},
                "stages": self._stage_specs_tp,
                "head": {"ln_f": P(), "lm_head": P(None, "model")},
            }
        return {"embed": P(), "stages": P("pipe"), "head": P()}

    def param_shardings(self):
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            self.param_specs(),
            is_leaf=lambda x: isinstance(x, P),
        )

    def opt_state_specs(self, tx: optax.GradientTransformation, params):
        """Specs for the optimizer state: moment trees (optax state nodes
        that mirror the param tree's structure) inherit the params' full
        spec tree; everything else (counts, scalars) replicates.

        Structural matching, not shape matching: under TP the per-leaf
        stage specs differ BETWEEN same-shaped leaves (e.g. ``mlp/up/bias``
        is model-sharded while an ``ln`` scale of the same shape is
        replicated — they collide whenever d_ff == d_model), which is
        exactly the case ``assign_by_shape``'s docstring disclaims."""
        full = expand_prefix(self.param_specs(), params)
        treedef_p = jax.tree.structure(params)

        def is_param_shaped(node) -> bool:
            try:
                return jax.tree.structure(node) == treedef_p
            except Exception:
                return False

        def specs_for(node):
            if is_param_shaped(node):
                return full
            return jax.tree.map(lambda _: P(), node)

        return jax.tree.map(
            specs_for, jax.eval_shape(tx.init, params),
            is_leaf=is_param_shaped,
        )

    # -- the schedule ---------------------------------------------------------
    def _stage_apply(self, stage_params, x):
        """Run this stage's layer blocks (scan over the stack's rows).

        The remat mode (``cfg.resolved_remat_mode``, settable through a
        core/precision.py policy) reaches the autodiff schedules here:
        under "block" the scan body is checkpointed per block, so
        GPipe/interleaved backward recomputes block internals from block
        boundaries instead of storing every intermediate — the same memory
        contract 1F1B gets from its manual per-stage recompute. The knob is
        deliberately NOT applied under 1F1B: its VJP already recomputes
        from the saved stage input, and checkpointing on top would just
        re-run each block once more per backward tick for no
        residual-memory gain. The "attention" mode (checkpoint only the
        attention sub-layer) lives INSIDE Block, so it applies uniformly to
        every schedule including 1F1B's per-tick recompute.
        prevent_cse=False as in models/transformer.py — the body lives
        inside lax.scan, where the CSE barriers are unnecessary.
        """

        def body(h, layer_params):
            return self.block_apply.apply({"params": layer_params}, h), None

        if (self.cfg.resolved_remat_mode == "block"
                and self.schedule != "1f1b"):
            body = jax.checkpoint(body, prevent_cse=False)
        out, _ = lax.scan(body, x, stage_params)
        return out

    def _embed_tokens(self, embed_params, tokens):
        """(B, S) int32 -> (B, S, D) cfg.dtype — THE embedding path, shared
        by the all-microbatch forward and the 1F1B embed-grad branches.

        Under TP the token table is vocab-sharded over ``model`` (Megatron
        parallel embedding): each device holds V/tp rows, looks up only
        the tokens that fall in its slice (masked gather), and one
        ``tp_allreduce`` (psum fwd, identity bwd — so each shard's rows
        receive exactly their own cotangents) assembles the full
        embedding. Positional table stays replicated (max_len × D is
        small)."""
        cfg = self.cfg
        if self.tp > 1:
            v_local = cfg.vocab_size // self.tp
            shard = lax.axis_index("model")
            W = embed_params["tok_emb"]["embedding"]  # (V/tp, D) local
            local_id = tokens - shard * v_local
            ok = (local_id >= 0) & (local_id < v_local)
            # cast to the activation dtype BEFORE the collective: matches
            # nn.Embed's compute dtype on the tp=1 path and halves the
            # psum's wire bytes under bf16
            e_local = (
                W[jnp.clip(local_id, 0, v_local - 1)]
                * ok[..., None].astype(W.dtype)
            ).astype(cfg.dtype)
            e = cc.tp_allreduce(e_local, "model")
            pos = embed_params["pos_emb"]["embedding"][
                jnp.arange(tokens.shape[1])
            ][None].astype(cfg.dtype)
            return e + pos
        return self.embedder.apply(
            {"params": embed_params}, tokens
        ).astype(cfg.dtype)

    def _embed_all(self, embed_params, tokens_mbs):
        """Embed all M microbatches at once: (M, mb, S) -> (M, mb, S, D)."""
        M, mb, S = tokens_mbs.shape
        flat = tokens_mbs.reshape(M * mb, S)
        e = self._embed_tokens(embed_params, flat)
        return e.reshape(M, mb, S, self.cfg.d_model)

    def _head_loss_sum(self, head_params, finals, tokens_mbs):
        """Sum of per-microbatch head losses — the single implementation
        both the plain and interleaved GPipe paths dispatch to on the last
        stage (a scan over microbatches, so logits memory stays at one)."""
        def body(acc, inp):
            x, toks = inp
            return acc + self._mb_loss(head_params, x, toks), None

        total, _ = lax.scan(body, jnp.float32(0.0), (finals, tokens_mbs))
        return total

    def _mb_loss(self, head_params, x, toks):
        """Head + next-token NLL for one microbatch's final activations.

        The single definition shared by every schedule — the schedules are
        contractually gradient-identical, so the loss math must not fork.
        With ``fused_ce`` on, the chunked fused cross-entropy serves tp=1
        AND tp>1 (one implementation, ``axis`` toggles the Megatron
        collectives); otherwise TP dispatches to the naive vocab-parallel
        cross-entropy and tp=1 to the full-logits head.
        """
        if self.fused_ce:
            return self._mb_loss_fused(head_params, x, toks)
        if self.tp > 1:
            return self._mb_loss_vocab_parallel(head_params, x, toks)
        logits = self.head.apply({"params": head_params}, x)
        logp = jax.nn.log_softmax(logits[:, :-1])
        ll = jnp.take_along_axis(
            logp, toks[:, 1:][..., None], axis=-1
        )[..., 0]
        return -jnp.mean(ll)

    def _mb_loss_fused(self, head_params, x, toks):
        """Chunked fused CE (ops/fused_ce.py): head matmul, online
        log-sum-exp, target gather and grad-of-logits all run per vocab
        chunk under one custom_vjp — no (mb, S, V) tensor live in fwd or
        bwd, which at GPT-2's 50304 vocab is the last stage's dominant
        HBM term. Under tp>1 the kernel is this device's vocab shard and
        ``axis="model"`` turns on the collective triple + dx psum,
        subsuming :meth:`_mb_loss_vocab_parallel`."""
        from distributed_tensorflow_guide_tpu.ops.fused_ce import (
            fused_next_token_loss,
        )

        xh = self._head_ln.apply({"params": head_params["ln_f"]}, x)
        kernel = head_params["lm_head"]["kernel"]  # (D, V/tp) local shard
        return fused_next_token_loss(
            xh, kernel, toks, chunk=self.ce_chunk,
            axis="model" if self.tp > 1 else None)

    def _mb_loss_vocab_parallel(self, head_params, x, toks):
        """Megatron vocab-parallel cross-entropy (Shoeybi et al. 2019 §3):
        the LM-head kernel is sharded over ``model`` along VOCAB, each
        device computes logits for its vocab slice only, and the NLL is
        assembled from three scalar-field collectives — max (stability),
        sum-exp (partition function), and the target logit (owned by
        exactly one shard). No device ever materializes (S, V) logits:
        peak logits memory drops by the TP degree, which at GPT-2's 50304
        vocab is the dominant activation on the last stage.

        Collective gradient discipline (same as the block f/g pairing):
        ``tp_allreduce`` (psum fwd, identity bwd) assembles the replicated
        scalars so each device's local-loss cotangent stays 1; the input
        ``x`` passes through ``tp_identity`` (identity fwd, psum bwd) so
        dx sums every shard's vocab-slice contribution; the stabilizer max
        is gradient-stopped (exact for logsumexp).
        """
        cfg = self.cfg
        f32 = jnp.float32
        v_local = cfg.vocab_size // self.tp
        shard = lax.axis_index("model")
        xh = self._head_ln.apply({"params": head_params["ln_f"]}, x)
        xh = cc.tp_identity(xh, "model")
        kernel = head_params["lm_head"]["kernel"]  # (D, V/tp) local shard
        # f32 head matmul — same computation dtype _Head's Dense pins
        z = xh[:, :-1].astype(f32) @ kernel.astype(f32)
        targets = toks[:, 1:]
        # stop_gradient BEFORE the collective: pmax has no differentiation
        # rule, and the logsumexp stabilizer is exact with zero gradient
        m = cc.pmax(
            lax.stop_gradient(jnp.max(z, axis=-1)), "model"
        )  # (B, S-1)
        sumexp = cc.tp_allreduce(
            jnp.sum(jnp.exp(z - m[..., None]), axis=-1), "model"
        )
        lse = jnp.log(sumexp) + m
        local_t = targets - shard * v_local
        in_shard = (local_t >= 0) & (local_t < v_local)
        t_clamped = jnp.clip(local_t, 0, v_local - 1)
        z_t_local = jnp.take_along_axis(
            z, t_clamped[..., None], axis=-1
        )[..., 0]
        z_t = cc.tp_allreduce(jnp.where(in_shard, z_t_local, 0.0), "model")
        return jnp.mean(lse - z_t)

    def _pipeline_loss(self, params, tokens_mbs):
        """Per-device pipeline forward + LM loss.

        tokens_mbs: (M, mb, S) — this data-shard's microbatches.
        Returns mean next-token loss over all microbatches.

        FLOP discipline (round-3 restructure): the embedder runs ONCE for all
        M microbatches and only on stage 0; the head runs ONCE per microbatch
        and only on the last stage. Both owner-only paths use ``lax.cond``,
        which executes a single branch at runtime — non-owning stages pay
        nothing. The tick loop itself contains only block compute + one
        neighbor ppermute; completed last-stage activations are carried out
        of the scan as its ys and consumed by a post-scan head loop (a scan
        over microbatches, so logits memory stays at one microbatch).
        """
        cfg = self.cfg
        M, mb, S = tokens_mbs.shape
        n_stages = self.n_stages
        stage = lax.axis_index("pipe")
        stage_params = jax.tree.map(lambda x: x[0], params["stages"])
        fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        embeds = lax.cond(
            stage == 0,
            lambda: self._embed_all(params["embed"], tokens_mbs),
            lambda: jnp.zeros((M, mb, S, cfg.d_model), cfg.dtype),
        )

        def tick(received, t):
            # stage 0 injects microbatch t (clamped during drain ticks)
            inject_idx = jnp.clip(t, 0, M - 1)
            x_inject = lax.dynamic_index_in_dim(
                embeds, inject_idx, axis=0, keepdims=False
            )
            x_in = jnp.where(stage == 0, x_inject, received)
            x_out = self._stage_apply(stage_params, x_in)
            received = cc.ppermute(x_out, "pipe", fwd)
            return received, x_out

        x0 = jnp.zeros((mb, S, cfg.d_model), cfg.dtype)
        _, taps = lax.scan(tick, x0, jnp.arange(M + n_stages - 1))
        # On the last stage, tick t completes microbatch m = t-(P-1); the
        # first P-1 ys are fill ticks on every stage.
        taps = taps[n_stages - 1:]  # (M, mb, S, d_model)

        loss_sum = lax.cond(
            stage == n_stages - 1,
            lambda: self._head_loss_sum(params["head"], taps, tokens_mbs),
            lambda: jnp.float32(0.0),
        )
        # LOCAL loss: nonzero only on the last stage. Do NOT psum here — the
        # transpose of psum under shard_map is another psum, which would
        # multiply every cotangent by n_stages. Differentiating the local
        # value is exact: cotangents reach earlier stages back through the
        # ppermute transposes (the backward pipeline). The caller psums the
        # VALUE for reporting.
        return loss_sum / M

    def _pipeline_loss_interleaved(self, params, tokens_mbs):
        """Interleaved-GPipe forward + LM loss (virtual_chunks > 1).

        Same contract as :meth:`_pipeline_loss` (autodiff produces the
        reversed drain), with each device cycling through its ``v`` layer
        chunks per the static table from :func:`_make_interleaved_schedule`.
        Landing buffer is a full (v*M) grid — the same order of memory as
        the autodiff residuals GPipe keeps anyway. Idle fill/drain ticks
        are FREE at runtime (``lax.cond`` executes one branch; note static
        FLOP counters that model cond as max-of-branches still charge
        them); embed and head stay owner-only and once-per-microbatch,
        preserving the round-3 FLOP discipline.
        """
        cfg = self.cfg
        M, mb, S = tokens_mbs.shape
        P_, v = self.n_stages, self.virtual_chunks
        Lc = self.layers_per_chunk
        stage = lax.axis_index("pipe")
        local_stack = params["stages"]  # (v, Lc, ...) per device
        fwd = [(i, (i + 1) % P_) for i in range(P_)]
        sched = _make_interleaved_schedule(M, P_, v)

        embeds = lax.cond(
            stage == 0,
            lambda: self._embed_all(params["embed"], tokens_mbs),
            lambda: jnp.zeros((M, mb, S, cfg.d_model), cfg.dtype),
        )
        x_zero = jnp.zeros((mb, S, cfg.d_model), cfg.dtype)
        buf0 = jnp.zeros((v * M, mb, S, cfg.d_model), cfg.dtype)

        def tick(carry, xs):
            buf, x_in = carry
            jr, mr, rf, rjr, rmr = xs
            j = jnp.take(jr, stage)
            m = jnp.take(mr, stage)

            # land last tick's arrival in its (chunk, microbatch) slot
            slot_r = jnp.take(rjr, stage) * M + jnp.take(rmr, stage)
            cur = lax.dynamic_index_in_dim(buf, slot_r, 0, keepdims=False)
            new = jnp.where(jnp.take(rf, stage).astype(bool), x_in, cur)
            buf = lax.dynamic_update_index_in_dim(buf, new, slot_r, 0)

            # this tick's op; lax.cond executes ONE branch, so idle
            # fill/drain ticks cost no chunk FLOPs (same discipline as the
            # 1F1B switch — collectives stay outside the cond)
            jc = jnp.clip(j, 0, v - 1)
            mc = jnp.clip(m, 0, M - 1)

            def run_chunk():
                x_src = lax.dynamic_index_in_dim(buf, jc * M + mc, 0,
                                                 keepdims=False)
                x_emb = lax.dynamic_index_in_dim(embeds, mc, 0,
                                                 keepdims=False)
                is_entry = (stage == 0) & (jc == 0)  # chunk-stage 0 injects
                x = jnp.where(is_entry, x_emb, x_src)
                chunk_params = jax.tree.map(
                    lambda p: lax.dynamic_index_in_dim(p, jc, 0,
                                                       keepdims=False),
                    local_stack,
                )
                return self._stage_apply(chunk_params, x)

            x_out = lax.cond(j >= 0, run_chunk, lambda: x_zero)
            nxt = cc.ppermute(x_out, "pipe", fwd)
            return (buf, nxt), x_out

        xs = tuple(
            jnp.asarray(sched[k])
            for k in ("jrow", "mbrow", "rflag", "rj", "rm")
        )
        (_, _), taps = lax.scan(tick, (buf0, x_zero), xs)

        # microbatch m's final activations appear on device P-1 at the tick
        # its last chunk-stage ran
        tick_idx = jnp.asarray(
            [sched["done"][P_ * v - 1][m] for m in range(M)], jnp.int32
        )
        finals = taps[tick_idx]  # (M, mb, S, d) — meaningful on stage P-1

        loss_sum = lax.cond(
            stage == P_ - 1,
            lambda: self._head_loss_sum(params["head"], finals, tokens_mbs),
            lambda: jnp.float32(0.0),
        )
        return loss_sum / M  # local; caller psums the VALUE (see above)

    # -- 1F1B schedule (manual VJP) -------------------------------------------
    def _loss_and_grads_1f1b(self, params, tokens_mbs):
        """Per-device 1F1B pipeline: ``(params, (M, mb, S)) -> (loss, grads)``.

        GPipe (``_pipeline_loss`` + ``jax.grad``) runs all M forwards, then
        all M backwards — activation residuals for every microbatch are live
        at the peak. 1F1B interleaves: after a warmup of min(P-s, M)
        forwards, each stage strictly alternates backward/forward, so at most
        ~P microbatches are ever in flight and the residual ring buffer is
        O(P), not O(M). The schedule is a STATIC table (``_make_1f1b_schedule``)
        consumed as scan xs — no data-dependent control flow reaches XLA; the
        per-tick op dispatch is one ``lax.switch``.

        Backward here is hand-written (jax.vjp per tick) because autodiff
        through the forward scan can only produce the all-forward-then-
        all-backward order. Stage backward recomputes its forward from the
        saved stage INPUT (per-stage remat — the 1F1B memory contract).
        Collectives stay OUTSIDE the switch: every tick unconditionally
        ppermutes one activation forward and one cotangent backward (zeros
        when idle), so every device always participates.
        """
        cfg = self.cfg
        M, mb, S = tokens_mbs.shape
        P_ = self.n_stages
        stage = lax.axis_index("pipe")
        stage_params = jax.tree.map(lambda x: x[0], params["stages"])
        fwd_perm = [(i, (i + 1) % P_) for i in range(P_)]
        bwd_perm = [(i, (i - 1) % P_) for i in range(P_)]
        sched = _make_1f1b_schedule(M, P_)
        R = sched["R"]

        embeds = lax.cond(
            stage == 0,
            lambda: self._embed_all(params["embed"], tokens_mbs),
            lambda: jnp.zeros((M, mb, S, cfg.d_model), cfg.dtype),
        )

        def stage_fn(sp, x):
            return self._stage_apply(sp, x)

        def last_stage_loss(sp, hp, x, toks):
            out = self._stage_apply(sp, x)
            return self._mb_loss(hp, out, toks) / M  # total loss = sum_m this

        f32 = jnp.float32
        zero_g = {
            "embed": jax.tree.map(lambda p: jnp.zeros(p.shape, f32),
                                  params["embed"]),
            "stage": jax.tree.map(lambda p: jnp.zeros(p.shape, f32),
                                  stage_params),
            "head": jax.tree.map(lambda p: jnp.zeros(p.shape, f32),
                                 params["head"]),
        }
        buf = jnp.zeros((R, mb, S, cfg.d_model), cfg.dtype)
        x_zero = jnp.zeros((mb, S, cfg.d_model), cfg.dtype)

        def tick(carry, xs):
            act_buf, cot_buf, resid_buf, act_in, cot_in, g_acc, loss_acc = carry
            op_row, mb_row, sa_row, sam_row, sc_row, scm_row = xs
            op = jnp.take(op_row, stage)
            m = jnp.take(mb_row, stage)

            # 1) land last tick's arrivals in their ring-buffer slots
            def land(buf_, val, flag, slot):
                cur = lax.dynamic_index_in_dim(buf_, slot, 0, keepdims=False)
                new = jnp.where(flag.astype(bool), val, cur)
                return lax.dynamic_update_index_in_dim(buf_, new, slot, 0)

            act_buf = land(act_buf, act_in, jnp.take(sa_row, stage),
                           jnp.take(sam_row, stage) % R)
            cot_buf = land(cot_buf, cot_in, jnp.take(sc_row, stage),
                           jnp.take(scm_row, stage) % R)

            slot = m % R
            toks = lax.dynamic_index_in_dim(
                tokens_mbs, jnp.clip(m, 0, M - 1), axis=0, keepdims=False
            )

            # 2) this tick's op
            def do_idle(resid_buf, g_acc, loss_acc):
                return resid_buf, g_acc, loss_acc, x_zero, x_zero

            def do_fwd(resid_buf, g_acc, loss_acc):
                x_prev = lax.dynamic_index_in_dim(act_buf, slot, 0,
                                                  keepdims=False)
                x_emb = lax.dynamic_index_in_dim(
                    embeds, jnp.clip(m, 0, M - 1), axis=0, keepdims=False
                )
                x_in = jnp.where(stage == 0, x_emb, x_prev)
                resid_buf = lax.dynamic_update_index_in_dim(
                    resid_buf, x_in, slot, 0
                )
                x_out = stage_fn(stage_params, x_in)
                return resid_buf, g_acc, loss_acc, x_out, x_zero

            def do_bwd(resid_buf, g_acc, loss_acc):
                x_in = lax.dynamic_index_in_dim(resid_buf, slot, 0,
                                                keepdims=False)

                def last_branch():
                    loss_m, vjp = jax.vjp(
                        lambda sp, hp, x: last_stage_loss(sp, hp, x, toks),
                        stage_params, params["head"], x_in,
                    )
                    d_sp, d_hp, dx = vjp(f32(1.0))
                    return loss_m, d_sp, d_hp, dx

                def mid_branch():
                    g_out = lax.dynamic_index_in_dim(cot_buf, slot, 0,
                                                     keepdims=False)
                    _, vjp = jax.vjp(stage_fn, stage_params, x_in)
                    d_sp, dx = vjp(g_out)
                    return f32(0.0), d_sp, zero_g["head"], dx

                loss_m, d_sp, d_hp, dx = lax.cond(
                    stage == P_ - 1, last_branch, mid_branch
                )

                def embed_branch():
                    _, evjp = jax.vjp(
                        lambda ep: self._embed_tokens(ep, toks),
                        params["embed"],
                    )
                    (d_emb,) = evjp(dx)
                    return jax.tree.map(lambda g: g.astype(f32), d_emb)

                d_emb = lax.cond(
                    stage == 0, embed_branch, lambda: zero_g["embed"]
                )
                g_acc = {
                    "embed": jax.tree.map(jnp.add, g_acc["embed"], d_emb),
                    "stage": jax.tree.map(
                        lambda a, g: a + g.astype(f32), g_acc["stage"], d_sp
                    ),
                    "head": jax.tree.map(
                        lambda a, g: a + g.astype(f32), g_acc["head"], d_hp
                    ),
                }
                return resid_buf, g_acc, loss_acc + loss_m, x_zero, dx

            resid_buf, g_acc, loss_acc, send_act, send_cot = lax.switch(
                op, [do_idle, do_fwd, do_bwd], resid_buf, g_acc, loss_acc
            )

            # 3) unconditional neighbor exchange (zeros when idle)
            act_in = cc.ppermute(send_act, "pipe", fwd_perm)
            cot_in = cc.ppermute(send_cot, "pipe", bwd_perm)
            return (act_buf, cot_buf, resid_buf, act_in, cot_in, g_acc,
                    loss_acc), None

        xs = tuple(
            jnp.asarray(sched[k]) for k in ("op", "mb", "sa", "sam", "sc",
                                            "scm")
        )
        (_, _, _, _, _, g_acc, loss_acc), _ = lax.scan(
            tick, (buf, buf, buf, x_zero, x_zero, zero_g, f32(0.0)), xs
        )
        grads = {
            "embed": g_acc["embed"],
            "stages": jax.tree.map(lambda g: g[None], g_acc["stage"]),
            "head": g_acc["head"],
        }
        return loss_acc, grads

    # -- interleaved 1F1B (manual VJP, v chunks per device) --------------------
    def _loss_and_grads_1f1b_interleaved(self, params, tokens_mbs):
        """Per-device interleaved-1F1B: Megatron's combined schedule
        (virtual chunks × 1F1B) as one static-table scan — the O(P)
        in-flight cap of :meth:`_loss_and_grads_1f1b` AND the ~v-fold
        bubble shrink of :meth:`_pipeline_loss_interleaved` together.

        Differences from the v=1 tick loop: the op dispatch carries a local
        chunk row ``j`` (chunk-stage k = j*P + s), chunk params are gathered
        from the (v, Lc, ...) local stack per tick, ring-buffer slots are
        keyed by (j*M + m) % R, and the embed/head ownership predicates
        sharpen from ``stage == 0`` / ``stage == P-1`` to chunk-stage 0 /
        chunk-stage D-1 (i.e. also require j == 0 / j == v-1). Collectives
        stay OUTSIDE the switch: one activation ppermute forward and one
        cotangent ppermute backward per tick, zeros when idle.
        """
        cfg = self.cfg
        M, mb, S = tokens_mbs.shape
        P_, v = self.n_stages, self.virtual_chunks
        stage = lax.axis_index("pipe")
        local_stack = params["stages"]  # (v, Lc, ...) per device
        fwd_perm = [(i, (i + 1) % P_) for i in range(P_)]
        bwd_perm = [(i, (i - 1) % P_) for i in range(P_)]
        sched = _make_interleaved_1f1b_schedule(M, P_, v)
        R = sched["R"]

        embeds = lax.cond(
            stage == 0,
            lambda: self._embed_all(params["embed"], tokens_mbs),
            lambda: jnp.zeros((M, mb, S, cfg.d_model), cfg.dtype),
        )

        def chunk_fn(cp, x):
            return self._stage_apply(cp, x)

        def last_chunk_loss(cp, hp, x, toks):
            out = self._stage_apply(cp, x)
            return self._mb_loss(hp, out, toks) / M

        f32 = jnp.float32
        zero_g = {
            "embed": jax.tree.map(lambda p: jnp.zeros(p.shape, f32),
                                  params["embed"]),
            "stages": jax.tree.map(lambda p: jnp.zeros(p.shape, f32),
                                   local_stack),
            "head": jax.tree.map(lambda p: jnp.zeros(p.shape, f32),
                                 params["head"]),
        }
        buf = jnp.zeros((R, mb, S, cfg.d_model), cfg.dtype)
        x_zero = jnp.zeros((mb, S, cfg.d_model), cfg.dtype)

        def tick(carry, xs):
            act_buf, cot_buf, resid_buf, act_in, cot_in, g_acc, loss_acc = carry
            (op_row, jr_row, mb_row, sa_row, saj_row, sam_row,
             sc_row, scj_row, scm_row) = xs
            op = jnp.take(op_row, stage)
            j = jnp.take(jr_row, stage)
            m = jnp.take(mb_row, stage)

            # 1) land last tick's arrivals in their (chunk, microbatch) slots
            def land(buf_, val, flag, jrow, mrow):
                slot = (jnp.take(jrow, stage) * M + jnp.take(mrow, stage)) % R
                cur = lax.dynamic_index_in_dim(buf_, slot, 0, keepdims=False)
                new = jnp.where(flag.astype(bool), val, cur)
                return lax.dynamic_update_index_in_dim(buf_, new, slot, 0)

            act_buf = land(act_buf, act_in, jnp.take(sa_row, stage),
                           saj_row, sam_row)
            cot_buf = land(cot_buf, cot_in, jnp.take(sc_row, stage),
                           scj_row, scm_row)

            slot = (j * M + m) % R
            is_first = (stage == 0) & (j == 0)        # chunk-stage 0
            is_last = (stage == P_ - 1) & (j == v - 1)  # chunk-stage D-1

            # The chunk-params gather and token slice live INSIDE the switch
            # branches (mirroring run_chunk in the gpipe-interleaved path):
            # lax.cond/switch executes one branch, so idle fill/drain ticks
            # pay neither the chunk-stack copy nor anything else.
            def gather_chunk():
                return jax.tree.map(
                    lambda p: lax.dynamic_index_in_dim(p, j, 0,
                                                       keepdims=False),
                    local_stack,
                )

            # 2) this tick's op
            def do_idle(resid_buf, g_acc, loss_acc):
                return resid_buf, g_acc, loss_acc, x_zero, x_zero

            def do_fwd(resid_buf, g_acc, loss_acc):
                chunk_params = gather_chunk()
                x_prev = lax.dynamic_index_in_dim(act_buf, slot, 0,
                                                  keepdims=False)
                x_emb = lax.dynamic_index_in_dim(embeds, m, axis=0,
                                                 keepdims=False)
                x_in = jnp.where(is_first, x_emb, x_prev)
                resid_buf = lax.dynamic_update_index_in_dim(
                    resid_buf, x_in, slot, 0
                )
                x_out = chunk_fn(chunk_params, x_in)
                return resid_buf, g_acc, loss_acc, x_out, x_zero

            def do_bwd(resid_buf, g_acc, loss_acc):
                chunk_params = gather_chunk()
                toks = lax.dynamic_index_in_dim(tokens_mbs, m, axis=0,
                                                keepdims=False)
                x_in = lax.dynamic_index_in_dim(resid_buf, slot, 0,
                                                keepdims=False)

                def last_branch():
                    loss_m, vjp = jax.vjp(
                        lambda cp, hp, x: last_chunk_loss(cp, hp, x, toks),
                        chunk_params, params["head"], x_in,
                    )
                    d_cp, d_hp, dx = vjp(f32(1.0))
                    return loss_m, d_cp, d_hp, dx

                def mid_branch():
                    g_out = lax.dynamic_index_in_dim(cot_buf, slot, 0,
                                                     keepdims=False)
                    _, vjp = jax.vjp(chunk_fn, chunk_params, x_in)
                    d_cp, dx = vjp(g_out)
                    return f32(0.0), d_cp, zero_g["head"], dx

                loss_m, d_cp, d_hp, dx = lax.cond(
                    is_last, last_branch, mid_branch
                )

                def embed_branch():
                    _, evjp = jax.vjp(
                        lambda ep: self._embed_tokens(ep, toks),
                        params["embed"],
                    )
                    (d_emb,) = evjp(dx)
                    return jax.tree.map(lambda g: g.astype(f32), d_emb)

                d_emb = lax.cond(
                    is_first, embed_branch, lambda: zero_g["embed"]
                )

                def acc_chunk(a, g):
                    cur = lax.dynamic_index_in_dim(a, j, 0, keepdims=False)
                    return lax.dynamic_update_index_in_dim(
                        a, cur + g.astype(f32), j, 0
                    )

                g_acc = {
                    "embed": jax.tree.map(jnp.add, g_acc["embed"], d_emb),
                    "stages": jax.tree.map(acc_chunk, g_acc["stages"], d_cp),
                    "head": jax.tree.map(
                        lambda a, g: a + g.astype(f32), g_acc["head"], d_hp
                    ),
                }
                return resid_buf, g_acc, loss_acc + loss_m, x_zero, dx

            resid_buf, g_acc, loss_acc, send_act, send_cot = lax.switch(
                op, [do_idle, do_fwd, do_bwd], resid_buf, g_acc, loss_acc
            )

            # 3) unconditional neighbor exchange (zeros when idle)
            act_in = cc.ppermute(send_act, "pipe", fwd_perm)
            cot_in = cc.ppermute(send_cot, "pipe", bwd_perm)
            return (act_buf, cot_buf, resid_buf, act_in, cot_in, g_acc,
                    loss_acc), None

        xs = tuple(
            jnp.asarray(sched[k]) for k in ("op", "jr", "mb", "sa", "saj",
                                            "sam", "sc", "scj", "scm")
        )
        (_, _, _, _, _, g_acc, loss_acc), _ = lax.scan(
            tick, (buf, buf, buf, x_zero, x_zero, zero_g, f32(0.0)), xs
        )
        return loss_acc, g_acc

    # -- compiled step --------------------------------------------------------
    def make_train_step(self, tx: optax.GradientTransformation, params,
                        *, donate: bool = True, steps_per_call: int = 1,
                        stacked_batch: bool = False):
        """``(opt_state, params, batch{tokens:(B,S)}) -> (opt_state, params,
        metrics)`` — B = n_data * num_microbatches * microbatch_size.
        ``params`` is used only to derive optimizer-state specs.

        ``steps_per_call > 1`` runs that many optimizer steps inside ONE
        compiled program (``lax.scan`` around the whole pipeline schedule) —
        the same dispatch-amortization knob as
        :meth:`DataParallel._compile_step`: on a remote-attached chip each
        executable launch costs milliseconds of tunnel latency, and a
        pipeline step is ONE launch regardless of its microbatch count, so
        K inner steps cut per-step launch overhead K-fold. With
        ``stacked_batch`` the tokens carry a leading ``steps_per_call``
        axis (one batch slice per inner step — the real-training mode);
        otherwise the same tokens are re-used every inner step (synthetic
        benchmarking mode). Metrics are the LAST inner step's."""
        if steps_per_call < 1:
            raise ValueError(
                f"steps_per_call must be >= 1, got {steps_per_call}")
        if stacked_batch and steps_per_call == 1:
            raise ValueError(
                "stacked_batch requires steps_per_call > 1 (a stacked "
                "batch's leading axis is consumed one slice per inner step)")
        M = self.num_microbatches
        opt_specs = self.opt_state_specs(tx, params)

        def sm_step(opt_state, params, tokens):
            mbs = tokens.reshape(M, tokens.shape[0] // M, tokens.shape[1])
            if self.schedule == "1f1b" and self.virtual_chunks > 1:
                local_loss, grads = self._loss_and_grads_1f1b_interleaved(
                    params, mbs
                )
            elif self.schedule == "1f1b":
                local_loss, grads = self._loss_and_grads_1f1b(params, mbs)
            elif self.virtual_chunks > 1:
                local_loss, grads = jax.value_and_grad(
                    self._pipeline_loss_interleaved
                )(params, mbs)
            else:
                local_loss, grads = jax.value_and_grad(self._pipeline_loss)(
                    params, mbs
                )
            loss = cc.psum(local_loss, "pipe")  # value only; see _pipeline_loss
            # embed/head grads are nonzero only on their owning stage;
            # stage grads are per-stage (no pipe reduction needed)
            grads = {
                "embed": cc.psum(grads["embed"], "pipe"),
                "stages": grads["stages"],
                "head": cc.psum(grads["head"], "pipe"),
            }
            if self.n_data > 1:
                grads = cc.pmean(grads, "data")
                loss = cc.pmean(loss, "data")
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return opt_state, params, {"loss": loss}

        if steps_per_call == 1:
            body = sm_step
            tokens_spec = P("data")
        else:
            def body(opt_state, params, tokens):
                if stacked_batch and tokens.shape[0] != steps_per_call:
                    raise ValueError(
                        f"stacked tokens leading axis {tokens.shape[0]} != "
                        f"steps_per_call={steps_per_call}; the scan would "
                        "silently run a different number of optimizer steps")

                def inner(carry, xs):
                    o, p = carry
                    o, p, m = sm_step(o, p, tokens if xs is None else xs)
                    return (o, p), m

                (opt_state, params), ms = lax.scan(
                    inner, (opt_state, params),
                    tokens if stacked_batch else None,
                    length=None if stacked_batch else steps_per_call)
                return opt_state, params, jax.tree.map(lambda x: x[-1], ms)

            tokens_spec = P(None, "data") if stacked_batch else P("data")

        sharded = shard_map(
            body,
            mesh=self.mesh,
            in_specs=(opt_specs, self.param_specs(), tokens_spec),
            out_specs=(opt_specs, self.param_specs(), P()),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())

    def make_eval_step(self):
        """``(params, tokens) -> {loss, perplexity}`` — the no-grad half for
        :class:`~distributed_tensorflow_guide_tpu.train.evaluation.Evaluator`
        (pass the param tree as the evaluator's ``state``). Forward-only
        GPipe traversal (a backward schedule is a training concern; the
        forward loss is schedule-independent), psum'd across stages,
        pmean'd across data shards."""
        M = self.num_microbatches

        def sm_eval(params, tokens):
            mbs = tokens.reshape(M, tokens.shape[0] // M, tokens.shape[1])
            if self.virtual_chunks > 1:
                local_loss = self._pipeline_loss_interleaved(params, mbs)
            else:
                local_loss = self._pipeline_loss(params, mbs)
            loss = cc.psum(local_loss, "pipe")
            if self.n_data > 1:
                loss = cc.pmean(loss, "data")
            return {"loss": loss, "perplexity": jnp.exp(loss)}

        sharded = shard_map(
            sm_eval,
            mesh=self.mesh,
            in_specs=(self.param_specs(), P("data")),
            out_specs=P(),
            check_vma=False,
        )
        return jax.jit(sharded)

    def to_serving_params(self, params) -> dict:
        """Pipeline param tree -> the flat ``models.transformer.Transformer``
        layout, so a pipeline-trained LM can be served by
        ``models/generation.py`` (or fine-tuned under any other strategy).

        Inverts the stage stacking of :meth:`init_params`: contiguous
        sharding (v=1) stores layers in global order; interleaved stacking
        stores row r = s*v + j as chunk-stage k = j*P + s, inverted here
        with the same index map. Works on host or on-device arrays (the
        gather is a pure indexing program); TP>1 params are global-shaped
        and convert unchanged. Logits parity is pinned by
        tests/test_pipeline.py::test_to_serving_params_logits_parity.
        """
        import numpy as np

        P_, v, Lc = self.n_stages, self.virtual_chunks, self.layers_per_chunk
        L = self.cfg.num_layers

        if v == 1:
            inv = None
        else:
            order = []
            for r in range(P_ * v):
                s, j = divmod(r, v)
                k = j * P_ + s
                order.extend(range(k * Lc, (k + 1) * Lc))
            inv = np.argsort(np.asarray(order))

        def unstack(x):  # (rows, Lc, ...) -> (L, ...) global layer order
            flat = x.reshape(L, *x.shape[2:])
            return flat if inv is None else flat[inv]

        stages = jax.tree.map(unstack, params["stages"])
        out = {
            "tok_emb": params["embed"]["tok_emb"],
            "pos_emb": params["embed"]["pos_emb"],
            "ln_f": params["head"]["ln_f"],
            "lm_head": params["head"]["lm_head"],
        }
        for i in range(L):
            out[f"block_{i}"] = jax.tree.map(lambda x, i=i: x[i], stages)
        return out

    def init_opt_state(self, tx, params):
        """Optimizer state materialized directly into its shard layout."""
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            self.opt_state_specs(tx, params),
            is_leaf=lambda x: isinstance(x, P),
        )
        with self.mesh:
            return jax.jit(tx.init, out_shardings=shardings)(params)


# ---- program contracts (analysis/) ------------------------------------------


def lint_contracts():
    """Contract for the GPipe train step with the fused-CE head: the
    no-full-logits memory pin (no f32 (mb*(S-1), V) intermediate anywhere
    in the schedule) plus the stage-boundary collective census — the
    counts are pinned at the 8-device (data=4, pipe=2, M=2) fixture."""
    from distributed_tensorflow_guide_tpu.analysis.contracts import (
        CostPin,
        CostSpec,
        DonationSpec,
        ProgramContract,
    )
    from distributed_tensorflow_guide_tpu.analysis.cost import closed_forms

    def _ppermute_expect():
        # per-microbatch boundary activation: (1, 32, 16) f32 per device.
        # The comm model counts the M useful handoffs per direction; the
        # static schedule rotates the ring every tick including the
        # (P-1) bubble ticks, so the trace carries (M+P-1)/M of the model
        m, p = 2, 2
        act_bytes = 1 * 32 * 16 * 4
        common = closed_forms()
        return (common.pipeline_ppermute_bytes(act_bytes, m, p)
                * (m + p - 1) / m)

    def _build():
        import jax
        import optax

        from distributed_tensorflow_guide_tpu.analysis.fixtures import (
            tiny_lm_cfg,
        )
        from distributed_tensorflow_guide_tpu.core.mesh import (
            MeshSpec,
            build_mesh,
        )

        # max_len=32 so one microbatch spans 31 target rows — ABOVE the
        # 16-row CE chunk; the vocab_rows floor can then admit the chunk
        # logits while still catching a full-logits regression
        cfg = tiny_lm_cfg(vocab_size=80, max_len=32)
        mesh = build_mesh(MeshSpec(data=4, pipe=2))
        pp = PipelinedLM(mesh, cfg, num_microbatches=2, fused_ce=True,
                         ce_chunk=16)
        params = jax.eval_shape(pp.init_host_params, jax.random.PRNGKey(0))
        tx = optax.sgd(0.1)
        opt_state = jax.eval_shape(tx.init, params)
        step = pp.make_train_step(tx, params, donate=True)
        tokens = jax.ShapeDtypeStruct((8, 32), "int32")
        return step, (opt_state, params, tokens)

    return [
        ProgramContract(
            name="pipeline_fused_ce_train_step",
            build=_build,
            policy="f32",
            vocab_dim=80,
            vocab_rows=17,  # > ce_chunk(16), <= microbatch rows (31)
            max_vocab_f32_elems=0,
            collectives={
                # one activation handoff + its backward transpose (M=2,
                # P=2 — the schedule fuses per-tick sends into one pair)
                "ppermute[pipe]": 2,
                # loss + embed-grad + head-grad reductions over pipe
                "psum[pipe]": 3,
                # grad-tree pmean + loss pmean over data
                "psum[data]": 2,
            },
            donation=DonationSpec(argnums=(0, 1)),
            sources=(
                "distributed_tensorflow_guide_tpu.parallel.pipeline",
                "distributed_tensorflow_guide_tpu.ops.fused_ce",
                "distributed_tensorflow_guide_tpu.collectives.collectives",
            ),
            cost=CostSpec(
                pins=(
                    CostPin("collective_bytes[ppermute[pipe]]",
                            _ppermute_expect,
                            note="stage-boundary ring traffic incl. the "
                                 "bubble-tick rotations"),
                ),
                # 549,822 observed per device (params + M in-flight
                # microbatch activation stacks + fused-CE bwd workspace)
                max_peak_live_bytes=655360),
            notes="GPipe schedule + fused-CE head: no full logits, "
                  "bounded stage-boundary traffic"),
    ]
