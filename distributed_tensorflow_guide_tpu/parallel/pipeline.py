"""Pipeline parallelism — judged config 5: "GPT-2 124M pipeline-parallel
across a v5e-16 pod slice" (BASELINE.md).

No pipeline exists in the reference (SURVEY.md §2c). Design: GPipe microbatch
schedule (Huang et al. 2019) expressed as ONE compiled SPMD program — the
pipeline "stages" are not processes (the reference's only composition
mechanism) but shards of a stacked-layer parameter tree over the ``pipe``
mesh axis, and the stage-to-stage hand-off is a single ICI-neighbor
``lax.ppermute`` per tick inside a ``lax.scan``:

    tick t:  stage 0 injects microbatch t | stage s runs layers on the
             activation it received at t-1 | everyone ppermutes output to s+1

    M microbatches, P stages → M+P-1 ticks; bubble fraction (P-1)/(M+P-1).

Differentiating *through* the scan+ppermute gives the backward pipeline for
free (ppermute's transpose is the reverse ppermute) — no hand-written
backward schedule, no send/recv pairs to keep in sync.

Embedding params live logically on stage 0 and head params on stage P-1:
every stage holds a copy, but only the owning stage's compute reaches the
loss, so the others' grads are structurally zero and one ``psum`` over
``pipe`` reconstitutes the true gradient. Composes with data parallelism
(``data`` axis pmean) in the same shard_map.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

import distributed_tensorflow_guide_tpu.collectives as cc
from distributed_tensorflow_guide_tpu.core.mesh import axis_sizes
from distributed_tensorflow_guide_tpu.utils.spec_utils import (
    assign_by_shape,
    expand_prefix,
)
from distributed_tensorflow_guide_tpu.models.transformer import (
    Block,
    TransformerConfig,
)


class _Embedder(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                     name="tok_emb")(tokens)
        pos = nn.Embed(cfg.max_len, cfg.d_model, dtype=cfg.dtype,
                       name="pos_emb")(jnp.arange(tokens.shape[1])[None, :])
        return x + pos


class _Head(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=self.cfg.dtype, name="ln_f")(x)
        return nn.Dense(self.cfg.vocab_size, dtype=jnp.float32, use_bias=False,
                        name="lm_head")(x)


class PipelinedLM:
    """GPipe LM training over the ``pipe`` (× ``data``) mesh axes."""

    def __init__(self, mesh: Mesh, cfg: TransformerConfig,
                 num_microbatches: int):
        self.mesh = mesh
        self.cfg = cfg
        sizes = axis_sizes(mesh)
        self.n_stages = sizes["pipe"]
        self.n_data = sizes["data"]
        self.num_microbatches = num_microbatches
        if cfg.num_layers % self.n_stages:
            raise ValueError(
                f"{cfg.num_layers} layers not divisible by {self.n_stages} stages"
            )
        self.layers_per_stage = cfg.num_layers // self.n_stages
        self.embedder = _Embedder(cfg)
        self.head = _Head(cfg)
        self.block = Block(cfg)

    # -- params ---------------------------------------------------------------
    def init_params(self, rng) -> dict:
        cfg = self.cfg
        r_emb, r_blocks, r_head = jax.random.split(rng, 3)
        dummy_tok = jnp.zeros((1, cfg.max_len), jnp.int32)
        emb = self.embedder.init(r_emb, dummy_tok)["params"]
        dummy_x = jnp.zeros((1, cfg.max_len, cfg.d_model), cfg.dtype)

        keys = jax.random.split(r_blocks, cfg.num_layers)
        stacked = jax.vmap(
            lambda k: self.block.init(k, dummy_x)["params"]
        )(keys)
        stacked = jax.tree.map(
            lambda x: x.reshape(self.n_stages, self.layers_per_stage, *x.shape[1:]),
            stacked,
        )
        head = self.head.init(r_head, dummy_x)["params"]
        params = {"embed": emb, "stages": stacked, "head": head}
        return jax.device_put(params, self.param_shardings())

    def param_specs(self) -> dict:
        """Prefix spec tree: stage stack sharded over pipe, rest replicated."""
        return {"embed": P(), "stages": P("pipe"), "head": P()}

    def param_shardings(self):
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            self.param_specs(),
            is_leaf=lambda x: isinstance(x, P),
        )

    def opt_state_specs(self, tx: optax.GradientTransformation, params):
        """Specs for the optimizer state: moments inherit their param's spec
        (matched by shape+dtype — stage stacks have a distinctive leading
        n_stages dim), counts/scalars replicate."""
        full = expand_prefix(self.param_specs(), params)
        return assign_by_shape(params, full, jax.eval_shape(tx.init, params), P())

    # -- the schedule ---------------------------------------------------------
    def _stage_apply(self, stage_params, x):
        """Run this stage's ``layers_per_stage`` blocks (scan over layers)."""

        def body(h, layer_params):
            return self.block.apply({"params": layer_params}, h), None

        out, _ = lax.scan(body, x, stage_params)
        return out

    def _pipeline_loss(self, params, tokens_mbs):
        """Per-device pipeline forward + LM loss.

        tokens_mbs: (M, mb, S) — this data-shard's microbatches.
        Returns mean next-token loss over all microbatches.
        """
        cfg = self.cfg
        M, mb, S = tokens_mbs.shape
        n_stages = self.n_stages
        stage = lax.axis_index("pipe")
        stage_params = jax.tree.map(lambda x: x[0], params["stages"])
        fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            received, loss_sum = carry
            # stage 0 injects microbatch t (clamped during drain ticks)
            inject_idx = jnp.clip(t, 0, M - 1)
            toks_in = lax.dynamic_index_in_dim(
                tokens_mbs, inject_idx, axis=0, keepdims=False
            )
            injected = self.embedder.apply({"params": params["embed"]}, toks_in)
            x_in = jnp.where(stage == 0, injected, received)
            x_out = self._stage_apply(stage_params, x_in)

            # last stage finishes microbatch m = t - (P-1)
            m_idx = t - (n_stages - 1)
            valid = jnp.logical_and(stage == n_stages - 1, m_idx >= 0)
            toks_out = lax.dynamic_index_in_dim(
                tokens_mbs, jnp.clip(m_idx, 0, M - 1), axis=0, keepdims=False
            )
            logits = self.head.apply({"params": params["head"]}, x_out)
            logp = jax.nn.log_softmax(logits[:, :-1])
            ll = jnp.take_along_axis(
                logp, toks_out[:, 1:][..., None], axis=-1
            )[..., 0]
            mb_loss = -jnp.mean(ll)
            loss_sum = loss_sum + jnp.where(valid, mb_loss, 0.0)

            received = cc.ppermute(x_out, "pipe", fwd)
            return (received, loss_sum), None

        x0 = jnp.zeros((mb, S, cfg.d_model), cfg.dtype)
        (_, loss_sum), _ = lax.scan(
            tick, (x0, jnp.float32(0.0)), jnp.arange(M + n_stages - 1)
        )
        # LOCAL loss: nonzero only on the last stage. Do NOT psum here — the
        # transpose of psum under shard_map is another psum, which would
        # multiply every cotangent by n_stages. Differentiating the local
        # value is exact: cotangents reach earlier stages back through the
        # ppermute transposes (the backward pipeline). The caller psums the
        # VALUE for reporting.
        return loss_sum / M

    # -- compiled step --------------------------------------------------------
    def make_train_step(self, tx: optax.GradientTransformation, params,
                        *, donate: bool = True):
        """``(opt_state, params, batch{tokens:(B,S)}) -> (opt_state, params,
        metrics)`` — B = n_data * num_microbatches * microbatch_size.
        ``params`` is used only to derive optimizer-state specs."""
        M = self.num_microbatches
        opt_specs = self.opt_state_specs(tx, params)

        def sm_step(opt_state, params, tokens):
            mbs = tokens.reshape(M, tokens.shape[0] // M, tokens.shape[1])
            local_loss, grads = jax.value_and_grad(self._pipeline_loss)(
                params, mbs
            )
            loss = cc.psum(local_loss, "pipe")  # value only; see _pipeline_loss
            # embed/head grads are nonzero only on their owning stage;
            # stage grads are per-stage (no pipe reduction needed)
            grads = {
                "embed": cc.psum(grads["embed"], "pipe"),
                "stages": grads["stages"],
                "head": cc.psum(grads["head"], "pipe"),
            }
            if self.n_data > 1:
                grads = cc.pmean(grads, "data")
                loss = cc.pmean(loss, "data")
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return opt_state, params, {"loss": loss}

        sharded = jax.shard_map(
            sm_step,
            mesh=self.mesh,
            in_specs=(opt_specs, self.param_specs(), P("data")),
            out_specs=(opt_specs, self.param_specs(), P()),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())

    def init_opt_state(self, tx, params):
        """Optimizer state materialized directly into its shard layout."""
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            self.opt_state_specs(tx, params),
            is_leaf=lambda x: isinstance(x, P),
        )
        with self.mesh:
            return jax.jit(tx.init, out_shardings=shardings)(params)
