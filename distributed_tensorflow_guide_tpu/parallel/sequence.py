"""Sequence/context parallelism: ring attention + Ulysses — first-class per
the build mandate (SURVEY.md §5 long-context row; absent from the reference).

Two standard layouts over the ``context`` mesh axis:

* **Ring attention** (Liu et al. 2023): Q/K/V are sequence-sharded; each of
  the ``n`` devices computes blockwise attention of its local Q against the
  KV block it currently holds, then rotates KV one hop around the ICI ring
  (``lax.ppermute``) — after ``n`` steps every Q block has seen every KV
  block, with per-device memory O(S/n) and only neighbor communication.
  The online-softmax carry (ops/attention.py) is what makes the partial
  results mergeable. Causality is enforced per (q-block, kv-block) pair:
  blocks strictly above the diagonal are skipped-by-masking.

* **Ulysses** (Jacobs et al. 2023): ``all_to_all`` reshards sequence ↔ heads
  around the attention core, so attention itself runs with full sequence on
  1/n of the heads — one transpose-style collective each way, no per-step
  ring traffic. Better when heads ≥ ring size and S/n is small.

Both compose with data parallelism (batch over ``data``) in one shard_map.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

import distributed_tensorflow_guide_tpu.collectives as cc
from distributed_tensorflow_guide_tpu.ops import attention as A
from distributed_tensorflow_guide_tpu.ops import flash_attention as F

# What ring_attention's impl="auto" resolves to — the ONE place the policy
# lives, so instruments (benchmarks/bench_ring_attention.py) report the
# actual pick instead of restating it. "xla" per the round-5 on-chip
# battery (Pallas at 0.157–0.487x of XLA at seq 1k–4k); flip here when a
# future capture inverts it.
RING_AUTO_IMPL = "xla"

# Last measured pallas/xla throughput ratios (round-5 on-chip battery,
# causal fwd+bwd, bf16, B=4 H=12 D=64) — what the impl="pallas" opt-in
# warning cites, and what the next capture should overwrite. Measured with
# the then-hardcoded 128x128 blocks; the autotune table (ops/autotune.py)
# is the bisect instrument for closing it.
RING_PALLAS_LAST_MEASURED = {1024: 0.157, 2048: 0.255, 4096: 0.487}


def ring_attention(q, k, v, *, axis: str = "context", causal: bool = False,
                   impl: str = "auto"):
    """Sequence-sharded attention over the ``axis`` ring.

    Per-device shapes (B, S_local, H, D); the global sequence is the
    concatenation of shards in axis order. Must run inside shard_map.

    ``impl``: "xla" is the pure-XLA blockwise path — the measured winner
    on-chip at EVERY tested length (round-5 battery: the Pallas carry path
    sustained only 0.157/0.255/0.487x of XLA at seq 1k/2k/4k), so "auto"
    now selects it unconditionally; the round-3 6.4x-the-other-way numbers
    predate the round-4 rewrites of both paths and are retired in
    BASELINE.md. "pallas" OPTS IN to the fused carry-kernel path
    (ops/flash_attention.py flash_carry_step, hand-written ring backward,
    ``lax.cond`` dead-rotation skip) — the survey's designated hard native
    part, kept first-class for the planned on-chip bisect and for any part
    where a future capture shows it winning; it needs S_local % 128 == 0
    and refuses otherwise rather than silently taking the other path.
    """
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(f"unknown ring impl {impl!r}")
    if impl == "auto":
        impl = RING_AUTO_IMPL
    s_local, d = q.shape[1], q.shape[-1]
    if impl == "pallas" and not F.supported(s_local, d):
        # The kernel grid covers s_local // 128 blocks; a ragged tail would
        # be silently left as uninitialized carry memory. Refuse loudly.
        raise ValueError(
            f"impl='pallas' needs per-device seq length divisible by 128 "
            f"(got S_local={s_local}); use impl='xla' or pad the sequence"
        )
    if impl == "pallas":
        # The opt-in path must never be SILENTLY slow: one warning per
        # shape (same once-per-shape registry as the flash fallback, so a
        # profiling audit reads a single surface) citing the last measured
        # pallas/xla ratio.
        ratios = ", ".join(f"{s}: {r}x"
                           for s, r in RING_PALLAS_LAST_MEASURED.items())
        F._note_fallback(
            s_local, d, 0, 0, origin="ring_attention_pallas_optin",
            msg=(
                "ring_attention impl='pallas' opted in: the last on-chip "
                "capture (round-5 battery) measured the Pallas carry path "
                f"at a fraction of the XLA path ({{seq: pallas/xla}} = "
                f"{{{ratios}}}). Tune it first (benchmarks/"
                "bench_flash_kernel.py --tune populates the carry_step "
                "autotune entry) or use impl='auto'."
            ))
        return _ring_flash_public(q, k, v, axis=axis, causal=causal)
    return _ring_xla(q, k, v, axis=axis, causal=causal)


def _ring_xla(q, k, v, *, axis: str, causal: bool):
    n = cc.axis_size(axis)
    my = lax.axis_index(axis)
    s_local = q.shape[1]
    scale = 1.0 / (q.shape[-1] ** 0.5)
    fwd = [(i, (i + 1) % n) for i in range(n)]

    qf = q.astype(jnp.float32)
    m, l, o = A.init_carry(q.shape)
    q_pos = my * s_local + jnp.arange(s_local)

    def body(carry, step):
        m, l, o, k_cur, v_cur, src = carry
        if causal:
            kv_pos = src * s_local + jnp.arange(s_local)
            mask = (q_pos[:, None] >= kv_pos[None, :])[None, None]
        else:
            mask = None
        m, l, o = A.block_update(
            qf, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
            m, l, o, scale=scale, mask=mask,
        )
        # rotate KV to the next device; the block we receive came from the
        # previous rank, so its global offset decrements by one each step
        k_cur = cc.ppermute(k_cur, axis, fwd)
        v_cur = cc.ppermute(v_cur, axis, fwd)
        src = (src - 1) % n
        return (m, l, o, k_cur, v_cur, src), None

    (m, l, o, _, _, _), _ = lax.scan(
        body, (m, l, o, k, v, my), jnp.arange(n)
    )
    return A.finalize(m, l, o).astype(q.dtype)


# -- Pallas-fused ring (carry kernel + hand-written ring backward) -----------
#
# Causality over aligned equal-length shards collapses to three static
# cases per rotation — the visiting KV shard is entirely before the local Q
# shard (full attention), IS the local shard (ordinary in-block causal), or
# entirely after (dead). lax.cond dispatches between two static kernel
# variants and skips dead rotations outright; the XLA path above computes
# then masks them (~2x FLOP waste at large rings, round-2 verdict weak 4).


def _pad_lane(x, d, dp):
    """Pad head_dim to the kernel lane width — LOCALLY, at the kernel
    boundary. The ring deliberately rotates UNPADDED tensors: at d=64 on
    the 128-lane kernel, rotating padded tensors would double every hop's
    ICI bytes (measured by bench_sp_comm — 2x wire for a VPU-cheap pad),
    so the pad is re-applied per visit instead of travelling."""
    if dp == d:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, dp - d)))


def _ring_steps_fwd(q, k, v, axis, causal, scale):
    """Ring forward in kernel layout (B, H, S_loc, D) -> (out, lse)."""
    n = cc.axis_size(axis)
    # the rotation-source index matters only for causal masking; tracing
    # axis_index into the non-causal program would put a live-but-unused
    # PartitionId in the scan carry, which jax 0.4.x's SPMD partitioner
    # refuses to lower
    my = lax.axis_index(axis) if causal else jnp.int32(0)
    b, h, s, d = q.shape
    dp = -(-d // F.LANE) * F.LANE
    fwd = [(i, (i + 1) % n) for i in range(n)]
    m, l, acc = F.carry_init(b, h, s, dp)
    qp = _pad_lane(q, d, dp)  # local: pad once, never rotates
    # tuned per-visit block sizes from the autotune table (keyed on the
    # LOGICAL head dim; tested default 128x128 on a miss)
    cblk = F.carry_blocks(b, h, s, d, q.dtype, causal)

    def step(diag):
        def run(m, l, acc, k_cur, v_cur):
            return F.flash_carry_step(qp, _pad_lane(k_cur, d, dp),
                                      _pad_lane(v_cur, d, dp), m, l, acc,
                                      scale=scale, diag=diag,
                                      blk_q=cblk[0], blk_k=cblk[1])

        return run

    def skip(m, l, acc, k_cur, v_cur):
        return m, l, acc

    def body(carry, _):
        m, l, acc, k_cur, v_cur, src = carry
        if causal:
            m, l, acc = lax.cond(
                src == my,
                step(True),
                lambda *a: lax.cond(src < my, step(False), skip, *a),
                m, l, acc, k_cur, v_cur,
            )
        else:
            m, l, acc = step(False)(m, l, acc, k_cur, v_cur)
        k_cur = cc.ppermute(k_cur, axis, fwd)
        v_cur = cc.ppermute(v_cur, axis, fwd)
        return (m, l, acc, k_cur, v_cur, (src - 1) % n), None

    (m, l, acc, _, _, _), _ = lax.scan(
        body, (m, l, acc, k, v, my), None, length=n
    )
    out, lse = F.carry_finalize(m, l, acc)
    return out[..., :d].astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, axis, causal, scale):
    out, _ = _ring_steps_fwd(q, k, v, axis, causal, scale)
    return out


def _ring_flash_fwd_rule(q, k, v, axis, causal, scale):
    out, lse = _ring_steps_fwd(q, k, v, axis, causal, scale)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd_rule(axis, causal, scale, res, g):
    """Second ring pass, Q-SIDE rotation: (k, v) stay home and (dk, dv)
    accumulate locally; the Q side — q, the output cotangent g, the
    travelling dq partial sum, and two lane-thin softmax stats (lse's
    first lane, delta) — rotates instead, arriving home after n hops.

    Why this orientation: the KV-side rotation moves FOUR head_dim-sized
    tensors per hop (k, v, dk-partial, dv-partial); this one moves THREE
    plus two (B, H, S)-thin rows — ~24% less backward wire at f32 D=64
    and ~32% at bf16 (the f32 partial dominates either way; measured by
    bench_sp_comm's traced table, pinned in tests/test_sp_comm.py).
    Causality flips perspective: the LOCAL kv shard at index ``my`` meets
    the visiting q-block from ``src_q``; src_q == my is the masked
    diagonal, src_q > my full (q after kv), src_q < my dead (skipped).
    Reuses the flash backward kernels per rotation; lse re-broadcasts to
    the lane width locally (broadcast is free, rotating it is not)."""
    q, k, v, out, lse = res
    n = cc.axis_size(axis)
    # causal-only, as in _ring_steps_fwd (PartitionId lowering note there)
    my = lax.axis_index(axis) if causal else jnp.int32(0)
    fwd = [(i, (i + 1) % n) for i in range(n)]
    f32 = jnp.float32
    d = q.shape[-1]
    dp = -(-d // F.LANE) * F.LANE
    delta = jnp.sum(g.astype(f32) * out.astype(f32), axis=-1)  # (B,H,S)
    kp = _pad_lane(k, d, dp)       # local + stationary: pad once
    vp = _pad_lane(v, d, dp)
    # per-kernel tuned blocks for the per-visit backward (dq and dkv have
    # their own autotune entries; tested default 128x128)
    blk_dq, blk_dkv = F.bwd_blocks(q.shape[0], q.shape[1], q.shape[2], d,
                                   q.dtype, causal)

    def run(diag):
        def go(q_cur, g_cur, lse1_cur, delta_cur):
            lse_b = jnp.broadcast_to(lse1_cur, (*lse1_cur.shape[:-1], F.LANE))
            dq_s, dk_s, dv_s = F._bwd_call(
                _pad_lane(q_cur, d, dp), kp, vp,
                _pad_lane(g_cur, d, dp), lse_b, delta_cur,
                scale=scale, causal=diag, blk_dq=blk_dq, blk_dkv=blk_dkv,
            )
            return (dq_s[..., :d].astype(f32), dk_s[..., :d].astype(f32),
                    dv_s[..., :d].astype(f32))

        return go

    def skip(q_cur, g_cur, lse1_cur, delta_cur):
        z = jnp.zeros(q.shape, f32)
        return z, z, z

    def body(carry, _):
        q_cur, g_cur, lse1_cur, delta_cur, dq_cur, dk, dv, src_q = carry
        if causal:
            dq_s, dk_s, dv_s = lax.cond(
                src_q == my,
                run(True),
                lambda *a: lax.cond(src_q > my, run(False), skip, *a),
                q_cur, g_cur, lse1_cur, delta_cur,
            )
        else:
            dq_s, dk_s, dv_s = run(False)(q_cur, g_cur, lse1_cur, delta_cur)
        dq_cur = dq_cur + dq_s
        dk = dk + dk_s
        dv = dv + dv_s
        q_cur = cc.ppermute(q_cur, axis, fwd)
        g_cur = cc.ppermute(g_cur, axis, fwd)
        lse1_cur = cc.ppermute(lse1_cur, axis, fwd)
        delta_cur = cc.ppermute(delta_cur, axis, fwd)
        dq_cur = cc.ppermute(dq_cur, axis, fwd)
        return (q_cur, g_cur, lse1_cur, delta_cur, dq_cur, dk, dv,
                (src_q - 1) % n), None

    z = jnp.zeros(q.shape, f32)
    (_, _, _, _, dq, dk, dv, _), _ = lax.scan(
        body, (q, g, lse[..., :1], delta, z, z, z, my), None, length=n
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


def _ring_flash_public(q, k, v, *, axis: str, causal: bool):
    """Public layout (B, S_loc, H, D) -> same. Head-dim lane padding
    happens INSIDE the ring steps (``_pad_lane``) so the rotations move
    unpadded tensors — see the wire-bytes rationale there."""
    d = q.shape[-1]
    scale = 1.0 / (d ** 0.5)

    def to_kernel(x):
        return jnp.transpose(x, (0, 2, 1, 3))

    out = _ring_flash(to_kernel(q), to_kernel(k), to_kernel(v), axis,
                      causal, scale)
    return jnp.transpose(out, (0, 2, 1, 3))


def ulysses_attention(q, k, v, *, axis: str = "context",
                      causal: bool = False, impl: str = "auto"):
    """Ulysses: all_to_all seq→heads, full-sequence attention on a head
    shard, all_to_all heads→seq back.

    Per-device in/out: (B, S_local, H, D); requires H % axis_size == 0.

    ``impl``: the attention core after resharding sees the FULL sequence,
    so long contexts need the fused kernel — "auto" uses the Pallas flash
    kernel (ops/flash_attention.py) when the global seq length fits its
    blocks, dense otherwise; "dense"/"flash" pin the choice.
    """
    if impl not in ("auto", "dense", "flash"):
        raise ValueError(f"unknown ulysses impl {impl!r}")
    n = cc.axis_size(axis)
    h = q.shape[2]
    if h % n:
        raise ValueError(
            f"context size {n} must divide num_heads {h} (each device "
            "takes H/n heads after the all_to_all)"
        )

    def to_heads(x):  # (B, S/n, H, D) -> (B, S, H/n, D)
        return cc.all_to_all(x, axis, split_axis=2, concat_axis=1)

    def to_seq(x):  # (B, S, H/n, D) -> (B, S/n, H, D)
        return cc.all_to_all(x, axis, split_axis=1, concat_axis=2)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    s_global, d = qh.shape[1], qh.shape[-1]
    fits = F.supported(s_global, d)
    if impl == "flash" and not fits:
        # pinning the kernel must not silently take the slow path (the same
        # contract as ring impl="pallas")
        raise ValueError(
            f"impl='flash' needs global seq length divisible by 128 (got "
            f"{s_global}); use impl='dense' or pad the sequence"
        )
    use_flash = impl == "flash" or (impl == "auto" and fits)
    if use_flash:
        out = F.flash_attention(qh, kh, vh, causal=causal)
    else:
        out = A.dense_attention(qh, kh, vh, causal=causal)
    return to_seq(out)
