"""Sequence/context parallelism: ring attention + Ulysses — first-class per
the build mandate (SURVEY.md §5 long-context row; absent from the reference).

Two standard layouts over the ``context`` mesh axis:

* **Ring attention** (Liu et al. 2023): Q/K/V are sequence-sharded; each of
  the ``n`` devices computes blockwise attention of its local Q against the
  KV block it currently holds, then rotates KV one hop around the ICI ring
  (``lax.ppermute``) — after ``n`` steps every Q block has seen every KV
  block, with per-device memory O(S/n) and only neighbor communication.
  The online-softmax carry (ops/attention.py) is what makes the partial
  results mergeable. Causality is enforced per (q-block, kv-block) pair:
  blocks strictly above the diagonal are skipped-by-masking.

* **Ulysses** (Jacobs et al. 2023): ``all_to_all`` reshards sequence ↔ heads
  around the attention core, so attention itself runs with full sequence on
  1/n of the heads — one transpose-style collective each way, no per-step
  ring traffic. Better when heads ≥ ring size and S/n is small.

Both compose with data parallelism (batch over ``data``) in one shard_map.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

import distributed_tensorflow_guide_tpu.collectives as cc
from distributed_tensorflow_guide_tpu.ops import attention as A


def ring_attention(q, k, v, *, axis: str = "context", causal: bool = False):
    """Sequence-sharded attention over the ``axis`` ring.

    Per-device shapes (B, S_local, H, D); the global sequence is the
    concatenation of shards in axis order. Must run inside shard_map.
    """
    n = lax.axis_size(axis)
    my = lax.axis_index(axis)
    s_local = q.shape[1]
    scale = 1.0 / (q.shape[-1] ** 0.5)
    fwd = [(i, (i + 1) % n) for i in range(n)]

    qf = q.astype(jnp.float32)
    m, l, o = A.init_carry(q.shape)
    q_pos = my * s_local + jnp.arange(s_local)

    def body(carry, step):
        m, l, o, k_cur, v_cur, src = carry
        if causal:
            kv_pos = src * s_local + jnp.arange(s_local)
            mask = (q_pos[:, None] >= kv_pos[None, :])[None, None]
        else:
            mask = None
        m, l, o = A.block_update(
            qf, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
            m, l, o, scale=scale, mask=mask,
        )
        # rotate KV to the next device; the block we receive came from the
        # previous rank, so its global offset decrements by one each step
        k_cur = cc.ppermute(k_cur, axis, fwd)
        v_cur = cc.ppermute(v_cur, axis, fwd)
        src = (src - 1) % n
        return (m, l, o, k_cur, v_cur, src), None

    (m, l, o, _, _, _), _ = lax.scan(
        body, (m, l, o, k, v, my), jnp.arange(n)
    )
    return A.finalize(m, l, o).astype(q.dtype)


def ulysses_attention(q, k, v, *, axis: str = "context",
                      causal: bool = False):
    """Ulysses: all_to_all seq→heads, full-sequence attention on a head
    shard, all_to_all heads→seq back.

    Per-device in/out: (B, S_local, H, D); requires H % axis_size == 0.
    """
    n = lax.axis_size(axis)
    h = q.shape[2]
    if h % n:
        raise ValueError(f"num_heads {h} must divide context size {n}")

    def to_heads(x):  # (B, S/n, H, D) -> (B, S, H/n, D)
        return cc.all_to_all(x, axis, split_axis=2, concat_axis=1)

    def to_seq(x):  # (B, S, H/n, D) -> (B, S/n, H, D)
        return cc.all_to_all(x, axis, split_axis=1, concat_axis=2)

    out = A.dense_attention(
        to_heads(q), to_heads(k), to_heads(v), causal=causal
    )
    return to_seq(out)
