"""Expert parallelism (MoE) over the ``expert`` mesh axis.

Not in the reference (SURVEY.md §2c EP row — the guide predates MoE); built
because the framework mandate makes every parallelism family first-class.
The reference's closest ancestor is its async-PS *sharding of whole
variables* across PS tasks (tensorflow/python/training/device_setter.py:129
round-robins variables over /job:ps) — EP is the modern descendant: shard
whole *experts* across devices and move the **tokens** to the experts
instead of the parameters to the workers.

Design (GShard/Switch dense-dispatch, TPU-first):

* Routing produces fixed-capacity dispatch/combine tensors via one-hot
  einsums — **static shapes only**, so XLA tiles everything onto the MXU;
  no gather/scatter, no dynamic shapes, overflow tokens drop (standard
  capacity-factor semantics).
* Token exchange is one ``all_to_all`` each way over the ``expert`` ICI
  ring (collectives/collectives.py all_to_all → lax.all_to_all), exactly
  the NCCL-alltoall pattern GPU MoE stacks use, but compiler-scheduled.
* Expert FFNs run as one batched einsum over the local expert shard —
  E_local weight matrices multiply in a single MXU-friendly contraction.

Aux outputs follow Switch Transformer: load-balance loss
``E * Σ_e f_e·p_e`` and router z-loss.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

import distributed_tensorflow_guide_tpu.collectives as cc
from distributed_tensorflow_guide_tpu.core.compat import shard_map
from distributed_tensorflow_guide_tpu.core.mesh import axis_sizes


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int
    d_ff: int
    num_experts: int          # global expert count, divisible by axis size
    top_k: int = 2
    capacity_factor: float = 1.25
    # "switch": fixed-capacity GShard/Switch dispatch — tokens past
    # ``capacity_factor`` headroom drop. "dropless": capacity-factor-free —
    # capacity widens to the per-shard token count, which provably admits
    # every token (a token picks each expert at most once, so no expert can
    # receive more than T_local rows), at the price of an E×-larger dispatch
    # buffer. Same one-hot algebra, same all_to_all census, zero drops.
    router: str = "switch"
    axis: str = "expert"
    # mesh axes (besides `axis`) that also shard the token dimension; aux
    # statistics are averaged over all of them so every device reports the
    # same global value. None for pure-EP shard_maps with no data axis bound.
    data_axis: str | None = "data"
    dtype: Any = jnp.float32

    def __post_init__(self):
        if self.router not in ("switch", "dropless"):
            raise ValueError(
                f"router must be 'switch' or 'dropless', got {self.router!r}")

    @property
    def token_axes(self) -> tuple[str, ...]:
        return (self.data_axis, self.axis) if self.data_axis else (self.axis,)


def init_moe_params(cfg: MoEConfig, rng) -> dict:
    """Router replicated; expert stacks laid out (E, d, ff)/(E, ff, d) so the
    leading axis shards over the ``expert`` mesh axis."""
    kr, ki, ko = jax.random.split(rng, 3)
    scale_in = 1.0 / np.sqrt(cfg.d_model)
    scale_out = 1.0 / np.sqrt(cfg.d_ff)
    return {
        "router": (jax.random.normal(kr, (cfg.d_model, cfg.num_experts))
                   * scale_in).astype(cfg.dtype),
        "w_in": (jax.random.normal(
            ki, (cfg.num_experts, cfg.d_model, cfg.d_ff)) * scale_in
        ).astype(cfg.dtype),
        "w_out": (jax.random.normal(
            ko, (cfg.num_experts, cfg.d_ff, cfg.d_model)) * scale_out
        ).astype(cfg.dtype),
    }


def _topk_dispatch(gates: jax.Array, top_k: int, capacity: int):
    """Fixed-capacity top-k assignment, entirely as one-hot algebra.

    Returns ``dispatch`` (T, E, C) in {0,1} and ``combine`` (T, E, C)
    gate-weighted. Slot s of each token goes to its s-th-choice expert at
    the next free capacity slot; tokens past capacity are dropped (their
    dispatch row is zero). No sorting, no dynamic shapes.
    """
    t, e = gates.shape
    dispatch = jnp.zeros((t, e, capacity), gates.dtype)
    combine = jnp.zeros((t, e, capacity), gates.dtype)
    fill = jnp.zeros((e,), jnp.int32)   # capacity slots already used
    g = gates
    for _ in range(top_k):
        idx = jnp.argmax(g, axis=1)                      # (T,)
        onehot = jax.nn.one_hot(idx, e, dtype=gates.dtype)
        # position of each token within its chosen expert's buffer
        pos = (jnp.cumsum(onehot, axis=0) - onehot) + fill[None, :]
        pos_i = pos.astype(jnp.int32)
        keep = onehot * (pos_i < capacity)
        slot = keep[:, :, None] * jax.nn.one_hot(
            pos_i, capacity, dtype=gates.dtype)           # (T, E, C)
        gate_val = jnp.sum(gates * onehot, axis=1)        # (T,)
        dispatch = dispatch + slot
        combine = combine + slot * gate_val[:, None, None]
        fill = fill + jnp.sum(keep, axis=0).astype(jnp.int32)
        g = g * (1.0 - onehot)                            # mask chosen expert
    return dispatch, combine


def moe_ffn(params: dict, x: jax.Array, cfg: MoEConfig):
    """One MoE FFN layer. Must run inside shard_map with ``x`` token-sharded
    and expert stacks sharded over ``cfg.axis``.

    Per-device shapes: x (T_local, d); w_in (E_local, d, ff).
    Returns (y (T_local, d), aux dict with load_balance/z losses).
    """
    n_dev = cc.axis_size(cfg.axis)
    e_global = cfg.num_experts
    e_local = params["w_in"].shape[0]
    if e_local * n_dev != e_global:
        raise ValueError(
            f"{e_global} experts over {n_dev} devices needs "
            f"{e_global // n_dev} local, got {e_local}")
    t_local = x.shape[0]
    if cfg.router == "dropless":
        # Each token selects an expert at most once across the top_k rounds
        # (the chosen column is masked between rounds), so no expert is ever
        # assigned more than t_local rows: capacity == t_local admits every
        # token and _topk_dispatch's ``pos < capacity`` guard never fires.
        capacity = t_local
    else:
        capacity = max(1, int(np.ceil(
            cfg.top_k * t_local * cfg.capacity_factor / e_global)))

    # router always in fp32: routing decisions are precision-sensitive
    logits = x.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)
    dispatch, combine = _topk_dispatch(gates, cfg.top_k, capacity)

    # Switch aux losses, averaged over every token-sharding axis so the
    # returned values are truly replicated (out_specs P() honest)
    frac_tokens = cc.pmean(jnp.mean(dispatch.sum(-1), axis=0), cfg.token_axes)
    frac_probs = cc.pmean(jnp.mean(gates, axis=0), cfg.token_axes)
    load_balance = e_global * jnp.sum(frac_tokens * frac_probs) / cfg.top_k
    z_loss = cc.pmean(jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2),
                      cfg.token_axes)

    xd = x.astype(cfg.dtype)
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(cfg.dtype), xd)
    # (E_global, C, d) -> (E_local, n_dev*C, d): rows for MY experts from all
    # devices land here
    expert_in = cc.all_to_all(expert_in, cfg.axis, split_axis=0, concat_axis=1)
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"]))
    out = jnp.einsum("ecf,efd->ecd", h, params["w_out"])
    # route results back: (E_local, n_dev*C, d) -> (E_global, C, d)
    out = cc.all_to_all(out, cfg.axis, split_axis=1, concat_axis=0)
    y = jnp.einsum("tec,ecd->td", combine.astype(cfg.dtype), out)
    return y.astype(x.dtype), {"load_balance": load_balance, "z_loss": z_loss}


class ExpertParallel:
    """Harness: shard params/tokens over the ``expert`` axis and build a
    jitted training step for a standalone MoE layer (the transformer wiring
    lives in models/; this class is the EP sibling of parallel/tensor.py's
    TensorParallel)."""

    def __init__(self, mesh: Mesh, cfg: MoEConfig):
        if cfg.axis not in axis_sizes(mesh):
            raise ValueError(
                f"mesh axes {tuple(axis_sizes(mesh))} lack {cfg.axis!r}")
        if cfg.num_experts % axis_sizes(mesh)[cfg.axis]:
            raise ValueError(
                f"num_experts {cfg.num_experts} not divisible by "
                f"{cfg.axis} axis size {axis_sizes(mesh)[cfg.axis]}")
        self.mesh = mesh
        self.cfg = cfg
        self.param_spec = {
            "router": P(),
            "w_in": P(cfg.axis),
            "w_out": P(cfg.axis),
        }
        # tokens sharded over data AND expert axes jointly: every device in
        # the (data x expert) grid holds a distinct token shard
        self.token_spec = P(cfg.token_axes)

    def shard_params(self, params: dict) -> dict:
        return jax.device_put(
            params,
            {k: NamedSharding(self.mesh, s)
             for k, s in self.param_spec.items()},
        )

    def apply(self, params: dict, x: jax.Array):
        """Jitted sharded forward: x (T_global, d) -> (y, aux). The jitted
        function is built once (per instance) so repeated calls hit the
        trace cache instead of recompiling."""
        if not hasattr(self, "_apply_jit"):
            cfg = self.cfg

            @functools.partial(
                jax.jit,
                in_shardings=(
                    {k: NamedSharding(self.mesh, s)
                     for k, s in self.param_spec.items()},
                    NamedSharding(self.mesh, self.token_spec),
                ),
            )
            def run(params, x):
                fn = functools.partial(moe_ffn, cfg=cfg)
                return shard_map(
                    fn, mesh=self.mesh,
                    in_specs=(self.param_spec, self.token_spec),
                    out_specs=(self.token_spec, P()),
                    check_vma=False,
                )(params, x)

            self._apply_jit = run
        return self._apply_jit(params, x)

    def make_train_step(self, lr: float = 0.1, *, aux_weight: float = 1e-2):
        """Jitted SGD step on an MSE toy objective — exercises the full EP
        path (routing, both all_to_alls, expert einsums, grads, reductions).
        Real models plug :func:`moe_ffn` into their blocks instead."""
        cfg = self.cfg
        p_specs = {k: NamedSharding(self.mesh, s)
                   for k, s in self.param_spec.items()}

        def step(params, x, y_target):
            def loss_fn(p):
                y, aux = moe_ffn(p, x, cfg)
                se = jnp.sum((y - y_target) ** 2)
                n = jnp.array(y.size, jnp.float32)
                loss = (cc.psum(se, cfg.token_axes)
                        / cc.psum(n, cfg.token_axes)
                        + aux_weight * (aux["load_balance"] + aux["z_loss"]))
                return loss, aux

            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params)
            # the loss is already the GLOBAL mean, so each device's grad is a
            # partial contribution and the reduction is psum (a pmean here
            # would under-scale by the axis size). Replicated router: sum
            # over every token-shard axis; expert stacks: contributions from
            # the expert axis already arrived via the backward all_to_all,
            # so sum over data only.
            grads["router"] = cc.psum(grads["router"], cfg.token_axes)
            if cfg.data_axis:
                grads["w_in"] = cc.psum(grads["w_in"], cfg.data_axis)
                grads["w_out"] = cc.psum(grads["w_out"], cfg.data_axis)
            params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                                  params, grads)
            return params, {"loss": loss, **aux}

        sm = shard_map(
            step, mesh=self.mesh,
            in_specs=(self.param_spec, self.token_spec, self.token_spec),
            out_specs=(self.param_spec, P()),
            check_vma=False,
        )
        return jax.jit(
            sm,
            in_shardings=(p_specs,
                          NamedSharding(self.mesh, self.token_spec),
                          NamedSharding(self.mesh, self.token_spec)),
        )
