"""ICI communication overlap: bucketed DP all-reduce + manual FSDP schedule.

After three rounds of host-overlap, kernel tuning and HBM dieting, the
data-parallel backward still synchronized gradients with ONE monolithic
``pmean`` over the whole gradient tree at the end of the backward pass
(parallel/data_parallel.py), and FSDP left its all-gather/reduce-scatter
schedule entirely to GSPMD defaults. Both leave the ICI idle exactly while
the MXU is busiest. The canonical fixes this module ports to the TPU-native
stack:

* **Bucketed backward all-reduce** (PyTorch DDP's gradient bucketing, Li et
  al., VLDB'20): partition the parameter tree into byte-budgeted buckets and
  mark each bucket with a ``custom_vjp`` boundary — identity forward, pmean
  backward. A bucket's reduction then *data-depends only on that bucket's
  cotangents*, which autodiff produces mid-backward, so the collective is
  emitted early in the backward HLO where XLA's latency-hiding scheduler can
  run collective-start / remaining-backward-compute / collective-done
  overlapped — instead of one giant all-reduce strictly after the full
  gradient tree. Numerics are untouched: all-reduce is elementwise per
  leaf, so any bucketing is bitwise-identical to the monolithic pmean
  (pinned in tests/test_overlap.py). The bucket byte budget resolves
  through the autotune table (ops/autotune.py — same persistence, same
  platform keying, same CPU defaults-only hermeticity as the flash blocks
  and CE chunks).

* **Manual FSDP gather/scatter markers** (ZeRO-3's layerwise schedule,
  Rajbhandari et al., SC'20): each sharded parameter leaf gets an explicit
  all-gather forward / reduce-scatter backward ``custom_vjp`` pair (the
  ZeRO conjugate of Megatron's f/g operators in collectives.py), replacing
  GSPMD's inferred schedule with one collective per leaf that the scheduler
  can prefetch: layer *i+1*'s gather has no data dependence on layer *i*'s
  compute, so with async collectives enabled it is issued during it.
  Replicated leaves (biases/norms) get identity-forward / pmean-backward.
  Gradients leave the backward already in shard layout — the optimizer
  update stays fully sharded (ZeRO-style), no full-tree gradient ever
  materializes.

* **The XLA async-collective knob**: the scheduler can only overlap
  collectives it is allowed to run async. :func:`apply_xla_overlap_flags`
  surfaces the relevant libtpu flags as ONE runtime knob (env
  ``DTG_XLA_OVERLAP=1`` or ``RunConfig.xla_overlap``), applied before
  backend init and echoed into bench JSON like ``BENCH_MODE`` is today.
  (docs/performance.md records that the latency-hiding scheduler flag
  measured as a no-op on a SINGLE chip — there is no ICI traffic to hide
  there; multi-chip DP/FSDP is where this knob has work to do.)

The instrument that judges all of this is benchmarks/bench_comm_overlap.py
(exposed-comm fraction from an overlap on/off A/B against a no-collective
compute floor) plus the ICI roofline models in benchmarks/common.py.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Callable, Sequence

import jax
import numpy as np

import distributed_tensorflow_guide_tpu.collectives as cc

__all__ = [
    "resolve_overlap",
    "resolve_prefetch",
    "resolve_compress",
    "bucket_assignment",
    "bucket_sync",
    "pmean_buckets",
    "bucketed_loss_fn",
    "gather_shard",
    "replicated_grad_sync",
    "gather_params",
    "XLA_OVERLAP_FLAGS",
    "apply_xla_overlap_flags",
    "xla_overlap_active",
]


# --------------------------------------------------------------------------
# knob resolution (mirrors ops/fused_ce.resolve_fused_ce)
# --------------------------------------------------------------------------


def _resolve_tpu_auto(setting, knob: str, platform: str | None) -> bool:
    """``"auto"|True|False`` (plus on/off spellings) -> bool; auto = ON only
    on a TPU backend. Off on CPU keeps tier-1 CI tracing the byte-identical
    legacy program — the same hermeticity posture as the autotune
    defaults-only path. The battery pins both sides explicitly so the
    on-chip capture adjudicates the policy, not the default."""
    if isinstance(setting, bool):
        return setting
    if setting is None:
        return False
    s = str(setting).lower()
    if s in ("on", "true", "1"):
        return True
    if s in ("off", "false", "0"):
        return False
    if s != "auto":
        raise ValueError(
            f"{knob} must be 'auto', on/True or off/False, got {setting!r}")
    plat = platform if platform is not None else jax.default_backend()
    return plat == "tpu"


def resolve_overlap(setting, *, platform: str | None = None) -> bool:
    """Resolve DataParallel's ``overlap`` knob (bucketed backward
    all-reduce). ``auto`` = TPU only."""
    return _resolve_tpu_auto(setting, "overlap", platform)


def resolve_prefetch(setting, *, platform: str | None = None) -> bool:
    """Resolve FSDP's ``prefetch`` knob (manual per-leaf gather/scatter
    schedule). ``auto`` = TPU only."""
    return _resolve_tpu_auto(setting, "fsdp prefetch", platform)


def resolve_compress(setting) -> str | None:
    """Normalize the gradient-compression knob: ``None``/"off"/"none" ->
    None (full-precision wire, the historical path), "int8" -> "int8".
    Deliberately NOT platform-auto: compression changes numerics (bounded
    but nonzero quantization error), so it is only ever an explicit
    opt-in — never a backend-resolved default."""
    if setting is None:
        return None
    s = str(setting).lower()
    if s in ("off", "none", ""):
        return None
    if s == "int8":
        return "int8"
    raise ValueError(
        f"compress must be None/'off' or 'int8', got {setting!r}")


# --------------------------------------------------------------------------
# bucketed DP all-reduce
# --------------------------------------------------------------------------


def _leaf_bytes(leaf) -> int:
    return int(leaf.size) * np.dtype(leaf.dtype).itemsize


def bucket_assignment(leaves: Sequence[Any],
                      bucket_bytes: int) -> list[list[int]]:
    """Partition leaf indices into contiguous byte-budgeted buckets.

    Deterministic in tree-flatten order (which groups a flax module's
    leaves with their neighbors — the locality DDP's bucketing wants: a
    bucket's reduction fires once the LAST of its members' cotangents is
    ready, so members should become ready together). Every index appears
    exactly once; a single leaf larger than the budget gets its own
    bucket rather than being split (all-reduce is per-buffer anyway).
    """
    if bucket_bytes < 1:
        raise ValueError(f"bucket_bytes must be >= 1, got {bucket_bytes}")
    groups: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i, leaf in enumerate(leaves):
        nb = _leaf_bytes(leaf)
        if cur and cur_bytes + nb > bucket_bytes:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
    if cur:
        groups.append(cur)
    return groups


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def bucket_sync(leaves: tuple, axis: str, compress: str | None = None):
    """The DDP bucket boundary: identity forward, pmean backward.

    Applied to one bucket's parameter leaves at the loss function's input,
    so the bucket's gradient all-reduce appears in the backward exactly
    where its cotangents are produced — mid-backward, overlappable —
    instead of after the full gradient tree.

    ``compress="int8"`` swaps the backward collective for the
    int8-compressed variant (ops/quant.int8_pmean): one shared scale per
    bucket rides a scalar ``pmax`` side-channel and the payload crosses
    the wire at 1 byte/elem — ``dp_allreduce_bytes(..., compress="int8")``
    is the closed form, ``dp_overlap_int8_round`` the audited program.
    The default keeps the historical bitwise-exact pmean.
    """
    return leaves


def _bucket_sync_fwd(leaves, axis, compress):
    return leaves, None


def _bucket_sync_bwd(axis, compress, _, cts):
    # one fused collective per bucket; recorded in the ambient trace_comm
    # like every collective the framework issues
    if compress == "int8":
        from distributed_tensorflow_guide_tpu.ops import quant

        return (quant.int8_pmean(cts, axis),)
    return (cc.pmean(cts, axis),)


bucket_sync.defvjp(_bucket_sync_fwd, _bucket_sync_bwd)


def pmean_buckets(tree: Any, axis: str, bucket_bytes: int,
                  compress: str | None = None) -> Any:
    """Wrap a parameter tree in per-bucket sync markers: values unchanged,
    gradients come out pmean-ed over ``axis`` per bucket (int8 on the wire
    when ``compress="int8"``)."""
    leaves, treedef = jax.tree.flatten(tree)
    out = list(leaves)
    for group in bucket_assignment(leaves, bucket_bytes):
        synced = bucket_sync(tuple(leaves[i] for i in group), axis,
                             compress)
        for i, v in zip(group, synced):
            out[i] = v
    return jax.tree.unflatten(treedef, out)


def bucketed_loss_fn(loss_fn: Callable, axis: str,
                     bucket_bytes: int | None = None,
                     compress: str | None = None) -> Callable:
    """Wrap ``loss_fn(params, *rest)`` so ``jax.grad`` of the result yields
    gradients that are ALREADY pmean-ed over ``axis``, one bucket at a time
    (call sites must not pmean again — that would double-reduce).

    ``bucket_bytes=None`` resolves through the autotune table at trace time
    (shapes are static): the tuned entry for (param bytes, world) when one
    exists, else the tested default. On CPU the table is never read — the
    defaults-only hermeticity contract. A compressed wire tunes under its
    OWN key (dtype=int8 — bigger buckets amortize differently at a quarter
    of the bytes), same defaults-only posture.
    """
    compress = resolve_compress(compress)

    def wrapped(params, *rest):
        bb = bucket_bytes
        if bb is None:
            from distributed_tensorflow_guide_tpu.ops import autotune

            p_leaves = jax.tree.leaves(params)
            # routed through the online front door for uniformity; the
            # bucket family never sweeps here (no measure — only callers
            # that can time a real train step, i.e. the benchmarks, may
            # sweep it), so this is exactly bucket_bytes_for
            bb = autotune.ensure_tuned_online(
                autotune.BUCKET_KERNEL,
                param_bytes=sum(_leaf_bytes(l) for l in p_leaves),
                world=cc.axis_size(axis),
                dtype=(np.int8 if compress == "int8"
                       else p_leaves[0].dtype if p_leaves else np.float32),
            )
        return loss_fn(pmean_buckets(params, axis, bb, compress), *rest)

    return wrapped


# --------------------------------------------------------------------------
# manual FSDP gather/scatter markers
# --------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_shard(x, axis: str, dim: int):
    """ZeRO-3 conjugate pair for one sharded leaf: all-gather the full
    parameter forward, reduce-scatter the MEAN gradient back into shard
    layout backward (so the optimizer update stays fully sharded)."""
    return cc.all_gather(x, axis, tiled=True, gather_axis=dim)


def _gather_shard_fwd(x, axis, dim):
    return gather_shard(x, axis, dim), None


def _gather_shard_bwd(axis, dim, _, ct):
    n = cc.axis_size(axis)
    return (cc.reduce_scatter(ct, axis, scatter_axis=dim) / n,)


gather_shard.defvjp(_gather_shard_fwd, _gather_shard_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def replicated_grad_sync(x, axis: str):
    """The replicated-leaf counterpart: identity forward, pmean backward
    (biases/norms are already full on every device; only their gradients
    need the data-axis mean)."""
    return x


def _replicated_fwd(x, axis):
    return x, None


def _replicated_bwd(axis, _, ct):
    return (cc.pmean(ct, axis),)


replicated_grad_sync.defvjp(_replicated_fwd, _replicated_bwd)


def sharded_dim(spec, axis: str) -> int | None:
    """The dimension a PartitionSpec splits over ``axis``, or None."""
    for i, names in enumerate(tuple(spec)):
        if names is None:
            continue
        if axis in (names if isinstance(names, tuple) else (names,)):
            return i
    return None


def gather_params(shards: Any, shardings: Any, axis: str) -> Any:
    """Reassemble full parameters from FSDP shards inside ``shard_map``,
    leaf by leaf, with the ZeRO backward attached: sharded leaves
    all-gather forward / reduce-scatter(mean) backward, replicated leaves
    pass through with a pmean backward. One collective per leaf — the
    per-layer schedule the latency-hiding scheduler can prefetch."""

    def one(x, sh):
        dim = sharded_dim(sh.spec, axis)
        if dim is None:
            return replicated_grad_sync(x, axis)
        return gather_shard(x, axis, dim)

    return jax.tree.map(one, shards, shardings)


# --------------------------------------------------------------------------
# the XLA async-collective / latency-hiding knob
# --------------------------------------------------------------------------

# The libtpu flag set that lets the scheduler actually run collectives
# async under compute. Applied via LIBTPU_INIT_ARGS (the TPU channel —
# docs/performance.md: tpu-scoped flags are unknown to this build's
# XLA_FLAGS parser). The latency-hiding scheduler flag itself measured as
# a no-op on a single chip (no ICI traffic to hide — "Knobs that did NOT
# pay"); it rides along here because multi-chip DP/FSDP is its workload.
XLA_OVERLAP_FLAGS: tuple[str, ...] = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_enable_async_all_gather=true",
    "--xla_enable_async_collective_permute=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
)


def apply_xla_overlap_flags(enable: bool | None = None) -> bool:
    """Append the async-collective flag set to ``LIBTPU_INIT_ARGS`` (before
    backend init — call it next to ``device_setup``). ``enable=None`` reads
    the ``DTG_XLA_OVERLAP`` env knob. Idempotent: flags already present
    (either spelling) are not duplicated. Returns whether the knob is
    active, which benches echo into their JSON line like ``BENCH_MODE``.
    """
    if enable is None:
        enable = os.environ.get(
            "DTG_XLA_OVERLAP", "0").lower() in ("1", "true", "on")
    if not enable:
        return False
    cur = os.environ.get("LIBTPU_INIT_ARGS", "")
    # whole-token name match, not substring: ..._fusion must still be
    # appended when only ..._fusion_fuse_all_gather is already present
    present = {t.split("=", 1)[0] for t in cur.split()}
    missing = [f for f in XLA_OVERLAP_FLAGS
               if f.split("=", 1)[0] not in present]
    if missing:
        os.environ["LIBTPU_INIT_ARGS"] = " ".join([cur, *missing]).strip()
    os.environ["DTG_XLA_OVERLAP"] = "1"
    return True


def xla_overlap_active() -> bool:
    """Whether the overlap flag set has been applied this process (the
    value benches echo — a capture must record the compiler mode it ran
    under)."""
    return os.environ.get("DTG_XLA_OVERLAP", "0").lower() in (
        "1", "true", "on")
