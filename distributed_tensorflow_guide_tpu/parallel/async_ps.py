"""Async parameter-server training, re-thought for a synchronous fabric.

The reference's main subject is async PS training via
``tf.train.replica_device_setter``
(tensorflow/python/training/device_setter.py:129) with three flavors:

  * ⚠ Hogwild/   — lock-free: every worker applies grads to PS-resident
    params immediately, racing freely (Niu et al. 2011).
  * ⚠ DOWNPOUR/  — workers accumulate local updates for ``fetch_period``
    steps, then push to the PS and pull fresh params (Dean et al. 2012).
  * ⚠ ADAG/      — async accumulated/adaptive gradients: workers push grads,
    the PS applies an adaptive (Adam-family) optimizer.

On TPU there is no PS and no asynchrony: the ICI fabric is globally
synchronous. The honest mapping (SURVEY.md §2c, judged config 4) keeps what
these algorithms *actually buy* — less communication per step and tolerance
of divergent local state — and replaces the mechanism:

  * Hogwild  → :class:`GossipSGD`: replicas update locally and mix params
    with a ring neighbor each step (one ``ppermute`` hop — O(1) comm vs
    allreduce's O(log n)/ring O(n) phases). Staleness is bounded by the ring
    diameter instead of unbounded PS races.
  * DOWNPOUR → :class:`LocalSGD`: ``sync_period`` local optimizer steps
    (``lax.scan``), then a parameter ``pmean``. "Push accumulated update,
    pull fresh params" becomes one collective every K steps — identical
    update algebra, deterministic instead of racy.
  * ADAG     → :class:`AccumulatedAdaptive`: accumulate grads over K
    sub-batches *without* applying, one ``pmean``, one global adaptive
    update — the PS-side Adam, minus the staleness.

The exact asynchronous semantics (stale reads, interleaved writes) are
preserved host-side in :mod:`.ps_emulator` for parity tests; the semantic
delta is documented in docs/async_ps_semantics.md.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

import distributed_tensorflow_guide_tpu.collectives as cc
from distributed_tensorflow_guide_tpu.core.compat import shard_map
from distributed_tensorflow_guide_tpu.core.mesh import axis_sizes
from distributed_tensorflow_guide_tpu.parallel.grad_accum import (
    accumulate_grads,
)

LossFn = Callable[[Any, Any], tuple[jax.Array, dict]]


def _pmean_floats(tree: Any, axis: str) -> Any:
    """pmean float leaves; pass through ints (identical across replicas —
    e.g. optax step counts), which integer pmean would corrupt."""
    return jax.tree.map(
        lambda x: cc.pmean(x, axis)
        if jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        tree,
    )


class _Strategy:
    def __init__(self, mesh: Mesh, axis: str = "data"):
        self.mesh = mesh
        self.axis = axis
        self.world = axis_sizes(mesh)[axis]

    def shard_batch(self, batch: Any, *, leading_time_axis: bool = False) -> Any:
        spec = P(None, self.axis) if leading_time_axis else P(self.axis)
        return jax.device_put(batch, NamedSharding(self.mesh, spec))

    def replicate(self, state: Any) -> Any:
        return jax.device_put(state, NamedSharding(self.mesh, P()))


class LocalSGD(_Strategy):
    """DOWNPOUR on a synchronous fabric.

    Each replica runs ``sync_period`` optimizer steps on its own shard
    stream, then all replicas average parameters (and float optimizer state)
    with one pmean. With ``sync_period=1`` this IS sync DP — tested parity.

    The train step consumes a super-batch whose leaves are shaped
    ``(sync_period, per_replica_batch, ...)`` (use
    ``shard_batch(..., leading_time_axis=True)``).
    """

    def __init__(self, mesh: Mesh, sync_period: int, axis: str = "data"):
        super().__init__(mesh, axis)
        self.sync_period = sync_period

    def make_train_step(self, loss_fn: LossFn, *, donate: bool = True):
        def sm_step(state, batches):
            def inner(carry, sub):
                params, opt_state = carry
                (loss, _), g = jax.value_and_grad(loss_fn, has_aux=True)(
                    params, sub
                )
                updates, opt_state = state.tx.update(g, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            (params, opt_state), losses = lax.scan(
                inner, (state.params, state.opt_state), batches
            )
            # the "push accumulated update / pull fresh params" collective:
            params = _pmean_floats(params, self.axis)
            opt_state = _pmean_floats(opt_state, self.axis)
            state = state.replace(
                step=state.step + self.sync_period,
                params=params,
                opt_state=opt_state,
            )
            mets = {"loss": cc.pmean(losses.mean(), self.axis)}
            return state, mets

        sharded = shard_map(
            sm_step,
            mesh=self.mesh,
            in_specs=(P(), P(None, self.axis)),
            out_specs=(P(), P()),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=(0,) if donate else ())


class GossipSGD(_Strategy):
    """Hogwild's bounded-staleness sibling: local step + ring-neighbor mixing.

    Per step each replica applies its local gradient, then mixes parameters
    with its two ring neighbors (two ppermute hops — both single ICI-neighbor
    transfers): ``p <- (1-mix)*p + mix/2*(left + right)``. Information
    diffuses around the ring in ``world/2`` steps, so staleness is bounded by
    the ring diameter; the PS race of Hogwild is unbounded. Comm per step is
    neighbor-only vs a full allreduce — the same "cheap, loose" trade Hogwild
    makes.

    Because replicas genuinely hold *different* parameters (the whole point),
    state lives with a leading replica axis sharded over ``axis``: leaf
    shapes are ``(world, ...)``. Use :meth:`distribute` / :meth:`consensus`
    to enter/leave that representation.
    """

    def __init__(self, mesh: Mesh, axis: str = "data", mix: float = 0.5):
        super().__init__(mesh, axis)
        self.mix = mix

    def distribute(self, state: Any) -> Any:
        """Tile a replicated state to per-replica copies, sharded on axis 0."""
        tiled = jax.tree.map(
            lambda x: jnp.broadcast_to(jnp.asarray(x)[None], (self.world, *jnp.shape(x))),
            state,
        )
        return jax.device_put(tiled, NamedSharding(self.mesh, P(self.axis)))

    def make_train_step(self, loss_fn: LossFn, *, donate: bool = True):
        fwd = [(i, (i + 1) % self.world) for i in range(self.world)]
        bwd = [(i, (i - 1) % self.world) for i in range(self.world)]

        def sm_step(state, batch):
            local = jax.tree.map(lambda x: x[0], state)  # drop replica dim
            (loss, _), g = jax.value_and_grad(loss_fn, has_aux=True)(
                local.params, batch
            )
            local = local.apply_gradients(grads=g)  # purely local update
            mixed = jax.tree.map(
                lambda p: (1.0 - self.mix) * p
                + (self.mix / 2.0)
                * (
                    lax.ppermute(p, self.axis, fwd)
                    + lax.ppermute(p, self.axis, bwd)
                ),
                local.params,
            )
            local = local.replace(params=mixed)
            new_state = jax.tree.map(lambda x: x[None], local)
            return new_state, {"loss": cc.pmean(loss, self.axis)}

        sharded = shard_map(
            sm_step,
            mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis)),
            out_specs=(P(self.axis), P()),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=(0,) if donate else ())

    def consensus(self, state: Any) -> Any:
        """Average the per-replica parameter copies (for eval/checkpoint);
        XLA inserts the cross-device reduction from the sharding."""
        return jax.jit(
            lambda s: jax.tree.map(lambda x: jnp.mean(x, axis=0), s.params)
        )(state)


class AccumulatedAdaptive(_Strategy):
    """ADAG on a synchronous fabric: accumulate grads over ``accum_steps``
    sub-batches (no local apply), pmean once, apply the adaptive optimizer
    globally. The PS's Adam state becomes replicated optimizer state updated
    identically everywhere; accumulation cuts collective frequency by
    ``accum_steps``x, the same bandwidth economy DOWNPOUR/ADAG bought.

    Super-batch leaves: ``(accum_steps, per_replica_batch, ...)``.
    """

    def __init__(self, mesh: Mesh, accum_steps: int, axis: str = "data"):
        super().__init__(mesh, axis)
        self.accum_steps = accum_steps

    def make_train_step(self, loss_fn: LossFn, *, donate: bool = True):
        def sm_step(state, batches):
            g, (losses, _) = accumulate_grads(
                loss_fn, state.params, batches, self.accum_steps
            )
            g = cc.pmean(g, self.axis)
            state = state.apply_gradients(grads=g)
            return state, {"loss": cc.pmean(losses.mean(), self.axis)}

        sharded = shard_map(
            sm_step,
            mesh=self.mesh,
            in_specs=(P(), P(None, self.axis)),
            out_specs=(P(), P()),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=(0,) if donate else ())
