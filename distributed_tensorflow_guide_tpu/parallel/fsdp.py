"""Fully-sharded data parallelism (ZeRO-3 / FSDP) over the ``data`` axis.

Reference context: the guide's synchronous track replicates every variable
on every worker (⚠ Synchronous-SGD/ via ``SyncReplicasOptimizer``,
tensorflow/python/training/sync_replicas_optimizer.py:42; modern surface
``MultiWorkerMirroredStrategy``) — parameter memory grows with model size
on EVERY device. FSDP is that strategy's at-scale completion: parameters
and optimizer state are *sharded* over the same ``data`` axis the batch is
split over, and the compiler materializes each parameter only for the
instant its layer runs.

The TPU expression is pure sharding annotation — no wrapper classes, no
hooks, no manual all-gathers (contrast torch FSDP's module wrapping): give
every large parameter leaf a ``NamedSharding`` that splits its largest
divisible dimension over ``data``, shard the batch over ``data``, and jit.
GSPMD then inserts exactly ZeRO-3's communication schedule: all-gather
params before use, reduce-scatter gradients after the backward — all on
ICI. Numerically equivalent to plain sync DP (tested to 1e-4 over a
training trajectory; reduction orders differ, so not bit-exact).

Memory per device: params/world + optimizer state/world + one layer's
gathered params transiently — how models ~world× larger than HBM fit.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

import distributed_tensorflow_guide_tpu.collectives as cc
from distributed_tensorflow_guide_tpu.core.compat import shard_map
from distributed_tensorflow_guide_tpu.core.mesh import axis_sizes
from distributed_tensorflow_guide_tpu.parallel import overlap as overlap_mod

LossFn = Callable[[Any, Any], tuple[jax.Array, dict]]


def shard_spec_for(shape: tuple[int, ...], world: int,
                   min_size: int = 2 ** 14, axis: str = "data") -> P:
    """Pick the FSDP spec for one parameter: split the largest dimension
    divisible by ``world``; tiny or indivisible leaves stay replicated
    (biases, norms — sharding them buys nothing and costs a gather)."""
    if int(np.prod(shape or (1,))) < min_size:
        return P()
    dims = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in dims:
        if shape[i] % world == 0 and shape[i] >= world:
            spec = [None] * len(shape)
            spec[i] = axis
            return P(*spec)
    return P()


class FSDP:
    """Build compiled fully-sharded train steps over the ``data`` axis.

    Same surface as :class:`~..parallel.tensor.TensorParallel`:
    ``init_params`` materializes each leaf directly into its shard,
    ``state_shardings`` extends the layout to the optimizer state, and
    ``make_train_step`` jits with those shardings pinned.
    """

    def __init__(self, mesh: Mesh, axis: str = "data",
                 min_shard_size: int = 2 ** 14, *, prefetch="off"):
        self.mesh = mesh
        self.axis = axis
        self.world = axis_sizes(mesh)[axis]
        self.min_shard_size = min_shard_size
        # "auto"|True|False: the manual per-leaf gather/scatter schedule
        # (parallel/overlap.py) instead of GSPMD's inferred one — each
        # sharded leaf gets an explicit all-gather fwd / reduce-scatter
        # bwd marker, one collective per leaf with no data dependence on
        # the preceding layer's compute, so the async-collective scheduler
        # can issue layer i+1's gather during layer i ("auto" = TPU only;
        # CPU tier-1 keeps tracing the GSPMD program).
        self.prefetch = overlap_mod.resolve_prefetch(prefetch)

    # -- layout ---------------------------------------------------------------
    def param_shardings(self, params_shape: Any) -> Any:
        """Shardings for an (abstract) param tree."""
        def one(leaf):
            spec = shard_spec_for(leaf.shape, self.world,
                                  self.min_shard_size, axis=self.axis)
            return NamedSharding(self.mesh, spec)

        return jax.tree.map(one, params_shape)

    def init_params(self, init_fn: Callable[[], Any]) -> tuple[Any, Any]:
        """Run ``init_fn`` with outputs materialized directly into their
        shards (no device ever holds the full parameter tree)."""
        abstract = jax.eval_shape(init_fn)
        shardings = self.param_shardings(abstract)
        params = jax.jit(init_fn, out_shardings=shardings)()
        return params, shardings

    def state_shardings(self, state: Any, param_shardings: Any) -> Any:
        """Optimizer moments inherit their param's sharding (matched by
        shape+dtype); everything else replicates."""
        from distributed_tensorflow_guide_tpu.utils.spec_utils import (
            assign_by_shape,
        )

        return assign_by_shape(
            state.params, param_shardings, state,
            NamedSharding(self.mesh, P()),
        )

    # -- compiled step ---------------------------------------------------------
    def make_train_step(self, loss_fn: LossFn, state_shardings: Any,
                        *, donate: bool = True):
        """``(state, batch) -> (state, metrics)``. The batch is sharded over
        ``data`` like plain DP; params stay in their FSDP shards across
        steps — only the transient gathered copies exist during compute.

        With ``prefetch`` resolved on, the schedule is the manual one
        (:meth:`_make_prefetch_step`) instead of GSPMD's."""
        if self.prefetch:
            return self._make_prefetch_step(loss_fn, state_shardings,
                                            donate=donate)
        batch_sharding = NamedSharding(self.mesh, P(self.axis))

        def step(state, batch):
            (loss, mets), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params, batch)
            state = state.apply_gradients(grads=grads)
            return state, {"loss": loss, **mets}

        return jax.jit(
            step,
            in_shardings=(state_shardings, batch_sharding),
            out_shardings=(state_shardings, NamedSharding(self.mesh, P())),
            donate_argnums=(0,) if donate else (),
        )

    def _make_prefetch_step(self, loss_fn: LossFn, state_shardings: Any,
                            *, donate: bool = True):
        """The manual ZeRO-3 schedule (parallel/overlap.py markers) under
        ``shard_map``: every sharded leaf all-gathers explicitly at the
        parameter boundary (reduce-scatter of the MEAN gradient backward,
        so grads land in shard layout and the optimizer update stays fully
        sharded); replicated leaves keep a pmean backward. One collective
        per leaf, none data-dependent on earlier layers' compute — the
        per-layer schedule an async-collective scheduler can prefetch,
        replacing whatever GSPMD inferred. Each device computes the loss
        on its batch shard; reported metrics are pmean-ed, and the mean-
        of-equal-local-means equals the GSPMD path's global mean (loss
        parity pinned in tests/test_overlap.py — reduction orders differ,
        so parity is close, not bitwise)."""
        spec_tree = jax.tree.map(lambda s: s.spec, state_shardings)
        param_shardings = state_shardings.params
        axis = self.axis

        def sm_step(state, batch):
            def sharded_loss(shard_params, batch):
                full = overlap_mod.gather_params(shard_params,
                                                 param_shardings, axis)
                return loss_fn(full, batch)

            (loss, mets), grads = jax.value_and_grad(
                sharded_loss, has_aux=True
            )(state.params, batch)
            state = state.apply_gradients(grads=grads)
            return state, {k: cc.pmean(v, axis)
                           for k, v in {"loss": loss, **mets}.items()}

        sharded = shard_map(
            sm_step,
            mesh=self.mesh,
            in_specs=(spec_tree, P(axis)),
            out_specs=(spec_tree, P()),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=(0,) if donate else ())

    def make_eval_step(self, metric_fn, state_shardings: Any):
        """``(state, batch) -> metrics`` — the no-grad half for the
        Evaluator: params stay in their ZeRO-3 shards (GSPMD gathers the
        transient copies exactly as in training), state untouched.
        ``metric_fn(params, batch) -> {name: scalar}``."""
        batch_sharding = NamedSharding(self.mesh, P(self.axis))
        param_shardings = state_shardings.params

        def step(params, batch):
            return metric_fn(params, batch)

        jitted = jax.jit(
            step,
            in_shardings=(param_shardings, batch_sharding),
            out_shardings=NamedSharding(self.mesh, P()),
        )
        return lambda state, batch: jitted(state.params, batch)


# ---- program contracts (analysis/) ------------------------------------------


def lint_contracts():
    """Contract for the manual prefetch schedule: exactly one all_gather
    forward and one reduce_scatter backward per SHARDED leaf, one pmean
    per replicated leaf + per metric — the explicit ZeRO-3 collective
    budget GSPMD used to infer (counts derived from the fixture's leaf
    partition, not hand-pinned)."""
    from distributed_tensorflow_guide_tpu.analysis.contracts import (
        CostPin,
        CostSpec,
        DonationSpec,
        ProgramContract,
    )
    from distributed_tensorflow_guide_tpu.analysis.cost import closed_forms

    # tiny_mlp under min_shard_size=64 over 8 devices: the two (16,32)/
    # (32,16) matrices shard, the two biases replicate
    n_sharded, n_replicated, n_metrics = 2, 2, 2
    sharded_bytes = (16 * 32 + 32 * 16) * 4    # the two sharded matrices
    replicated_bytes = (32 + 16) * 4           # the two replicated biases

    def _term(name):
        def expect():
            import jax

            common = closed_forms()
            terms = common.fsdp_comm_terms(
                sharded_bytes, jax.device_count(), replicated_bytes)
            if name == "replicated_grad_allreduce":
                # the replicated-leaf pmeans share the psum census key
                # with the 2 scalar metric pmeans
                return (terms[name] + n_metrics
                        * common.dp_allreduce_bytes(4, jax.device_count()))
            return terms[name]

        return expect

    def _build():
        import jax

        from distributed_tensorflow_guide_tpu.analysis.fixtures import (
            tiny_mlp,
        )
        from distributed_tensorflow_guide_tpu.core.mesh import (
            MeshSpec,
            build_mesh,
        )

        loss_fn, state, batch = tiny_mlp()
        mesh = build_mesh(MeshSpec(data=-1))
        fsdp = FSDP(mesh, min_shard_size=64, prefetch=True)
        shardings = fsdp.param_shardings(
            jax.eval_shape(lambda: state.params))
        st_sh = fsdp.state_shardings(state, shardings)
        step = fsdp.make_train_step(loss_fn, st_sh, donate=True)
        return step, (state, batch)

    return [
        ProgramContract(
            name="fsdp_prefetch_train_step",
            build=_build,
            policy="f32",
            collectives={
                "all_gather[data]": n_sharded,
                "reduce_scatter[data]": n_sharded,
                "psum[data]": n_replicated + n_metrics,
            },
            donation=DonationSpec(argnums=(0,)),
            sources=(
                "distributed_tensorflow_guide_tpu.parallel.fsdp",
                "distributed_tensorflow_guide_tpu.parallel.overlap",
                "distributed_tensorflow_guide_tpu.collectives.collectives",
            ),
            cost=CostSpec(
                pins=(
                    CostPin("collective_bytes[all_gather[data]]",
                            _term("param_all_gather"),
                            note="ZeRO-3 fwd: unshard both matrices, "
                                 "S*(n-1)/n"),
                    CostPin("collective_bytes[reduce_scatter[data]]",
                            _term("grad_reduce_scatter"),
                            note="ZeRO-3 bwd: reshard both matrix grads"),
                    CostPin("collective_bytes[psum[data]]",
                            _term("replicated_grad_allreduce"),
                            note="bias-grad pmeans + 2 scalar metric "
                                 "pmeans"),
                ),
                # sharded params never fully materialize at once, so the
                # peak sits well under the DP step's (8,076 observed)
                max_peak_live_bytes=10240),
            notes="manual ZeRO-3 schedule: per-leaf gather/scatter budget"),
    ]
