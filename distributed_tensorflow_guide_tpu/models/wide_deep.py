"""Wide&Deep recommender — judged config 4: "Wide&Deep recommender, async PS
→ synchronous ICI allreduce" (BASELINE.md).

Reference context: recommender training is the canonical
ParameterServerStrategy workload
(tensorflow/python/distribute/parameter_server_strategy_v2.py:77) — huge
embedding tables live on PS shards, workers push sparse gradient rows
asynchronously. The TPU inversion: embedding tables are dense on-device
arrays (HBM is the parameter server), lookups are gathers that XLA fuses,
and gradient exchange is the same sync pmean as every other parameter —
see docs/async_ps_semantics.md for what that changes.

Model (Cheng et al. 2016): a *wide* linear path over categorical fields
(memorization) + a *deep* embeddings→MLP path (generalization), summed into
one logit, trained jointly.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class WideDeep(nn.Module):
    vocab_sizes: Sequence[int]  # one per categorical field
    num_dense: int = 8
    embed_dim: int = 16
    mlp_dims: Sequence[int] = (128, 64)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, cat: jax.Array, dense: jax.Array) -> jax.Array:
        """cat: (B, n_fields) int32; dense: (B, num_dense) float. → (B,) logit."""
        # wide: per-field scalar weight per id — the linear one-hot path
        wide_logit = jnp.zeros(cat.shape[0], self.dtype)
        for i, vocab in enumerate(self.vocab_sizes):
            w = nn.Embed(vocab, 1, name=f"wide_{i}", dtype=self.dtype)(cat[:, i])
            wide_logit = wide_logit + w[:, 0]
        wide_logit = wide_logit + nn.Dense(1, name="wide_dense",
                                           dtype=self.dtype)(dense)[:, 0]

        # deep: embeddings + dense features → MLP
        embs = [
            nn.Embed(vocab, self.embed_dim, name=f"emb_{i}", dtype=self.dtype)(
                cat[:, i]
            )
            for i, vocab in enumerate(self.vocab_sizes)
        ]
        x = jnp.concatenate(embs + [dense.astype(self.dtype)], axis=-1)
        for j, d in enumerate(self.mlp_dims):
            x = nn.Dense(d, name=f"mlp_{j}", dtype=self.dtype)(x)
            x = nn.relu(x)
        deep_logit = nn.Dense(1, name="deep_out", dtype=jnp.float32)(x)[:, 0]
        return wide_logit.astype(jnp.float32) + deep_logit


def make_loss_fn(model: WideDeep):
    """``(params, batch) -> (loss, metrics)`` — binary cross-entropy (CTR)."""

    def loss_fn(params, batch):
        logit = model.apply({"params": params}, batch["cat"], batch["dense"])
        label = batch["label"].astype(jnp.float32)
        loss = jnp.mean(
            jnp.maximum(logit, 0) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))
        )
        auc_proxy = jnp.mean((logit > 0) == (label > 0.5))
        return loss, {"accuracy": auc_proxy}

    return loss_fn
