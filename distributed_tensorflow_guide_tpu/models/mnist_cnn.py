"""MNIST CNN — the guide's toy model, re-expressed in Flax.

Reference: the small convnet/softmax models used by every example
(⚠ Non-Distributed-Setup/, Hogwild/, Synchronous-SGD/ in the reference tree;
behavior = GradientDescentOptimizer training,
tensorflow/python/training/gradient_descent.py:27). Judged config 1:
"MNIST CNN under tf.distribute.MirroredStrategy (single host)".

TPU notes: NHWC layout (XLA:TPU native), channel counts padded to
MXU/VPU-friendly multiples, bf16-ready via the ``dtype`` attribute while
params stay f32.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class MNISTCNN(nn.Module):
    """Conv(32) → Conv(64) → Dense(128) → Dense(10), ReLU + avg-pool."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:  # x: (B, 28, 28, 1)
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean(jnp.argmax(logits, axis=-1) == labels)


def make_loss_fn(model: MNISTCNN):
    """``(params, batch) -> (loss, metrics)`` for the DP strategy."""

    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["image"])
        loss = cross_entropy_loss(logits, batch["label"])
        return loss, {"accuracy": accuracy(logits, batch["label"])}

    return loss_fn


def make_metric_fn(model: MNISTCNN):
    """``(params, batch) -> metrics`` for
    :meth:`DataParallel.make_eval_step` (held-out evaluation: same
    forward, no gradient, no optimizer)."""

    def metric_fn(params, batch):
        logits = model.apply({"params": params}, batch["image"])
        return {
            "loss": cross_entropy_loss(logits, batch["label"]),
            "accuracy": accuracy(logits, batch["label"]),
        }

    return metric_fn
