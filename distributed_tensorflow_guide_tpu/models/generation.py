"""Autoregressive generation — the serving half of the LM family.

The reference is a training tutorial and has no inference path at all;
this is capability the TPU build adds on top of parity. TPU-first shape:

* **Static shapes everywhere.** The KV cache is a fixed (B, max_len, H,
  hd) buffer per layer (flax "cache" collection, written with
  ``lax.dynamic_update_slice``); the decode loop is ONE ``lax.scan`` whose
  body processes exactly one token — the whole generate call compiles to
  a single XLA program, no per-token dispatch, no retraces as the
  sequence grows.
* **One attention code path for prefill and decode**: a chunk of C tokens
  attends to the full cache under ``key_pos <= q_pos`` (masking both
  causality and not-yet-written slots), so the prompt is ingested in one
  forward pass (C = prompt length) and decode steps reuse the same module
  with C = 1 (models/transformer.py ``_decode_attend``).
* Sampling: greedy (``temperature=0``), temperature, and top-k — all
  branchless (top-k via ``lax.top_k`` threshold masking) so the scan body
  stays a single fused program.

Decode-mode parity with the training forward is pinned by
tests/test_generation.py (prefill logits == full-forward logits; greedy
decode == argmax-rescoring the growing prefix with the training model).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_guide_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)


def decode_config(cfg: TransformerConfig) -> TransformerConfig:
    """The serving view of a training config: KV-cache attention (dense —
    flash is a long-context *training* kernel; decode chunks are 1 token),
    no remat (nothing to rematerialize without a backward pass)."""
    return dataclasses.replace(cfg, decode=True, attn_impl="dense",
                               remat=False)


def init_cache(cfg: TransformerConfig, params, batch_size: int):
    """Allocate the fixed-size KV cache for ``batch_size`` sequences."""
    model = Transformer(decode_config(cfg))
    variables = jax.eval_shape(
        model.init, jax.random.PRNGKey(0),
        jnp.zeros((batch_size, 1), jnp.int32), 0)
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                         variables["cache"])
    del params  # shape/dtype only — kept in the signature for call-site symmetry
    return cache


def _sample(logits, rng, temperature: float, top_k: int | None):
    """(B, V) logits -> (B,) int32 token ids. Branchless; greedy when
    temperature == 0 (exact argmax, not a limit)."""
    if temperature == 0.0:
        return jnp.argmax(logits, -1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits).astype(jnp.int32)


def make_generate_fn(cfg: TransformerConfig, *, max_new_tokens: int,
                     temperature: float = 1.0, top_k: int | None = None):
    """Build a jitted ``(params, prompt (B, P) int32, rng) -> (B, P + N)``
    generator. Compiles once per (B, P) shape; P + max_new_tokens must fit
    ``cfg.max_len`` (checked at trace time)."""
    dcfg = decode_config(cfg)
    model = Transformer(dcfg)
    sample = partial(_sample, temperature=temperature, top_k=top_k)

    @jax.jit
    def generate(params, prompt, rng):
        B, P = prompt.shape
        if P + max_new_tokens > dcfg.max_len:
            raise ValueError(
                f"prompt {P} + max_new_tokens {max_new_tokens} exceeds "
                f"max_len {dcfg.max_len}")
        cache = init_cache(cfg, params, B)
        # prefill: the whole prompt in one forward pass, cache filled
        logits, vs = model.apply({"params": params, "cache": cache},
                                 prompt, 0, mutable=["cache"])
        rng, sub = jax.random.split(rng)
        tok = sample(logits[:, -1], sub)

        def body(carry, _):
            cache, tok, idx, rng = carry
            logits, vs = model.apply({"params": params, "cache": cache},
                                     tok[:, None], idx, mutable=["cache"])
            rng, sub = jax.random.split(rng)
            nxt = sample(logits[:, -1], sub)
            return (vs["cache"], nxt, idx + 1, rng), tok

        (_, last, _, _), toks = lax.scan(
            body, (vs["cache"], tok, jnp.int32(P), rng), None,
            length=max_new_tokens - 1)
        new = jnp.concatenate([toks.T, last[:, None]], axis=1)  # (B, N)
        return jnp.concatenate([prompt, new], axis=1)

    return generate
