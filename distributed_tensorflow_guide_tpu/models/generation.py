"""Autoregressive generation — the serving half of the LM family.

The reference is a training tutorial and has no inference path at all;
this is capability the TPU build adds on top of parity. TPU-first shape:

* **Static shapes everywhere.** The KV cache is a fixed (B, max_len, H,
  hd) buffer per layer (flax "cache" collection, written with
  ``lax.dynamic_update_slice``); the decode loop is ONE ``lax.scan`` whose
  body processes exactly one token — the whole generate call compiles to
  a single XLA program, no per-token dispatch, no retraces as the
  sequence grows.
* **One attention code path for prefill and decode**: a chunk of C tokens
  attends to the full cache under ``key_pos <= q_pos`` (masking both
  causality and not-yet-written slots), so the prompt is ingested in one
  forward pass (C = prompt length) and decode steps reuse the same module
  with C = 1 (models/transformer.py ``_decode_attend``).
* Sampling: greedy (``temperature=0``), temperature, and top-k — all
  branchless (top-k via ``lax.top_k`` threshold masking) so the scan body
  stays a single fused program.

Decode-mode parity with the training forward is pinned by
tests/test_generation.py (prefill logits == full-forward logits; greedy
decode == argmax-rescoring the growing prefix with the training model).
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_guide_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)


def decode_config(cfg: TransformerConfig) -> TransformerConfig:
    """The serving view of a training config: KV-cache attention (dense —
    flash is a long-context *training* kernel; decode chunks are 1 token),
    no remat (nothing to rematerialize without a backward pass)."""
    # remat cleared at BOTH spellings: the precision-policy remat_mode wins
    # over the legacy bool in resolved_remat_mode, so leaving it set would
    # silently keep checkpointing in the serving forward
    return dataclasses.replace(cfg, decode=True, attn_impl="dense",
                               remat=False, remat_mode=None)


def cache_shapes(cfg: TransformerConfig, batch_size: int):
    """Abstract (shape/dtype) tree of the decode KV cache — the SINGLE
    derivation :func:`init_cache` and :func:`make_generate_fn` share, so
    the allocated cache can never drift from what generate traces."""
    model = Transformer(decode_config(cfg))
    variables = jax.eval_shape(
        model.init, jax.random.PRNGKey(0),
        jnp.zeros((batch_size, 1), jnp.int32), 0)
    return variables["cache"]


def init_cache(cfg: TransformerConfig, params, batch_size: int):
    """Allocate the fixed-size KV cache for ``batch_size`` sequences."""
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                         cache_shapes(cfg, batch_size))
    del params  # shape/dtype only — kept in the signature for call-site symmetry
    return cache


def decode_hbm_bytes_per_step(cfg: TransformerConfig, params,
                              batch_size: int) -> float:
    """Minimal algorithmic HBM traffic of ONE decode step: every
    NON-EMBEDDING parameter read once (the embedding tables are gathered,
    not streamed — a step touches B rows of the token table and one
    position row, not the ~154 MB table; counting it whole would inflate
    the roofline fraction the ≥0.4 acceptance gate judges), the full
    fixed-size KV cache read once (static-shape attention attends against
    all ``max_len`` slots every step), plus the one-token cache write.
    Decode is bandwidth-bound — this is the roofline denominator
    ``benchmarks/bench_generate.py`` reports ``hbm_gb_per_s`` against.
    ``params`` may be arrays or the eval_shape tree (sizes/dtypes only)."""
    import numpy as np

    p_bytes = sum(
        leaf.size * np.dtype(leaf.dtype).itemsize
        for leaf in jax.tree.leaves(params)
    )
    from collections.abc import Mapping

    emb_bytes = gathered = 0.0
    if isinstance(params, Mapping):  # plain dict or flax FrozenDict alike
        for name, rows in (("tok_emb", batch_size), ("pos_emb", 1)):
            for leaf in jax.tree.leaves(params.get(name, {})):
                it = np.dtype(leaf.dtype).itemsize
                emb_bytes += leaf.size * it
                gathered += rows * leaf.shape[-1] * it
    item = np.dtype(cfg.dtype).itemsize
    kv_slots = (batch_size * cfg.max_len * cfg.num_heads * cfg.head_dim
                * item * 2)  # k and v
    cache_read = cfg.num_layers * kv_slots
    cache_write = cfg.num_layers * kv_slots // cfg.max_len  # one slot
    return float(p_bytes - emb_bytes + gathered + cache_read + cache_write)


def _sample(logits, rng, temperature: float, top_k: int | None):
    """(B, V) logits -> (B,) int32 token ids. Branchless; greedy when
    temperature == 0 (exact argmax, not a limit)."""
    if temperature == 0.0:
        return jnp.argmax(logits, -1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits).astype(jnp.int32)


def make_generate_fn(cfg: TransformerConfig, *, max_new_tokens: int,
                     temperature: float = 1.0, top_k: int | None = None,
                     donate_cache: bool = True, unroll: int = 1):
    """Build a jitted ``(params, prompt (B, P) int32, rng) -> (B, P + N)``
    generator. Compiles once per (B, P) shape; P + max_new_tokens must fit
    ``cfg.max_len`` (checked eagerly per call).

    Decode-path knobs (the HBM-roofline levers — decode is bandwidth-bound:
    every step re-reads the params and the KV cache):

    * ``donate_cache`` (default True): the cache is allocated OUTSIDE the
      compiled program and donated into it, so XLA aliases the buffers and
      the per-step ``dynamic_update_slice`` writes land in place — no
      second live copy of ``layers x (B, max_len, H, hd) x 2`` in HBM.
      Safe by construction: each call allocates a fresh cache and nothing
      re-reads it after the call (donation-safety pinned in
      tests/test_generation.py, the buffer-reuse oracle pattern of
      tests/test_prefetch.py).
    * ``unroll``: ``lax.scan`` unroll factor for the decode loop — trades
      program size for per-token loop/dispatch overhead; parity is pinned
      (the unrolled loop is the same program repeated).
    """
    dcfg = decode_config(cfg)
    model = Transformer(dcfg)
    sample = partial(_sample, temperature=temperature, top_k=top_k)

    def _generate(params, prompt, cache, rng):
        B, P = prompt.shape
        # prefill: the whole prompt in one forward pass, cache filled
        logits, vs = model.apply({"params": params, "cache": cache},
                                 prompt, 0, mutable=["cache"])
        rng, sub = jax.random.split(rng)
        tok = sample(logits[:, -1], sub)

        def body(carry, _):
            cache, tok, idx, rng = carry
            logits, vs = model.apply({"params": params, "cache": cache},
                                     tok[:, None], idx, mutable=["cache"])
            rng, sub = jax.random.split(rng)
            nxt = sample(logits[:, -1], sub)
            return (vs["cache"], nxt, idx + 1, rng), tok

        (_, last, _, _), toks = lax.scan(
            body, (vs["cache"], tok, jnp.int32(P), rng), None,
            length=max_new_tokens - 1, unroll=unroll)
        new = jnp.concatenate([toks.T, last[:, None]], axis=1)  # (B, N)
        return jnp.concatenate([prompt, new], axis=1)

    # Donation is a no-op the CPU backend additionally WARNS about
    # ("donated buffers were not usable"), so the knob is gated off there
    # — the fresh-cache-per-call safety contract is backend-independent
    # and stays tested either way.
    donate = donate_cache and jax.default_backend() != "cpu"
    jitted = jax.jit(_generate, donate_argnums=(2,) if donate else ())

    # The cache SHAPE tree is a full Flax module trace — far too expensive
    # to re-derive inside the per-call serving path (it would sit in every
    # bench's timed loop); memoize it per batch size and only the zeros
    # allocation happens per call (fresh buffers are what donation safety
    # rests on).
    @lru_cache(maxsize=8)
    def _cache_shapes(batch_size: int):
        return cache_shapes(cfg, batch_size)

    def generate(params, prompt, rng):
        B, P = prompt.shape
        if P + max_new_tokens > dcfg.max_len:
            raise ValueError(
                f"prompt {P} + max_new_tokens {max_new_tokens} exceeds "
                f"max_len {dcfg.max_len}")
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                             _cache_shapes(B))
        return jitted(params, prompt, cache, rng)

    # introspection for tests/benches: whether the compiled program
    # actually aliases the cache argument (False on the CPU backend)
    generate.donates_cache = donate
    return generate
