"""Autoregressive generation — the serving half of the LM family.

The reference is a training tutorial and has no inference path at all;
this is capability the TPU build adds on top of parity. TPU-first shape:

* **Static shapes everywhere.** The KV cache is a fixed (B, max_len, H,
  hd) buffer per layer (flax "cache" collection, written with
  ``lax.dynamic_update_slice``); the decode loop is ONE ``lax.scan`` whose
  body processes exactly one token — the whole generate call compiles to
  a single XLA program, no per-token dispatch, no retraces as the
  sequence grows.
* **One attention code path for prefill and decode**: a chunk of C tokens
  attends to the full cache under ``key_pos <= q_pos`` (masking both
  causality and not-yet-written slots), so the prompt is ingested in one
  forward pass (C = prompt length) and decode steps reuse the same module
  with C = 1 (models/transformer.py ``_decode_attend``).
* Sampling: greedy (``temperature=0``), temperature, and top-k — all
  branchless (top-k via ``lax.top_k`` threshold masking) so the scan body
  stays a single fused program. Keys derive from the absolute position
  (``fold_in(rng, position)``), which is what lets speculative decoding
  reproduce the vanilla stream token-for-token.
* Decode bandwidth levers (round 11): ``cfg.kv_dtype="int8"`` (quantized
  cache, fused dequant), ``cfg.decode_impl`` (length-aware Pallas
  decode-attention — ops/decode_attention.py), and
  ``spec_draft_layers``/``spec_lookahead`` (self-speculative decoding —
  see :func:`make_generate_fn`). docs/serving.md "Decode levers" covers
  when each pays.

Decode-mode parity with the training forward is pinned by
tests/test_generation.py (prefill logits == full-forward logits; greedy
decode == argmax-rescoring the growing prefix with the training model).
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_guide_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)


def decode_config(cfg: TransformerConfig) -> TransformerConfig:
    """The serving view of a training config: KV-cache attention (dense —
    flash is a long-context *training* kernel; decode chunks are 1 token),
    no remat (nothing to rematerialize without a backward pass)."""
    # remat cleared at BOTH spellings: the precision-policy remat_mode wins
    # over the legacy bool in resolved_remat_mode, so leaving it set would
    # silently keep checkpointing in the serving forward
    return dataclasses.replace(cfg, decode=True, attn_impl="dense",
                               remat=False, remat_mode=None)


def cache_shapes(cfg: TransformerConfig, batch_size: int):
    """Abstract (shape/dtype) tree of the decode KV cache — the SINGLE
    derivation :func:`init_cache` and :func:`make_generate_fn` share, so
    the allocated cache can never drift from what generate traces."""
    model = Transformer(decode_config(cfg))
    variables = jax.eval_shape(
        model.init, jax.random.PRNGKey(0),
        jnp.zeros((batch_size, 1), jnp.int32), 0)
    return variables["cache"]


def init_cache(cfg: TransformerConfig, params, batch_size: int):
    """Allocate the fixed-size KV cache for ``batch_size`` sequences."""
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                         cache_shapes(cfg, batch_size))
    del params  # shape/dtype only — kept in the signature for call-site symmetry
    return cache


def decode_cache_bytes_per_step(cfg: TransformerConfig, batch_size: int, *,
                                effective_len: int | None = None) -> float:
    """KV-cache HBM traffic of ONE decode step: ``effective_len`` slots
    read (K and V, at the CACHE dtype — 1 byte under ``kv_dtype="int8"`` —
    plus the two per-slot f32 scales when quantized) and one slot written.

    ``effective_len=None`` models the dense static-shape path, which
    attends against all ``max_len`` slots every step. The length-aware
    Pallas kernel (``decode_impl="pallas"``) reads only written blocks, so
    its caller passes the block-rounded live length — charging it the full
    cache would overstate its achieved bandwidth and flatter the roofline
    fraction the ≥0.4 gate judges."""
    import jax.numpy as _jnp

    from distributed_tensorflow_guide_tpu.ops.decode_attention import (
        cache_slot_bytes,
    )

    length = cfg.max_len if effective_len is None else min(
        int(effective_len), cfg.max_len)
    kv_dtype = _jnp.int8 if cfg.kv_dtype == "int8" else cfg.dtype
    # bytes per (batch, slot): K + V vectors across heads (+ scales when
    # quantized) — the shared per-(slot, head) definition, so this model
    # and the kernel-only bench's can never disagree on the same cache
    per_slot = cfg.num_heads * cache_slot_bytes(cfg.head_dim, kv_dtype)
    read = cfg.num_layers * batch_size * length * per_slot
    write = cfg.num_layers * batch_size * per_slot  # one slot
    return float(read + write)


def paged_decode_cache_bytes_per_step(cfg: TransformerConfig, *,
                                      block_size: int, live_blocks: int,
                                      active_slots: int) -> float:
    """KV-cache HBM traffic of ONE paged decode step: the pool's LIVE
    blocks read (continuous batching reads what resident requests have
    written, not ``batch * max_len``) and one slot written per active
    decode slot. Built on the same per-(slot, head)
    ``ops.decode_attention.cache_slot_bytes`` definition as the dense
    model above — the serve engine and ``bench_generate.py`` share one
    byte model, so the serving roofline rows cannot silently reuse the
    dense ``max_len`` charge (the whole point of paging)."""
    import jax.numpy as _jnp

    from distributed_tensorflow_guide_tpu.ops.decode_attention import (
        cache_slot_bytes,
    )

    kv_dtype = _jnp.int8 if cfg.kv_dtype == "int8" else cfg.dtype
    per_slot = cfg.num_heads * cache_slot_bytes(cfg.head_dim, kv_dtype)
    read = cfg.num_layers * live_blocks * block_size * per_slot
    write = cfg.num_layers * active_slots * per_slot
    return float(read + write)


def decode_hbm_bytes_per_step(cfg: TransformerConfig, params,
                              batch_size: int, *,
                              effective_len: int | None = None) -> float:
    """Minimal algorithmic HBM traffic of ONE decode step: every
    NON-EMBEDDING parameter read once (the embedding tables are gathered,
    not streamed — a step touches B rows of the token table and one
    position row, not the ~154 MB table; counting it whole would inflate
    the roofline fraction the ≥0.4 acceptance gate judges), plus the
    cache-dtype-aware KV traffic of :func:`decode_cache_bytes_per_step`
    (full ``max_len`` read for the dense path; pass ``effective_len`` for
    the length-aware kernel so the denominator stays honest either way).
    Decode is bandwidth-bound — this is the roofline denominator
    ``benchmarks/bench_generate.py`` reports ``hbm_gb_per_s`` against.
    ``params`` may be arrays or the eval_shape tree (sizes/dtypes only).
    Leaf-driven by construction, so weight-only quantization needs no
    special case: hand it the ``ops.quant.quantize_params`` tree and the
    params term shrinks with the stored bytes — ~4x for int8 qkernels,
    ~8x for int4 packed two-per-byte (scales are d_out-sized noise)."""
    import numpy as np

    p_bytes = sum(
        leaf.size * np.dtype(leaf.dtype).itemsize
        for leaf in jax.tree.leaves(params)
    )
    from collections.abc import Mapping

    emb_bytes = gathered = 0.0
    if isinstance(params, Mapping):  # plain dict or flax FrozenDict alike
        for name, rows in (("tok_emb", batch_size), ("pos_emb", 1)):
            for leaf in jax.tree.leaves(params.get(name, {})):
                it = np.dtype(leaf.dtype).itemsize
                emb_bytes += leaf.size * it
                gathered += rows * leaf.shape[-1] * it
    cache = decode_cache_bytes_per_step(cfg, batch_size,
                                        effective_len=effective_len)
    return float(p_bytes - emb_bytes + gathered + cache)


def _sample(logits, key, temperature: float, top_k: int | None):
    """(B, V) logits -> (B,) int32 token ids. Branchless; greedy when
    temperature == 0 (exact argmax, not a limit).

    ``key`` is the POSITION-derived key ``fold_in(rng, position)`` — not a
    split chain. Deriving the key from the absolute sequence position makes
    the sampled stream a pure function of (rng, position, logits), which is
    what lets speculative decoding reproduce the vanilla stream exactly:
    the draft and the verifier sample position p with the SAME key, so a
    draft whose logits agree with the full model yields the same token
    (the Gumbel coupling behind the accept test), and every accepted token
    is bitwise the one vanilla decoding would have emitted."""
    if temperature == 0.0:
        return jnp.argmax(logits, -1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits).astype(jnp.int32)


def sample_rows(logits, keys, temperature: float, top_k: int | None):
    """Per-row sampling: (B, V) logits + (B,) per-row position-derived
    keys -> (B,) int32 tokens, row b bitwise what a B=1 :func:`_sample`
    call would emit. This is the serve engine's sampler: continuous
    batching puts every slot at its own position with its own request
    rng, and ``vmap`` over the B=1 call is what makes each slot's stream
    identical to that request's one-shot ``make_generate_fn`` run — the
    engine-parity acceptance pin."""
    return jax.vmap(
        lambda row, key: _sample(row[None], key, temperature, top_k)[0]
    )(logits, keys)


def make_generate_fn(cfg: TransformerConfig, *, max_new_tokens: int,
                     temperature: float = 1.0, top_k: int | None = None,
                     donate_cache: bool = True, unroll: int = 1,
                     spec_draft_layers: int = 0, spec_lookahead: int = 4,
                     adapters=None, adapter_id: int = 0):
    """Build a jitted ``(params, prompt (B, P) int32, rng) -> (B, P + N)``
    generator. Compiles once per (B, P) shape; P + max_new_tokens (+ the
    speculative lookahead, when on) must fit ``cfg.max_len`` (checked
    eagerly per call).

    Decode-path knobs (the HBM-roofline levers — decode is bandwidth-bound:
    every step re-reads the params and the KV cache; ``cfg.kv_dtype`` and
    ``cfg.decode_impl`` attack the cache bytes, the knobs here attack the
    steps):

    * ``donate_cache`` (default True): the cache is allocated OUTSIDE the
      compiled program and donated into it, so XLA aliases the buffers and
      the per-step ``dynamic_update_slice`` writes land in place — no
      second live copy of ``layers x (B, max_len, H, hd) x 2`` in HBM.
      Safe by construction: each call allocates a fresh cache and nothing
      re-reads it after the call (donation-safety pinned in
      tests/test_generation.py, the buffer-reuse oracle pattern of
      tests/test_prefetch.py).
    * ``unroll``: ``lax.scan`` unroll factor for the decode loop — trades
      program size for per-token loop/dispatch overhead; parity is pinned
      (the unrolled loop is the same program repeated).
    * ``spec_draft_layers`` (K > 0 turns speculative decoding on): draft
      with the K-layer PREFIX of the same model — shared params (flax
      ignores the unused deeper blocks), its own small K-layer cache —
      then verify all ``spec_lookahead`` drafted tokens in ONE full-model
      forward (a (G+1)-token chunk through the same ``_decode_attend``
      path) and accept the longest matching prefix, batch-lockstep (the
      accept count is the min over rows, which keeps the cache write index
      a scalar and every shape static). Sampling keys derive from the
      absolute position (see ``_sample``), so the emitted stream is the
      vanilla stream exactly: every accepted token is the verifier's own
      token for that position, and on the first mismatch the verifier's
      token is emitted instead — greedy speculative output is pinned
      BITWISE-identical to vanilla greedy (it is a reordering of the same
      argmaxes; tests/test_generation.py pins the sampled mode too). The
      outer accept loop is a ``lax.while_loop`` (static shapes, dynamic
      trip count — no wasted verify passes after the budget is met);
      rejected slots hold stale k/v but are ALWAYS rewritten by the next
      draft/verify chunk before any later query can attend to them.
      Per-call acceptance stats land in ``generate.last_stats``.
    """
    dcfg = decode_config(cfg)
    model = Transformer(dcfg)
    sample = partial(_sample, temperature=temperature, top_k=top_k)
    spec = spec_draft_layers > 0
    if spec and not 0 < spec_draft_layers < cfg.num_layers:
        raise ValueError(
            f"spec_draft_layers {spec_draft_layers} must lie strictly "
            f"between 0 and num_layers {cfg.num_layers} (the draft is a "
            "proper prefix of the same model)")
    if spec and spec_lookahead < 1:
        raise ValueError(f"spec_lookahead {spec_lookahead} must be >= 1")
    if spec:
        draft_cfg = dataclasses.replace(cfg, num_layers=spec_draft_layers)
        draft_model = Transformer(decode_config(draft_cfg))
    # Multi-LoRA one-shot path (the serve engine's per-adapter oracle):
    # ``adapters`` is the bank tree ("adapters" collection) and
    # ``adapter_id`` selects one row for the whole batch. The bank is
    # closed over (a jit constant — the oracle serves parity tests, not
    # production traffic), and the adapter-free trace stays verbatim.
    lora = dcfg.lora_rank is not None
    if lora and adapters is None:
        raise ValueError(
            "cfg.lora_rank set: pass the adapters bank "
            "(serve.init_adapter_bank)")
    if not lora and adapters is not None:
        raise ValueError("adapters given but cfg.lora_rank is None")
    if lora and not 0 <= adapter_id <= cfg.lora_adapters:
        raise ValueError(
            f"adapter_id {adapter_id} out of range "
            f"[0, {cfg.lora_adapters}]")
    if lora and spec:
        raise ValueError("speculative decoding + LoRA is not supported")

    def _apply(params, cache, toks, idx):
        variables = {"params": params, "cache": cache}
        if not lora:
            return model.apply(variables, toks, idx, mutable=["cache"])
        variables["adapters"] = adapters
        ids = jnp.full((toks.shape[0],), adapter_id, jnp.int32)
        return model.apply(variables, toks, idx, adapter=ids,
                           mutable=["cache"])

    def _generate(params, prompt, cache, rng):
        B, P = prompt.shape
        # prefill: the whole prompt in one forward pass, cache filled
        logits, vs = _apply(params, cache, prompt, 0)
        tok = sample(logits[:, -1], jax.random.fold_in(rng, P))

        def body(carry, _):
            cache, tok, idx = carry
            logits, vs = _apply(params, cache, tok[:, None], idx)
            nxt = sample(logits[:, -1], jax.random.fold_in(rng, idx + 1))
            return (vs["cache"], nxt, idx + 1), tok

        (_, last, _), toks = lax.scan(
            body, (vs["cache"], tok, jnp.int32(P)), None,
            length=max_new_tokens - 1, unroll=unroll)
        new = jnp.concatenate([toks.T, last[:, None]], axis=1)  # (B, N)
        return jnp.concatenate([prompt, new], axis=1)

    def _generate_spec(params, prompt, cache, draft_cache, rng):
        B, P = prompt.shape
        G = spec_lookahead
        # prefill BOTH caches with the prompt; the first token comes from
        # the full model, exactly as in the vanilla path
        logits, vs = model.apply({"params": params, "cache": cache},
                                 prompt, 0, mutable=["cache"])
        cache = vs["cache"]
        _, dvs = draft_model.apply(
            {"params": params, "cache": draft_cache}, prompt, 0,
            mutable=["cache"])
        draft_cache = dvs["cache"]
        t0 = sample(logits[:, -1], jax.random.fold_in(rng, P))
        # emitted-token buffer, G slots of slack: one verify chunk may
        # emit up to G+1 tokens and the loop exits as soon as the budget
        # is met — overshoot is sliced off below
        buf = jnp.zeros((B, max_new_tokens + G), jnp.int32)
        buf = lax.dynamic_update_slice(buf, t0[:, None], (0, 0))

        def cond(carry):
            return carry[4] < max_new_tokens

        def body(carry):
            cache, draft_cache, buf, last, produced, steps, accepted = carry
            idx0 = P + produced - 1  # position of `last` (k/v unwritten)

            def draft_body(dc, _):
                draft_cache, tok, idx = dc
                dl, dvs = draft_model.apply(
                    {"params": params, "cache": draft_cache}, tok[:, None],
                    idx, mutable=["cache"])
                nxt = sample(dl[:, -1], jax.random.fold_in(rng, idx + 1))
                return (dvs["cache"], nxt, idx + 1), nxt

            # G+1 steps, last output discarded: the extra step exists to
            # WRITE the draft-cache slot of the final draft (position
            # idx0+G). Without it a fully-accepted round (m == G) jumps
            # past that slot forever and every later draft attends a
            # zero-initialized k/v hole — output would stay correct (the
            # verifier is authoritative) but the draft stream would drift
            # from the true K-layer model and acceptance would decay in
            # exactly the high-acceptance regime the lever exists for.
            (draft_cache, _, _), drafts = lax.scan(
                draft_body, (draft_cache, last, idx0), None, length=G + 1,
                unroll=unroll)
            drafts = jnp.moveaxis(drafts[:G], 0, 1)  # (B, G)
            # verify: one (G+1)-token chunk through the FULL model — its
            # row j scores position idx0+j+1; the same position-derived
            # key as the draft makes the accept test a pure token match
            chunk = jnp.concatenate([last[:, None], drafts], axis=1)
            vl, vvs = model.apply({"params": params, "cache": cache},
                                  chunk, idx0, mutable=["cache"])
            cache = vvs["cache"]
            verified = jnp.stack(
                [sample(vl[:, j], jax.random.fold_in(rng, idx0 + 1 + j))
                 for j in range(G + 1)], axis=1)  # (B, G+1)
            matches = (verified[:, :G] == drafts).astype(jnp.int32)
            # longest accepted prefix per row, then batch-lockstep min so
            # the cache index stays a scalar
            m = jnp.min(jnp.sum(jnp.cumprod(matches, axis=1), axis=1))
            # emit the verifier's tokens 0..m: positions j < m equal the
            # drafts (that is what accepted means) and position m is the
            # verifier's correction/bonus — all are exactly what vanilla
            # decode would emit. Columns past m are garbage conditioned on
            # rejected drafts; they are overwritten by the next chunk
            # before the slice below can see them.
            buf = lax.dynamic_update_slice(buf, verified, (0, produced))
            last = lax.dynamic_index_in_dim(verified, m, axis=1,
                                            keepdims=False)
            return (cache, draft_cache, buf, last, produced + m + 1,
                    steps + 1, accepted + m)

        init = (cache, draft_cache, buf, t0, jnp.int32(1), jnp.int32(0),
                jnp.int32(0))
        _, _, buf, _, produced, steps, accepted = lax.while_loop(
            cond, body, init)
        out = jnp.concatenate([prompt, buf[:, :max_new_tokens]], axis=1)
        return out, steps, accepted

    # Donation is a no-op the CPU backend additionally WARNS about
    # ("donated buffers were not usable"), so the knob is gated off there
    # — the fresh-cache-per-call safety contract is backend-independent
    # and stays tested either way.
    donate = donate_cache and jax.default_backend() != "cpu"
    if spec:
        jitted = jax.jit(_generate_spec,
                         donate_argnums=(2, 3) if donate else ())
    else:
        jitted = jax.jit(_generate, donate_argnums=(2,) if donate else ())

    # The cache SHAPE tree is a full Flax module trace — far too expensive
    # to re-derive inside the per-call serving path (it would sit in every
    # bench's timed loop); memoize it per batch size and only the zeros
    # allocation happens per call (fresh buffers are what donation safety
    # rests on).
    @lru_cache(maxsize=8)
    def _cache_shapes(batch_size: int):
        return cache_shapes(cfg, batch_size)

    @lru_cache(maxsize=8)
    def _draft_cache_shapes(batch_size: int):
        return cache_shapes(draft_cfg, batch_size)

    def _fresh(shapes):
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    def generate(params, prompt, rng):
        B, P = prompt.shape
        budget = max_new_tokens + (spec_lookahead if spec else 0)
        if P + budget > dcfg.max_len:
            raise ValueError(
                f"prompt {P} + max_new_tokens {max_new_tokens}"
                + (f" + spec_lookahead {spec_lookahead}" if spec else "")
                + f" exceeds max_len {dcfg.max_len}")
        cache = _fresh(_cache_shapes(B))
        if not spec:
            return jitted(params, prompt, cache, rng)
        draft_cache = _fresh(_draft_cache_shapes(B))
        out, steps, accepted = jitted(params, prompt, cache, draft_cache,
                                      rng)
        # raw device scalars — reading them synchronizes, so benches fetch
        # AFTER the timed region
        generate.last_stats = {"verify_steps": steps,
                               "accepted_drafts": accepted}
        return out

    # introspection for tests/benches: whether the compiled program
    # actually aliases the cache argument (False on the CPU backend)
    generate.donates_cache = donate
    generate.last_stats = None
    # static-analysis hooks (analysis/): the compiled entry itself and the
    # donation INTENT — what a TPU run donates, even where the cpu gate
    # turned actual donation off (the lint audits the intent's soundness)
    generate.jitted = jitted
    generate.declared_donate_argnums = (2, 3) if spec else (2,)
    return generate


# ---- program contracts (analysis/) ------------------------------------------


def lint_contracts():
    """Contracts for the decode entry programs (vanilla + speculative).

    The serve path must be collective-free (it runs single-device or
    replicated; a stray psum here would deadlock a sharded server),
    host-callback-free (determinism + no per-token host round-trips),
    and its declared cache donation must be *scratch*-sound: the program
    returns only tokens, so the cache can never alias an output — the
    donation exists to let XLA reuse the buffer in place — and the lint
    checks the cache is read exactly once at top level instead."""
    from distributed_tensorflow_guide_tpu.analysis.contracts import (
        CostSpec,
        DonationSpec,
        ProgramContract,
    )

    def build(spec_layers):
        def _build():
            import jax

            from distributed_tensorflow_guide_tpu.analysis.fixtures import (
                tiny_lm_cfg,
            )

            cfg = tiny_lm_cfg(max_len=32)
            gen = make_generate_fn(
                cfg, max_new_tokens=4, spec_draft_layers=spec_layers,
                spec_lookahead=2 if spec_layers else 4)
            B, P = 2, 8
            prompt = jax.ShapeDtypeStruct((B, P), "int32")
            model = Transformer(decode_config(cfg))
            params = jax.eval_shape(
                lambda p: model.init(jax.random.PRNGKey(0), p, 0),
                prompt)["params"]
            cache = _jax_sds_tree(cache_shapes(cfg, B))
            rng = jax.random.PRNGKey(0)
            args = [params, prompt, cache, rng]
            if spec_layers:
                dcfg = dataclasses.replace(cfg, num_layers=spec_layers)
                args.insert(3, _jax_sds_tree(cache_shapes(dcfg, B)))
            return gen.jitted, tuple(args)

        return _build

    def _jax_sds_tree(tree):
        import jax

        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), tree)

    common = dict(
        policy="f32",
        collectives={},  # strict: the serve path is collective-free
        sources=("distributed_tensorflow_guide_tpu.models.generation",
                 "distributed_tensorflow_guide_tpu.models.transformer"),
    )
    return [
        ProgramContract(
            name="decode_step",
            build=build(0),
            donation=DonationSpec(argnums=(2,), mode="scratch"),
            # 123,596 observed: params + donated KV cache dominate; a
            # regression that holds a second cache copy live doubles this
            cost=CostSpec(max_peak_live_bytes=131072),
            notes="vanilla scan decode: cache donated as scratch",
            **common),
        ProgramContract(
            name="decode_spec_step",
            build=build(1),
            donation=DonationSpec(argnums=(2, 3), mode="scratch"),
            cost=CostSpec(max_peak_live_bytes=196608),
            notes="self-speculative decode (while_loop body audited too)",
            **common),
    ]
