"""Transformer family — shared backbone for judged configs 3 (BERT-base TP)
and 5 (GPT-2 124M PP), with Megatron-style tensor-parallel annotations.

No transformer exists in the reference (its largest model is a small CNN);
these configs come from BASELINE.json. The tensor-parallel design follows
the Megatron factorization (Shoeybi et al. 2019) expressed the JAX way:
parameters carry *logical* axis names via ``nn.with_logical_partitioning``,
``parallel/tensor.py`` maps logical names → mesh axes
(vocab/mlp/heads → "model"), and XLA inserts the collectives that Megatron
hand-writes as NCCL calls (the north-star mapping: NCCL allreduce →
``lax.psum``, here implicit through ``pjit`` shardings).

Logical axes: "batch", "seq", "embed" (d_model), "mlp" (d_ff),
"heads", "kv" (head_dim), "vocab".

TPU-first: bf16 activations / f32 params; d_ff and head counts MXU-friendly;
optional ``jax.checkpoint`` rematerialization per block (HBM ↔ FLOPs trade);
static shapes throughout (fixed seq_len — no dynamic padding).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_guide_tpu.collectives import (
    tp_allreduce,
    tp_identity,
)
from distributed_tensorflow_guide_tpu.utils.activation_sharding import (
    activation_mesh,  # noqa: F401 - re-export (strategy API lived here first)
    constrain as _constrain,
)

Dtype = Any


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    max_len: int = 1024
    causal: bool = True
    dtype: Dtype = jnp.bfloat16
    remat: bool = False
    # Selective rematerialization (core/precision.py): None derives from the
    # legacy ``remat`` bool ("block" when True); "attention" checkpoints ONLY
    # the attention sub-layer per block — recompute the high-traffic part,
    # keep the MLP activations resident; "block" is the classic full-block
    # checkpoint (what remat=True always meant); "none" stores everything.
    remat_mode: str | None = None
    num_classes: int | None = None  # set → classification head (BERT/GLUE)
    # "dense"  — XLA softmax attention (materializes (S, S) scores). GSPMD
    #            partitions it under pjit, so it composes with TP sharding.
    # "flash"  — fused Pallas kernel (ops/flash_attention.py); falls back to
    #            the pure-XLA blockwise path on unsupported shapes. Works
    #            inside shard_map strategies (DP/PP/SP — per-device local
    #            arrays) AND under pjit/TP: the kernel carries a
    #            custom_partitioning rule that shards batch/heads (heads →
    #            the "model" axis) and replicates seq/head_dim.
    # "auto"   — flash for causal long-context (max_len >= 1024), else dense.
    #            Measured on the v5 lite chip: dense wins below ~1k tokens
    #            (XLA's fused softmax beats the kernel-dispatch overhead) and
    #            CANNOT COMPILE at >= 1024 under remat, where flash runs.
    attn_impl: str = "auto"
    # Manual-SPMD tensor parallelism (TP inside shard_map, e.g. TP-sharded
    # pipeline stages): set ``tp_axis`` to the mesh axis name and build the
    # module with LOCAL head/ff counts (num_heads / tp, d_ff / tp, plus
    # ``override_head_dim`` to keep head_dim at its global value). The
    # modules then bracket each sub-layer with Megatron's f/g conjugate
    # operators (collectives.tp_identity / tp_allreduce) so both values and
    # gradients are exact. Leave None under pjit/GSPMD (TensorParallel
    # strategy), where XLA inserts the collectives itself.
    tp_axis: str | None = None
    override_head_dim: int | None = None
    # Autoregressive serving mode (models/generation.py): attention keeps a
    # (B, max_len, H, hd) KV cache in the flax "cache" collection and the
    # caller passes the write ``index``; a call processes an arbitrary
    # chunk (the whole prompt at prefill, 1 token per decode step) with
    # static shapes throughout — the lax.scan decode loop compiles once.
    # False (default) leaves the training path byte-identical.
    decode: bool = False
    # KV-cache storage dtype (decode mode only). None keeps the cache at
    # ``dtype``; "int8" stores cached_key/cached_value as int8 with
    # per-slot-per-head f32 scales (key_scale/value_scale in the cache
    # collection) and folds dequantization into _decode_attend's QK^T and
    # AV contractions — halving the dominant cache-read term of the
    # bandwidth-bound decode step (ops/decode_attention.quantize_kv).
    kv_dtype: str | None = None
    # Decode-attention implementation (decode mode only):
    # "dense"  — XLA softmax attention over the full fixed-size cache (the
    #            historical path; the only one that keeps the legacy
    #            (B, S, H, hd) cache layout when kv_dtype is None).
    # "pallas" — length-aware streaming kernel (ops/decode_attention.py):
    #            reads only written cache blocks, consumes int8 + scales
    #            natively, blocks resolved from the autotune table. The
    #            cache lives in kernel layout (B, H, S, hd).
    # "auto"   — pallas on TPU, dense elsewhere (the flash/ring TPU-only
    #            convention; CPU tier-1 traces stay byte-identical).
    decode_impl: str = "auto"
    # Paged KV cache (serve/paged_cache.py, decode mode only): both set →
    # the cache collection holds a POOL of ``paged_num_blocks`` blocks of
    # ``paged_block_size`` slots shared across requests instead of a
    # per-request (B, max_len, ...) buffer, and every decode call takes a
    # (B, blocks_per_seq) ``block_tables`` operand plus a per-request
    # (B,) write ``index`` vector. The serve engine is the only caller;
    # the one-shot path (both None) is untouched.
    paged_num_blocks: int | None = None
    paged_block_size: int | None = None
    # Batched multi-LoRA (serve/engine.py, PR 12): ``lora_rank`` set → every
    # projection (attention qkv/proj, MLP up/down) owns a BANK of
    # ``lora_adapters + 1`` low-rank (A, B) delta pairs in the flax
    # "adapters" collection (row 0 is all-zero = the base model), and a call
    # may pass a per-request (B,) int32 ``adapter`` id vector: the deltas
    # are GATHERED by id and applied as one batched einsum per projection,
    # so one compiled step serves many fine-tunes (no per-adapter
    # programs). ``adapter=None`` (and lora_rank=None) keep every
    # historical trace byte-identical; adapter id 0 is bitwise the base
    # model at the token-stream level (a zero delta cannot move an argmax
    # or a gumbel comparison).
    lora_rank: int | None = None
    lora_adapters: int = 0
    # Weight-only quantized serving (decode mode only): "int8" / "int4"
    # store every projection kernel (attention qkv/proj, MLP up/down,
    # lm_head) quantized per-OUTPUT-channel with f32 scales — the param
    # tree carries {qkernel, scale} where the f32 model has {kernel}
    # (ops/quant.quantize_params is the tree transform) — and the dequant
    # is FUSED into each matmul (scale on the output columns, never a
    # materialized f32 kernel copy: the int8-KV discipline applied to the
    # weights). Cuts the params term of decode_hbm_bytes_per_step ~4x
    # (int8) / ~8x (int4-packed, two nibbles per byte). Embeddings and
    # LayerNorms stay full precision (gathered, never streamed). None
    # (default) keeps every historical trace byte-identical.
    weight_dtype: str | None = None
    # AQT-style int8 TRAINING matmuls (core/precision.py PRESETS["int8"]):
    # the projection contractions run int8 x int8 -> int32 with per-tensor
    # dynamic scales and straight-through gradients (ops/quant.
    # int8_ste_dot); params stay f32 masters with the IDENTICAL tree and
    # init draws as the unquantized model (loss-parity pins). lm_head and
    # the classifier keep full-precision accumulation. Training-side only
    # — decode uses ``weight_dtype``.
    quantized_matmuls: bool = False
    # fp8 TRAINING matmuls (core/precision.py PRESETS["fp8"], round 21):
    # the projection contractions cast both operands to e4m3 with
    # per-tensor dynamic scales and accumulate in f32, backward straight-
    # through (ops/quant.fp8_ste_dot) — the same tree-transparent
    # QuantTrainDense shape as quantized_matmuls, so loss-parity pins
    # transfer. Gate with core.precision.require_fp8(): pre-fp8 TPU
    # generations emulate e4m3 at a net loss.
    fp8_matmuls: bool = False
    # Routed MoE FFN (Switch-style top-1) for the flat Transformer — the
    # serve-side sibling of models/moe_lm.py's SwitchLM. ``moe_experts``
    # set → every Block's FFN becomes MoEMLP: a per-token f32 router picks
    # one expert from a bank of (E, d, ff)/(E, ff, d) kernels and the
    # token travels through a fixed-capacity dispatch buffer (static
    # shapes, one-hot algebra — the parallel/expert.py discipline on a
    # single device). ``moe_capacity`` bounds the per-expert buffer for
    # SINGLE-TOKEN (decode) calls: a token past capacity is NOT dropped —
    # its FFN output is zeroed and its per-token overflow flag is sown
    # into the "moe_stats" collection so the serve engine can stall the
    # slot and retry (degrade-to-overflow semantics; serve/engine.py).
    # Multi-token calls (prefill chunks, one-shot, training) widen the
    # buffer to the token count, which provably admits every token.
    # ``moe_capacity=None`` is the always-dropless oracle. None/None
    # (default) keeps every historical trace byte-identical.
    moe_experts: int | None = None
    moe_capacity: int | None = None

    def __post_init__(self):
        if self.attn_impl not in ("auto", "dense", "flash"):
            raise ValueError(
                "attn_impl must be 'auto', 'dense' or 'flash', "
                f"got {self.attn_impl!r}"
            )
        if self.decode_impl not in ("auto", "dense", "pallas"):
            raise ValueError(
                "decode_impl must be 'auto', 'dense' or 'pallas', "
                f"got {self.decode_impl!r}"
            )
        if self.kv_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_dtype must be None or 'int8', got {self.kv_dtype!r}"
            )
        if self.remat_mode not in (None, "none", "attention", "block"):
            raise ValueError(
                "remat_mode must be None, 'none', 'attention' or 'block', "
                f"got {self.remat_mode!r}"
            )
        if (self.paged_num_blocks is None) != (self.paged_block_size is None):
            raise ValueError(
                "paged_num_blocks and paged_block_size must be set together"
            )
        if self.paged_block_size is not None:
            bad = (self.paged_block_size < 1
                   or self.max_len % self.paged_block_size)
            if bad:
                raise ValueError(
                    f"paged_block_size {self.paged_block_size} must divide "
                    f"max_len {self.max_len}"
                )
            if self.paged_num_blocks < 2:
                raise ValueError(
                    "paged_num_blocks must be >= 2 (one is the trash block)"
                )
        if self.lora_rank is not None:
            if self.lora_rank < 1:
                raise ValueError(
                    f"lora_rank must be >= 1, got {self.lora_rank}")
            if self.lora_adapters < 1:
                raise ValueError(
                    "lora_rank set requires lora_adapters >= 1 "
                    f"(got {self.lora_adapters})")
        elif self.lora_adapters:
            raise ValueError("lora_adapters requires lora_rank")
        if self.weight_dtype not in (None, "int8", "int4", "fp8"):
            raise ValueError(
                "weight_dtype must be None, 'int8', 'int4' or 'fp8', "
                f"got {self.weight_dtype!r}"
            )
        if self.weight_dtype is not None:
            # NOT decode-gated: the serving flow attaches weight_dtype to
            # the training-view config and decode_config() flips decode
            # later; the training-side exclusion is quantized_matmuls.
            if self.quantized_matmuls:
                raise ValueError(
                    "weight_dtype (decode-side) and quantized_matmuls "
                    "(training-side) are mutually exclusive"
                )
            if self.fp8_matmuls:
                raise ValueError(
                    "weight_dtype (decode-side) and fp8_matmuls "
                    "(training-side) are mutually exclusive"
                )
            if self.lora_rank is not None:
                raise ValueError(
                    "weight_dtype and lora_rank are mutually exclusive "
                    "(the quantized projections have no f32 kernel for "
                    "the deltas to ride on)"
                )
        if self.moe_capacity is not None and self.moe_experts is None:
            raise ValueError("moe_capacity requires moe_experts")
        if self.moe_experts is not None:
            if self.moe_experts < 2:
                raise ValueError(
                    f"moe_experts must be >= 2, got {self.moe_experts}")
            if self.moe_capacity is not None and self.moe_capacity < 1:
                raise ValueError(
                    f"moe_capacity must be >= 1, got {self.moe_capacity}")
            if self.lora_rank is not None:
                raise ValueError(
                    "moe_experts and lora_rank are mutually exclusive "
                    "(no delta bank wiring on the routed FFN)")
            if self.quantized_matmuls or self.fp8_matmuls:
                raise ValueError(
                    "moe_experts and the training quant levers are "
                    "mutually exclusive (SwitchLM owns MoE training)")
            if self.tp_axis:
                raise ValueError(
                    "moe_experts and tp_axis are mutually exclusive "
                    "(expert parallelism is the MoE sharding story)")
        if self.quantized_matmuls or self.fp8_matmuls:
            lever = ("quantized_matmuls" if self.quantized_matmuls
                     else "fp8_matmuls")
            if self.quantized_matmuls and self.fp8_matmuls:
                raise ValueError(
                    "quantized_matmuls and fp8_matmuls are mutually "
                    "exclusive — one quantized representation per model"
                )
            if self.decode:
                raise ValueError(
                    f"{lever} is the training lever; decode-side "
                    "quantization is weight_dtype"
                )
            if self.lora_rank is not None:
                raise ValueError(
                    f"{lever} and lora_rank are mutually exclusive"
                )

    @property
    def paged(self) -> bool:
        return self.paged_num_blocks is not None

    @property
    def moe(self) -> bool:
        return self.moe_experts is not None

    @property
    def lora(self) -> bool:
        return self.lora_rank is not None

    @property
    def resolved_remat_mode(self) -> str:
        """The effective remat mode: explicit ``remat_mode`` wins, else the
        legacy bool maps True -> "block"."""
        if self.remat_mode is not None:
            return self.remat_mode
        return "block" if self.remat else "none"

    def resolve_decode_impl(self) -> str:
        """Resolve the decode-attention impl: 'auto' is pallas on TPU and
        dense everywhere else (same backend-resolution convention as the
        ring kernel and the KV-cache donation gate)."""
        if self.decode_impl != "auto":
            return self.decode_impl
        import jax

        return "pallas" if jax.default_backend() == "tpu" else "dense"

    def resolve_attn_impl(self, seq_len: int | None = None) -> str:
        """Resolve 'auto' against the actual (trace-time) sequence length;
        falls back to ``max_len`` when none is given (the config-level upper
        bound, used by e.g. the TensorParallel flash guard)."""
        if self.attn_impl != "auto":
            return self.attn_impl
        s = self.max_len if seq_len is None else seq_len
        return "flash" if (self.causal and s >= 1024) else "dense"

    @property
    def resolved_attn_impl(self) -> str:
        return self.resolve_attn_impl()

    @property
    def head_dim(self) -> int:
        if self.override_head_dim is not None:
            return self.override_head_dim
        assert self.d_model % self.num_heads == 0
        return self.d_model // self.num_heads

    def tp_local(self, tp: int, axis: str = "model") -> "TransformerConfig":
        """The per-shard view of this config under ``tp``-way manual tensor
        parallelism: local head/ff counts, global head_dim pinned, f/g
        operators enabled on ``axis``."""
        if self.num_heads % tp or self.d_ff % tp:
            raise ValueError(
                f"num_heads={self.num_heads} and d_ff={self.d_ff} must both "
                f"divide by tp={tp}"
            )
        return dataclasses.replace(
            self,
            num_heads=self.num_heads // tp,
            d_ff=self.d_ff // tp,
            override_head_dim=self.head_dim,
            tp_axis=axis,
        )


def gpt2_124m(**kw) -> TransformerConfig:
    """GPT-2 small (124M): 12L, 768d, 12h, causal. Vocab 50257 padded to
    50304 (multiple of 128) so the vocab dim shards evenly over any model
    axis and tiles the MXU — the standard Megatron-style padding."""
    return TransformerConfig(
        vocab_size=50304, num_layers=12, num_heads=12, d_model=768,
        d_ff=3072, max_len=1024, causal=True, **kw,
    )


def bert_base(num_classes: int = 2, **kw) -> TransformerConfig:
    """BERT-base (110M): 12L, 768d, 12h, bidirectional. Vocab 30522 padded
    to 30592 (multiple of 128) for even vocab sharding / MXU tiling."""
    return TransformerConfig(
        vocab_size=30592, num_layers=12, num_heads=12, d_model=768,
        d_ff=3072, max_len=512, causal=False, num_classes=num_classes, **kw,
    )


def _dense_init(*names):
    return nn.with_logical_partitioning(
        nn.initializers.normal(stddev=0.02), names
    )


# Binding activation constraints: see utils/activation_sharding.py — the
# strategy (parallel/tensor.py) enters ``activation_mesh`` at trace time
# and these modules' ``_constrain`` sites lower to real
# with_sharding_constraint ops; outside that context they stay advisory
# (shard_map paths must not emit wsc).


def _lora_bank(module: nn.Module, cfg: TransformerConfig, name: str,
               d_in: int, d_out: int):
    """The (A, B) delta bank of one projection: ``lora_adapters + 1`` rows
    (row 0 all-zero = the base model), created at init whenever
    ``cfg.lora_rank`` is set so the "adapters" collection has known shapes
    regardless of whether a call passes adapter ids."""
    n_bank = cfg.lora_adapters + 1
    a = module.variable("adapters", f"{name}_A", jnp.zeros,
                        (n_bank, d_in, cfg.lora_rank), cfg.dtype)
    b = module.variable("adapters", f"{name}_B", jnp.zeros,
                        (n_bank, cfg.lora_rank, d_out), cfg.dtype)
    return a, b


def _lora_delta(a, b, x: jax.Array, adapter: jax.Array) -> jax.Array:
    """x @ A[id] @ B[id] with per-request ids — ONE gathered batched
    einsum pair serves every adapter resident in the batch."""
    a_e = jnp.take(a.value, adapter, axis=0)  # (B, d_in, r)
    b_e = jnp.take(b.value, adapter, axis=0)  # (B, r, d_out)
    t = jnp.einsum("bcd,bdr->bcr", x, a_e)
    return jnp.einsum("bcr,bre->bce", t, b_e)


_WQ_BITS = {"int8": 8, "int4": 4, "fp8": "fp8"}


def _prod(dims) -> int:
    out = 1
    for d in dims:
        out *= int(d)
    return out


class WeightQuantDense(nn.Module):
    """Weight-only quantized projection (``cfg.weight_dtype``, decode).

    Declares the serving-side param layout directly — ``qkernel`` (int8,
    int4 packed two-per-byte into uint8, or fp8-e4m3) plus
    per-output-column f32
    ``scale`` — exactly what ``ops.quant.quantize_params`` produces from
    the f32 sibling's ``kernel``, under the SAME module name, so the
    quantized tree drops straight into ``model.apply``. The dequant is
    fused into the matmul (``ops.quant.wq_matmul``): the int cast rides
    the contraction and the scale lands on the output columns, so no
    dequantized kernel copy is ever materialized (pinned by the jaxpr
    walk in tests/test_quant.py). Init values (zeros/ones) are
    placeholders — real weights always arrive via ``quantize_params``.
    """

    features: tuple
    in_axes: int = 1
    bits: Any = 8  # 8 | 4 | "fp8"
    dtype: Dtype = jnp.float32
    use_bias: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        feats = tuple(self.features)
        d_in = _prod(x.shape[-self.in_axes:])
        out_flat = _prod(feats)
        if self.bits == 4:
            if d_in % 2:
                raise ValueError(
                    f"int4 packing needs an even fan-in, got {d_in}")
            rows, store = d_in // 2, jnp.uint8
        elif self.bits == "fp8":
            rows, store = d_in, jnp.float8_e4m3fn
        else:
            rows, store = d_in, jnp.int8
        qkernel = self.param("qkernel", nn.initializers.zeros_init(),
                             (rows, out_flat), store)
        scale = self.param("scale", nn.initializers.ones_init(),
                           (out_flat,), jnp.float32)
        from distributed_tensorflow_guide_tpu.ops import quant

        xf = x.reshape(x.shape[:-self.in_axes] + (d_in,)).astype(self.dtype)
        y = quant.wq_matmul(xf, qkernel, scale, bits=self.bits,
                            dtype=self.dtype)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros_init(),
                              (out_flat,), jnp.float32)
            y = y + bias.astype(self.dtype)
        return y.reshape(x.shape[:-self.in_axes] + feats)


class QuantTrainDense(nn.Module):
    """AQT-style quantized training projection (``cfg.quantized_matmuls``
    for int8, ``cfg.fp8_matmuls`` for e4m3 via ``mode="fp8"``).

    Param-tree transparent: declares the SAME ``kernel`` (and optional
    ``bias``) — names, shapes, f32 param dtype, initializers — as the
    ``nn.Dense``/``nn.DenseGeneral`` it replaces, and flax derives init
    RNG from the param path, so the init draws are bit-identical to the
    unquantized model (the basis of the loss-parity pins). Only the
    contraction changes: ``ops.quant.int8_ste_dot`` (or ``fp8_ste_dot``)
    quantizes both operands per-tensor dynamically each step, accumulates
    int8 x int8 in int32 (e4m3 x e4m3 in f32 for fp8), rescales in f32,
    and backpropagates straight-through.
    """

    features: tuple
    in_axes: int = 1
    dtype: Dtype = jnp.float32
    kernel_init: Any = None
    use_bias: bool = False
    bias_init: Any = None
    mode: str = "int8"  # "int8" | "fp8"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        feats = tuple(self.features)
        in_shape = tuple(x.shape[-self.in_axes:])
        d_in = _prod(in_shape)
        kernel = self.param("kernel", self.kernel_init, in_shape + feats,
                            jnp.float32)
        from distributed_tensorflow_guide_tpu.ops import quant

        dot = quant.fp8_ste_dot if self.mode == "fp8" else quant.int8_ste_dot
        xf = x.reshape(x.shape[:-self.in_axes] + (d_in,)).astype(self.dtype)
        k2d = kernel.astype(self.dtype).reshape(d_in, -1)
        y = dot(xf, k2d).astype(self.dtype)
        if self.use_bias:
            bias = self.param("bias", self.bias_init, feats, jnp.float32)
            y = y + bias.reshape(-1).astype(self.dtype)
        return y.reshape(x.shape[:-self.in_axes] + feats)


class MultiHeadAttention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: jax.Array, index=None, *,
                 block_tables=None, adapter=None) -> jax.Array:  # (B, S, D)
        cfg = self.cfg
        h, hd = cfg.num_heads, cfg.head_dim
        if cfg.tp_axis:  # Megatron f: identity fwd, psum bwd (see tp_axis doc)
            x = tp_identity(x, cfg.tp_axis)
        if cfg.weight_dtype:
            qkv = WeightQuantDense(
                (3, h, hd), in_axes=1, bits=_WQ_BITS[cfg.weight_dtype],
                dtype=cfg.dtype, name="qkv",
            )(x)
        elif cfg.quantized_matmuls or cfg.fp8_matmuls:
            qkv = QuantTrainDense(
                (3, h, hd), in_axes=1, dtype=cfg.dtype,
                kernel_init=_dense_init("embed", "qkv", "heads", "kv"),
                mode="fp8" if cfg.fp8_matmuls else "int8",
                name="qkv",
            )(x)
        else:
            # the historical call, kept verbatim
            qkv = nn.DenseGeneral(
                (3, h, hd),
                axis=-1,
                dtype=cfg.dtype,
                kernel_init=_dense_init("embed", "qkv", "heads", "kv"),
                use_bias=False,
                name="qkv",
            )(x)
        if cfg.lora:
            qkv_a, qkv_b = _lora_bank(self, cfg, "qkv",
                                      cfg.d_model, 3 * h * hd)
            if adapter is not None:
                qkv = qkv + _lora_delta(qkv_a, qkv_b, x,
                                        adapter).reshape(qkv.shape)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (B, S, H, hd)
        # "seq_inner": inside a sub-layer the sequence dim is deliberately
        # a DIFFERENT logical axis from the residual stream's "seq" — under
        # Megatron-SP rules "seq" maps to the model axis (sequence-sharded
        # residual stream) while "seq_inner" stays unsharded, so attention
        # and the MLP see the full sequence on a head/ff shard and GSPMD
        # places the all-gather/reduce-scatter pair at the boundary.
        q = _constrain(q, ("batch", "seq_inner", "heads", "kv"))
        k = _constrain(k, ("batch", "seq_inner", "heads", "kv"))
        v = _constrain(v, ("batch", "seq_inner", "heads", "kv"))

        if cfg.decode and cfg.paged:
            out = self._paged_decode_attend(q, k, v, index, block_tables)
        elif cfg.decode:
            out = self._decode_attend(q, k, v, index)
        elif cfg.resolve_attn_impl(x.shape[1]) == "flash":
            from distributed_tensorflow_guide_tpu.ops.flash_attention import (
                flash_attention,
            )

            out = flash_attention(q, k, v, causal=cfg.causal)
        else:
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(hd).astype(
                cfg.dtype
            )
            if cfg.causal:
                s = x.shape[1]
                mask = jnp.tril(jnp.ones((s, s), bool))
                scores = jnp.where(
                    mask[None, None], scores, jnp.finfo(cfg.dtype).min
                )
            probs = jax.nn.softmax(
                scores.astype(jnp.float32), axis=-1
            ).astype(cfg.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        proj_in = out
        if cfg.weight_dtype:
            out = WeightQuantDense(
                (cfg.d_model,), in_axes=2, bits=_WQ_BITS[cfg.weight_dtype],
                dtype=cfg.dtype, name="proj",
            )(out)
        elif cfg.quantized_matmuls or cfg.fp8_matmuls:
            out = QuantTrainDense(
                (cfg.d_model,), in_axes=2, dtype=cfg.dtype,
                kernel_init=_dense_init("heads", "kv", "embed"),
                mode="fp8" if cfg.fp8_matmuls else "int8",
                name="proj",
            )(out)
        else:
            # the historical call, kept verbatim
            out = nn.DenseGeneral(
                cfg.d_model,
                axis=(-2, -1),
                dtype=cfg.dtype,
                kernel_init=_dense_init("heads", "kv", "embed"),
                use_bias=False,
                name="proj",
            )(out)
        if cfg.lora:
            proj_a, proj_b = _lora_bank(self, cfg, "proj",
                                        h * hd, cfg.d_model)
            if adapter is not None:
                flat = proj_in.reshape(proj_in.shape[:2] + (h * hd,))
                out = out + _lora_delta(proj_a, proj_b, flat, adapter)
        if cfg.tp_axis:  # Megatron g: psum fwd (row-parallel proj), id bwd
            out = tp_allreduce(out, cfg.tp_axis)
        return out

    def _decode_attend(self, q, k, v, index):
        """KV-cache incremental attention over a (B, C, H, hd) chunk.

        Writes the chunk's k/v at cache positions [index, index+C) and
        attends q against the cache under the mask ``key_pos <= q_pos`` —
        which simultaneously enforces causality within the chunk AND hides
        every not-yet-written cache slot (a slot is written only once its
        position has been reached), so one code path serves prefill
        (C = prompt length) and decode (C = 1) with fully static shapes.

        Two bandwidth levers hang off the config (decode is HBM-bound —
        the cache read dominates the step): ``kv_dtype="int8"`` stores the
        cache quantized with per-slot-per-head f32 scales and folds
        dequantization into the two contractions; ``decode_impl`` selects
        the length-aware Pallas streaming kernel
        (ops/decode_attention.py) over the dense full-cache read. The
        default (dense, unquantized) path is byte-identical to the
        historical trace — the tier-1 hermeticity pin in
        tests/test_generation.py. Any non-default lever moves the cache
        to the kernel layout (B, H, max_len, hd) so the Pallas path never
        pays a per-step cache transpose.
        """
        cfg = self.cfg
        if index is None:
            raise ValueError("cfg.decode=True requires the write index")
        B, C, h, hd = q.shape
        quantized = cfg.kv_dtype == "int8"
        impl = cfg.resolve_decode_impl()
        if not quantized and impl == "dense":
            # the historical path, kept verbatim (hermeticity pin)
            ck = self.variable("cache", "cached_key", jnp.zeros,
                               (B, cfg.max_len, h, hd), cfg.dtype)
            cv = self.variable("cache", "cached_value", jnp.zeros,
                               (B, cfg.max_len, h, hd), cfg.dtype)
            ck.value = lax.dynamic_update_slice(ck.value, k,
                                                (0, index, 0, 0))
            cv.value = lax.dynamic_update_slice(cv.value, v,
                                                (0, index, 0, 0))
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, ck.value) / jnp.sqrt(
                hd).astype(cfg.dtype)
            q_pos = index + jnp.arange(C)
            k_pos = jnp.arange(cfg.max_len)
            mask = k_pos[None, :] <= q_pos[:, None]  # (C, max_len)
            scores = jnp.where(mask[None, None], scores,
                               jnp.finfo(cfg.dtype).min)
            probs = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(
                cfg.dtype)
            return jnp.einsum("bhqk,bkhd->bqhd", probs, cv.value)

        from distributed_tensorflow_guide_tpu.ops import (
            decode_attention as DA,
        )

        cache_dtype = jnp.int8 if quantized else cfg.dtype
        ck = self.variable("cache", "cached_key", jnp.zeros,
                           (B, h, cfg.max_len, hd), cache_dtype)
        cv = self.variable("cache", "cached_value", jnp.zeros,
                           (B, h, cfg.max_len, hd), cache_dtype)
        kT = jnp.transpose(k, (0, 2, 1, 3))  # (B, H, C, hd)
        vT = jnp.transpose(v, (0, 2, 1, 3))
        k_scale = v_scale = None
        if quantized:
            ks = self.variable("cache", "key_scale", jnp.zeros,
                               (B, h, 1, cfg.max_len), jnp.float32)
            vs = self.variable("cache", "value_scale", jnp.zeros,
                               (B, h, 1, cfg.max_len), jnp.float32)
            k8, k_sc = DA.quantize_kv(kT)
            v8, v_sc = DA.quantize_kv(vT)
            ck.value = lax.dynamic_update_slice(ck.value, k8,
                                                (0, 0, index, 0))
            cv.value = lax.dynamic_update_slice(cv.value, v8,
                                                (0, 0, index, 0))
            ks.value = lax.dynamic_update_slice(ks.value,
                                                k_sc[:, :, None, :],
                                                (0, 0, 0, index))
            vs.value = lax.dynamic_update_slice(vs.value,
                                                v_sc[:, :, None, :],
                                                (0, 0, 0, index))
            k_scale, v_scale = ks.value, vs.value
        else:
            ck.value = lax.dynamic_update_slice(ck.value, kT,
                                                (0, 0, index, 0))
            cv.value = lax.dynamic_update_slice(cv.value, vT,
                                                (0, 0, index, 0))

        if impl == "pallas":
            blk_k = DA.decode_blk_k_for(b=B, h=h, s=cfg.max_len, d=hd,
                                        dtype=cache_dtype)
            if DA.supported(cfg.max_len, blk_k, C):
                return DA.decode_attention(
                    q, ck.value, cv.value, index,
                    key_scale=k_scale, value_scale=v_scale, blk_k=blk_k)
            if C <= DA.DECODE_MAX_CHUNK:
                # a chunk the kernel SHOULD take fell through (no usable
                # KV block for this max_len) — that is a degradation
                # worth the fallback registry; an over-cap prefill chunk
                # routing dense is the designed split, not a fallback
                from distributed_tensorflow_guide_tpu.ops.flash_attention import (  # noqa: E501
                    _note_fallback,
                )

                _note_fallback(
                    cfg.max_len, hd, C, blk_k, origin="decode_attention",
                    msg=f"decode_attention: max_len {cfg.max_len} has no "
                        f"usable KV block (resolved {blk_k}); falling "
                        "back to the dense full-cache path (slower)")

        # dense attention on the kernel layout, dequant folded into the
        # contractions (the scale is constant along the contracted hd axis
        # for QK^T and along the probability axis for AV, so it factors
        # out exactly — no dequantized cache copy is ever materialized)
        scores = jnp.einsum("bqhd,bhkd->bhqk", q,
                            ck.value.astype(cfg.dtype)) / jnp.sqrt(
            hd).astype(cfg.dtype)
        if quantized:
            scores = scores.astype(jnp.float32) * k_scale  # (B, H, 1, S)
        q_pos = index + jnp.arange(C)
        k_pos = jnp.arange(cfg.max_len)
        mask = k_pos[None, :] <= q_pos[:, None]  # (C, max_len)
        scores = jnp.where(mask[None, None], scores,
                           jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32), -1)
        if quantized:
            probs = probs * v_scale  # fold v dequant into the AV columns
        probs = probs.astype(cfg.dtype)
        return jnp.einsum("bhqk,bhkd->bqhd", probs,
                          cv.value.astype(cfg.dtype))

    def _paged_decode_attend(self, q, k, v, index, block_tables):
        """Paged-pool variant of :meth:`_decode_attend` — same math,
        different cache residency.

        The cache collection holds a POOL of ``cfg.paged_num_blocks``
        fixed-size blocks shared across requests (serve/paged_cache.py);
        ``block_tables`` (B, blocks_per_seq) maps each request's logical
        positions to physical blocks and ``index`` is a PER-REQUEST (B,)
        write-position vector (continuous batching: every slot sits at
        its own length). Writes scatter the chunk through the table;
        reads either stream the pool directly through the Pallas
        block-table kernel (``decode_impl="pallas"``) or gather the
        logical views and run the exact dense math of the non-paged
        branches — the per-row mask zeroes whatever junk the trash block
        and unwritten slots carry, which is what keeps the fallback
        token-identical to the one-shot path on CPU.
        """
        cfg = self.cfg
        if index is None or block_tables is None:
            raise ValueError(
                "paged decode requires the per-request index vector and "
                "the block tables")
        from distributed_tensorflow_guide_tpu.serve.paged_cache import (
            gather_view,
            scatter_chunk,
        )

        B, C, h, hd = q.shape
        N, bs = cfg.paged_num_blocks, cfg.paged_block_size
        idx = jnp.asarray(index)
        if idx.ndim == 0:
            idx = jnp.broadcast_to(idx, (B,))
        quantized = cfg.kv_dtype == "int8"
        impl = cfg.resolve_decode_impl()
        if not quantized and impl == "dense":
            # legacy-layout pool: gather -> the historical dense math
            ck = self.variable("cache", "cached_key", jnp.zeros,
                               (N, bs, h, hd), cfg.dtype)
            cv = self.variable("cache", "cached_value", jnp.zeros,
                               (N, bs, h, hd), cfg.dtype)
            ck.value = scatter_chunk(ck.value, k, block_tables, idx,
                                     block_size=bs, seq_axis=1)
            cv.value = scatter_chunk(cv.value, v, block_tables, idx,
                                     block_size=bs, seq_axis=1)
            keys = gather_view(ck.value, block_tables, seq_axis=1)
            vals = gather_view(cv.value, block_tables, seq_axis=1)
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, keys) / jnp.sqrt(
                hd).astype(cfg.dtype)
            q_pos = idx[:, None] + jnp.arange(C)  # (B, C)
            k_pos = jnp.arange(cfg.max_len)
            mask = k_pos[None, None, :] <= q_pos[:, :, None]  # (B, C, S)
            scores = jnp.where(mask[:, None], scores,
                               jnp.finfo(cfg.dtype).min)
            probs = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(
                cfg.dtype)
            return jnp.einsum("bhqk,bkhd->bqhd", probs, vals)

        from distributed_tensorflow_guide_tpu.ops import (
            decode_attention as DA,
        )

        cache_dtype = jnp.int8 if quantized else cfg.dtype
        ck = self.variable("cache", "cached_key", jnp.zeros,
                           (N, h, bs, hd), cache_dtype)
        cv = self.variable("cache", "cached_value", jnp.zeros,
                           (N, h, bs, hd), cache_dtype)
        kT = jnp.transpose(k, (0, 2, 1, 3))  # (B, H, C, hd)
        vT = jnp.transpose(v, (0, 2, 1, 3))
        ks = vs = None
        if quantized:
            ks = self.variable("cache", "key_scale", jnp.zeros,
                               (N, h, 1, bs), jnp.float32)
            vs = self.variable("cache", "value_scale", jnp.zeros,
                               (N, h, 1, bs), jnp.float32)
            k8, k_sc = DA.quantize_kv(kT)
            v8, v_sc = DA.quantize_kv(vT)
            ck.value = scatter_chunk(ck.value, k8, block_tables, idx,
                                     block_size=bs, seq_axis=2)
            cv.value = scatter_chunk(cv.value, v8, block_tables, idx,
                                     block_size=bs, seq_axis=2)
            ks.value = scatter_chunk(ks.value, k_sc[:, :, None, :],
                                     block_tables, idx,
                                     block_size=bs, seq_axis=3)
            vs.value = scatter_chunk(vs.value, v_sc[:, :, None, :],
                                     block_tables, idx,
                                     block_size=bs, seq_axis=3)
        else:
            ck.value = scatter_chunk(ck.value, kT, block_tables, idx,
                                     block_size=bs, seq_axis=2)
            cv.value = scatter_chunk(cv.value, vT, block_tables, idx,
                                     block_size=bs, seq_axis=2)

        lengths = idx + C  # (B,) live length after the write
        if impl == "pallas":
            blk_k = DA.paged_decode_blk_k_for(
                b=B, h=h, s=cfg.max_len, d=hd, dtype=cache_dtype,
                block_size=bs)
            if DA.paged_supported(cfg.max_len, bs, blk_k, C):
                return DA.paged_decode_attention(
                    q, ck.value, cv.value, block_tables, lengths,
                    key_scale_pool=ks.value if quantized else None,
                    value_scale_pool=vs.value if quantized else None,
                    block_size=bs, blk_k=blk_k)
            if C <= DA.DECODE_MAX_CHUNK:
                from distributed_tensorflow_guide_tpu.ops.flash_attention import (  # noqa: E501
                    _note_fallback,
                )

                _note_fallback(
                    cfg.max_len, hd, C, blk_k,
                    origin="paged_decode_attention",
                    msg=f"paged_decode_attention: block_size {bs} has no "
                        f"usable KV edge (resolved {blk_k}); falling back "
                        "to the gathered dense path (slower)")

        # dense gather fallback on the kernel layout: identical math to
        # the non-paged kernel-layout branch, per-request mask rows
        keys = gather_view(ck.value, block_tables, seq_axis=2)
        vals = gather_view(cv.value, block_tables, seq_axis=2)
        scores = jnp.einsum("bqhd,bhkd->bhqk", q,
                            keys.astype(cfg.dtype)) / jnp.sqrt(
            hd).astype(cfg.dtype)
        if quantized:
            k_scale = gather_view(ks.value, block_tables, seq_axis=3)
            v_scale = gather_view(vs.value, block_tables, seq_axis=3)
            scores = scores.astype(jnp.float32) * k_scale  # (B, H, 1, S)
        q_pos = idx[:, None] + jnp.arange(C)  # (B, C)
        k_pos = jnp.arange(cfg.max_len)
        mask = k_pos[None, None, :] <= q_pos[:, :, None]  # (B, C, S)
        scores = jnp.where(mask[:, None], scores,
                           jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32), -1)
        if quantized:
            probs = probs * v_scale
        probs = probs.astype(cfg.dtype)
        return jnp.einsum("bhqk,bhkd->bqhd", probs,
                          vals.astype(cfg.dtype))


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: jax.Array, *, adapter=None) -> jax.Array:
        cfg = self.cfg
        if cfg.tp_axis:  # Megatron f
            x = tp_identity(x, cfg.tp_axis)
        if cfg.weight_dtype:
            y = WeightQuantDense(
                (cfg.d_ff,), in_axes=1, bits=_WQ_BITS[cfg.weight_dtype],
                dtype=cfg.dtype, use_bias=True, name="up",
            )(x)
        elif cfg.quantized_matmuls or cfg.fp8_matmuls:
            y = QuantTrainDense(
                (cfg.d_ff,), in_axes=1, dtype=cfg.dtype,
                kernel_init=_dense_init("embed", "mlp"),
                use_bias=True,
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), ("mlp",)
                ),
                mode="fp8" if cfg.fp8_matmuls else "int8",
                name="up",
            )(x)
        else:
            # the historical call, kept verbatim
            y = nn.Dense(
                cfg.d_ff,
                dtype=cfg.dtype,
                kernel_init=_dense_init("embed", "mlp"),
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), ("mlp",)
                ),
                name="up",
            )(x)
        if cfg.lora:
            up_a, up_b = _lora_bank(self, cfg, "up", cfg.d_model, cfg.d_ff)
            if adapter is not None:
                y = y + _lora_delta(up_a, up_b, x, adapter)
        y = nn.gelu(y)
        y = _constrain(y, ("batch", "seq_inner", "mlp"))
        down_in = y
        if cfg.weight_dtype:
            y = WeightQuantDense(
                (cfg.d_model,), in_axes=1, bits=_WQ_BITS[cfg.weight_dtype],
                dtype=cfg.dtype, name="down",
            )(y)
        elif cfg.quantized_matmuls or cfg.fp8_matmuls:
            y = QuantTrainDense(
                (cfg.d_model,), in_axes=1, dtype=cfg.dtype,
                kernel_init=_dense_init("mlp", "embed"),
                mode="fp8" if cfg.fp8_matmuls else "int8",
                name="down",
            )(y)
        else:
            # the historical call, kept verbatim
            y = nn.Dense(
                cfg.d_model,
                dtype=cfg.dtype,
                kernel_init=_dense_init("mlp", "embed"),
                use_bias=False,
                name="down",
            )(y)
        if cfg.lora:
            down_a, down_b = _lora_bank(self, cfg, "down",
                                        cfg.d_ff, cfg.d_model)
            if adapter is not None:
                y = y + _lora_delta(down_a, down_b, down_in, adapter)
        if cfg.tp_axis:  # Megatron g (row-parallel down-projection)
            y = tp_allreduce(y, cfg.tp_axis)
        return y


class _ExpertBank(nn.Module):
    """The f32 per-expert kernel stack of one MoE projection: a single
    ``kernel`` param of shape (E, d_in, d_out) under this module's name —
    the exact ``{name: {kernel}}`` layout ``ops.quant.quantize_params``
    rewrites per expert (``WQ_BANKS``)."""

    shape: tuple
    names: tuple

    @nn.compact
    def __call__(self):
        return self.param("kernel", _dense_init(*self.names), self.shape,
                          jnp.float32)


class _WeightQuantBank(nn.Module):
    """Weight-only quantized sibling of :class:`_ExpertBank`
    (``cfg.weight_dtype`` on the expert banks): declares ``qkernel``
    (E, d_in[, /2], d_out) at the storage dtype plus per-expert
    per-output-column ``scale`` (E, d_out) f32 — exactly what
    ``quantize_params`` produces from the f32 bank under the SAME module
    name. The dequant is fused after the expert gather
    (``ops.quant.wq_bank_matmul``); init values are placeholders."""

    shape: tuple  # logical (E, d_in, d_out)
    bits: Any = 8

    @nn.compact
    def __call__(self):
        e, d_in, d_out = self.shape
        if self.bits == 4:
            if d_in % 2:
                raise ValueError(
                    f"int4 packing needs an even fan-in, got {d_in}")
            rows, store = d_in // 2, jnp.uint8
        elif self.bits == "fp8":
            rows, store = d_in, jnp.float8_e4m3fn
        else:
            rows, store = d_in, jnp.int8
        qkernel = self.param("qkernel", nn.initializers.zeros_init(),
                             (e, rows, d_out), store)
        scale = self.param("scale", nn.initializers.ones_init(),
                           (e, d_out), jnp.float32)
        return qkernel, scale


class MoEMLP(nn.Module):
    """Routed top-1 MoE FFN (``cfg.moe_experts``) — the MoE sibling of
    :class:`MLP`, single-device (the serve engine's view; EP sharding is
    models/moe_lm.py's story).

    The parallel/expert.py dispatch discipline without the mesh: a f32
    router picks one expert per token, tokens are copied into a
    fixed-capacity (E, C, d) buffer by one-hot einsum (static shapes,
    MXU-friendly batched expert contraction), and the combine gathers the
    gated outputs back. ``C = cfg.moe_capacity`` for single-token
    (decode) calls; multi-token calls (prefill chunks, one-shot oracle,
    ``moe_capacity=None``) widen ``C`` to the token count, which provably
    admits every token (top-1: an expert can receive at most T rows).

    A token past capacity is never dropped silently OR routed elsewhere:
    its dispatch row is zero (the FFN contributes nothing) and its
    overflow flag is sown into the ``moe_stats`` collection —
    ``serve/engine.py`` discards the slot's sampled token and retries the
    SAME token next tick, so every emitted token was computed by its true
    expert (degrade-to-overflow semantics). Dispatch fills in token order
    (cumsum), so the lowest-indexed contending slot always wins a
    capacity seat and at least one slot advances every tick.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: jax.Array, *, moe_mask=None) -> jax.Array:
        cfg = self.cfg
        e = cfg.moe_experts
        b, s, d = x.shape
        t = b * s
        xt = x.reshape(t, d)
        if cfg.moe_capacity is None or s > 1:
            capacity = t
        else:
            capacity = cfg.moe_capacity

        # router always in f32: routing decisions are precision-sensitive
        # (the parallel/expert.py rule); name "router" is NOT in
        # WQ_PROJECTIONS, so quantize_params leaves it full precision
        logits = nn.Dense(
            e, dtype=jnp.float32,
            kernel_init=_dense_init("embed", "expert"),
            use_bias=False, name="router",
        )(xt.astype(jnp.float32))
        gates = jax.nn.softmax(logits, axis=-1)

        # top-1 fixed-capacity dispatch, entirely one-hot algebra: exact
        # row copies in, exact gated gathers out — zeros added everywhere
        # else, so the per-token value is independent of C (the basis of
        # the engine-vs-oracle bitwise pin)
        idx = jnp.argmax(gates, axis=1)                       # (T,)
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)
        if moe_mask is not None:
            # serve-engine padding mask: idle decode slots / prefill pad
            # rows route NOWHERE — they consume no capacity (an idle slot
            # must never starve a live one) and contribute nothing to the
            # load/overflow census. Masking cannot change a live token's
            # value: it only ever frees capacity seats, and a row's dot
            # is independent of its buffer position.
            onehot = onehot * moe_mask.reshape(t).astype(
                jnp.float32)[:, None]
        pos = jnp.cumsum(onehot, axis=0) - onehot             # (T, E)
        pos_i = pos.astype(jnp.int32)
        keep = onehot * (pos_i < capacity)
        dispatch = keep[:, :, None] * jax.nn.one_hot(
            pos_i, capacity, dtype=jnp.float32)               # (T, E, C)
        gate_val = jnp.sum(gates * onehot, axis=1)            # (T,)
        combine = dispatch * gate_val[:, None, None]

        # per-expert load / overflow census for the obs plane; sow is a
        # no-op unless the caller passes mutable=["moe_stats"] (the serve
        # step fns do; training and the one-shot oracle don't)
        dropped = onehot - keep
        self.sow("moe_stats", "load", jnp.sum(keep, axis=0))
        self.sow("moe_stats", "overflow", jnp.sum(dropped, axis=0))
        self.sow("moe_stats", "overflow_tok", jnp.sum(dropped, axis=1))

        xb = jnp.einsum("tec,td->ecd", dispatch.astype(cfg.dtype),
                        xt.astype(cfg.dtype))
        shape_in = (e, cfg.d_model, cfg.d_ff)
        shape_out = (e, cfg.d_ff, cfg.d_model)
        from distributed_tensorflow_guide_tpu.ops import quant

        if cfg.weight_dtype:
            bits = _WQ_BITS[cfg.weight_dtype]
            q_in, s_in = _WeightQuantBank(shape_in, bits=bits,
                                          name="w_in")()
            q_out, s_out = _WeightQuantBank(shape_out, bits=bits,
                                            name="w_out")()
            h = nn.gelu(quant.wq_bank_matmul(xb, q_in, s_in, bits=bits,
                                             dtype=cfg.dtype))
            out = quant.wq_bank_matmul(h, q_out, s_out, bits=bits,
                                       dtype=cfg.dtype)
        else:
            w_in = _ExpertBank(shape_in, ("expert", "embed", "mlp"),
                               name="w_in")()
            w_out = _ExpertBank(shape_out, ("expert", "mlp", "embed"),
                                name="w_out")()
            h = nn.gelu(jnp.einsum("ecd,edf->ecf", xb,
                                   w_in.astype(cfg.dtype)))
            out = jnp.einsum("ecf,efd->ecd", h, w_out.astype(cfg.dtype))
        y = jnp.einsum("tec,ecd->td", combine.astype(cfg.dtype), out)
        return y.reshape(b, s, d).astype(x.dtype)


class Block(nn.Module):
    """Pre-LN transformer block: x + attn(LN(x)); x + mlp(LN(x))."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: jax.Array, index=None, *,
                 block_tables=None, adapter=None,
                 moe_mask=None) -> jax.Array:
        cfg = self.cfg
        # Attention-only selective remat (core/precision.py): checkpoint the
        # attention sub-layer here so EVERY consumer — the flat Transformer,
        # all four pipeline schedules — gets the same HBM/FLOP trade without
        # per-schedule wiring. nn.remat preserves the "attn" param path, so
        # the layout is identical across modes. prevent_cse=False as in the
        # block-level sites (scan bodies need no CSE barrier).
        attn_cls = MultiHeadAttention
        if cfg.resolved_remat_mode == "attention":
            attn_cls = nn.remat(MultiHeadAttention, prevent_cse=False)
        attn = attn_cls(cfg, name="attn")
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln1")(x)
        if block_tables is None and adapter is None:
            # the historical call, kept verbatim
            x = x + attn(h, index)
        elif adapter is None:
            x = x + attn(h, index, block_tables=block_tables)
        else:
            x = x + attn(h, index, block_tables=block_tables,
                         adapter=adapter)
        mlp = (MoEMLP(cfg, name="mlp") if cfg.moe
               else MLP(cfg, name="mlp"))
        h2 = nn.LayerNorm(dtype=cfg.dtype, name="ln2")(x)
        if moe_mask is not None:
            x = x + mlp(h2, moe_mask=moe_mask)
        elif adapter is None:  # the historical call, kept verbatim
            x = x + mlp(h2)
        else:
            x = x + mlp(h2, adapter=adapter)
        return _constrain(x, ("batch", "seq", "embed"))


class Transformer(nn.Module):
    """Token-in, logits-out. ``cfg.num_classes`` set → [CLS]-pooled
    classification logits (BERT/GLUE); otherwise per-token LM logits."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens: jax.Array, index=None, *,
                 block_tables=None, adapter=None, moe_mask=None,
                 return_hidden: bool = False) -> jax.Array:
        # tokens (B, S) int32; ``index`` only in cfg.decode mode: the
        # absolute position of tokens[:, 0] (prefill passes 0, the decode
        # loop passes the running length). ``return_hidden`` stops after the
        # final LayerNorm and returns the (B, S, D) hidden states WITHOUT
        # applying the LM head — the entry point of the fused
        # cross-entropy loss path (ops/fused_ce.py), which must never see
        # full-vocab logits. Param layout is unchanged (init runs the
        # default call, so lm_head still materializes).
        cfg = self.cfg
        if cfg.decode and index is None:
            raise ValueError("cfg.decode=True requires the position index")
        x = nn.Embed(
            cfg.vocab_size,
            cfg.d_model,
            dtype=cfg.dtype,
            embedding_init=_dense_init("vocab", "embed"),
            name="tok_emb",
        )(tokens)
        positions = jnp.arange(tokens.shape[1])[None, :]
        if cfg.decode:
            # the serve engine passes a PER-REQUEST (B,) index vector
            # (continuous batching: each slot sits at its own length);
            # the scalar one-shot line stays verbatim (hermeticity pin)
            if getattr(index, "ndim", 0):
                positions = positions + index[:, None]
            else:
                positions = positions + index
        pos = nn.Embed(
            cfg.max_len,
            cfg.d_model,
            dtype=cfg.dtype,
            embedding_init=_dense_init("seq", "embed"),
            name="pos_emb",
        )(positions)
        x = x + pos
        x = _constrain(x, ("batch", "seq", "embed"))

        block = Block
        if cfg.resolved_remat_mode == "block":
            block = nn.remat(Block, prevent_cse=False)
        for i in range(cfg.num_layers):
            if moe_mask is not None:
                x = block(cfg, name=f"block_{i}")(
                    x, index, block_tables=block_tables,
                    moe_mask=moe_mask)
            elif block_tables is None and adapter is None:
                # the historical call, kept verbatim
                x = block(cfg, name=f"block_{i}")(x, index)
            elif adapter is None:
                x = block(cfg, name=f"block_{i}")(
                    x, index, block_tables=block_tables)
            else:
                x = block(cfg, name=f"block_{i}")(
                    x, index, block_tables=block_tables, adapter=adapter)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        if return_hidden:
            return x

        if cfg.num_classes is not None:
            cls = x[:, 0]  # [CLS] pooling
            return nn.Dense(
                cfg.num_classes, dtype=jnp.float32, name="classifier"
            )(cls)
        if cfg.weight_dtype:
            # quantized head: logits still f32 (the scale multiply IS the
            # f32 promotion); quantized_matmuls deliberately leaves the
            # head at full precision (accumulation/loss contract)
            logits = WeightQuantDense(
                (cfg.vocab_size,), in_axes=1,
                bits=_WQ_BITS[cfg.weight_dtype],
                dtype=jnp.float32, name="lm_head",
            )(x)
        else:
            # the historical call, kept verbatim
            logits = nn.Dense(
                cfg.vocab_size,
                dtype=jnp.float32,
                use_bias=False,
                kernel_init=_dense_init("embed", "vocab"),
                name="lm_head",
            )(x)
        return logits


def make_lm_loss_fn(model: Transformer, *, fused_ce="auto",
                    ce_chunk: int | None = None):
    """Next-token LM loss: ``(params, batch{tokens}) -> (loss, metrics)``.

    ``fused_ce`` ("auto"|True|False, resolved by
    ``ops.fused_ce.resolve_fused_ce``) routes the head through the chunked
    fused cross-entropy: the trunk stops at the final LayerNorm
    (``return_hidden``) and loss + grad-of-logits run per vocab chunk, so
    no ``(B, S, V)`` tensor is ever live — the HBM diet for every DP/FSDP
    LM call site. The naive path is byte-identical to the historical one.
    """
    from distributed_tensorflow_guide_tpu.ops.fused_ce import (
        fused_next_token_loss,
        resolve_fused_ce,
    )

    use_fused = resolve_fused_ce(fused_ce, vocab_size=model.cfg.vocab_size)

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        if use_fused:
            hidden = model.apply({"params": params}, tokens,
                                 return_hidden=True)
            # params may carry flax partitioning boxes (logical-axis
            # metadata); the kernel itself is the boxed value
            kernel = nn.meta.unbox(params["lm_head"]["kernel"])
            loss = fused_next_token_loss(hidden, kernel, tokens,
                                         chunk=ce_chunk)
            return loss, {"perplexity": jnp.exp(loss)}
        logits = model.apply({"params": params}, tokens)  # (B, S, V)
        targets = tokens[:, 1:]
        logp = jax.nn.log_softmax(logits[:, :-1])
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        loss = -jnp.mean(ll)
        return loss, {"perplexity": jnp.exp(loss)}

    return loss_fn


def make_cls_loss_fn(model: Transformer):
    """Sequence classification (GLUE-style): batch {tokens, label}."""

    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["tokens"])
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(
            jnp.take_along_axis(logp, batch["label"][:, None], axis=1)
        )
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return loss, {"accuracy": acc}

    return loss_fn
