"""Switch-Transformer LM — the MoE model family, wired end to end.

``parallel/expert.py`` provides the EP machinery (static-shape top-k
dispatch, dual ``all_to_all`` token exchange, Switch aux losses) as a
standalone layer; this module is the model that USES it: a causal LM whose
every block replaces the dense FFN with the routed MoE FFN (Switch
Transformer, Fedus et al. 2021), trained over a ``data × expert`` mesh.

No reference equivalent (the guide predates MoE; SURVEY.md §2c lists EP as
a stretch goal). Structure mirrors :class:`~..parallel.pipeline.PipelinedLM`:
a strategy-owning class whose flax submodules (embedder, attention blocks,
head) carry replicated params while the expert stacks are raw arrays
sharded over the ``expert`` axis — tokens travel, parameters stay.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

import distributed_tensorflow_guide_tpu.collectives as cc
from distributed_tensorflow_guide_tpu.core.compat import shard_map
from distributed_tensorflow_guide_tpu.core.mesh import axis_sizes
from distributed_tensorflow_guide_tpu.models.transformer import (
    MultiHeadAttention,
    TransformerConfig,
)
from distributed_tensorflow_guide_tpu.parallel.expert import (
    MoEConfig,
    init_moe_params,
    moe_ffn,
)
from distributed_tensorflow_guide_tpu.utils.spec_utils import (
    assign_by_shape,
    expand_prefix,
)


class _AttnBlock(nn.Module):
    """Pre-LN attention half of a block: x + attn(LN(x)). The FFN half is
    the routed MoE layer, applied outside flax."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        return x + MultiHeadAttention(self.cfg, name="attn")(
            nn.LayerNorm(dtype=self.cfg.dtype, name="ln1")(x)
        )


class _Embedder(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                     name="tok_emb")(tokens)
        pos = nn.Embed(cfg.max_len, cfg.d_model, dtype=cfg.dtype,
                       name="pos_emb")(jnp.arange(tokens.shape[1])[None, :])
        return x + pos


class _Head(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=self.cfg.dtype, name="ln_f")(x)
        return nn.Dense(self.cfg.vocab_size, dtype=jnp.float32,
                        use_bias=False, name="lm_head")(x)


class SwitchLM:
    """Causal Switch-MoE LM over the ``data × expert`` mesh axes.

    Batch rows are sharded jointly over both axes (every device in the
    grid holds a distinct slice); expert stacks are sharded over
    ``expert``; everything else is replicated. Aux losses (load balance +
    router z) are added to the LM loss with ``aux_weight``.
    """

    def __init__(self, mesh: Mesh, cfg: TransformerConfig,
                 num_experts: int, *, top_k: int = 1,
                 capacity_factor: float = 2.0, router: str = "switch",
                 aux_weight: float = 1e-2,
                 fused_ce="auto", ce_chunk: int | None = None,
                 precision=None):
        if precision is not None:
            from distributed_tensorflow_guide_tpu.core import (
                precision as precision_mod,
            )

            cfg = precision_mod.resolve(precision).apply_to_transformer(cfg)
        sizes = axis_sizes(mesh)
        if num_experts % sizes["expert"]:
            raise ValueError(
                f"num_experts {num_experts} not divisible by expert axis "
                f"size {sizes['expert']}"
            )
        self.mesh = mesh
        self.cfg = cfg
        self.n_data = sizes["data"]
        self.n_expert = sizes["expert"]
        self.aux_weight = aux_weight
        self.moe_cfg = MoEConfig(
            d_model=cfg.d_model, d_ff=cfg.d_ff, num_experts=num_experts,
            top_k=top_k, capacity_factor=capacity_factor, router=router,
            dtype=cfg.dtype,
        )
        self.embedder = _Embedder(cfg)
        self.attn_block = _AttnBlock(cfg)
        self.ln2 = nn.LayerNorm(dtype=cfg.dtype)
        self.head = _Head(cfg)
        # chunked fused CE (ops/fused_ce.py): loss + grad-of-logits per
        # vocab chunk, no (B, S, V) logits live — same knob/resolution as
        # PipelinedLM; the raw LN applies ln_f with explicit params on the
        # fused path (the _Head module would materialize full logits)
        from distributed_tensorflow_guide_tpu.ops.fused_ce import (
            resolve_fused_ce,
        )

        self.fused_ce = resolve_fused_ce(fused_ce,
                                         vocab_size=cfg.vocab_size)
        self.ce_chunk = ce_chunk
        self._head_ln = nn.LayerNorm(dtype=cfg.dtype)

    # -- params ---------------------------------------------------------------
    def init_params(self, rng) -> dict:
        cfg = self.cfg
        r_emb, r_attn, r_ln, r_moe, r_head = jax.random.split(rng, 5)
        dummy_tok = jnp.zeros((1, cfg.max_len), jnp.int32)
        dummy_x = jnp.zeros((1, cfg.max_len, cfg.d_model), cfg.dtype)

        attn = jax.vmap(
            lambda k: self.attn_block.init(k, dummy_x)["params"]
        )(jax.random.split(r_attn, cfg.num_layers))
        ln2 = jax.vmap(
            lambda k: self.ln2.init(k, dummy_x)["params"]
        )(jax.random.split(r_ln, cfg.num_layers))
        moe = jax.vmap(
            lambda k: init_moe_params(self.moe_cfg, k)
        )(jax.random.split(r_moe, cfg.num_layers))
        params = {
            "embed": self.embedder.init(r_emb, dummy_tok)["params"],
            "attn": attn,
            "ln2": ln2,
            "moe": moe,
            "head": self.head.init(r_head, dummy_x)["params"],
        }
        return jax.device_put(params, self.param_shardings())

    def param_specs(self) -> dict:
        return {
            "embed": P(), "attn": P(), "ln2": P(),
            "moe": {
                "router": P(),
                # (L, E, d, ff): expert dim sharded over the expert axis
                "w_in": P(None, "expert"),
                "w_out": P(None, "expert"),
            },
            "head": P(),
        }

    def param_shardings(self):
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), self.param_specs(),
            is_leaf=lambda x: isinstance(x, P),
        )

    # -- forward --------------------------------------------------------------
    def _forward(self, params, tokens, *, return_hidden: bool = False):
        """Per-device forward: tokens (B_local, S) -> (logits, aux) — or
        (pre-head hidden states, aux) with ``return_hidden`` (the fused-CE
        entry point, which must never see full-vocab logits)."""
        cfg = self.cfg
        x = self.embedder.apply({"params": params["embed"]}, tokens)
        b, s, d = x.shape

        def layer(h, lp):
            h = self.attn_block.apply({"params": lp["attn"]}, h)
            pre = self.ln2.apply({"params": lp["ln2"]}, h)
            y, aux = moe_ffn(lp["moe"], pre.reshape(b * s, d), self.moe_cfg)
            return h + y.reshape(b, s, d), aux

        x, auxs = lax.scan(
            layer, x, {"attn": params["attn"], "ln2": params["ln2"],
                       "moe": params["moe"]}
        )
        aux = jax.tree.map(jnp.mean, auxs)  # mean over layers
        if return_hidden:
            return x, aux
        logits = self.head.apply({"params": params["head"]}, x)
        return logits, aux

    def _local_loss(self, params, tokens):
        """Global-mean LM loss + aux, computed from this device's shard.

        Both paths produce the identical (sum-of-NLL, count) pair so the
        global mean stays the same psum/psum assembly; the fused path just
        never materializes the (B, S, V) logits it sums over.
        """
        n = jnp.array(tokens.shape[0] * (tokens.shape[1] - 1), jnp.float32)
        if self.fused_ce:
            from distributed_tensorflow_guide_tpu.ops.fused_ce import (
                fused_next_token_loss,
            )

            x, aux = self._forward(params, tokens, return_hidden=True)
            xh = self._head_ln.apply(
                {"params": params["head"]["ln_f"]}, x)
            se = fused_next_token_loss(
                xh, params["head"]["lm_head"]["kernel"], tokens,
                chunk=self.ce_chunk, reduction="sum")
        else:
            logits, aux = self._forward(params, tokens)
            logp = jax.nn.log_softmax(logits[:, :-1])
            ll = jnp.take_along_axis(
                logp, tokens[:, 1:][..., None], axis=-1
            )[..., 0]
            se = -jnp.sum(ll)
        axes = self.moe_cfg.token_axes
        lm = cc.psum(se, axes) / cc.psum(n, axes)
        loss = lm + self.aux_weight * (aux["load_balance"] + aux["z_loss"])
        return loss, {"lm_loss": lm, **aux}

    # -- compiled step --------------------------------------------------------
    def opt_state_specs(self, tx: optax.GradientTransformation, params):
        """Optimizer moments inherit their param's spec (matched by
        shape+dtype); scalars/counts replicate."""
        return assign_by_shape(
            params, expand_prefix(self.param_specs(), params),
            jax.eval_shape(tx.init, params), P(),
        )

    def make_train_step(self, tx: optax.GradientTransformation, params,
                        *, donate: bool = True):
        """``(opt_state, params, tokens (B, S)) -> (opt_state, params,
        metrics)``; B divisible by n_data * n_expert."""
        specs = self.param_specs()
        opt_specs = self.opt_state_specs(tx, params)
        axes = self.moe_cfg.token_axes

        def sm_step(opt_state, params, tokens):
            (loss, mets), grads = jax.value_and_grad(
                self._local_loss, has_aux=True
            )(params, tokens)
            # loss is the GLOBAL mean -> per-device grads are partial
            # contributions. Replicated leaves (embed/attn/ln/head/router):
            # psum over both token axes. Expert-sharded stacks: the expert
            # axis contributions already arrived through the backward
            # all_to_all, so psum over data only.
            grads = {
                "embed": cc.psum(grads["embed"], axes),
                "attn": cc.psum(grads["attn"], axes),
                "ln2": cc.psum(grads["ln2"], axes),
                "moe": {
                    "router": cc.psum(grads["moe"]["router"], axes),
                    "w_in": cc.psum(grads["moe"]["w_in"], "data"),
                    "w_out": cc.psum(grads["moe"]["w_out"], "data"),
                },
                "head": cc.psum(grads["head"], axes),
            }
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return opt_state, params, {"loss": loss, **mets}

        sharded = shard_map(
            sm_step,
            mesh=self.mesh,
            in_specs=(opt_specs, specs, P(self.moe_cfg.token_axes)),
            out_specs=(opt_specs, specs, P()),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=(0, 1) if donate else ())

    def init_opt_state(self, tx, params):
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            self.opt_state_specs(tx, params),
            is_leaf=lambda x: isinstance(x, P),
        )
        with self.mesh:
            return jax.jit(tx.init, out_shardings=shardings)(params)


# ---- program contracts (analysis/) ------------------------------------------


def lint_contracts():
    """Contract for the Switch train step over the data x expert mesh.
    The defining expectation is the all_to_all census: exactly 4 eqns on
    the expert axis — dispatch + return in the forward scan body, their
    transposes in the backward — and NOTHING else crossing expert as raw
    token traffic. The cost pin holds the byte side of the same promise:
    derived all_to_all traffic must equal the comm_bytes_model's
    4·L·B·(e−1)/e with B the fixed-capacity dispatch buffer."""
    from distributed_tensorflow_guide_tpu.analysis.contracts import (
        CostPin,
        CostSpec,
        DonationSpec,
        ProgramContract,
    )
    from distributed_tensorflow_guide_tpu.analysis.cost import closed_forms

    # 8-device fixture: data=2 x expert=4, E=4 experts, top_k=1.
    # t_local = (8 tokens / 8 devices) * max_len 8 = 8 rows per device;
    # capacity = ceil(1 * 8 * 2.0 / 4) = 4 -> dispatch buffer
    # (E=4, C=4, d=16) f32 = 1024 B per device (the return buffer
    # (e_local=1, E*C=16, d=16) is the same 1024 B by construction)
    n_expert, n_layers, top_k, cap_factor = 4, 2, 1, 2.0

    def _make_build(router):
        def _build():
            import jax
            import optax

            from distributed_tensorflow_guide_tpu.analysis.fixtures import (
                tiny_lm_cfg,
            )
            from distributed_tensorflow_guide_tpu.core.mesh import (
                MeshSpec,
                build_mesh,
            )

            cfg = tiny_lm_cfg()
            mesh = build_mesh(MeshSpec(data=2, expert=n_expert))
            lm = SwitchLM(mesh, cfg, num_experts=n_expert, top_k=top_k,
                          capacity_factor=cap_factor, router=router,
                          fused_ce=False)
            params = jax.eval_shape(lm.init_params, jax.random.PRNGKey(0))
            tx = optax.sgd(0.1)
            opt_state = jax.eval_shape(tx.init, params)
            step = lm.make_train_step(tx, params, donate=True)
            tokens = jax.ShapeDtypeStruct((8, 8), "int32")
            return step, (opt_state, params, tokens)

        return _build

    _build = _make_build("switch")

    def _a2a_expect(router="switch"):
        t_local, d_model = 8, 16
        if router == "dropless":
            capacity = t_local
        else:
            capacity = max(1,
                           -(-top_k * t_local * int(cap_factor) // n_expert))
        dispatch_bytes = n_expert * capacity * d_model * 4
        return closed_forms().moe_all_to_all_bytes(
            dispatch_bytes, n_expert, n_layers=n_layers)

    # Same census as the switch row (dropless changes the CAPACITY, not the
    # collective structure), but the byte pin doubles: C = t_local = 8 vs
    # the fixed-capacity 4 — the price of zero drops, stated exactly.
    _moe_census = {
        # dispatch + return per scan body, forward and backward
        "all_to_all[expert]": 4,
        # replicated-leaf grad psums (embed/attn/ln2/router/head
        # trees) + the loss/aux metric pmeans over both token axes
        "psum[data,expert]": 13,
        # the two expert-sharded stacks (w_in, w_out) reduce over
        # data ONLY — their expert contributions arrived through
        # the backward all_to_all; a psum[data,expert] here would
        # double-count across experts
        "psum[data]": 2,
    }

    return [
        ProgramContract(
            name="moe_train_step",
            build=_build,
            policy="f32",
            collectives=dict(_moe_census),
            donation=DonationSpec(argnums=(0, 1)),
            sources=(
                "distributed_tensorflow_guide_tpu.models.moe_lm",
                "distributed_tensorflow_guide_tpu.parallel.expert",
                "distributed_tensorflow_guide_tpu.collectives.collectives",
            ),
            cost=CostSpec(
                pins=(
                    CostPin("collective_bytes[all_to_all[expert]]",
                            _a2a_expect,
                            note="4·L·B·(e-1)/e expert-routing traffic "
                                 "at the fixed-capacity dispatch buffer"),
                ),
                max_peak_live_bytes=262144),
            notes="Switch-MoE step: tokens travel, expert params stay"),
        ProgramContract(
            name="moe_dropless_train_step",
            build=_make_build("dropless"),
            policy="f32",
            collectives=dict(_moe_census),
            donation=DonationSpec(argnums=(0, 1)),
            sources=(
                "distributed_tensorflow_guide_tpu.models.moe_lm",
                "distributed_tensorflow_guide_tpu.parallel.expert",
                "distributed_tensorflow_guide_tpu.collectives.collectives",
            ),
            cost=CostSpec(
                pins=(
                    CostPin("collective_bytes[all_to_all[expert]]",
                            lambda: _a2a_expect("dropless"),
                            note="same 4-crossing census, C widened to "
                                 "t_local — the exact byte price of "
                                 "dropless routing"),
                ),
                max_peak_live_bytes=262144),
            notes="dropless Switch step: capacity = t_local, zero drops"),
    ]
