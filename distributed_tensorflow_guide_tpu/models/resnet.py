"""ResNet — judged config 2: "ResNet-50 ImageNet MultiWorkerMirroredStrategy
(NCCL allreduce → lax.psum)" (BASELINE.md), the north-star throughput model.

Reference context: the guide's multi-GPU tower example (⚠
Multiple-GPUs-Single-Machine/) replicates a model per GPU and averages tower
gradients on the CPU — the hand-rolled MirroredStrategy
(tensorflow/python/distribute/mirrored_strategy.py:200). Here the replication
is SPMD over the data mesh axis and the average is one ICI psum
(parallel/data_parallel.py).

TPU-first choices:
  * NHWC layout, bf16 activations/f32 params (MXU-native mixed precision)
  * BatchNorm stats are *local* per step and cross-replica pmean-ed along
    with gradients (sync running stats — the MultiWorkerMirrored behavior)
  * stride-2 3x3 center conv in the bottleneck (the "v1.5" variant every
    modern benchmark uses)
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

ModuleDef = Any


class FusedBatchNormAct(nn.Module):
    """BatchNorm (+ optional ReLU) as ONE folded normalize-activate pass.

    The round-3 trace put the ResNet-50 backward at 88–96% of HBM
    bandwidth with BN+ReLU re-reading activations the convs just wrote —
    this module is the XLA-level restructure that attacks it:

    * **bf16 batch-stats reduction**: the mean / mean-of-squares reductions
      read the bf16 activations ONCE, with the f32 cast/square fused into
      the reduction (XLA keeps it elementwise-in-registers) — no separate
      upcast copy of the (N, H, W, C) tensor feeds the stats, and the
      squaring stays f32 so the E[x²]−E[x]² identity cannot go negative
      from bf16 rounding.
    * **single fused normalize-activate**: the affine fold
      ``k = scale·rsqrt(var+eps); b = bias − mean·k`` turns
      normalize+scale+shift(+ReLU) into one FMA + max over x — one read,
      one write, and a backward that re-derives everything from the same
      single expression instead of flax's separate subtract/multiply/add
      chain.

    Param/variable layout is IDENTICAL to ``nn.BatchNorm`` (params
    ``scale``/``bias``, batch_stats ``mean``/``var``, same init, same
    running-average update), so fused and plain models share checkpoints
    and the DataParallel cross-replica ``pmean`` of batch_stats is
    unchanged — pinned in tests/test_resnet.py.
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    act: bool = False
    scale_init: Callable = nn.initializers.ones_init()

    @nn.compact
    def __call__(self, x):
        feat = x.shape[-1]
        f32 = jnp.float32
        scale = self.param("scale", self.scale_init, (feat,), f32)
        bias = self.param("bias", nn.initializers.zeros_init(), (feat,), f32)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda s: jnp.zeros(s, f32), (feat,))
        ra_var = self.variable("batch_stats", "var",
                               lambda s: jnp.ones(s, f32), (feat,))
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            axes = tuple(range(x.ndim - 1))
            # reduce the activations AS STORED: the bf16 tensor is read
            # once and the f32 cast/square fuse INTO the reductions (no
            # materialized upcast copy — the traffic diet is the bf16
            # read). The square must happen in f32: squaring in bf16 puts
            # ~0.4% relative error on E[x²], enough to drive the
            # E[x²]−E[x]² identity negative for high-mean/low-variance
            # channels and NaN the rsqrt. The residual clamp guards the
            # same cancellation at f32 precision.
            x32 = x.astype(f32)
            mean = jnp.mean(x32, axes)
            mean2 = jnp.mean(jnp.square(x32), axes)
            var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            if not self.is_initializing():
                ra_mean.value = (self.momentum * ra_mean.value
                                 + (1 - self.momentum) * mean)
                ra_var.value = (self.momentum * ra_var.value
                                + (1 - self.momentum) * var)
        k = (scale * lax.rsqrt(var + self.epsilon)).astype(self.dtype)
        b = (bias - mean * scale * lax.rsqrt(var + self.epsilon)).astype(
            self.dtype)
        y = x.astype(self.dtype) * k + b
        return nn.relu(y) if self.act else y


class BottleneckBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu
    # norm is FusedBatchNormAct: norm+ReLU collapse into its single fused
    # pass wherever the pair occurs (the BN names are pinned to the
    # historical auto-names so both paths share one parameter layout)
    fused_bn: bool = False

    @nn.compact
    def __call__(self, x):
        def norm_act(y, name):
            if self.fused_bn:
                return self.norm(act=True, name=name)(y)
            return self.act(self.norm(name=name)(y))

        residual = x
        y = self.conv(self.filters, (1, 1), use_bias=False)(x)
        y = norm_act(y, "BatchNorm_0")
        y = self.conv(self.filters, (3, 3), self.strides, use_bias=False)(y)
        y = norm_act(y, "BatchNorm_1")
        y = self.conv(self.filters * 4, (1, 1), use_bias=False)(y)
        y = self.norm(scale_init=nn.initializers.zeros_init(),
                      name="BatchNorm_2")(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, use_bias=False,
                name="conv_proj",
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    small_inputs: bool = False  # skip the stride-4 stem for <=64px images
    # Rematerialize each bottleneck block in the backward pass: stores only
    # block-boundary activations, trading conv re-FLOPs (cheap — the step is
    # HBM-bound, docs/performance.md roofline) for resident HBM, to admit
    # larger per-chip batches without spilling. Numerically identical.
    remat: bool = False
    # Fused BN+ReLU path (FusedBatchNormAct): bf16 batch-stats reduction +
    # the normalize-activate pair folded into one FMA/max pass — the A/B
    # knob against the measured backward-conv/BN HBM re-reads (bench.py
    # --fused-bn). Parameter and batch_stats layout is unchanged.
    fused_bn: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, dtype=self.dtype)
        norm_cls = FusedBatchNormAct if self.fused_bn else nn.BatchNorm
        norm = functools.partial(
            norm_cls,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        if self.small_inputs:
            x = conv(self.num_filters, (3, 3), use_bias=False, name="conv_init")(x)
        else:
            x = conv(
                self.num_filters, (7, 7), (2, 2),
                padding=[(3, 3), (3, 3)], use_bias=False, name="conv_init",
            )(x)
        if self.fused_bn:
            x = norm(name="bn_init", act=True)(x)
        else:
            x = norm(name="bn_init")(x)
            x = nn.relu(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        block_cls = nn.remat(BottleneckBlock) if self.remat else BottleneckBlock
        k = 0
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                # explicit name pins the param-tree path to the historical
                # auto-name, so remat=True/False share one parameter layout
                x = block_cls(
                    self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    fused_bn=self.fused_bn,
                    name=f"BottleneckBlock_{k}",
                )(x)
                k += 1
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


ResNet50 = functools.partial(ResNet, stage_sizes=(3, 4, 6, 3))
ResNet18ish = functools.partial(ResNet, stage_sizes=(1, 1, 1, 1))  # test-sized


def _ce_and_accuracy(logits, labels):
    """Softmax cross-entropy + top-1 accuracy — ONE definition shared by
    the train loss and the eval metrics, so they cannot diverge."""
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))
    acc = jnp.mean(jnp.argmax(logits, -1) == labels)
    return loss, acc


def make_loss_fn(model: ResNet, weight_decay: float = 0.0):
    """``(params, model_state, batch) -> (loss, (metrics, new_model_state))``
    for :meth:`DataParallel.make_train_step_with_stats`."""

    def loss_fn(params, model_state, batch):
        logits, new_model_state = model.apply(
            {"params": params, **model_state},
            batch["image"],
            train=True,
            mutable=["batch_stats"],
        )
        loss, acc = _ce_and_accuracy(logits, batch["label"])
        if weight_decay:
            loss = loss + 0.5 * weight_decay * sum(
                jnp.sum(p.astype(jnp.float32) ** 2)
                for p in jax.tree.leaves(params)
                if p.ndim > 1  # skip BN scales/biases
            )
        return loss, ({"accuracy": acc}, new_model_state)

    return loss_fn


def make_metric_fn(model: ResNet):
    """``(params, model_state, batch) -> metrics`` for
    :meth:`DataParallel.make_eval_step_with_stats`: BatchNorm inference
    mode (running stats, not batch stats), nothing written back."""

    def metric_fn(params, model_state, batch):
        logits = model.apply(
            {"params": params, **model_state}, batch["image"], train=False
        )
        loss, acc = _ce_and_accuracy(logits, batch["label"])
        return {"loss": loss, "accuracy": acc}

    return metric_fn
