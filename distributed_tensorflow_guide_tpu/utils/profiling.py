"""Tracing/profiling — the guide has none; the TF runtime it drives ships a
timeline/profiler (the TF wheel bundles ``_pywrap_profiler_plugin.so``; the
reference itself never calls it, SURVEY.md §5 tracing row).

TPU-native: ``jax.profiler`` writes XPlane traces viewable in
TensorBoard/XProf. This module is a thin, dependency-free veneer:

* :func:`trace` — context manager around ``jax.profiler.trace`` (start/stop
  a trace into a logdir).
* :func:`annotate` — host-side span annotation (``jax.profiler.TraceAnnotation``),
  shows up as a named region on the host timeline.
* :func:`step_annotation` — marks one training step so XProf's step-time
  analysis can segment the timeline (``StepTraceAnnotation``).
* :func:`save_memory_profile` — dump a device-memory profile (pprof format).
* :class:`ProfilerHook` — train-loop hook that traces steps
  ``[start_step, end_step)``; the TF sibling is ``tf.train.ProfilerHook``
  (tensorflow/python/training/basic_session_run_hooks.py).
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import time
from pathlib import Path
from typing import Any, Callable, Iterator

import jax

from distributed_tensorflow_guide_tpu.obs import events as obs_events
from distributed_tensorflow_guide_tpu.train.hooks import BaseHook

log = logging.getLogger("dtg.profiling")


# -- dispatch / host-gap accounting ------------------------------------------
#
# The overlap layer's instrument: how many executable dispatches did a run
# issue, and how much host time elapsed BETWEEN them (batch fetch, hook
# work, Python overhead)? Dispatch is async, so host gap is not device
# idleness per se — but it is the only part of the gap the host can cause,
# and it is exactly what multi-step dispatch (fewer, fatter dispatches) and
# device prefetch (puts issued ahead) exist to shrink. Counting it makes
# the win measurable instead of asserted.


@dataclasses.dataclass
class DispatchStats:
    """Counters for a stream of compiled-step dispatches."""

    dispatches: int = 0
    steps: int = 0          # optimizer steps = dispatches * steps_per_call
    host_gap_s: float = 0.0  # host time between consecutive dispatches
    dispatch_s: float = 0.0  # host time inside dispatch calls (enqueue cost)

    def as_dict(self) -> dict:
        out = {
            "dispatches": self.dispatches,
            "opt_steps": self.steps,
            "host_gap_s": round(self.host_gap_s, 4),
            "dispatch_enqueue_s": round(self.dispatch_s, 4),
        }
        if self.dispatches:
            out["host_gap_ms_per_dispatch"] = round(
                1e3 * self.host_gap_s / self.dispatches, 3)
        return out


class DispatchRecorder:
    """Wrap a compiled ``(state, batch) -> (state, metrics)`` step so every
    call updates a :class:`DispatchStats` — composable with any loop that
    drives a step function (TrainLoop keeps its own inline accounting; this
    is the standalone instrument for benches and ad-hoc loops)."""

    def __init__(self, step_fn: Callable[[Any, Any], tuple[Any, Any]],
                 steps_per_call: int = 1,
                 stats: DispatchStats | None = None):
        self.step_fn = step_fn
        self.steps_per_call = steps_per_call
        self.stats = stats if stats is not None else DispatchStats()
        self._last_return: float | None = None

    def __call__(self, state, batch):
        t0 = time.perf_counter()
        if self._last_return is not None:
            self.stats.host_gap_s += t0 - self._last_return
        out = self.step_fn(state, batch)
        self._last_return = time.perf_counter()
        self.stats.dispatch_s += self._last_return - t0
        self.stats.dispatches += 1
        self.stats.steps += self.steps_per_call
        return out


@contextlib.contextmanager
def trace(logdir: str | Path, *, create_perfetto_link: bool = False) -> Iterator[None]:
    """Trace everything inside the block into ``logdir`` (XPlane format).

    View with ``tensorboard --logdir <logdir>`` (profile tab / XProf).
    """
    logdir = str(logdir)
    with jax.profiler.trace(logdir, create_perfetto_link=create_perfetto_link):
        yield
    log.info("profiler trace written to %s", logdir)


def annotate(name: str, **kwargs):
    """Named host-side span; nests. Use around data loading, checkpointing,
    eval — anything host-bound worth seeing on the trace timeline."""
    return jax.profiler.TraceAnnotation(name, **kwargs)


def step_annotation(step: int, name: str = "train"):
    """Mark one step for XProf step-time analysis."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)


def save_memory_profile(path: str | Path) -> None:
    """Dump current device memory usage as a pprof profile."""
    Path(path).parent.mkdir(parents=True, exist_ok=True)
    jax.profiler.save_device_memory_profile(str(path))


class ProfilerHook(BaseHook):
    """Trace steps ``[start_step, end_step)`` of the training loop into
    ``logdir``. Chief-only is NOT enforced: on multi-host, every host traces
    its own devices (XProf merges by host); pass ``chief_only=True`` to
    restrict."""

    def __init__(self, logdir: str | Path, start_step: int = 10,
                 end_step: int = 15, chief_only: bool = False,
                 recorder=None):
        if end_step <= start_step:
            raise ValueError("end_step must be > start_step")
        self.logdir = str(logdir)
        self.start_step = start_step
        self.end_step = end_step
        self.chief_only = chief_only
        self._active = False
        # observability (PR 14): profiler.start/profiler.stop instants
        # in the flight recorder bracket the XPlane trace window
        self.rec = recorder if recorder is not None else obs_events.current()

    def _obs(self, kind: str, step: int | None) -> None:
        if self.rec.enabled:
            self.rec.emit(kind, cat="train", actor="profiler",
                          payload={"logdir": self.logdir, "step": step})

    def _enabled(self) -> bool:
        if not self.chief_only:
            return True
        from distributed_tensorflow_guide_tpu.core.dist import is_chief

        return is_chief()

    def begin(self, loop) -> None:
        # covers start_step == loop's first step (incl. 0) and warm resumes
        # that land inside the window, where the arming after_step never runs
        if self._active:
            # elastic restart reuses hook instances and the crashed attempt
            # never ran end(); JAX allows one active trace, so close it out
            jax.profiler.stop_trace()
            self._active = False
            self._obs("profiler.stop", None)
        first = getattr(loop, "step", 0)
        if self._enabled() and self.start_step <= first < self.end_step:
            jax.profiler.start_trace(self.logdir)
            self._active = True
            self._obs("profiler.start", first)

    def after_step(self, step: int, metrics) -> None:
        # after_step(step) runs once step `step` is done; start the trace
        # after step start_step-1 so it covers [start_step, end_step).
        if not self._enabled():
            return
        if (not self._active and self.start_step <= step + 1 < self.end_step):
            jax.profiler.start_trace(self.logdir)
            self._active = True
            self._obs("profiler.start", step + 1)
        elif self._active and step + 1 >= self.end_step:
            jax.profiler.stop_trace()
            self._active = False
            self._obs("profiler.stop", step + 1)
            log.info("profiler trace for steps [%d, %d) written to %s",
                     self.start_step, self.end_step, self.logdir)

    def end(self, step: int) -> None:
        if self._active:  # loop stopped mid-window
            jax.profiler.stop_trace()
            self._active = False
            self._obs("profiler.stop", step)
