"""Minimal TensorBoard scalar-event writer — no TF dependency.

Reference equivalent: ``SummarySaverHook``
(tensorflow/python/training/basic_session_run_hooks.py:793) writing TF
``Event`` protos that TensorBoard renders. The JAX stack has no bundled
summary writer (flax's needs TF), so this module hand-encodes the two tiny
protos involved and the TFRecord framing around them — ~100 lines, zero deps,
and the output opens in stock TensorBoard.

Wire format (tensorflow/core/util/event.proto, …/framework/summary.proto,
…/lib/io/record_writer):

    record  := len:uint64le  masked_crc32c(len):uint32le
               data:bytes    masked_crc32c(data):uint32le
    Event   := 1: wall_time (double)  2: step (int64)
               3: file_version (string, first record only)  5: Summary
    Summary := 1: repeated Value { 1: tag (string), 2: simple_value (float) }

crc32c is the Castagnoli CRC (not zlib's crc32); masking is TF's
``((crc >> 15) | (crc << 17)) + 0xa282ead8``.
"""

from __future__ import annotations

import socket
import struct
import time
from pathlib import Path
from typing import Mapping

# -- crc32c (Castagnoli, reflected poly 0x82F63B78), table-driven ------------

_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- minimal protobuf encoding ----------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _field(num: int, wire: int) -> bytes:
    return _varint((num << 3) | wire)


def _len_field(num: int, payload: bytes) -> bytes:
    return _field(num, 2) + _varint(len(payload)) + payload


def _scalar_summary(values: Mapping[str, float]) -> bytes:
    out = b""
    for tag, v in values.items():
        val = (_len_field(1, tag.encode()) +
               _field(2, 5) + struct.pack("<f", float(v)))
        out += _len_field(1, val)
    return out


def _event(wall_time: float, step: int, *, file_version: str | None = None,
           summary: bytes | None = None) -> bytes:
    ev = _field(1, 1) + struct.pack("<d", wall_time)
    ev += _field(2, 0) + _varint(step)
    if file_version is not None:
        ev += _len_field(3, file_version.encode())
    if summary is not None:
        ev += _len_field(5, summary)
    return ev


def _record(data: bytes) -> bytes:
    hdr = struct.pack("<Q", len(data))
    return (hdr + struct.pack("<I", _masked_crc(hdr)) +
            data + struct.pack("<I", _masked_crc(data)))


class SummaryWriter:
    """Append scalar events to an ``events.out.tfevents.*`` file in
    ``logdir``; TensorBoard picks it up live."""

    def __init__(self, logdir: str | Path):
        self.logdir = Path(logdir)
        self.logdir.mkdir(parents=True, exist_ok=True)
        name = f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}"
        self._fh = (self.logdir / name).open("ab")
        self._fh.write(_record(_event(time.time(), 0,
                                      file_version="brain.Event:2")))
        self._fh.flush()

    def scalars(self, step: int, values: Mapping[str, float]) -> None:
        ev = _event(time.time(), step, summary=_scalar_summary(values))
        self._fh.write(_record(ev))
        # flush per event: records must survive a crash/SIGKILL (the fault
        # mode runtime/multiprocess injects) and be visible to a live
        # TensorBoard; event volume is low (scalars only)
        self._fh.flush()

    def log_metrics(self, snapshot: Mapping[str, object],
                    step: int) -> None:
        """Write an ``obs.metrics Registry.snapshot()`` as one scalar
        event: plain counters/gauges keep their (labeled) name, histogram
        dicts expand to ``<name>_count`` / ``<name>_sum`` (bucket detail
        stays in the Prometheus exposition — TB scalars can't render it)."""
        scalars: dict[str, float] = {}
        for name, v in snapshot.items():
            if isinstance(v, Mapping):
                scalars[f"{name}_count"] = float(v.get("count", 0))
                scalars[f"{name}_sum"] = float(v.get("sum", 0.0))
            else:
                scalars[name] = float(v)
        if scalars:
            self.scalars(step, scalars)

    def flush(self) -> None:
        self._fh.flush()

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.flush()
            self._fh.close()

    def __enter__(self) -> "SummaryWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_scalars(path: str | Path) -> list[tuple[int, dict[str, float]]]:
    """Decode an event file written by :class:`SummaryWriter` (test helper /
    offline consumer). Returns ``[(step, {tag: value}), ...]`` skipping the
    file_version record. Validates CRCs."""
    raw = Path(path).read_bytes()
    out: list[tuple[int, dict[str, float]]] = []
    off = 0
    while off < len(raw):
        if off + 12 > len(raw):
            break  # truncated tail (crash mid-write) == EOF, like TF's reader
        (ln,) = struct.unpack_from("<Q", raw, off)
        if off + 12 + ln + 4 > len(raw):
            break  # payload or trailing CRC incomplete
        hdr = raw[off:off + 8]
        (hcrc,) = struct.unpack_from("<I", raw, off + 8)
        data = raw[off + 12:off + 12 + ln]
        (dcrc,) = struct.unpack_from("<I", raw, off + 12 + ln)
        if _masked_crc(hdr) != hcrc or _masked_crc(data) != dcrc:
            raise ValueError(f"corrupt record at offset {off}")
        off += 12 + ln + 4
        step, scalars = 0, {}
        i = 0
        while i < len(data):
            key, i = _read_varint(data, i)
            num, wire = key >> 3, key & 7
            if wire == 1:
                i += 8
            elif wire == 0:
                val, i = _read_varint(data, i)
                if num == 2:
                    step = val
            elif wire == 5:
                i += 4
            elif wire == 2:
                ln2, i = _read_varint(data, i)
                payload = data[i:i + ln2]
                i += ln2
                if num == 5:
                    scalars = _decode_summary(payload)
        if scalars:
            out.append((step, scalars))
    return out


def _read_varint(data: bytes, i: int) -> tuple[int, int]:
    shift = n = 0
    while True:
        b = data[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def _decode_summary(data: bytes) -> dict[str, float]:
    out: dict[str, float] = {}
    i = 0
    while i < len(data):
        key, i = _read_varint(data, i)
        if key >> 3 != 1 or key & 7 != 2:
            break
        ln, i = _read_varint(data, i)
        val = data[i:i + ln]
        i += ln
        tag, simple = "", 0.0
        j = 0
        while j < len(val):
            k2, j = _read_varint(val, j)
            num, wire = k2 >> 3, k2 & 7
            if wire == 2:
                ln2, j = _read_varint(val, j)
                if num == 1:
                    tag = val[j:j + ln2].decode()
                j += ln2
            elif wire == 5:
                if num == 2:
                    (simple,) = struct.unpack_from("<f", val, j)
                j += 4
            elif wire == 0:
                _, j = _read_varint(val, j)
            elif wire == 1:
                j += 8
        if tag:
            out[tag] = simple
    return out
