"""Hang detection — deadlines that turn silent stalls into fail-fast errors.

The reference stack's failure mode for a stall is *nothing*: a worker
blocked on a dead PS's gRPC channel sits there until an operator notices
(SURVEY.md §5). The supervised analogue (`runtime/multiprocess.py`) bounds
a whole RUN with a wall-clock timeout, but inside a run a stalled data
iterator or a wedged dispatch still eats the entire budget before anyone
acts. A watchdog converts those into prompt, diagnosable failures: a
background timer thread that, when a guarded section overruns its
deadline, dumps every thread's stack (the diagnosis), then either
interrupts the main thread (recoverable in-process — the loop re-raises
it as :class:`WatchdogTimeout`, which ``run_with_recovery`` treats like
any crash) or exits the process (``action="kill"`` — the crash-only mode
for hard C-level hangs, which the multiprocess supervisor restarts).

Caveat, stated rather than hidden: ``action="interrupt"`` relies on
``_thread.interrupt_main()``, which fires between Python bytecodes — it
reliably breaks Python-level stalls (a loader stuck in a retry loop, a
socket read in small timeouts) but cannot crack a single blocking C call
that never returns. For those, ``action="kill"`` is the honest tool: the
process dies with a distinctive exit code and the stack dump on disk,
and supervision handles the restart.
"""

from __future__ import annotations

import faulthandler
import logging
import os
import sys
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Callable

from distributed_tensorflow_guide_tpu.obs import events as obs_events

log = logging.getLogger("dtg.watchdog")

KILL_EXIT_CODE = 124  # same convention as coreutils `timeout`


class WatchdogTimeout(RuntimeError):
    """A guarded section overran its deadline (fail-fast, recoverable)."""


class DataStallError(RuntimeError):
    """The upstream data iterator exceeded its per-batch deadline."""


@dataclass
class TripInfo:
    tag: str
    deadline_s: float
    waited_s: float


class Watchdog:
    """Arm/disarm deadline guard backed by one daemon thread.

    ``arm(tag, deadline_s)`` starts the clock; ``disarm()`` stops it; an
    overrun *trips* the watchdog: diagnostics (all-thread stacks via
    ``faulthandler``) go to ``diag_path`` (or stderr), then ``action``
    runs — ``"interrupt"`` (default) raises KeyboardInterrupt in the main
    thread, ``"kill"`` exits the process with :data:`KILL_EXIT_CODE`, or
    a callable receives the :class:`TripInfo`. After a trip the guard is
    disarmed until re-armed; ``check()`` raises :class:`WatchdogTimeout`
    if a trip happened (the cooperative half — the caller that survived
    the interrupt converts it into a clean error).
    """

    def __init__(self, *, name: str = "watchdog",
                 diag_path: str | Path | None = None,
                 action: str | Callable[[TripInfo], None] = "interrupt",
                 poll_s: float = 0.02, recorder=None):
        if isinstance(action, str) and action not in ("interrupt", "kill"):
            raise ValueError(f"unknown watchdog action {action!r}")
        self.name = name
        # observability (PR 14): a trip is the canonical black-box
        # moment — _dump crash-dumps the flight-recorder tail alongside
        # the thread stacks (observe-only; the trip itself is unchanged)
        self.rec = recorder if recorder is not None else obs_events.current()
        self.diag_path = Path(diag_path) if diag_path else None
        self.action = action
        self.poll_s = poll_s
        self.tripped: TripInfo | None = None
        self._lock = threading.Lock()
        self._deadline: float | None = None  # monotonic
        self._armed_at: float | None = None
        self._tag = ""
        self._budget = 0.0
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._watch, name=f"{name}-thread", daemon=True
        )
        self._thread.start()

    def arm(self, tag: str, deadline_s: float) -> None:
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        with self._lock:
            now = time.monotonic()
            self.tripped = None  # a new guard starts clean
            self._tag, self._budget = tag, deadline_s
            self._armed_at, self._deadline = now, now + deadline_s

    def disarm(self) -> None:
        with self._lock:
            self._deadline = self._armed_at = None

    def check(self) -> None:
        """Raise the trip (if any) as a clean :class:`WatchdogTimeout`.

        The trip is NOT cleared here (the next ``arm`` clears it): if the
        trip's ``interrupt_main`` lands while the WatchdogTimeout from a
        cooperative ``check`` is already propagating, the caller's
        KeyboardInterrupt handler can still see the trip and re-raise the
        clean error instead of the raw interrupt."""
        info = self.tripped
        if info is not None:
            raise WatchdogTimeout(
                f"{self.name}: '{info.tag}' exceeded its "
                f"{info.deadline_s:g}s deadline (waited {info.waited_s:.2f}s)"
            )

    def close(self) -> None:
        self._closed.set()
        self._thread.join(timeout=5)

    def __enter__(self) -> "Watchdog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- internals ---------------------------------------------------------

    def _watch(self) -> None:
        while not self._closed.wait(self.poll_s):
            with self._lock:
                deadline = self._deadline
                if deadline is None or time.monotonic() < deadline:
                    continue
                info = TripInfo(self._tag, self._budget,
                                time.monotonic() - self._armed_at)
                # one-shot until re-armed: the interrupt/exit is underway.
                # Publishing `tripped` INSIDE the lock matters: arm() also
                # takes the lock to clear it, so a trip can never be
                # half-committed when the main thread moves on to guard
                # the next section (a late publication would blame a
                # healthy section for the previous one's overrun).
                self._deadline = self._armed_at = None
                self.tripped = info
            self._dump(info)
            self._act(info)

    def _dump(self, info: TripInfo) -> None:
        rec = self.rec
        if rec.enabled:
            try:
                rec.crash_dump(
                    "watchdog.trip", cat="watchdog", actor=self.name,
                    payload={"tag": info.tag,
                             "deadline_s": info.deadline_s,
                             "waited_s": info.waited_s},
                    path=rec.crash_dump_path or (
                        f"{self.diag_path}.flightrec.json"
                        if self.diag_path else None))
            except Exception:
                log.exception("%s: flight-recorder dump failed", self.name)
        try:
            if self.diag_path is not None:
                self.diag_path.parent.mkdir(parents=True, exist_ok=True)
                with self.diag_path.open("a") as fh:
                    fh.write(
                        f"=== {self.name} trip: '{info.tag}' exceeded "
                        f"{info.deadline_s:g}s (waited {info.waited_s:.2f}s) "
                        f"===\n"
                    )
                    faulthandler.dump_traceback(file=fh)
            else:
                faulthandler.dump_traceback(file=sys.stderr)
            log.error(
                "%s: '%s' exceeded %gs deadline (waited %.2fs)%s",
                self.name, info.tag, info.deadline_s, info.waited_s,
                f"; stacks -> {self.diag_path}" if self.diag_path else "",
            )
        except Exception:  # diagnostics must never mask the trip itself
            log.exception("%s: diagnostics dump failed", self.name)

    def _act(self, info: TripInfo) -> None:
        if callable(self.action):
            self.action(info)
        elif self.action == "kill":
            # crash-only: flush what we can, exit with a distinctive code
            # the supervisor (runtime/multiprocess.py) reaps and restarts
            sys.stderr.flush()
            os._exit(KILL_EXIT_CODE)
        else:  # "interrupt"
            import _thread

            _thread.interrupt_main()
