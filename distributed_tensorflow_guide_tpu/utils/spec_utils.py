"""Sharding-assignment helpers shared by the parallel strategies."""

from __future__ import annotations

from typing import Any

import jax


def assign_by_shape(ref_tree: Any, ref_assignments: Any, target_tree: Any,
                    default: Any) -> Any:
    """Map each leaf of ``target_tree`` to the assignment of the ``ref_tree``
    leaf with the same (shape, dtype), else ``default``.

    The standard trick for laying out optimizer state: optax moments (mu, nu,
    trace, ...) are copies of the param tree, so matching by shape+dtype
    recovers each moment's param sharding; counts and scalars fall through to
    ``default`` (replicated). First match wins on collisions — identical
    shapes with different assignments would need path-based matching instead.
    """
    def key(leaf):
        # python scalars (e.g. TrainState.step == 0) have no shape/dtype
        return (tuple(getattr(leaf, "shape", ())), getattr(leaf, "dtype", None))

    lookup: dict = {}
    for leaf, a in zip(
        jax.tree.leaves(ref_tree), jax.tree.leaves(ref_assignments)
    ):
        lookup.setdefault(key(leaf), a)
    return jax.tree.map(lambda l: lookup.get(key(l), default), target_tree)


def expand_prefix(prefix_assignments: dict, tree: dict) -> dict:
    """Expand a prefix-assignment tree into a full per-leaf tree.

    Each position in ``prefix_assignments`` is either a dict (recursed —
    the assignment goes deeper than one level, e.g. SwitchLM's
    ``{"moe": {"router": P(), "w_in": P(None, "expert"), ...}}``) or a
    single assignment broadcast over the whole corresponding subtree."""
    def expand(assign: Any, sub: Any) -> Any:
        if isinstance(assign, dict):
            return {k: expand(assign[k], sub[k]) for k in sub}
        return jax.tree.map(lambda _: assign, sub)

    return expand(prefix_assignments, tree)
