"""Schedule-aware matmul FLOP accounting from traced jaxprs.

Why not ``compiled.cost_analysis()``: XLA's HLO cost analysis counts a loop
body ONCE, ignoring trip counts — a pipeline that wastefully re-runs its LM
head inside every scheduling tick reports the same "flops" as one that runs
it once per microbatch (measured: identical numbers for schedules whose real
work differs 7x). This module walks the *jaxpr* instead, multiplying
``lax.scan`` bodies by their static trip count, so the number reflects the
work as scheduled.

Counts ``dot_general`` only — the MXU-relevant FLOPs that dominate every
model here (elementwise work is bandwidth, not FLOPs, on TPU). Control-flow
conventions:

* ``scan``: body flops x trip count (the whole point).
* ``cond``/``switch``/``platform_index``: runtime executes ONE branch; we
  take the max — an upper bound that is exact when the expensive branch is
  the one taken (e.g. a pipeline stage that owns the head).
* ``while``: trip count is dynamic; body counted once (documented
  undercount — none of the framework's hot paths use raw while_loop).
* anything else carrying sub-jaxprs (pjit, remat, custom_vjp, shard_map):
  summed.

There is no reference equivalent: the reference has no benchmarks at all
(SURVEY.md §6); this is part of the test/bench capability gap the TPU build
fills (SURVEY.md §4).
"""

from __future__ import annotations

import math
from typing import Any

import jax
from jax.extend import core


def _dot_general_flops(eqn) -> float:
    lhs, rhs = (v.aval for v in eqn.invars[:2])
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    batch = math.prod(lhs.shape[i] for i in lb)
    contract = math.prod(lhs.shape[i] for i in lc)
    lhs_free = math.prod(
        d for i, d in enumerate(lhs.shape) if i not in set(lb) | set(lc)
    )
    rhs_free = math.prod(
        d for i, d in enumerate(rhs.shape) if i not in set(rb) | set(rc)
    )
    return 2.0 * batch * contract * lhs_free * rhs_free


def _conv_flops(eqn) -> float:
    # conv_general_dilated: 2 * out_spatial_elems * batch * Cout * Cin * prod(k)
    lhs, rhs = (v.aval for v in eqn.invars[:2])
    out = eqn.outvars[0].aval
    dn = eqn.params["dimension_numbers"]
    k_spatial = math.prod(rhs.shape[i] for i in dn.rhs_spec[2:])
    c_in = rhs.shape[dn.rhs_spec[1]]
    c_out = out.shape[dn.out_spec[1]]
    batch = out.shape[dn.out_spec[0]]
    out_spatial = math.prod(out.shape[i] for i in dn.out_spec[2:])
    groups = eqn.params.get("feature_group_count", 1)
    return 2.0 * batch * out_spatial * c_out * c_in * k_spatial / groups


def jaxpr_matmul_flops(jaxpr: Any) -> float:
    """Total dot_general+conv FLOPs of a (Closed)Jaxpr, scan-trip-aware."""
    if isinstance(jaxpr, core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    total = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            total += _dot_general_flops(eqn)
        elif name == "conv_general_dilated":
            total += _conv_flops(eqn)
        elif name == "scan":
            total += eqn.params["length"] * jaxpr_matmul_flops(
                eqn.params["jaxpr"]
            )
        elif name in ("cond", "switch"):
            total += max(
                jaxpr_matmul_flops(b) for b in eqn.params["branches"]
            )
        elif name == "while":
            total += jaxpr_matmul_flops(eqn.params["body_jaxpr"])
        else:
            for v in eqn.params.values():
                if isinstance(v, (core.Jaxpr, core.ClosedJaxpr)):
                    total += jaxpr_matmul_flops(v)
                elif isinstance(v, (tuple, list)):
                    total += sum(
                        jaxpr_matmul_flops(x) for x in v
                        if isinstance(x, (core.Jaxpr, core.ClosedJaxpr))
                    )
    return total


def traced_matmul_flops(fn, *args, **kwargs) -> float:
    """Per-device matmul FLOPs of ``fn(*args, **kwargs)`` as scheduled.

    Under ``shard_map`` the jaxpr is the per-device program, so the result is
    per-device work — multiply by the mesh size for machine totals.
    """
    return jaxpr_matmul_flops(
        jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    )
