from distributed_tensorflow_guide_tpu.utils.determinism import (  # noqa: F401
    DeterminismReport,
    check_runs,
    check_topologies,
)
from distributed_tensorflow_guide_tpu.utils.tb_writer import (  # noqa: F401
    SummaryWriter,
    read_scalars,
)
from distributed_tensorflow_guide_tpu.utils.watchdog import (  # noqa: F401
    DataStallError,
    Watchdog,
    WatchdogTimeout,
)
