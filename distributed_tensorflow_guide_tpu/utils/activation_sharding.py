"""Binding activation-sharding constraints — strategy↔model plumbing.

Under the legacy ``with mesh:`` trace context the pjit strategies must use
(see parallel/tensor.py's set_mesh/flax-boxing note), a bare
``nn.with_logical_constraint`` cannot resolve a mesh and silently degrades
to a no-op. Passing the mesh EXPLICITLY makes the constraint a real
``jax.lax.with_sharding_constraint`` in any context — which is what lets
Megatron-SP (residual-stream sequence sharding) actually bind.

The mesh travels via a trace-time contextvar so model code stays
mesh-agnostic: strategies enter :func:`activation_mesh` around tracing,
models route their constraint sites through :func:`constrain`. Manual-SPMD
paths (shard_map pipelines, where a wsc would be wrong) never set the
contextvar and keep the advisory behavior. Lives in utils so any model
family can use it without importing another model's module.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Any

import flax.linen as nn

_ACT_MESH: contextvars.ContextVar[Any] = contextvars.ContextVar(
    "dtg_activation_mesh", default=None
)


@contextlib.contextmanager
def activation_mesh(mesh):
    """Trace-time context: make :func:`constrain` sites BINDING against
    ``mesh`` (TensorParallel enters this inside its step)."""
    token = _ACT_MESH.set(mesh)
    try:
        yield
    finally:
        _ACT_MESH.reset(token)


def constrain(x, names):
    """``nn.with_logical_constraint`` that binds when a strategy has
    provided a mesh via :func:`activation_mesh`, and stays advisory
    otherwise.

    With a mesh set, the wsc is issued DIRECTLY (flax's own
    ``_with_sharding_constraint`` declares itself "no-op on cpu" in the
    flax 0.10 line, which would silently un-bind every constraint on the
    fake-CPU test meshes — the regression the bindingness test pins).
    Logical→mesh translation still uses the ambient
    ``nn.logical_axis_rules`` via flax's resolver, unmatched names
    defaulting to unsharded, so rule semantics are identical."""
    mesh = _ACT_MESH.get()
    if mesh is not None:
        import jax

        spec = nn.logical_to_mesh_axes(tuple(names))
        if spec is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(mesh, spec))
    return nn.with_logical_constraint(x, names)
