"""Determinism checker — the TPU-native answer to race detection.

The reference has no race detection; its Hogwild example (⚠ Hogwild/hogwild.py)
*is* a deliberate data race — lock-free `apply_gradients` on shared PS
variables, correctness-by-robustness (SURVEY.md §5 race-detection row). In
SPMD-sync land races are impossible by construction, so the useful invariant
flips: **the same seed must produce the same numbers — across runs and across
mesh topologies**. A violation means nondeterministic collectives, stray host
RNG, or a topology-dependent reduction order leaking into the math.

Two checks:

* :func:`check_runs` — run the same training function twice with the same
  seed; metrics must match bit-for-bit (sync SPMD has no excuse for drift).
* :func:`check_topologies` — run under different MeshSpecs; metrics must
  match within ``rtol`` (reduction orders legitimately differ across mesh
  shapes, so exact equality is not required — this mirrors SURVEY.md §4's
  "within tolerance" tier).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Mapping, Sequence

from distributed_tensorflow_guide_tpu.core.mesh import MeshSpec

Metrics = Mapping[str, float]


@dataclasses.dataclass
class DeterminismReport:
    ok: bool
    max_abs_diff: float
    max_rel_diff: float
    detail: str

    def raise_if_failed(self) -> None:
        if not self.ok:
            raise AssertionError(f"determinism check failed: {self.detail}")


def _flatten(ms: Sequence[Metrics]) -> dict[str, list[float]]:
    out: dict[str, list[float]] = {}
    for step_metrics in ms:
        for k, v in step_metrics.items():
            out.setdefault(k, []).append(float(v))
    return out


def _compare(a: Sequence[Metrics], b: Sequence[Metrics], rtol: float,
             label: str) -> DeterminismReport:
    fa, fb = _flatten(a), _flatten(b)
    if fa.keys() != fb.keys():
        return DeterminismReport(False, math.inf, math.inf,
                                 f"{label}: metric keys differ: "
                                 f"{sorted(fa)} vs {sorted(fb)}")
    max_abs = max_rel = 0.0
    for k in fa:
        if len(fa[k]) != len(fb[k]):
            return DeterminismReport(False, math.inf, math.inf,
                                     f"{label}: {k} has {len(fa[k])} vs "
                                     f"{len(fb[k])} entries")
        for x, y in zip(fa[k], fb[k]):
            if math.isnan(x) and math.isnan(y):
                continue
            if math.isnan(x) or math.isnan(y):
                # one-sided NaN is the classic nondeterministic-divergence
                # symptom; Python max() would silently drop a NaN diff
                return DeterminismReport(
                    False, math.inf, math.inf,
                    f"{label}: {k} diverged to NaN in one run only "
                    f"({x} vs {y})")
            ad = abs(x - y)
            rd = ad / max(abs(x), abs(y), 1e-12)
            max_abs, max_rel = max(max_abs, ad), max(max_rel, rd)
    ok = max_rel <= rtol
    return DeterminismReport(
        ok, max_abs, max_rel,
        f"{label}: max_abs_diff={max_abs:.3g} max_rel_diff={max_rel:.3g} "
        f"(rtol={rtol:g})",
    )


def check_runs(train: Callable[[int], Sequence[Metrics]], *, seed: int = 0,
               runs: int = 2, rtol: float = 0.0) -> DeterminismReport:
    """``train(seed)`` returns per-step metrics; all ``runs`` invocations with
    the SAME seed must agree (default: bit-for-bit, rtol=0)."""
    ref = train(seed)
    worst = DeterminismReport(True, 0.0, 0.0, "single run")
    for i in range(1, runs):
        rep = _compare(ref, train(seed), rtol, f"run 0 vs run {i} (seed {seed})")
        if rep.max_rel_diff >= worst.max_rel_diff:
            worst = rep
        if not rep.ok:
            return rep
    return worst


def check_topologies(
    train: Callable[[MeshSpec, int], Sequence[Metrics]],
    specs: Sequence[MeshSpec], *, seed: int = 0, rtol: float = 1e-5,
) -> DeterminismReport:
    """``train(mesh_spec, seed)`` must produce matching metrics for every
    spec in ``specs`` — same global batch, different shardings."""
    if len(specs) < 2:
        raise ValueError("need at least two MeshSpecs to compare")
    ref = train(specs[0], seed)
    worst = DeterminismReport(True, 0.0, 0.0, "single topology")
    for spec in specs[1:]:
        rep = _compare(ref, train(spec, seed), rtol,
                       f"{specs[0]} vs {spec} (seed {seed})")
        if rep.max_rel_diff >= worst.max_rel_diff:
            worst = rep
        if not rep.ok:
            return rep
    return worst
